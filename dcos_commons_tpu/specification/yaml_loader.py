"""YAML service definition front-end.

Reference: ``specification/yaml/RawServiceSpec.java:24`` (mustache render +
parse) and ``YAMLToInternalMappers.java:83`` (the 805-LoC semantic mapping:
resource-set synthesis for inline task resources, ``TASKCFG_ALL_*`` env
routing, port/volume conversion, plan parsing).

Our YAML dialect (close to the reference svc.yml, TPU fields added)::

    name: {{FRAMEWORK_NAME}}
    pods:
      hello:
        count: {{HELLO_COUNT}}
        placement: '[["hostname", "UNIQUE"]]'
        tpu:                      # optional — TPU gang request
          chips: 4
          topology: v4-32
        resource-sets:            # optional; tasks may also inline resources
          node-resources:
            cpus: 1.0
            memory: 4096
            tpus: 4
        tasks:
          server:
            goal: RUNNING
            cmd: ./run.sh
            cpus: 0.5             # inline => synthesized resource set
            memory: 256
            ports:
              http: {port: 0, vip: server}
            volumes:
              - {path: data, size: 1024, type: ROOT}
            env: {FOO: bar}
            configs:
              app-conf: {template: cfg.mustache, dest: conf/app.cfg}
            health-check: {cmd: ./ok.sh, interval: 30, grace-period: 60}
            readiness-check: {cmd: ./ready.sh, interval: 5}
    plans:
      deploy:
        strategy: serial
        phases:
          server-deploy:
            pod: hello
            strategy: parallel
"""

from __future__ import annotations

import os
from typing import Any, Mapping, Optional

import yaml

from ..matching.placement import parse_marathon_constraints, rule_from_json
from ..utils.template import render_template
from .spec import (ConfigFileSpec, DiscoverySpec, GoalState, HealthCheckSpec,
                   HostVolumeSpec, PhaseSpec, PlanSpecModel, PodSpec,
                   PortSpec, ReadinessCheckSpec, ReplacementFailurePolicy,
                   ResourceSet, RLimitSpec, SecretSpec, ServiceSpec,
                   StepSpecEntry, TaskSpec, TpuSpec, TransportEncryptionSpec,
                   VolumeSpec, VolumeType)

TASKCFG_ALL_PREFIX = "TASKCFG_ALL_"
TASKCFG_POD_PREFIX = "TASKCFG_"


def yaml_bool(value: Any) -> bool:
    """Mustache-rendered booleans arrive as strings ('true'/'false').

    Public because task entry points share the convention: env knobs a
    spec routes via ``TASKCFG_*`` (e.g. ``FUSED_CE``) land in the task's
    environment as strings and must parse the same way the scheduler
    parses spec booleans (``frameworks/jax/worker.py --fused-ce``).
    """
    if isinstance(value, str):
        return value.strip().lower() in ("true", "yes", "1")
    return bool(value)


_yaml_bool = yaml_bool  # internal alias (existing call sites)


def load_service_yaml(path: str | os.PathLike,
                      env: Optional[Mapping[str, str]] = None) -> ServiceSpec:
    """Render + parse a service YAML file (reference ``RawServiceSpec.newBuilder``)."""
    with open(path) as f:
        return load_service_yaml_str(f.read(), env, base_dir=os.path.dirname(path))


def load_service_yaml_str(text: str, env: Optional[Mapping[str, str]] = None,
                          base_dir: str = ".") -> ServiceSpec:
    env = dict(env if env is not None else os.environ)
    rendered = render_template(text, env, strict=True)
    raw = yaml.safe_load(rendered)
    spec = _map_raw(raw, env, base_dir)
    errors = spec.validate()
    if errors:
        raise ValueError("invalid service spec:\n  " + "\n  ".join(errors))
    return spec


def taskcfg_env(env: Mapping[str, str], pod_type: str) -> dict[str, str]:
    """``TASKCFG_ALL_X=v`` / ``TASKCFG_<POD>_X=v`` scheduler env -> per-task env
    (reference ``config/TaskEnvRouter.java:26``)."""
    out: dict[str, str] = {}
    pod_prefix = f"{TASKCFG_POD_PREFIX}{pod_type.upper().replace('-', '_')}_"
    for key, value in env.items():
        if key.startswith(TASKCFG_ALL_PREFIX):
            out[key[len(TASKCFG_ALL_PREFIX):]] = value
    # pod-specific overrides ALL; applied second so it wins. For a pod whose
    # name upper-cases to ALL_* the key matches both prefixes — pod-specific
    # routing takes precedence for that pod (the reference's TaskEnvRouter
    # simply can't scope such pods at all).
    for key, value in env.items():
        if key.startswith(pod_prefix):
            out[key[len(pod_prefix):]] = value
    return out


def _map_raw(raw: Mapping[str, Any], env: Mapping[str, str], base_dir: str) -> ServiceSpec:
    if not isinstance(raw, Mapping) or "name" not in raw or "pods" not in raw:
        raise ValueError("service yaml must define 'name' and 'pods'")
    pods = tuple(
        _map_pod(pod_type, pod_raw or {}, env, base_dir)
        for pod_type, pod_raw in raw["pods"].items())
    rfp_raw = raw.get("replacement-failure-policy")
    return ServiceSpec(
        name=str(raw["name"]),
        pods=pods,
        user=raw.get("user"),
        web_url=raw.get("web-url"),
        priority=int(raw.get("priority", 0)),
        replacement_failure_policy=ReplacementFailurePolicy(
            permanent_failure_timeout_s=_seconds(rfp_raw.get("permanent-failure-timeout-mins"), 60),
            min_replace_delay_s=_seconds(rfp_raw.get("min-replace-delay-mins"), 60) or 0.0,
        ) if rfp_raw else None,
        plans=_map_plans(raw.get("plans") or {}),
    )


def _seconds(value, scale) -> Optional[float]:
    return None if value is None else float(value) * scale


def _map_pod(pod_type: str, raw: Mapping[str, Any], env: Mapping[str, str],
             base_dir: str) -> PodSpec:
    resource_sets = [
        _map_resource_set(rs_id, rs_raw or {})
        for rs_id, rs_raw in (raw.get("resource-sets") or {}).items()]
    routed_env = taskcfg_env(env, pod_type)

    tasks = []
    for task_name, task_raw in (raw.get("tasks") or {}).items():
        task_raw = task_raw or {}
        rs_id = task_raw.get("resource-set")
        if rs_id is None:
            # inline resources => synthesized per-task resource set
            # (reference YAMLToInternalMappers "<taskname>-resources" synthesis)
            rs_id = f"{task_name}-resources"
            resource_sets.append(_map_resource_set(rs_id, task_raw))
        tasks.append(_map_task(task_name, task_raw, rs_id, routed_env, base_dir))

    placement = raw.get("placement")
    if placement is None:
        rule = None
    elif isinstance(placement, str):
        # an empty/whitespace constraint means "no constraint" (the reference
        # MarathonConstraintParser.java:35 returns a pass-through for it, so
        # svc.ymls can say placement: '{{POD_PLACEMENT}}' with empty default)
        if not placement.strip():
            rule = None
        else:
            try:
                rule = parse_marathon_constraints(placement)
            except (ValueError, KeyError) as e:
                # keep the spec loadable; the placement_rules_valid config
                # validator blocks the rollout (reference InvalidPlacementRule)
                from ..matching.placement import InvalidPlacementRule
                rule = InvalidPlacementRule(placement, str(e))
    else:
        rule = rule_from_json(placement)

    tpu_raw = raw.get("tpu")
    tpu = TpuSpec(
        chips=int(tpu_raw.get("chips", 0)),
        topology=tpu_raw.get("topology"),
        gang=bool(tpu_raw.get("gang", True)),
        slices=int(tpu_raw.get("slices", 1)),
    ) if tpu_raw else None
    if tpu is None and any(rs.tpus for rs in resource_sets):
        tpu = TpuSpec(chips=max(rs.tpus for rs in resource_sets))

    secrets = []
    for _, sec_raw in (raw.get("secrets") or {}).items():
        sec_raw = sec_raw or {}
        secrets.append(SecretSpec(
            secret_path=sec_raw["secret"],
            env_key=sec_raw.get("env-key"),
            file_path=sec_raw.get("file"),
        ))

    host_volumes = []
    for _, hv_raw in (raw.get("host-volumes") or {}).items():
        hv_raw = hv_raw or {}
        host_volumes.append(HostVolumeSpec(
            host_path=hv_raw["host-path"],
            container_path=hv_raw["container-path"],
        ))

    rlimits = []
    for rl_name, rl_raw in (raw.get("rlimits") or {}).items():
        rl_raw = rl_raw or {}
        rlimits.append(RLimitSpec(
            # canonical upper-case form (the agent matches case-sensitively)
            name=str(rl_name).upper(),
            soft=None if rl_raw.get("soft") is None else int(rl_raw["soft"]),
            hard=None if rl_raw.get("hard") is None else int(rl_raw["hard"]),
        ))

    return PodSpec(
        type=pod_type,
        count=int(raw.get("count", 1)),
        tasks=tuple(tasks),
        resource_sets=tuple(resource_sets),
        user=raw.get("user"),
        image=raw.get("image"),
        networks=tuple((raw.get("networks") or {}).keys()
                       if isinstance(raw.get("networks"), Mapping)
                       else raw.get("networks") or ()),
        placement_rule=rule,
        tpu=tpu,
        pre_reserved_role=raw.get("pre-reserved-role"),
        allow_decommission=_yaml_bool(raw.get("allow-decommission", True)),
        share_pid_namespace=_yaml_bool(
            raw.get("share-pid-namespace", False)),
        seccomp_unconfined=_yaml_bool(raw.get("seccomp-unconfined", False)),
        seccomp_profile=raw.get("seccomp-profile-name") or None,
        ipc_mode=raw.get("ipc-mode") or None,
        shm_size_mb=(None if raw.get("shm-size") is None
                     else int(raw["shm-size"])),
        secrets=tuple(secrets),
        volumes=tuple(_map_volumes(raw)),
        host_volumes=tuple(host_volumes),
        rlimits=tuple(rlimits),
    )


def _map_resource_set(rs_id: str, raw: Mapping[str, Any]) -> ResourceSet:
    ports = []
    for name, port_raw in (raw.get("ports") or {}).items():
        if isinstance(port_raw, Mapping):
            ports.append(PortSpec(
                name=name,
                port=int(port_raw.get("port", 0)),
                env_key=port_raw.get("env-key"),
                vip=port_raw.get("vip"),
                vip_port=port_raw.get("vip-port"),
            ))
        else:
            ports.append(PortSpec(name=name, port=int(port_raw)))
    volumes = _map_volumes(raw)
    return ResourceSet(
        id=rs_id,
        cpus=float(raw.get("cpus", 0.0)),
        memory_mb=int(raw.get("memory", 0)),
        disk_mb=int(raw.get("disk", 0)),
        tpus=int(raw.get("tpus", 0)),
        ports=tuple(ports),
        volumes=tuple(volumes),
    )


def _map_volumes(raw: Mapping[str, Any]) -> list[VolumeSpec]:
    """``volume:`` (single) and/or ``volumes:`` (list) -> VolumeSpecs; used
    at both resource-set/task and pod level (reference RawPod/RawTask)."""
    vol_raw = raw.get("volume")
    vols_raw = list(raw.get("volumes") or ([] if vol_raw is None else [vol_raw]))
    if vol_raw is not None and raw.get("volumes"):
        vols_raw.append(vol_raw)
    out = []
    for v in vols_raw:
        profiles = v.get("profiles") or ()
        if isinstance(profiles, str):
            profiles = (profiles,)
        out.append(VolumeSpec(
            container_path=v["path"],
            size_mb=int(v["size"]),
            type=VolumeType(str(v.get("type", "ROOT")).upper()),
            profiles=tuple(str(p) for p in profiles if p),
        ))
    return out


def _map_task(name: str, raw: Mapping[str, Any], rs_id: str,
              routed_env: Mapping[str, str], base_dir: str) -> TaskSpec:
    env = dict(routed_env)
    env.update({str(k): str(v) for k, v in (raw.get("env") or {}).items()})

    configs = []
    for cfg_name, cfg_raw in (raw.get("configs") or {}).items():
        if "content" in cfg_raw:
            # inline template body (tests / simple services)
            template = cfg_raw["content"]
        else:
            template_path = os.path.join(base_dir, cfg_raw["template"])
            try:
                with open(template_path) as f:
                    template = f.read()
            except OSError as e:
                raise ValueError(
                    f"task {name}: config {cfg_name!r} template not readable: "
                    f"{template_path} ({e})") from None
        configs.append(ConfigFileSpec(
            name=cfg_name, relative_path=cfg_raw["dest"], template=template))

    hc_raw = raw.get("health-check")
    rc_raw = raw.get("readiness-check")
    disc_raw = raw.get("discovery")
    return TaskSpec(
        name=name,
        goal=GoalState(str(raw.get("goal", "RUNNING")).upper()),
        cmd=str(raw.get("cmd", "")),
        resource_set_id=rs_id,
        env=env,
        configs=tuple(configs),
        health_check=HealthCheckSpec(
            cmd=hc_raw["cmd"],
            interval_s=float(hc_raw.get("interval", 30)),
            grace_period_s=float(hc_raw.get("grace-period", 60)),
            max_consecutive_failures=int(hc_raw.get("max-consecutive-failures", 3)),
            timeout_s=float(hc_raw.get("timeout", 20)),
            delay_s=float(hc_raw.get("delay", 0)),
        ) if hc_raw else None,
        readiness_check=ReadinessCheckSpec(
            cmd=rc_raw["cmd"],
            interval_s=float(rc_raw.get("interval", 5)),
            timeout_s=float(rc_raw.get("timeout", 10)),
            delay_s=float(rc_raw.get("delay", 0)),
        ) if rc_raw else None,
        discovery=DiscoverySpec(
            prefix=disc_raw.get("prefix"),
            visibility=disc_raw.get("visibility", "CLUSTER"),
        ) if disc_raw else None,
        essential=_yaml_bool(raw.get("essential", True)),
        kill_grace_period_s=int(raw.get("kill-grace-period", 5)),
        uris=tuple(raw.get("uris") or ()),
        transport_encryption=tuple(
            TransportEncryptionSpec(name=te["name"])
            for te in raw.get("transport-encryption") or ()),
    )


def _map_plans(raw: Mapping[str, Any]) -> tuple[PlanSpecModel, ...]:
    plans = []
    for plan_name, plan_raw in raw.items():
        plan_raw = plan_raw or {}
        phases = []
        for phase_name, phase_raw in (plan_raw.get("phases") or {}).items():
            phase_raw = phase_raw or {}
            steps = []
            for step_raw in phase_raw.get("steps") or ():
                # YAML form: [index, [task, ...]] or {pod-instance:, tasks:}
                if isinstance(step_raw, Mapping):
                    steps.append(StepSpecEntry(
                        pod_instance=int(step_raw.get("pod-instance", -1)),
                        tasks=tuple(step_raw.get("tasks") or ()),
                    ))
                else:
                    idx, tasks = step_raw[0], step_raw[1] if len(step_raw) > 1 else ()
                    idx = -1 if idx in ("default", None) else int(idx)
                    steps.append(StepSpecEntry(
                        pod_instance=idx,
                        tasks=tuple(tasks) if isinstance(tasks, (list, tuple)) else (tasks,)))
            depends = phase_raw.get("depends") or ()
            if isinstance(depends, str):
                depends = (depends,)
            phases.append(PhaseSpec(
                name=phase_name,
                pod_type=phase_raw["pod"],
                strategy=str(phase_raw.get("strategy", "serial")).lower(),
                steps=tuple(steps),
                deps=tuple(depends),
            ))
        plans.append(PlanSpecModel(
            name=plan_name,
            strategy=str(plan_raw.get("strategy", "serial")).lower(),
            phases=tuple(phases),
        ))
    return tuple(plans)
