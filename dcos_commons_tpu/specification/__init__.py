from .spec import (ConfigFileSpec, DiscoverySpec, GoalState, HealthCheckSpec,
                   HostVolumeSpec, PhaseSpec, PlanSpecModel, PodInstance,
                   PodSpec, PortSpec, ReadinessCheckSpec,
                   ReplacementFailurePolicy, ResourceSet, RLimitSpec,
                   SecretSpec, ServiceSpec, StepSpecEntry, TaskSpec, TpuSpec,
                   VolumeSpec, VolumeType, with_pod_count)
from .yaml_loader import (load_service_yaml, load_service_yaml_str,
                          taskcfg_env, yaml_bool)
