"""Disaggregated prefill/decode serving: paged-KV shipping between
scheduler-placed tiers.

The split receipts (``bench_r5/flag8b_long_split.jsonl``) put prefill
and decode in different regimes — 8B prefill at 7,749 tok/s against
decode at 52.7 tok/s — so co-locating both phases on one gang wastes
whichever resource the traffic mixture doesn't bind. This module is the
DistServe/Splitwise-style split over the PR-6 block-paged engine: a
finished prefill is a list of fixed-size pages, so it ships to a decode
tier and attaches to its pool without reshaping.

Three pieces, one wire format:

* :class:`KVShipper` — serializes a finished prefill span (prompt
  tokens, first generated token, int8/bf16 K/V pages, per-page
  prefix-hash metadata) into a framed byte blob and moves it over
  ``security/transport.py`` (TLS when ``TPU_TLS_CA``/co. are set and
  the optional ``cryptography`` package is present; cleartext
  otherwise, matching every other control-plane hop).
* :class:`PrefillWorker` — the prefill tier's front door: an HTTP
  server wrapping one :class:`~dcos_commons_tpu.models.serving.PagedServer`
  in prefill-only mode (``prefill_span`` — chunked prefill flat-out,
  no decode interleave). ``POST /v1/prefill`` takes a prompt and
  returns the packed span; pool exhaustion is a 503 (spans release
  right after packing, so it is transient back-pressure, not failure).
* :class:`DisaggCoordinator` — rank-0 ingress driver for the decode
  tier, structured exactly like the gang broadcast loop
  (``serving_gang.GangServingDriver.run_iteration``) over the same
  external-driver seams (``mark_driven`` / ``drain_intake`` /
  ``attach`` / ``sync`` / ``fail_inflight``): drains new prompts from
  the front door, routes them to the prefill tier (a small sender
  pool; the coordinator thread stays the ONLY thread that touches the
  donation-based engine), tracks in-flight transfers, and admits
  arrived spans into the decode tier's ``PagedServer`` on **pages
  free** via ``adopt_pages()``. A dead or absent peer degrades, never
  crashes: the request falls back to the co-located paged path
  (normal ``submit``) and the receipt says so (``peer_fallbacks``).

The prefix-hash metadata rides so the decode tier's ``PrefixRadix``
can dedupe shipped system prompts (adoption shares cached full pages
by reference and skips their payload writes) and so a corrupted or
truncated transfer aborts BEFORE touching the ledger — and when a
failure does land after pages are reserved, ``adopt_pages`` unwinds
every reservation (``PagePool.check()``/``reconcile()`` hold across
aborted transfers; the chaos tier pins seeds on exactly this seam).
"""

from __future__ import annotations

import hashlib
import json
import queue
import struct
import threading
import time
import urllib.request
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

# the one shared prefix-hash: the wire format, the fleet router, and the
# prefix radix must all key full prompt pages identically or affinity
# routing sends requests where their pages are NOT (see paging.page_hashes)
from ..metrics import MetricsRegistry
from ..tracing import TRACE_HEADER, Tracer, parse_header
from .paging import page_hashes

_MAGIC = b"KVSPAN1\0"
_WIRE_VERSION = 1


class PageShipError(RuntimeError):
    """A KV shipment that must not be adopted: framing, digest, or
    prefix-hash verification failed."""


def _flatten_payload(payload: Dict[str, Any]) -> List[Tuple[str, Any]]:
    """Span payload as a flat (key, ndarray) list in a FIXED order —
    the wire layout. int8 pools carry q + scales per side."""
    out: List[Tuple[str, Any]] = []
    for side in ("k", "v"):
        val = payload[side]
        if isinstance(val, dict):
            out.append((f"{side}.q", np.asarray(val["q"])))
            out.append((f"{side}.s", np.asarray(val["s"])))
        else:
            out.append((side, np.asarray(val)))
    return out


def _wire_dtype(name: str) -> np.dtype:
    """Resolve a dtype name from the wire; bfloat16 and friends live in
    ml_dtypes (a jax dependency, always present here)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def pack_span(span: Dict[str, Any]) -> bytes:
    """Frame a ``PagedServer.prefill_span()`` result for the wire:
    ``MAGIC | header_len | header JSON | raw array bytes``. The header
    names every array (shape + dtype + offset), carries the prompt,
    first token, page size, kv dtype flag, the per-page prefix hashes,
    and a digest of the body — everything :func:`unpack_span` needs to
    verify before the decode tier goes anywhere near its ledger."""
    arrays = _flatten_payload(span["payload"])
    body = b"".join(a.tobytes() for _, a in arrays)
    meta = {
        "version": _WIRE_VERSION,
        "prompt": [int(t) for t in span["prompt"]],
        "first_token": int(span["first_token"]),
        "page_size": int(span["page_size"]),
        "kv_quant": bool(span["kv_quant"]),
        "page_hashes": page_hashes(span["prompt"], span["page_size"]),
        "body_digest": hashlib.blake2s(body).hexdigest(),
        "arrays": [{"key": k, "shape": list(a.shape),
                    "dtype": a.dtype.name} for k, a in arrays],
    }
    header = json.dumps(meta).encode()
    return _MAGIC + struct.pack("<I", len(header)) + header + body


def unpack_span(data: bytes) -> Dict[str, Any]:
    """Parse + VERIFY a framed span: magic, version, body digest, and
    the prefix hashes against the shipped prompt. Raises
    :class:`PageShipError` on any mismatch — a lost or mangled
    transfer dies here, holding zero decode-tier pages."""
    if not data.startswith(_MAGIC):
        raise PageShipError("bad magic: not a KV span frame")
    off = len(_MAGIC)
    if len(data) < off + 4:
        raise PageShipError("truncated frame: no header length")
    (hlen,) = struct.unpack_from("<I", data, off)
    off += 4
    try:
        meta = json.loads(data[off:off + hlen])
    except ValueError as e:
        raise PageShipError(f"bad header: {e}") from None
    off += hlen
    if meta.get("version") != _WIRE_VERSION:
        raise PageShipError(f"wire version {meta.get('version')} != "
                            f"{_WIRE_VERSION}")
    body = data[off:]
    if hashlib.blake2s(body).hexdigest() != meta["body_digest"]:
        raise PageShipError("body digest mismatch: corrupt transfer")
    prompt = [int(t) for t in meta["prompt"]]
    if page_hashes(prompt, meta["page_size"]) != meta["page_hashes"]:
        raise PageShipError("prefix-hash mismatch: prompt and pages "
                            "disagree")
    arrays: Dict[str, np.ndarray] = {}
    pos = 0
    for spec in meta["arrays"]:
        dt = _wire_dtype(spec["dtype"])
        shape = tuple(spec["shape"])
        nbytes = dt.itemsize * int(np.prod(shape))
        if pos + nbytes > len(body):
            raise PageShipError(f"truncated body at {spec['key']!r}")
        arrays[spec["key"]] = np.frombuffer(
            body, dt, count=int(np.prod(shape)),
            offset=pos).reshape(shape)
        pos += nbytes
    payload: Dict[str, Any] = {}
    for side in ("k", "v"):
        if side in arrays:
            payload[side] = arrays[side]
        elif f"{side}.q" in arrays and f"{side}.s" in arrays:
            payload[side] = {"q": arrays[f"{side}.q"],
                             "s": arrays[f"{side}.s"]}
        else:
            raise PageShipError(f"frame missing the {side!r} pages")
    return {"version": meta["version"], "prompt": prompt,
            "first_token": meta["first_token"],
            "page_size": meta["page_size"],
            "kv_quant": meta["kv_quant"],
            "page_hashes": meta["page_hashes"], "payload": payload}


def _transport_urlopen(req, timeout: float):
    """Every shipped byte moves through ``security/transport.py`` when
    it is importable (the env contract then upgrades https:// hops to
    verified TLS); without the optional ``cryptography`` package,
    cleartext http:// falls back to plain urllib and https:// is a
    hard error — silently unverified TLS would defeat the point."""
    try:
        from dcos_commons_tpu.security.transport import urlopen
    except ImportError:
        url = req.full_url if hasattr(req, "full_url") else str(req)
        if str(url).startswith("https://"):
            raise PageShipError(
                "https:// KV shipping needs security/transport.py "
                "(optional cryptography package not installed)")
        return urllib.request.urlopen(req, timeout=timeout)
    return urlopen(req, timeout=timeout)


class KVShipper:
    """Moves packed prefill spans between tiers and keeps the receipt
    counters (``bytes_shipped`` is the on-wire frame size — the number
    the A/B bench reports as KV bytes shipped)."""

    def __init__(self, timeout_s: float = 600.0):
        self.timeout_s = timeout_s
        self._lock = threading.Lock()
        self.spans_shipped = 0
        self.bytes_shipped = 0

    pack = staticmethod(pack_span)
    unpack = staticmethod(unpack_span)

    def fetch(self, peer: str, prompt: List[int],
              trace=None) -> Dict[str, Any]:
        """Ship ``prompt`` to the prefill tier at ``peer`` and return
        the verified span its pages came back as. Raises
        :class:`PageShipError` on transport failure, a peer 503
        (pool back-pressure), or a frame that fails verification.
        ``trace`` (a ``tracing.TraceContext``) propagates over the hop
        as the ``X-Tpu-Trace`` header."""
        headers = {"Content-Type": "application/json"}
        if trace is not None:
            headers[TRACE_HEADER] = trace.header()
        req = urllib.request.Request(
            peer.rstrip("/") + "/v1/prefill",
            data=json.dumps({"prompt": [int(t) for t in prompt]}).encode(),
            headers=headers)
        try:
            with _transport_urlopen(req, timeout=self.timeout_s) as r:
                data = r.read()
        except PageShipError:
            raise
        except Exception as e:
            raise PageShipError(f"peer {peer}: {e}") from None
        span = unpack_span(data)
        with self._lock:
            self.spans_shipped += 1
            self.bytes_shipped += len(data)
        return span


def fetch_prefix(peer: str, prompt: List[int],
                 timeout_s: float = 30.0) -> Optional[Dict[str, Any]]:
    """Fetch a sibling replica's longest cached prefix of ``prompt`` as
    a verified span (``ServingFrontend``'s ``POST /v1/prefix``) — the
    fleet prefix-adoption transport, wired as ``PagedServer``'s
    ``peer_fetch``. Adoption is an OPTIMIZATION: on a miss (404 — the
    sibling holds nothing resident), a transport failure, or a frame
    that fails :func:`unpack_span` verification, this returns None and
    the asker recomputes. Contrast :meth:`KVShipper.fetch`, where the
    prefill tier owes an answer and every failure raises."""
    req = urllib.request.Request(
        peer.rstrip("/") + "/v1/prefix",
        data=json.dumps({"prompt": [int(t) for t in prompt]}).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with _transport_urlopen(req, timeout=timeout_s) as r:
            data = r.read()
        return unpack_span(data)
    except Exception:
        return None


class PrefillWorker:
    """The prefill tier's front door: one prefill-only
    :class:`~dcos_commons_tpu.models.serving.PagedServer` behind HTTP.

    ``POST /v1/prefill`` body ``{"prompt": [...]}`` runs chunked
    prefill flat-out (no decode interleave — the engine never
    dispatches a decode step) and answers with the packed span.
    Exactly ONE request runs the engine at a time (the donation
    contract); concurrent posts queue on the lock, which is the right
    back-pressure for a tier whose whole job is sequential prefill
    throughput. A full pool is a 503, transient by construction:
    spans release every working page right after packing."""

    def __init__(self, engine, port: int = 0, host: str = "0.0.0.0",
                 window_s: float = 60.0,
                 metrics: Optional[MetricsRegistry] = None,
                 trace_store=None):
        self.engine = engine
        self._lock = threading.Lock()
        # rolling-window load signal, same shape + keys as
        # ServingFrontend.load_gauges(): the fleet router and the
        # autoscaler read `"load"` from /v1/healthz on EVERY replica
        # shape, prefill tier included
        self.window_s = window_s
        self._window: deque = deque(maxlen=4096)   # t of each span served
        self._sheds: deque = deque(maxlen=4096)    # t of each 503
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self._own_metrics = metrics is None
        self.tracer = Tracer("prefill", trace_store)
        if getattr(engine, "tracer", None) is None:
            engine.tracer = Tracer("prefill-engine", trace_store)
        for key in ("completed", "shed", "shed_rate", "pages_free",
                    "pages_total"):
            self.metrics.gauge(f"prefill.{key}",
                               lambda k=key: self.load_gauges().get(k))
        worker = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def _json(self, code: int, payload: dict) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/v1/healthz":
                    st = worker.engine.page_stats()
                    self._json(200, {"ok": True, "role": "prefill",
                                     "pages_free": st["pages_free"],
                                     "shipped_spans": st["shipped_spans"],
                                     "load": worker.load_gauges()})
                elif self.path == "/v1/metrics":
                    self._json(200, worker.metrics.to_dict())
                elif self.path == "/v1/metrics/prometheus":
                    body = worker.metrics.to_prometheus().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path == "/v1/traces":
                    store = worker.tracer.store
                    self._json(200, {
                        "trace_ids": store.trace_ids(),
                        "incomplete": store.incomplete_trace_ids()})
                elif self.path.startswith("/v1/trace/"):
                    trace_id = self.path[len("/v1/trace/"):].split("?")[0]
                    self._json(200, worker.tracer.store.export(trace_id))
                else:
                    self._json(404, {"error": f"no route {self.path}"})

            def do_POST(self):
                if self.path != "/v1/prefill":
                    self._json(404, {"error": f"no route {self.path}"})
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(n))
                    prompt = [int(t) for t in body["prompt"]]
                except Exception as e:
                    self._json(400, {"error": f"bad request: {e}"})
                    return
                ctx = parse_header(self.headers.get(TRACE_HEADER))
                t0 = time.perf_counter()
                try:
                    with worker._lock:
                        span = worker.engine.prefill_span(prompt,
                                                          trace=ctx)
                except ValueError as e:
                    self._json(400, {"error": str(e)})
                    return
                except Exception as e:
                    worker.metrics.counter("prefill.errors")
                    self._json(500, {"error": f"prefill failed: {e}"})
                    return
                if span is None:
                    worker._sheds.append(time.monotonic())
                    worker.metrics.counter("prefill.sheds")
                    if ctx is not None:
                        worker.tracer.record("prefill.request", t0,
                                             time.perf_counter(),
                                             parent=ctx, status="shed")
                    self._json(503, {"error": "page pool exhausted"})
                    return
                worker._window.append(time.monotonic())
                frame = pack_span(span)
                worker.metrics.counter("prefill.spans_served")
                worker.metrics.counter("prefill.bytes_served", len(frame))
                worker.metrics.observe("prefill.span_seconds",
                                       time.perf_counter() - t0)
                if ctx is not None:
                    worker.tracer.record(
                        "prefill.request", t0, time.perf_counter(),
                        parent=ctx, prompt_len=len(prompt),
                        frame_bytes=len(frame))
                self.send_response(200)
                self.send_header("Content-Type",
                                 "application/octet-stream")
                self.send_header("Content-Length", str(len(frame)))
                self.end_headers()
                self.wfile.write(frame)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def load_gauges(self) -> dict:
        """The ``scheduler/elastic.py`` ``backpressure()`` contract over
        the prefill tier: spans served stand in for completions, 503s
        (pool exhaustion) are sheds, page occupancy is the utilization
        signal. ``queue_depth`` is 0 by construction — concurrent posts
        serialize on the engine lock, not a queue."""
        horizon = time.monotonic() - self.window_s
        shed = sum(1 for t in self._sheds if t >= horizon)
        completed = sum(1 for t in self._window if t >= horizon)
        out = {
            "window_s": self.window_s,
            "queue_depth": 0,
            "queue_capacity": 0,
            "completed": completed,
            "shed": shed,
            "shed_rate": shed / max(1, shed + completed),
            "ttft_p95_ms": None,
        }
        if hasattr(self.engine, "pages_free"):
            out["pages_free"] = self.engine.pages_free()
            ledger = getattr(self.engine, "ledger", None)
            if ledger is not None:
                out["pages_total"] = ledger.pages
        return out

    def start(self) -> "PrefillWorker":
        try:
            # same opt-in TLS contract as the ingress: wraps when the
            # env asks for it AND the optional dependency is present
            from dcos_commons_tpu.security.transport import (
                server_tls_from_env)
            creds = server_tls_from_env()
            if creds is not None:
                from dcos_commons_tpu.security.transport import wrap_server
                wrap_server(self._httpd, creds)
        except ImportError:
            pass
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="prefill-http")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=10)
        if self._own_metrics:
            self.metrics.close()


class DisaggCoordinator:
    """Rank-0 ingress driver for the decode tier of a disaggregated
    pair — the same loop shape as
    ``serving_gang.GangServingDriver.run_iteration`` over the same
    front-door seams, with the prefill dispatch replaced by a shipping
    path:

    1. stamp liveness (``mark_driven``) and resolve failed transfers
       (peer down → co-located fallback submit, loudly counted);
    2. admit ARRIVED spans head-of-FIFO into the decode engine on
       pages free (``adopt_pages``); a span that does not fit stalls
       the arrival queue (counted — this is the transfer-stall metric)
       rather than leapfrogging, mirroring paged ``submit_many``;
    3. re-offer the co-located fallback backlog, then drain NEW
       prompts from the front door (bounded by the in-flight transfer
       cap) into the sender pool;
    4. one decode window + fan-out (``step_many`` + ``sync``).

    The coordinator thread is the only thread that touches the
    donation-based engine; sender threads do HTTP + numpy framing
    only. ``run()`` wraps iterations in the gang driver's crash
    discipline: on an engine error every in-flight request fails fast
    and the engine resets.

    ``peer`` may be a single URL or a comma-separated list (the
    ``SERVE_PEER`` convention): requests round-robin across the
    healthy peers, a fetch failure marks that peer down and the
    request tries the NEXT peer before degrading to the co-located
    path, and a down peer rejoins the rotation once its
    ``/v1/healthz`` probe answers (re-probed at most every
    ``health_recheck_s``)."""

    def __init__(self, engine, frontend, peer,
                 shipper: Optional[KVShipper] = None,
                 max_intake: int = 4, decode_window: int = 8,
                 max_inflight: int = 8, transfer_workers: int = 2,
                 idle_sleep_s: float = 0.005,
                 colocated_fallback: bool = True,
                 health_recheck_s: float = 5.0):
        self.engine = engine
        self.frontend = frontend
        if isinstance(peer, str):
            self.peers = [p.strip() for p in peer.split(",") if p.strip()]
        elif peer:
            self.peers = [str(p).strip() for p in peer if str(p).strip()]
        else:
            self.peers = []
        # single-peer compat: existing callers and receipts read .peer
        self.peer = self.peers[0] if self.peers else None
        self.health_recheck_s = health_recheck_s
        self._peer_lock = threading.Lock()
        self._rr = 0
        self._peer_down: Dict[str, float] = {}  # peer -> monotonic mark
        self.shipper = shipper if shipper is not None else KVShipper()
        self.max_intake = max(1, max_intake)
        self.decode_window = max(1, decode_window)
        self.max_inflight = max(1, max_inflight)
        self.idle_sleep_s = idle_sleep_s
        self.colocated_fallback = colocated_fallback
        self._send_q: "queue.Queue" = queue.Queue()
        self._arrivals: "queue.Queue" = queue.Queue()
        self._failed: "queue.Queue" = queue.Queue()
        self._arrival_backlog: List[Tuple[Dict[str, Any], Any]] = []
        self._local_backlog: List[Any] = []
        self._outstanding = 0              # transfers in flight
        self._count_lock = threading.Lock()
        self._stop = threading.Event()
        self.tracer = Tracer("disagg")
        self.transfer_stalls = 0
        self.peer_fallbacks = 0
        self.iterations = 0
        self._senders = [
            threading.Thread(target=self._sender_loop, daemon=True,
                             name=f"kv-sender-{i}")
            for i in range(max(1, transfer_workers))]
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------ sender pool

    def _probe_healthz(self, peer: str) -> bool:
        try:
            req = urllib.request.Request(peer.rstrip("/") + "/v1/healthz")
            with _transport_urlopen(req, timeout=5.0) as r:
                return bool(json.loads(r.read()).get("ok"))
        except Exception:
            return False

    def _mark_down(self, peer: str) -> None:
        with self._peer_lock:
            self._peer_down[peer] = time.monotonic()

    def _peer_ok(self, peer: str) -> bool:
        """True when the peer is in rotation. A down peer stays out
        until the recheck window elapses AND its healthz probe (done
        here, outside the lock) answers ok."""
        with self._peer_lock:
            marked = self._peer_down.get(peer)
            if marked is None:
                return True
            if time.monotonic() - marked < self.health_recheck_s:
                return False
        if self._probe_healthz(peer):
            with self._peer_lock:
                self._peer_down.pop(peer, None)
            return True
        self._mark_down(peer)
        return False

    def _peer_order(self) -> List[str]:
        """Healthy peers in round-robin order for one request."""
        with self._peer_lock:
            n = len(self.peers)
            if n == 0:
                return []
            start = self._rr % n
            self._rr += 1
            ordered = self.peers[start:] + self.peers[:start]
        return [p for p in ordered if self._peer_ok(p)]

    def _sender_loop(self) -> None:
        while not self._stop.is_set():
            try:
                pending = self._send_q.get(timeout=0.1)
            except queue.Empty:
                continue
            last_err = "no healthy prefill peer"
            sent = False
            ctx = getattr(pending, "trace", None)
            # peer-by-peer: only after every healthy peer refused does
            # the request degrade to the co-located path
            for peer in self._peer_order():
                t0 = time.perf_counter()
                try:
                    span = self.shipper.fetch(peer, pending.prompt,
                                              trace=ctx)
                except Exception as e:
                    last_err = str(e)
                    if ctx is not None:
                        self.tracer.record("disagg.ship", t0,
                                           time.perf_counter(),
                                           parent=ctx, status="error",
                                           peer=peer)
                    self._mark_down(peer)
                    continue
                if ctx is not None:
                    self.tracer.record("disagg.ship", t0,
                                       time.perf_counter(), parent=ctx,
                                       peer=peer)
                self._arrivals.put((span, pending))
                sent = True
                break
            if not sent:
                self._failed.put((pending, last_err))

    def _dec_outstanding(self) -> None:
        with self._count_lock:
            self._outstanding -= 1

    # ------------------------------------------------------- drive loop

    def _admit_colocated(self, pending) -> None:
        """Degrade-not-crash: the peer is absent or failing, so this
        request runs BOTH phases on the decode tier's engine (the
        normal chunked-prefill path). Capacity misses re-offer from
        the local backlog next iteration — never dropped."""
        if pending.t_submit is None:
            pending.t_submit = time.perf_counter()
        slot = self.engine.submit(pending.prompt, pending.max_new,
                                  request_id=pending)
        if slot is None:
            self._local_backlog.append(pending)
        else:
            self.frontend.attach(slot, pending)

    def run_iteration(self) -> bool:
        fe = self.frontend
        fe.mark_driven()
        worked = False
        # 1. failed transfers: degrade to the co-located paged path
        while True:
            try:
                pending, err = self._failed.get_nowait()
            except queue.Empty:
                break
            self._dec_outstanding()
            worked = True
            if self.colocated_fallback:
                self.peer_fallbacks += 1
                self._admit_colocated(pending)
            else:
                pending.finish(f"prefill peer failed: {err}")
        # 2. arrived spans admit on pages free, FIFO — a blocked head
        # stalls the queue (transfer_stalls) instead of being leapt
        while True:
            try:
                self._arrival_backlog.append(self._arrivals.get_nowait())
            except queue.Empty:
                break
        while self._arrival_backlog:
            span, pending = self._arrival_backlog[0]
            try:
                slot = self.engine.adopt_pages(
                    span, max_new=pending.max_new, request_id=pending)
            except (ValueError, PageShipError) as e:
                self._arrival_backlog.pop(0)
                self._dec_outstanding()
                pending.finish(f"span rejected: {e}")
                worked = True
                continue
            if slot is None:
                self.transfer_stalls += 1
                break
            self._arrival_backlog.pop(0)
            self._dec_outstanding()
            pending.t_submit = time.perf_counter()
            fe.attach(slot, pending)
            worked = True
        # 3. co-located fallback backlog, then new intake
        backlog, self._local_backlog = self._local_backlog, []
        for pending in backlog:
            if pending.done.is_set():
                continue
            self._admit_colocated(pending)
        with self._count_lock:
            room = self.max_inflight - self._outstanding
        budget = min(self.max_intake, max(0, room))
        for pending in fe.drain_intake(budget):
            worked = True
            if not self.peers:
                self.peer_fallbacks += 1
                self._admit_colocated(pending)
                continue
            with self._count_lock:
                self._outstanding += 1
            self._send_q.put(pending)
        # 4. one decode window + fan-out
        if self.engine.requests_active():
            self.engine.step_many(self.decode_window)
            fe.sync()
            worked = True
        self.iterations += 1
        return worked

    def run(self, max_iterations: Optional[int] = None) -> None:
        """Drive until stopped (or ``max_iterations``), with the gang
        driver's crash discipline: an engine error fails every
        in-flight request fast and resets the engine — a serving
        replica must come back serving."""
        it = 0
        while not self._stop.is_set():
            if max_iterations is not None and it >= max_iterations:
                break
            it += 1
            try:
                worked = self.run_iteration()
            except Exception as e:
                self.frontend.fail_inflight(f"engine error: {e}")
                for _, pending in self._arrival_backlog:
                    self._dec_outstanding()
                    pending.finish(f"engine error: {e}")
                self._arrival_backlog = []
                for pending in self._local_backlog:
                    pending.finish(f"engine error: {e}")
                self._local_backlog = []
                self.engine.reset()
                continue
            if not worked:
                time.sleep(self.idle_sleep_s)

    def start(self) -> "DisaggCoordinator":
        for th in self._senders:
            th.start()
        self._thread = threading.Thread(target=self.run, daemon=True,
                                        name="disagg-coordinator")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=10)
        for th in self._senders:
            th.join(timeout=2)

    def stats(self) -> Dict[str, Any]:
        with self._count_lock:
            outstanding = self._outstanding
        with self._peer_lock:
            down = sorted(self._peer_down)
        return {
            "peer": self.peer,
            "peers": list(self.peers),
            "peers_down": down,
            "spans_shipped": self.shipper.spans_shipped,
            "kv_bytes_shipped": self.shipper.bytes_shipped,
            "transfer_stalls": self.transfer_stalls,
            "peer_fallbacks": self.peer_fallbacks,
            "transfers_inflight": outstanding,
            "iterations": self.iterations,
        }
