"""ResNet-50 — the data-parallel north-star workload (BASELINE.json #4).

TPU-first choices: NHWC layout (the TPU-native conv layout), bf16 weights
and activations with fp32 batch-norm statistics, and a pure-functional
(params, state) split so the whole train step jits as one XLA program with
the cross-replica gradient all-reduce inserted by GSPMD from the ``dp``
batch sharding. BN running stats are updated in the step (momentum EMA).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from dcos_commons_tpu.ops import softmax_cross_entropy

Params = Dict[str, Any]
BLOCKS = {18: (2, 2, 2, 2), 34: (3, 4, 6, 3), 50: (3, 4, 6, 3),
          101: (3, 4, 23, 3), 152: (3, 8, 36, 3)}
BOTTLENECK = {50, 101, 152}


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    depth: int = 50
    n_classes: int = 1000
    width: int = 64
    bn_momentum: float = 0.9
    bn_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    # space-to-depth stem (MLPerf-style): fold 2x2 spatial blocks into
    # channels so the 7x7/s2 stem over 3 channels becomes a numerically
    # identical 4x4/s1 conv over 12 — 4x the contraction depth for the MXU
    # on the one layer whose arithmetic intensity is worst. Weights stay in
    # the canonical [7,7,3,w] layout (checkpoints interchangeable); the
    # fold happens inside the jitted step. Measured on v5e: +0.4% at batch
    # 256 with the fused BN — within noise, so off by default
    # (docs/performance.md round-3 experiments).
    stem_s2d: bool = False

    @property
    def stage_blocks(self) -> Tuple[int, ...]:
        return BLOCKS[self.depth]

    @property
    def bottleneck(self) -> bool:
        return self.depth in BOTTLENECK


def _conv_init(key, kh, kw, cin, cout, dtype):
    fan_in = kh * kw * cin
    return (jax.random.normal(key, (kh, kw, cin, cout), jnp.float32)
            * (2.0 / fan_in) ** 0.5).astype(dtype)


def _bn_init(c):
    return {"scale": jnp.ones((c,), jnp.float32),
            "bias": jnp.zeros((c,), jnp.float32)}


def _bn_state(c):
    return {"mean": jnp.zeros((c,), jnp.float32),
            "var": jnp.ones((c,), jnp.float32)}


def init_params(cfg: ResNetConfig, key: jax.Array) -> Tuple[Params, Params]:
    """Returns (params, bn_state)."""
    keys = iter(jax.random.split(key, 256))
    p: Params = {"stem": {"conv": _conv_init(next(keys), 7, 7, 3, cfg.width,
                                             cfg.dtype),
                          "bn": _bn_init(cfg.width)}}
    s: Params = {"stem": {"bn": _bn_state(cfg.width)}}
    cin = cfg.width
    expansion = 4 if cfg.bottleneck else 1
    for stage, n_blocks in enumerate(cfg.stage_blocks):
        width = cfg.width * (2 ** stage)
        cout = width * expansion
        for b in range(n_blocks):
            name = f"stage{stage}_block{b}"
            stride = 2 if (b == 0 and stage > 0) else 1
            bp: Params = {}
            bs: Params = {}
            if cfg.bottleneck:
                convs = [(1, 1, cin, width, 1), (3, 3, width, width, stride),
                         (1, 1, width, cout, 1)]
            else:
                convs = [(3, 3, cin, width, stride), (3, 3, width, cout, 1)]
            for i, (kh, kw, ci, co, st) in enumerate(convs):
                bp[f"conv{i}"] = _conv_init(next(keys), kh, kw, ci, co,
                                            cfg.dtype)
                bp[f"bn{i}"] = _bn_init(co)
                bs[f"bn{i}"] = _bn_state(co)
            if b == 0 and (cin != cout or stride != 1):
                bp["proj"] = _conv_init(next(keys), 1, 1, cin, cout,
                                        cfg.dtype)
                bp["proj_bn"] = _bn_init(cout)
                bs["proj_bn"] = _bn_state(cout)
            p[name], s[name] = bp, bs
            cin = cout
    p["head"] = {"w": (jax.random.normal(next(keys), (cin, cfg.n_classes),
                                         jnp.float32)
                       * cin ** -0.5).astype(cfg.dtype),
                 "b": jnp.zeros((cfg.n_classes,), jnp.float32)}
    return p, s


def _batch_norm(x, bn, st, cfg, train):
    """Fused-apply batch norm: statistics accumulate in fp32 (reduction-only
    consumers of the cast let XLA fuse without materializing an fp32 copy),
    then normalize+scale+shift folds into ONE per-channel bf16 FMA that XLA
    fuses into the producing conv — measured +9% ResNet-50 step throughput
    on v5e vs normalizing in fp32 (docs/performance.md)."""
    if train:
        mean = jnp.mean(x, axis=(0, 1, 2), dtype=jnp.float32)
        mean_sq = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=(0, 1, 2))
        var = jnp.maximum(mean_sq - jnp.square(mean), 0.0)
        m = cfg.bn_momentum
        new_st = {"mean": m * st["mean"] + (1 - m) * mean,
                  "var": m * st["var"] + (1 - m) * var}
    else:
        mean, var = st["mean"], st["var"]
        new_st = st
    a = bn["scale"] * lax.rsqrt(var + cfg.bn_eps)
    b = bn["bias"] - mean * a
    return x * a.astype(x.dtype) + b.astype(x.dtype), new_st


def _conv(x, w, stride=1):
    return lax.conv_general_dilated(
        x, w, window_strides=(stride, stride), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _stem_s2d(x, w):
    """The stem conv as space-to-depth: [B,H,W,3] x [7,7,3,C] -> the exact
    SAME-padded 7x7/s2 result via a 4x4/s1 conv on 2x2-folded input.

    SAME for k=7/s=2 pads (2, 3), so output i taps rows 2i-2..2i+4; in
    2x2-block space that is blocks i-1..i+2 — a 4-block window, padding
    (1, 2), with the kernel zero-padded to 8 rows/cols before folding.
    """
    b, h, w_, c = x.shape
    xs = x.reshape(b, h // 2, 2, w_ // 2, 2, c)
    xs = xs.transpose(0, 1, 3, 2, 4, 5).reshape(b, h // 2, w_ // 2, 4 * c)
    w8 = jnp.pad(w, ((0, 1), (0, 1), (0, 0), (0, 0)))
    cin, cout = w.shape[2], w.shape[3]
    wf = w8.reshape(4, 2, 4, 2, cin, cout).transpose(0, 2, 1, 3, 4, 5)
    wf = wf.reshape(4, 4, 4 * cin, cout)
    return lax.conv_general_dilated(
        xs, wf, window_strides=(1, 1), padding=((1, 2), (1, 2)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def forward(cfg: ResNetConfig, params: Params, state: Params,
            x: jnp.ndarray, train: bool = True
            ) -> Tuple[jnp.ndarray, Params]:
    """x [B, H, W, 3] -> (logits [B, n_classes] fp32, new bn_state)."""
    x = x.astype(cfg.dtype)
    new_state: Params = {}
    if cfg.stem_s2d and x.shape[1] % 2 == 0 and x.shape[2] % 2 == 0:
        x = _stem_s2d(x, params["stem"]["conv"])
    else:
        x = _conv(x, params["stem"]["conv"], stride=2)
    x, st = _batch_norm(x, params["stem"]["bn"], state["stem"]["bn"], cfg,
                        train)
    new_state["stem"] = {"bn": st}
    x = jax.nn.relu(x)
    x = lax.reduce_window(x, -jnp.inf, lax.max, (1, 3, 3, 1), (1, 2, 2, 1),
                          "SAME")
    for stage, n_blocks in enumerate(cfg.stage_blocks):
        for b in range(n_blocks):
            name = f"stage{stage}_block{b}"
            bp, bs = params[name], state[name]
            ns: Params = {}
            stride = 2 if (b == 0 and stage > 0) else 1
            shortcut = x
            y = x
            n_convs = 3 if cfg.bottleneck else 2
            for i in range(n_convs):
                st_i = stride if ((cfg.bottleneck and i == 1)
                                  or (not cfg.bottleneck and i == 0)) else 1
                y = _conv(y, bp[f"conv{i}"], st_i)
                y, ns[f"bn{i}"] = _batch_norm(y, bp[f"bn{i}"], bs[f"bn{i}"],
                                              cfg, train)
                if i < n_convs - 1:
                    y = jax.nn.relu(y)
            if "proj" in bp:
                shortcut = _conv(shortcut, bp["proj"], stride)
                shortcut, ns["proj_bn"] = _batch_norm(
                    shortcut, bp["proj_bn"], bs["proj_bn"], cfg, train)
            x = jax.nn.relu(y + shortcut)
            new_state[name] = ns
    x = x.mean(axis=(1, 2)).astype(jnp.float32)          # global avg pool
    logits = x @ params["head"]["w"].astype(jnp.float32) + params["head"]["b"]
    return logits, new_state


def loss_fn(cfg: ResNetConfig, params: Params, state: Params,
            batch: Tuple[jnp.ndarray, jnp.ndarray]
            ) -> Tuple[jnp.ndarray, Tuple[jnp.ndarray, Params]]:
    x, y = batch
    logits, new_state = forward(cfg, params, state, x, train=True)
    loss, acc = softmax_cross_entropy(logits, y)
    return loss, (acc, new_state)
