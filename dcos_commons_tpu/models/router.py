"""Fleet front door: prefix-affinity router tier across decode replicas.

Everything below the ingress scales — paged prefix-shared KV (PR 6),
disaggregated tiers (PR 7), an autoscaler that grows the decode fleet
(PR 10) — but each ``ServingFrontend`` is an island: the ``PrefixRadix``
win is per-process, so scaling OUT resets the prefix-cache hit rate
unless something routes a request to the replica whose radix already
holds its pages. This module is that something — the SGLang/Mooncake
cache-aware-routing insight as a schedulable pod tier:

* **Consistent-hash affinity** (:class:`HashRing`): requests hash on
  their prompt's radix prefix — the same full-page content hash the KV
  wire format and the radix key on (``paging.page_hashes``; see
  :func:`route_key`) — so the millions of users sharing a system prompt
  land on the replica that already caches it, and a decode-tier resize
  moves only ~K/N of the keyspace instead of reshuffling everything.
* **Per-tenant QoS** (:class:`TenantAdmission`): token-bucket admission
  per tenant, with :class:`QoSClass` carrying the SAME integer priority
  classes the scheduler's ``priority:`` field uses (``dist/fleet.yml``
  maps them onto the pod specs), a per-class TTFT SLO for conformance
  receipts, and a spill floor — classes at/above it may chase idle
  capacity fleet-wide when their affinity target runs hot; classes
  below it wait their turn (spill-on-DOWN applies to everyone:
  availability is not a paid feature).
* **Streaming fan-in**: the router relays each replica's chunked token
  stream straight back to the client connection, and because decode is
  deterministic greedy, a replica that dies mid-stream is survivable —
  the relay re-issues the request on the next candidate and skips the
  tokens the client already has (``spill_resumes``) — after checking
  each replayed token against what was relayed, so replicas that
  diverge (mixed versions mid-rolling-deploy) fail over again instead
  of splicing two completions (``resume_divergences``). An admitted stream
  is only ever dropped after every healthy candidate was attempted
  (``dropped_streams`` — the chaos invariant pins this to spill-first).
* **Health/load-aware spill** (:class:`ReplicaSet`): generalizes
  ``DisaggCoordinator``'s health-gated multi-peer rotation
  (``models/disagg.py``) — a failing replica is marked down and
  re-probed after ``health_recheck_s`` via ``/v1/healthz``, whose
  ``"load"`` gauges (``ServingFrontend.load_gauges()``) collapse
  through ``scheduler/elastic.py``'s ``backpressure()`` into the
  pressure signal that decides hot-spill and least-loaded placement.

Elasticity contract: when the autoscaler resizes the decode tier,
:meth:`Router.set_replicas` rebalances the ring — departing replicas
leave the ring FIRST (no new affinity), while relays already attached
to them run to completion (drain, not drop); a mid-drain death falls
into the normal spill-resume path.
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
import urllib.error
import urllib.request
from bisect import bisect_right, insort
from collections import OrderedDict, deque
from dataclasses import dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from ..metrics import MetricsRegistry
from ..scheduler.elastic import backpressure
from ..tracing import (TRACE_HEADER, Span, TraceContext, Tracer, new_id,
                       parse_header, perf_to_epoch)
from .disagg import _transport_urlopen
from .paging import chain_keys, page_hashes


def route_key(prompt: Sequence[int], page_size: int,
              affinity_pages: int = 1) -> str:
    """The affinity key for a prompt: the chain of its first
    ``affinity_pages`` FULL-page prefix hashes (``paging.page_hashes``
    — the exact hashes the radix and the KV wire format agree on), so
    two prompts share a key iff they share the radix pages affinity is
    chasing. Prompts shorter than one page hash their raw tokens —
    still stable, nothing cached to chase."""
    hashes = page_hashes(prompt, page_size)[:max(1, affinity_pages)]
    if hashes:
        return "/".join(hashes)
    raw = ",".join(str(int(t)) for t in prompt).encode()
    return "p:" + hashlib.blake2s(raw).hexdigest()[:16]


class HashRing:
    """Consistent-hash ring with virtual nodes.

    Each replica owns ``vnodes`` points (blake2s of ``"name#i"``); a key
    maps to the first point clockwise. Adding or removing one replica
    moves only the keys in its arcs — the bounded-key-movement property
    ``tests/test_router.py`` pins — so a decode-tier resize does not
    reshuffle the whole fleet's prefix affinity."""

    def __init__(self, nodes: Iterable[str] = (), vnodes: int = 64):
        if vnodes < 1:
            raise ValueError(f"vnodes must be >= 1, got {vnodes}")
        self.vnodes = vnodes
        self._points: List[Tuple[int, str]] = []   # sorted (point, node)
        self._nodes: Dict[str, List[int]] = {}
        for n in nodes:
            self.add(n)

    @staticmethod
    def _point(node: str, i: int) -> int:
        digest = hashlib.blake2s(f"{node}#{i}".encode()).digest()
        return int.from_bytes(digest[:8], "big")

    def nodes(self) -> List[str]:
        return sorted(self._nodes)

    def __len__(self) -> int:
        return len(self._nodes)

    def add(self, node: str) -> None:
        if node in self._nodes:
            return
        pts = [self._point(node, i) for i in range(self.vnodes)]
        self._nodes[node] = pts
        for p in pts:
            insort(self._points, (p, node))

    def remove(self, node: str) -> None:
        pts = self._nodes.pop(node, None)
        if pts is None:
            return
        dead = set(pts)
        self._points = [(p, n) for p, n in self._points
                        if not (n == node and p in dead)]

    def lookup(self, key: str) -> Optional[str]:
        pref = self.preference(key, 1)
        return pref[0] if pref else None

    def preference(self, key: str, n: Optional[int] = None) -> List[str]:
        """Distinct replicas in clockwise order from the key's point —
        the failover order for spill, so a key's fallback target is as
        stable as its primary."""
        if not self._points:
            return []
        want = len(self._nodes) if n is None else min(n, len(self._nodes))
        kp = int.from_bytes(hashlib.blake2s(key.encode()).digest()[:8],
                            "big")
        start = bisect_right(self._points, (kp, chr(0x10FFFF)))
        out: List[str] = []
        for i in range(len(self._points)):
            node = self._points[(start + i) % len(self._points)][1]
            if node not in out:
                out.append(node)
                if len(out) >= want:
                    break
        return out


class TokenBucket:
    """Token-bucket admission: ``burst`` capacity, ``rate`` tokens/s
    refill. ``rate=0`` freezes the bucket — the initial burst is all it
    ever admits; ``burst=0`` admits nothing. The clock is injectable so
    tests and the chaos soak replay deterministically."""

    def __init__(self, rate: float, burst: float, clock=time.monotonic):
        if rate < 0 or burst < 0:
            raise ValueError(f"rate/burst must be >= 0, got "
                             f"{rate}/{burst}")
        self.rate = float(rate)
        self.burst = float(burst)
        self._clock = clock
        self._tokens = float(burst)
        self._last = clock()
        self._lock = threading.Lock()

    def try_take(self, n: float = 1.0) -> bool:
        with self._lock:
            now = self._clock()
            self._tokens = min(self.burst,
                               self._tokens + (now - self._last) * self.rate)
            self._last = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def available(self) -> float:
        with self._lock:
            now = self._clock()
            self._tokens = min(self.burst,
                               self._tokens + (now - self._last) * self.rate)
            self._last = now
            return self._tokens


@dataclass(frozen=True)
class QoSClass:
    """One tenant QoS class. ``priority`` uses the scheduler's
    ``priority:`` integers (``specification``/``dist/fleet.yml``) so
    tenant classes and pod tiers rank on one scale; ``rate``/``burst``
    parameterize each tenant's admission bucket; ``ttft_slo_ms`` is the
    per-class conformance bar the bench receipts report against."""

    name: str
    priority: int = 0
    rate: float = float("inf")
    burst: float = float("inf")
    ttft_slo_ms: Optional[float] = None


DEFAULT_CLASS = QoSClass("default")


def parse_qos_classes(spec: str) -> Dict[str, QoSClass]:
    """Parse the ``TENANT_CLASSES`` knob:
    ``name:priority:rate:burst[:ttft_slo_ms]`` entries, comma-separated
    — e.g. ``gold:10:50:100:250,free:1:2:4``. Empty spec → no classes
    (every tenant admits unlimited under :data:`DEFAULT_CLASS`)."""
    out: Dict[str, QoSClass] = {}
    for entry in (spec or "").split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        if len(parts) not in (4, 5):
            raise ValueError(
                f"bad TENANT_CLASSES entry {entry!r}: want "
                "name:priority:rate:burst[:ttft_slo_ms]")
        name = parts[0]
        slo = float(parts[4]) if len(parts) == 5 and parts[4] else None
        out[name] = QoSClass(name, priority=int(parts[1]),
                             rate=float(parts[2]), burst=float(parts[3]),
                             ttft_slo_ms=slo)
    return out


DEFAULT_MAX_TENANTS = 4096


class TenantAdmission:
    """Per-tenant token buckets over the configured QoS classes.

    A request names its tenant and (optionally) its class; unknown
    classes fall back to ``default`` when configured, else to the
    unlimited :data:`DEFAULT_CLASS`. Buckets key on ``(tenant, class)``:
    each TENANT gets its own bucket per class (two gold tenants cannot
    eat each other's budget — the isolation the ``tenant_flood`` chaos
    invariant leans on), and naming a DIFFERENT class on the next
    request never resets an existing bucket — ``qos`` is client-
    supplied, so a tenant alternating gold/free holds at most the sum
    of both budgets instead of minting a fresh burst per request. If a
    class is reconfigured in place, the old balance carries over
    (capped at the new burst); a config change is never a refill.

    All per-tenant state (buckets, admitted/shed counters) is LRU-
    capped at ``max_tenants`` entries, so an unauthenticated client
    spraying unique ``X-Tenant`` values cannot grow router memory
    without bound. An idle tenant evicted by the cap restarts from a
    fresh burst if it returns — the price of bounding state — while
    ``admitted_total``/``shed_total`` keep exact fleet-wide tallies."""

    def __init__(self, classes: Optional[Dict[str, QoSClass]] = None,
                 clock=time.monotonic,
                 max_tenants: int = DEFAULT_MAX_TENANTS):
        if max_tenants < 1:
            raise ValueError(f"max_tenants must be >= 1, "
                             f"got {max_tenants}")
        self.classes = dict(classes or {})
        self._clock = clock
        self.max_tenants = int(max_tenants)
        self._buckets: "OrderedDict[Tuple[str, str], TokenBucket]" = (
            OrderedDict())
        self._lock = threading.Lock()
        self.admitted: "OrderedDict[str, int]" = OrderedDict()
        self.shed: "OrderedDict[str, int]" = OrderedDict()
        self.admitted_total = 0
        self.shed_total = 0

    def qos(self, qos_name: Optional[str]) -> QoSClass:
        if qos_name and qos_name in self.classes:
            return self.classes[qos_name]
        return self.classes.get("default", DEFAULT_CLASS)

    def _bump(self, counters: "OrderedDict[str, int]",
              tenant: str) -> None:
        counters[tenant] = counters.get(tenant, 0) + 1
        counters.move_to_end(tenant)
        while len(counters) > self.max_tenants:
            counters.popitem(last=False)

    def admit(self, tenant: str, qos_name: Optional[str] = None
              ) -> Tuple[bool, QoSClass]:
        cls = self.qos(qos_name)
        key = (tenant, cls.name)
        with self._lock:
            bucket = self._buckets.get(key)
            if bucket is None:
                bucket = self._buckets[key] = TokenBucket(
                    cls.rate, cls.burst, clock=self._clock)
            elif (bucket.rate, bucket.burst) != (cls.rate, cls.burst):
                fresh = TokenBucket(cls.rate, cls.burst,
                                    clock=self._clock)
                fresh._tokens = min(bucket.available(), fresh.burst)
                bucket = self._buckets[key] = fresh
            self._buckets.move_to_end(key)
            while len(self._buckets) > self.max_tenants:
                self._buckets.popitem(last=False)
        if bucket.burst == float("inf") or bucket.try_take():
            with self._lock:
                self._bump(self.admitted, tenant)
                self.admitted_total += 1
            return True, cls
        with self._lock:
            self._bump(self.shed, tenant)
            self.shed_total += 1
        return False, cls

    def counters(self) -> Tuple[Dict[str, int], Dict[str, int]]:
        """Consistent (admitted, shed) snapshot — handler threads bump
        the live OrderedDicts under ``_lock``, so a scrape iterating
        them bare can see a mid-``popitem`` resize."""
        with self._lock:
            return dict(self.admitted), dict(self.shed)


class ReplicaError(RuntimeError):
    """A replica that could not serve the relayed request — transport
    failure, truncated stream, or engine error. Marks the replica down
    and moves the relay to the next candidate."""


class ReplicaBusy(ReplicaError):
    """A replica 503 (queue full): back-pressure, not death — the relay
    tries the next candidate WITHOUT taking the replica out of
    rotation."""


class ReplicaSet:
    """Health- and load-tracked view of the decode endpoints —
    ``DisaggCoordinator``'s health-gated peer rotation generalized into
    a reusable piece: a failed replica is marked down and stays out of
    rotation until ``health_recheck_s`` elapses AND its ``/v1/healthz``
    answers ok again; each probe also caches the response's ``"load"``
    gauges, collapsed via ``scheduler/elastic.backpressure()`` into the
    spill signal."""

    def __init__(self, endpoints: Iterable[str] = (),
                 health_recheck_s: float = 5.0,
                 probe_timeout_s: float = 5.0, probe=None):
        self._lock = threading.Lock()
        self._endpoints: List[str] = []
        self._down: Dict[str, float] = {}      # endpoint -> monotonic mark
        self._gauges: Dict[str, dict] = {}
        self.health_recheck_s = health_recheck_s
        self.probe_timeout_s = probe_timeout_s
        self._probe = probe if probe is not None else self._http_probe
        for ep in endpoints:
            self.add(ep)

    # ------------------------------------------------------------ members

    def endpoints(self) -> List[str]:
        with self._lock:
            return list(self._endpoints)

    def add(self, endpoint: str) -> None:
        endpoint = endpoint.rstrip("/")
        with self._lock:
            if endpoint not in self._endpoints:
                self._endpoints.append(endpoint)

    def remove(self, endpoint: str) -> None:
        endpoint = endpoint.rstrip("/")
        with self._lock:
            if endpoint in self._endpoints:
                self._endpoints.remove(endpoint)
            self._down.pop(endpoint, None)
            self._gauges.pop(endpoint, None)

    # ------------------------------------------------------------- health

    def _http_probe(self, endpoint: str) -> Tuple[bool, Optional[dict]]:
        try:
            req = urllib.request.Request(endpoint + "/v1/healthz")
            with _transport_urlopen(req, timeout=self.probe_timeout_s) as r:
                body = json.loads(r.read())
            return bool(body.get("ok")), body.get("load")
        except Exception:
            return False, None

    def mark_down(self, endpoint: str) -> None:
        with self._lock:
            self._down[endpoint.rstrip("/")] = time.monotonic()

    def note_gauges(self, endpoint: str, gauges: Optional[dict]) -> None:
        if gauges is not None:
            with self._lock:
                self._gauges[endpoint.rstrip("/")] = gauges

    def gauges(self, endpoint: str) -> dict:
        with self._lock:
            return dict(self._gauges.get(endpoint.rstrip("/"), {}))

    def pressure(self, endpoint: str,
                 ttft_slo_ms: Optional[float] = None) -> float:
        return backpressure(self.gauges(endpoint), ttft_slo_ms)

    def ok(self, endpoint: str) -> bool:
        """True when the endpoint is in rotation. A down endpoint stays
        out until the recheck window elapses AND a fresh probe (done
        here, outside the lock) answers ok."""
        endpoint = endpoint.rstrip("/")
        with self._lock:
            if endpoint not in self._endpoints:
                return False
            marked = self._down.get(endpoint)
            if marked is None:
                return True
            if time.monotonic() - marked < self.health_recheck_s:
                return False
        up, gauges = self._probe(endpoint)
        if up:
            with self._lock:
                self._down.pop(endpoint, None)
            self.note_gauges(endpoint, gauges)
            return True
        self.mark_down(endpoint)
        return False

    def healthy(self) -> List[str]:
        return [ep for ep in self.endpoints() if self.ok(ep)]

    def down(self) -> List[str]:
        with self._lock:
            return sorted(ep for ep in self._down
                          if ep in self._endpoints)

    def refresh(self) -> None:
        """Probe every endpoint once: refresh cached gauges, clear or
        set down marks. The router's probe thread calls this on its
        interval; tests call it directly."""
        for ep in self.endpoints():
            up, gauges = self._probe(ep)
            if up:
                with self._lock:
                    self._down.pop(ep, None)
                self.note_gauges(ep, gauges)
            else:
                self.mark_down(ep)

    def least_loaded(self, exclude: Iterable[str] = ()) -> Optional[str]:
        skip = {e.rstrip("/") for e in exclude}
        best, best_p = None, None
        for ep in self.endpoints():
            if ep in skip or not self.ok(ep):
                continue
            p = self.pressure(ep)
            if best_p is None or p < best_p:
                best, best_p = ep, p
        return best


class Router:
    """The fleet front door: one HTTP pod routing ``/v1/generate``
    across N decode replicas.

    * ``POST /v1/generate`` — the ingress request shape plus optional
      ``"tenant"`` / ``"qos"`` fields (headers ``X-Tenant`` /
      ``X-QoS-Class`` also honored). 429 when the tenant's bucket is
      dry; otherwise the request routes by prefix affinity (or
      uniformly under ``policy="random"`` — the A/B control arm) and
      the replica's token stream relays back, chunked or unary, with
      ``"replica"`` and ``"routed"`` stamped into the trailer.
    * ``GET /v1/healthz`` — router liveness + per-replica health.
    * ``GET /v1/routestats`` — the ``tpuctl route-stats`` surface.
    * ``POST /v1/replicas`` ``{"replicas": [...]}`` — the resize hook
      (the worker main and the smoke drive :meth:`set_replicas`
      through it).
    """

    def __init__(self, replicas: Iterable[str] = (), port: int = 0,
                 host: str = "0.0.0.0", page_size: int = 64,
                 affinity_pages: int = 1, vnodes: int = 64,
                 classes: Optional[Dict[str, QoSClass]] = None,
                 max_tenants: int = DEFAULT_MAX_TENANTS,
                 policy: str = "affinity",
                 spill_pressure: float = 0.85,
                 spill_floor: int = 0,
                 health_recheck_s: float = 5.0,
                 probe_interval_s: float = 2.0,
                 request_timeout_s: float = 600.0,
                 seed: int = 0,
                 metrics: Optional[MetricsRegistry] = None,
                 trace_store=None,
                 directory=None):
        if policy not in ("affinity", "random"):
            raise ValueError(f"unknown routing policy {policy!r}")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.page_size = page_size
        self.affinity_pages = max(1, affinity_pages)
        self.policy = policy
        self.spill_pressure = spill_pressure
        self.spill_floor = spill_floor
        self.request_timeout_s = request_timeout_s
        self.probe_interval_s = probe_interval_s
        # optional fleet prefix directory (paging.PrefixDirectory):
        # replicas publish which radix chains they hold, and route_plan
        # consults it when the affinity primary is unavailable so the
        # stream lands where the prefix is already resident (or
        # adoptable) instead of on an arbitrary least-loaded spill.
        self.directory = directory
        self.ring = HashRing(
            (e.rstrip("/") for e in replicas), vnodes=vnodes)
        self.replicas = ReplicaSet(replicas,
                                   health_recheck_s=health_recheck_s)
        self.admission = TenantAdmission(classes,
                                         max_tenants=max_tenants)
        import random as _random
        self._rng = _random.Random(seed)
        self._lock = threading.Lock()
        self._resize_lock = threading.Lock()
        self._counts: Dict[str, int] = {
            "routed": 0, "affinity_hits": 0, "spills_hot": 0,
            "spills_down": 0, "spill_attempts": 0, "spill_resumes": 0,
            "resume_divergences": 0, "dropped_streams": 0, "sheds": 0,
            "rebalances": 0, "errors": 0, "migration_redirects": 0,
            "directory_hits": 0}
        # live-migration forwarding: victim endpoint -> destination the
        # MigrationManager drained its streams to. Applied to every
        # route plan so relays (and resume-exact failover replays)
        # follow the stream instead of re-prefilling on a doomed or
        # departed replica.
        self._redirects: Dict[str, str] = {}
        self._per_replica: Dict[str, int] = {}
        self._active: Dict[str, int] = {}      # replica -> live relays
        self._ttfts: deque = deque(maxlen=4096)  # (t, tenant, ttft_ms)
        self._stop = threading.Event()
        self._probe_thread: Optional[threading.Thread] = None
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self._own_metrics = metrics is None
        self.tracer = Tracer("router", trace_store)
        # routestats folded into the shared registry: counters mirror
        # _counts via _count(); these gauges sample live fleet state
        self.metrics.gauge("router.replicas",
                           lambda: len(self.replicas.endpoints()))
        self.metrics.gauge("router.replicas_down",
                           lambda: len(self.replicas.down()))

        def _relays() -> int:
            with self._lock:
                return sum(n for n in self._active.values() if n > 0)

        self.metrics.gauge("router.active_relays", _relays)
        router = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def _json(self, code: int, payload: dict,
                      extra_headers: Optional[dict] = None) -> None:
                body = (json.dumps(payload) + "\n").encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (extra_headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/v1/healthz":
                    self._json(200, router.health())
                elif self.path in ("/v1/routestats", "/v1/stats"):
                    self._json(200, router.stats())
                elif self.path == "/v1/metrics":
                    self._json(200, router.metrics.to_dict())
                elif self.path == "/v1/metrics/prometheus":
                    body = router.metrics.to_prometheus().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path == "/v1/traces":
                    store = router.tracer.store
                    self._json(200, {
                        "trace_ids": store.trace_ids(),
                        "incomplete": store.incomplete_trace_ids()})
                elif self.path.startswith("/v1/trace/"):
                    tid = self.path[len("/v1/trace/"):]
                    self._json(200, router.trace_export(tid))
                else:
                    self._json(404, {"error": f"no route {self.path}"})

            def do_POST(self):
                try:
                    n = int(self.headers.get("Content-Length", "0"))
                    req = json.loads(self.rfile.read(n) or b"{}")
                except (ValueError, json.JSONDecodeError) as e:
                    self._json(400, {"error": str(e)})
                    return
                if self.path == "/v1/replicas":
                    eps = req.get("replicas")
                    if (not isinstance(eps, list)
                            or not all(isinstance(e, str) for e in eps)):
                        self._json(400, {"error": "replicas must be a "
                                                  "list of endpoint URLs"})
                        return
                    self._json(200, router.set_replicas(eps))
                    return
                if self.path != "/v1/generate":
                    self._json(404, {"error": f"no route {self.path}"})
                    return
                try:
                    prompt = req.get("prompt")
                    max_new = int(req.get("max_new", 32))
                    if (not isinstance(prompt, list) or not prompt
                            or not all(isinstance(t, int) for t in prompt)):
                        raise ValueError("prompt must be a non-empty "
                                         "list of ints")
                    if max_new < 1:
                        raise ValueError("max_new must be >= 1")
                except ValueError as e:
                    self._json(400, {"error": str(e)})
                    return
                tenant = (req.get("tenant")
                          or self.headers.get("X-Tenant") or "anonymous")
                qos = (req.get("qos")
                       or self.headers.get("X-QoS-Class") or None)
                stream = bool(req.get("stream", False))
                ctx = parse_header(self.headers.get(TRACE_HEADER))
                router._serve(self, prompt, max_new, stream,
                              str(tenant), qos, ctx)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._http_thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- routing

    def _count(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._counts[key] = self._counts.get(key, 0) + n
        # mirrored into the registry so /v1/metrics/prometheus exposes
        # routestats without a second bookkeeping path
        self.metrics.counter(f"router.{key}", n)

    def route_plan(self, prompt: Sequence[int],
                   cls: QoSClass) -> Tuple[List[str], str]:
        """The ordered candidate list for one request and how its head
        was chosen (``affinity`` | ``spill_hot`` | ``spill_down`` |
        ``directory`` | ``random`` | ``none``). The tail is the
        mid-stream failover
        order: the rest of the ring's preference walk (stable per key),
        healthy-first."""
        if self.policy == "random":
            healthy = self.replicas.healthy()
            if not healthy:
                return [], "none"
            with self._lock:
                # Random instances are not thread-safe: an unguarded
                # shuffle from concurrent handler threads corrupts the
                # control arm's distribution and seed-determinism
                self._rng.shuffle(healthy)
            return healthy, "random"
        key = route_key(prompt, self.page_size, self.affinity_pages)
        pref = self.ring.preference(key)
        if not pref:
            return [], "none"
        primary = pref[0]
        rest = [ep for ep in pref[1:] if self.replicas.ok(ep)]
        if self.replicas.ok(primary):
            hot = (self.replicas.pressure(
                primary, cls.ttft_slo_ms) >= self.spill_pressure)
            if hot and cls.priority >= self.spill_floor and rest:
                spill = self.replicas.least_loaded(exclude=(primary,))
                if spill is not None:
                    order = [spill] + [ep for ep in [primary] + rest
                                       if ep != spill]
                    return order, "spill_hot"
            return [primary] + rest, "affinity"
        if rest:
            holder = self._directory_hint(prompt)
            if holder is not None and holder in rest:
                self._count("directory_hits")
                order = [holder] + [ep for ep in rest if ep != holder]
                return order, "directory"
            spill = self.replicas.least_loaded(exclude=(primary,))
            if spill is not None and spill in rest:
                rest = [spill] + [ep for ep in rest if ep != spill]
            return rest, "spill_down"
        return [], "none"

    def _directory_hint(self, prompt: Sequence[int]) -> Optional[str]:
        """Deepest fresh :class:`paging.PrefixDirectory` holder for this
        prompt's chain, or ``None``. Only consulted when the affinity
        primary is down: landing the stream where the prefix is already
        resident beats least-loaded spill, because the spill target
        would recompute the whole prefix from scratch."""
        if self.directory is None:
            return None
        try:
            chains = chain_keys(list(prompt), self.page_size)
        except Exception:
            return None
        for ck in reversed(chains):
            holder = self.directory.lookup(ck)
            if holder is not None:
                return holder.rstrip("/")
        return None

    # ------------------------------------------------------------- relay

    def _upstream(self, target: str, prompt: List[int], max_new: int,
                  trace: Optional[TraceContext] = None):
        """Generator over one replica's chunked token stream: yields
        the parsed JSON objects, raising :class:`ReplicaError` (or
        :class:`ReplicaBusy` on 503 back-pressure) instead of ever
        yielding a broken tail. When the relay carries a trace, its
        context crosses the hop in ``X-Tpu-Trace`` so the replica's
        spans parent onto the router's."""
        body = json.dumps({"prompt": prompt, "max_new": max_new,
                           "stream": True}).encode()
        headers = {"Content-Type": "application/json"}
        if trace is not None:
            headers[TRACE_HEADER] = trace.header()
        req = urllib.request.Request(
            target + "/v1/generate", data=body, headers=headers)
        try:
            resp = _transport_urlopen(req, timeout=self.request_timeout_s)
        except urllib.error.HTTPError as e:
            if e.code == 503:
                raise ReplicaBusy(f"{target}: queue full") from None
            raise ReplicaError(f"{target}: HTTP {e.code}") from None
        except Exception as e:
            raise ReplicaError(f"{target}: {e}") from None
        with resp:
            while True:
                try:
                    line = resp.readline()
                except Exception as e:
                    raise ReplicaError(f"{target}: {e}") from None
                if not line:
                    raise ReplicaError(f"{target}: stream truncated")
                try:
                    obj = json.loads(line)
                except ValueError:
                    continue                   # keep-alive noise
                yield obj
                if obj.get("done"):
                    return

    def _finish_trace(self, root: TraceContext,
                      parent: Optional[TraceContext], t0: float,
                      status: str, **attrs) -> None:
        """Record the terminal ``router.request`` root span — every
        admitted request's trace ends through here exactly once, the
        completeness guarantee the chaos tier audits."""
        t1 = time.perf_counter()
        self.tracer.store.add(Span(
            root.trace_id, root.span_id,
            parent.span_id if parent else None,
            "router.request", self.tracer.service,
            perf_to_epoch(t0), max(0.0, t1 - t0), attrs,
            terminal=True, status=status))

    def _serve(self, handler, prompt: List[int], max_new: int,
               stream: bool, tenant: str, qos: Optional[str],
               ctx: Optional[TraceContext] = None) -> None:
        t0 = time.perf_counter()
        ok, cls = self.admission.admit(tenant, qos)
        if not ok:
            self._count("sheds")
            # a shed is a complete (one-span) trace: admitted requests
            # are the ones whose traces must reach router.request
            self.tracer.record("router.admission", t0,
                               time.perf_counter(), parent=ctx,
                               terminal=True, status="shed",
                               tenant=tenant, qos=cls.name)
            handler._json(429, {"error": f"tenant {tenant!r} over its "
                                         f"{cls.name} admission budget"},
                          {"Retry-After": "1"})
            return
        # the root context downstream hops parent onto; the root span
        # itself is recorded at the end via _finish_trace
        root = TraceContext(ctx.trace_id if ctx else new_id(), new_id())
        self.tracer.record("router.admission", t0, time.perf_counter(),
                           parent=root, tenant=tenant, qos=cls.name)
        plan, routed = self.route_plan(prompt, cls)
        plan = self._apply_redirects(plan)
        if not plan:
            self._count("errors")
            self._finish_trace(root, ctx, t0, "error", tenant=tenant,
                               error="no healthy decode replica")
            handler._json(503, {"error": "no healthy decode replica"},
                          {"Retry-After": "1"})
            return
        self._count("routed")
        if routed == "affinity":
            self._count("affinity_hits")
        elif routed == "spill_hot":
            self._count("spills_hot")
        elif routed == "spill_down":
            self._count("spills_down")

        chunk = None
        if stream:
            handler.send_response(200)
            handler.send_header("Content-Type", "application/json")
            handler.send_header("Transfer-Encoding", "chunked")
            handler.end_headers()

            def chunk(obj: dict) -> None:
                data = (json.dumps(obj) + "\n").encode()
                handler.wfile.write(f"{len(data):x}\r\n".encode()
                                    + data + b"\r\n")

        sent: List[int] = []
        t_first: Optional[float] = None
        final: Optional[dict] = None
        last_err = "no candidates"
        for attempt, target in enumerate(plan):
            if attempt > 0:
                # failover: deterministic greedy decode replays the
                # same tokens on the next replica; skip what the
                # client already has
                self._count("spill_attempts")
            with self._lock:
                self._active[target] = self._active.get(target, 0) + 1
            seen = 0
            t_attempt = time.perf_counter()
            relay_status = "ok"
            try:
                for obj in self._upstream(target, prompt, max_new,
                                          trace=root):
                    if "token" in obj:
                        seen += 1
                        tok = int(obj["token"])
                        if seen <= len(sent):
                            if tok != sent[seen - 1]:
                                # the replacement replica disagrees on
                                # the replayed prefix (mixed model or
                                # config versions mid-rolling-deploy):
                                # splicing the two completions would
                                # hand the client a corrupt stream
                                self._count("resume_divergences")
                                raise ReplicaError(
                                    f"{target}: resume divergence at "
                                    f"token {seen - 1} ({tok} != "
                                    f"{sent[seen - 1]})")
                            continue           # resume skip, verified
                        if t_first is None:
                            t_first = time.perf_counter()
                        sent.append(tok)
                        if chunk is not None:
                            chunk({"token": tok})
                    elif obj.get("done"):
                        if obj.get("error"):
                            raise ReplicaError(
                                f"{target}: {obj['error']}")
                        final = obj
                if attempt > 0 and final is not None:
                    self._count("spill_resumes")
                break
            except ReplicaBusy as e:
                last_err = str(e)              # back-pressure: next
                relay_status = "busy"
            except ReplicaError as e:
                last_err = str(e)
                relay_status = "error"
                self.replicas.mark_down(target)
            finally:
                with self._lock:
                    self._active[target] = max(
                        0, self._active.get(target, 1) - 1)
                    if final is not None:
                        self._per_replica[target] = (
                            self._per_replica.get(target, 0) + 1)
                self.tracer.record("router.relay", t_attempt,
                                   time.perf_counter(), parent=root,
                                   status=relay_status, target=target,
                                   attempt=attempt, tokens=seen)
            if final is not None:
                break
        if final is None:
            # every candidate was attempted before giving up — the
            # spill-before-drop invariant the chaos tier audits
            self._count("dropped_streams")
            self._finish_trace(root, ctx, t0, "error", tenant=tenant,
                               routed=routed, error=last_err)
            err = {"error": f"all replicas failed: {last_err}"}
            if chunk is not None:
                chunk({"done": True, **err})
                handler.wfile.write(b"0\r\n\r\n")
            else:
                handler._json(502, err)
            return
        ttft_ms = (round((t_first - t0) * 1e3, 3)
                   if t_first is not None else None)
        with self._lock:
            self._ttfts.append((time.monotonic(), tenant, ttft_ms))
        if t_first is not None:
            self.metrics.observe("router.ttft_seconds", t_first - t0)
        self.metrics.observe("router.request_seconds",
                             time.perf_counter() - t0)
        self._finish_trace(root, ctx, t0, "ok", tenant=tenant,
                           routed=routed, replica=target,
                           tokens=len(sent), ttft_ms=ttft_ms)
        trailer = {k: v for k, v in final.items() if k != "done"}
        trailer.update({"replica": target, "routed": routed,
                        "tenant": tenant, "qos": cls.name})
        if ttft_ms is not None:
            trailer["router_ttft_ms"] = ttft_ms
        if chunk is not None:
            chunk({"done": True, **trailer})
            handler.wfile.write(b"0\r\n\r\n")
        else:
            # "tokens" last: a replica trailer field must never clobber
            # the relayed token list
            handler._json(200, {**trailer, "tokens": sent})

    # ----------------------------------------------------------- elasticity

    def note_migration(self, src: str, dst: str) -> None:
        """Record a "migrated-to" redirect: streams drained off ``src``
        now live on ``dst``, so any plan that would try ``src`` tries
        ``dst`` there instead. Existing redirects pointing AT ``src``
        re-target ``dst`` (two scale events in a row must not leave a
        chain through a dead middle hop)."""
        src, dst = src.rstrip("/"), dst.rstrip("/")
        if src == dst:
            return
        with self._lock:
            for k, v in list(self._redirects.items()):
                if v == src:
                    self._redirects[k] = dst
            self._redirects.pop(dst, None)      # dst is live again
            self._redirects[src] = dst
        self._count("migration_redirects")

    def _apply_redirects(self, plan: List[str]) -> List[str]:
        """Map a route plan through the migration redirects (chains
        followed with a visited guard, order-preserving dedupe). Cheap
        no-op on the common path — no redirects, no work."""
        with self._lock:
            if not self._redirects:
                return plan
            redirects = dict(self._redirects)
        out: List[str] = []
        for ep in plan:
            seen = {ep}
            while ep in redirects and redirects[ep] not in seen:
                ep = redirects[ep]
                seen.add(ep)
            if ep not in out:
                out.append(ep)
        return out

    def set_replicas(self, endpoints: Sequence[str]) -> dict:
        """Rebalance the ring to a resized decode tier. Departing
        replicas leave the ring and the replica set immediately — no
        NEW streams route to them — while relays already attached keep
        their connections and drain to completion (``draining`` counts
        them). Arriving replicas take over only their arcs of the
        keyspace (bounded movement). The resized ring is built aside
        and swapped in as one reference assignment — ``HashRing`` makes
        no thread-safety promise, so concurrent ``route_plan`` calls
        must see the old ring or the new one, never a half-mutated
        point list."""
        with self._resize_lock:
            want = [e.rstrip("/") for e in endpoints]
            have = set(self.ring.nodes())
            added = [e for e in want if e not in have]
            removed = [e for e in have if e not in want]
            for ep in added:
                self.replicas.add(ep)
            self.ring = HashRing(want, vnodes=self.ring.vnodes)
            for ep in removed:
                self.replicas.remove(ep)
            if added or removed:
                self._count("rebalances")
            with self._lock:
                # migration redirects die with the fleet change that
                # obsoletes them: a destination that departed can't
                # receive forwards, and a victim that REJOINED is a
                # fresh replica that should take traffic directly
                for ep in list(self._redirects):
                    if (self._redirects[ep] not in want
                            or ep in added):
                        del self._redirects[ep]
                draining = {ep: n for ep, n in self._active.items()
                            if ep in removed and n > 0}
            return {"replicas": self.ring.nodes(),
                    "added": sorted(added), "removed": sorted(removed),
                    "draining": draining}

    # ------------------------------------------------------------- tracing

    def trace_export(self, trace_id: str) -> dict:
        """One trace, fleet-wide: the router's local spans merged with
        whatever each healthy replica retained for the same id (served
        over its own ``/v1/trace`` endpoint), de-duplicated by span_id
        — colocated tiers sharing the process-global store would
        otherwise report every span twice."""
        spans = {s.span_id: s.to_dict()
                 for s in self.tracer.store.spans(trace_id)}
        for ep in self.replicas.healthy():
            try:
                req = urllib.request.Request(f"{ep}/v1/trace/{trace_id}")
                with _transport_urlopen(req, timeout=5.0) as r:
                    body = json.loads(r.read())
            except Exception:
                continue
            for d in body.get("spans", ()):
                sid = d.get("span_id")
                if sid:
                    spans.setdefault(sid, d)
        ordered = sorted(spans.values(),
                         key=lambda d: (d.get("t_start", 0.0),
                                        d.get("span_id", "")))
        return {"trace_id": trace_id, "spans": ordered,
                "complete": any(d.get("terminal") for d in ordered)}

    # ------------------------------------------------------------- status

    def health(self) -> dict:
        eps = self.replicas.endpoints()
        down = self.replicas.down()
        return {"ok": True, "role": "router", "policy": self.policy,
                "replicas": eps, "replicas_down": down,
                "replicas_healthy": len(eps) - len(down)}

    def stats(self) -> dict:
        from dcos_commons_tpu.utils.stats import percentiles
        with self._lock:
            counts = dict(self._counts)
            redirects = dict(self._redirects)
            per_replica = dict(self._per_replica)
            active = {ep: n for ep, n in self._active.items() if n > 0}
            ttfts = [t for _, _, t in self._ttfts if t is not None]
            per_tenant_ttft: Dict[str, List[float]] = {}
            for _, tenant, t in self._ttfts:
                if t is not None:
                    per_tenant_ttft.setdefault(tenant, []).append(t)
        routed = max(1, counts["routed"])
        tenants = {}
        admitted, shed = self.admission.counters()
        seen = set(admitted) | set(shed)
        for tenant in sorted(seen):
            tenants[tenant] = {
                "admitted": admitted.get(tenant, 0),
                "shed": shed.get(tenant, 0),
                "ttft_ms": percentiles(per_tenant_ttft.get(tenant, [])),
            }
        return {
            "policy": self.policy,
            "page_size": self.page_size,
            "affinity_pages": self.affinity_pages,
            "replicas": self.replicas.endpoints(),
            "replicas_down": self.replicas.down(),
            "ring_nodes": len(self.ring),
            **counts,
            "migration_redirects_active": redirects,
            "affinity_rate": round(counts["affinity_hits"] / routed, 4),
            "per_replica": per_replica,
            "active_relays": active,
            "ttft_ms": percentiles(ttfts),
            "tenants": tenants,
            "tenants_tracked": len(seen),
            "admitted_total": self.admission.admitted_total,
            "shed_total": self.admission.shed_total,
            "classes": {name: {"priority": c.priority, "rate": c.rate,
                               "burst": c.burst,
                               "ttft_slo_ms": c.ttft_slo_ms}
                        for name, c in self.admission.classes.items()},
        }

    # ----------------------------------------------------------- lifecycle

    def _probe_loop(self) -> None:
        while not self._stop.wait(self.probe_interval_s):
            try:
                self.replicas.refresh()
            except Exception:
                pass                            # probes must never kill

    def start(self) -> "Router":
        try:
            from dcos_commons_tpu.security.transport import (
                server_tls_from_env)
            creds = server_tls_from_env()
            if creds is not None:
                from dcos_commons_tpu.security.transport import wrap_server
                wrap_server(self._httpd, creds)
        except ImportError:
            pass
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="router-http")
        self._http_thread.start()
        if self.probe_interval_s > 0:
            self._probe_thread = threading.Thread(
                target=self._probe_loop, daemon=True, name="router-probe")
            self._probe_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._http_thread is not None:
            # shutdown() blocks on serve_forever's ack; never-started
            # routers (construct-only use) would wait forever
            self._httpd.shutdown()
        self._httpd.server_close()
        if self._http_thread:
            self._http_thread.join(timeout=10)
        if self._probe_thread:
            self._probe_thread.join(timeout=5)
        if self._own_metrics:
            self.metrics.close()
