"""Host-side page ledger + prefix-sharing radix for the paged serving
engine (``models/serving.py:PagedServer``).

The device side of paged serving is a fixed pool of KV pages
(``llama.init_page_pool``) consulted through per-stream page tables;
this module is the HOST side — who owns which physical page, with the
same durability discipline as the scheduler's reservation ledger
(``state/reservation_store.py``): every page is either free or
refcounted, transitions are explicit (alloc/ref/unref), and
:meth:`PagePool.check` audits the whole ledger so the chaos invariant
checker can prove no page ever leaks or is double-booked across
abort/retire/reset.

Sharing model (vLLM/SGLang-style prefix caching, TPU-simplified):

* Only FULL pages of prompt tokens are hash-consed: a page whose every
  position is determined by the prompt (and its absolute positions —
  prefixes are position-aligned from 0) has bit-identical K/V across
  requests, so one physical copy serves them all behind a refcount.
* A retiring stream's full prompt pages are ADOPTED into the radix
  (one extra reference each); the radix evicts least-recently-used
  childless nodes under allocation pressure.
* The boundary partial page copies eagerly (copy-on-write at admission:
  the new stream gets a private copy of a cached page whose prefix
  matches its remaining prompt, then prefills only the tail). Pages a
  decode stream writes into are always private by construction, so the
  hot decode scatter needs no ownership check.
"""

from __future__ import annotations

import hashlib
import json
import os
import struct
import threading
import time
from collections import OrderedDict
from typing import (Any, Callable, Dict, List, Mapping, Optional, Sequence,
                    Tuple)

import numpy as np


def page_hashes(prompt: Sequence[int], page_size: int) -> List[str]:
    """Content hash per FULL page of prompt tokens — THE prefix-hash
    every tier must agree on. One blake2s over the page's int32 bytes,
    truncated to 16 hex chars, one entry per full page, partial tail
    excluded (a partial page is never position-aligned shareable).

    Three consumers, one function, by design: the KV-span wire format
    (``models/disagg.py`` ``pack_span``/``unpack_span`` verify shipped
    pages against these), the fleet router's consistent-hash affinity
    key (``models/router.py`` ``route_key``), and this module's
    :class:`PrefixRadix` (whose hash-cons keys are the same full-page
    token runs these hashes summarize). If any of them hashed
    differently, requests would land on replicas whose radix holds
    nothing for them and affinity would silently degrade — the
    cross-module parity test in ``tests/test_router.py`` pins this.
    """
    out = []
    for j in range(len(prompt) // page_size):
        page = np.asarray(prompt[j * page_size:(j + 1) * page_size],
                          np.int32)
        out.append(hashlib.blake2s(page.tobytes()).hexdigest()[:16])
    return out


def chain_keys(prompt: Sequence[int], page_size: int) -> List[str]:
    """Position-aligned CHAIN identity per full-page prefix, derived
    from the same :func:`page_hashes` every tier shares: entry ``j``
    keys the whole prefix ``prompt[:(j + 1) * page_size]``, not the
    ``j``-th page alone. A bare per-page hash depends only on that
    page's tokens — two different prompts sharing one middle page would
    collide — while prefix K/V is only valid for the exact
    position-aligned token run that produced it. The chain key is what
    the demote/promote tiers (:class:`PageTierStore`) and the fleet
    prefix directory (:class:`PrefixDirectory`) address by; it folds
    the per-page hashes so the router's ``route_key`` (which joins the
    same hashes) and this identity can never disagree about what a
    prefix *is*."""
    out: List[str] = []
    acc = hashlib.blake2s()
    for h in page_hashes(prompt, page_size):
        acc.update(h.encode())
        out.append(acc.copy().hexdigest()[:16])
    return out


class PageLedgerError(RuntimeError):
    """A page transition that must never happen (double free, ref of a
    free page) — raised loudly rather than corrupting shared K/V."""


class PageFrameError(RuntimeError):
    """A demoted page frame that must not be promoted: framing, digest,
    or identity verification failed (bit-rot on disk, a truncated
    write, a frame filed under the wrong chain)."""


class PagePool:
    """Refcounted ledger over ``pages`` physical KV pages.

    Pure host bookkeeping — it never touches the device pool; the
    serving engine translates (alloc/unref) into page-table edits.
    """

    def __init__(self, pages: int, page_size: int):
        if pages < 1:
            raise ValueError(f"page pool needs >= 1 page, got {pages}")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.pages = pages
        self.page_size = page_size
        self._ref = [0] * pages
        # pop() from the tail -> ascending allocation order (determinism
        # across gang ranks matters: every rank must pick the same page)
        self._free = list(range(pages - 1, -1, -1))
        self.in_use_peak = 0

    # ------------------------------------------------------------ queries

    def free_count(self) -> int:
        return len(self._free)

    def in_use(self) -> int:
        return self.pages - len(self._free)

    def refcount(self, page: int) -> int:
        return self._ref[page]

    # -------------------------------------------------------- transitions

    def alloc(self, n: int = 1) -> Optional[List[int]]:
        """``n`` fresh pages at refcount 1, or None when fewer than ``n``
        are free (all-or-nothing: a partial grant would strand a stream
        mid-prefill with nowhere to write)."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        out = [self._free.pop() for _ in range(n)]
        for p in out:
            self._ref[p] = 1
        self.in_use_peak = max(self.in_use_peak, self.in_use())
        return out

    def ref(self, page: int) -> None:
        """One more reference to a live page (prefix sharing)."""
        if not 0 <= page < self.pages:
            raise PageLedgerError(f"ref of unknown page {page}")
        if self._ref[page] <= 0:
            raise PageLedgerError(
                f"ref of free page {page}: sharing a page nobody owns")
        self._ref[page] += 1

    def unref(self, page: int) -> None:
        """Drop one reference; the page returns to the free list at 0."""
        if not 0 <= page < self.pages:
            raise PageLedgerError(f"unref of unknown page {page}")
        if self._ref[page] <= 0:
            raise PageLedgerError(f"double free of page {page}")
        self._ref[page] -= 1
        if self._ref[page] == 0:
            self._free.append(page)

    # ---------------------------------------------------- audit + recovery

    def check(self, expected_refs: Optional[Mapping[int, int]] = None
              ) -> List[str]:
        """Ledger violations (empty == healthy).

        Structural: refcounts non-negative, the free list and the
        refcounts agree (a page is free iff refcount 0), no duplicate
        free-list entries. With ``expected_refs`` (page -> references
        actually held by live page tables + the radix) also cross-checks
        that no page leaked (counted but unreferenced) or is
        double-booked (referenced more times than counted).
        """
        out: List[str] = []
        free_set = set(self._free)
        if len(free_set) != len(self._free):
            dupes = sorted({p for p in self._free
                            if self._free.count(p) > 1})
            out.append(f"free list holds duplicates {dupes}: a double "
                       "free put the same page up for grabs twice")
        for p in range(self.pages):
            r = self._ref[p]
            if r < 0:
                out.append(f"page {p}: negative refcount {r}")
            elif r == 0 and p not in free_set:
                out.append(f"page {p}: leaked (refcount 0 but not in "
                           "the free list)")
            elif r > 0 and p in free_set:
                out.append(f"page {p}: double-booked (refcount {r} "
                           "while on the free list)")
        if expected_refs is not None:
            for p in range(self.pages):
                want = expected_refs.get(p, 0)
                if self._ref[p] != want:
                    out.append(
                        f"page {p}: refcount {self._ref[p]} != {want} "
                        "references held by live tables/radix")
        return out

    def reconcile(self, expected_refs: Mapping[int, int]) -> List[int]:
        """Crash-recovery sweep: force the ledger to the reference
        counts derivable from surviving state (live page tables + the
        radix) and rebuild the free list — the page analogue of the
        reservation ledger's orphan GC. Returns the reclaimed page ids
        (pages the crash left counted but unreferenced)."""
        reclaimed = []
        for p in range(self.pages):
            want = expected_refs.get(p, 0)
            if self._ref[p] > 0 and want == 0:
                reclaimed.append(p)
            self._ref[p] = want
        self._free = [p for p in range(self.pages - 1, -1, -1)
                      if self._ref[p] == 0]
        return reclaimed


class _Node:
    __slots__ = ("children", "page", "parent", "key", "stamp")

    def __init__(self, parent: Optional["_Node"],
                 key: Optional[tuple], page: Optional[int]):
        self.children: Dict[tuple, "_Node"] = {}
        self.parent = parent
        self.key = key
        self.page = page
        self.stamp = 0


class PrefixRadix:
    """Hash-consed radix of full prompt-prefix pages.

    Each edge is one page's worth of prompt tokens (the tuple is the
    hash-cons key); each non-root node owns ONE reference to a physical
    page in the :class:`PagePool`. Lookups reference matched pages on
    the caller's behalf; retirement adopts new pages via :meth:`insert`;
    :meth:`evict` trims least-recently-used unshared leaves when the
    pool runs dry.
    """

    def __init__(self, pool: PagePool):
        self._pool = pool
        self._root = _Node(None, None, None)
        self._clock = 0
        self.hits = 0            # lookups that shared >= 1 page
        self.shared_pages = 0    # pages served from the radix, cumulative

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    # ------------------------------------------------------------- lookup

    def lookup(self, prompt: List[int]) -> Tuple[List[int], _Node]:
        """Longest cached chain of full pages covering a PROPER prefix
        of ``prompt``; takes one pool reference per matched page on the
        caller's behalf. At least one prompt token is always left
        uncached so the final prefill chunk has a live position to take
        first-token logits from. Returns (pages, stop_node) — feed the
        stop node to :meth:`boundary` for the partial-page tail."""
        ps = self._pool.page_size
        n = len(prompt)
        node, pages = self._root, []
        j = 0
        while (j + 1) * ps < n:
            child = node.children.get(tuple(prompt[j * ps:(j + 1) * ps]))
            if child is None:
                break
            self._pool.ref(child.page)
            child.stamp = self._tick()
            pages.append(child.page)
            node = child
            j += 1
        if pages:
            self.hits += 1
            self.shared_pages += len(pages)
        return pages, node

    def boundary(self, node: _Node, prompt: List[int],
                 matched_tokens: int) -> Optional[Tuple[int, int]]:
        """Partial-page tail match under ``node``: a cached child whose
        page STARTS with the next (shareable) prompt tokens. Returns
        (src_page, valid_tokens) or None. The caller must COPY the page
        (eager copy-on-write) — the source stays owned by the radix, and
        positions past ``valid_tokens`` in the copy are garbage the
        caller's prefill/decode writes overwrite."""
        ps = self._pool.page_size
        valid = min(ps - 1, len(prompt) - 1 - matched_tokens)
        if valid <= 0:
            return None
        want = tuple(prompt[matched_tokens:matched_tokens + valid])
        for key, child in node.children.items():
            if key[:valid] == want:
                child.stamp = self._tick()
                return child.page, valid
        return None

    # ------------------------------------------------------------- insert

    def insert(self, prompt: List[int], pages: List[int]) -> int:
        """Adopt a retiring stream's full prompt pages (hash-consing:
        an existing node keeps ITS page and the stream's duplicate is
        simply not adopted; a new node takes one reference on the
        stream's page). Returns how many pages were newly adopted."""
        ps = self._pool.page_size
        node, adopted = self._root, 0
        full = min(len(prompt) // ps, len(pages))
        for j in range(full):
            key = tuple(prompt[j * ps:(j + 1) * ps])
            child = node.children.get(key)
            if child is None:
                child = _Node(node, key, pages[j])
                self._pool.ref(pages[j])
                node.children[key] = child
                adopted += 1
            child.stamp = self._tick()
            node = child
        return adopted

    # ----------------------------------------------------- evict + audit

    def _iter_nodes(self):
        stack = list(self._root.children.values())
        while stack:
            node = stack.pop()
            yield node
            stack.extend(node.children.values())

    def size(self) -> int:
        return sum(1 for _ in self._iter_nodes())

    def held(self) -> Dict[int, int]:
        """page -> references the radix holds (the invariant checker's
        input alongside the live page tables)."""
        out: Dict[int, int] = {}
        for node in self._iter_nodes():
            out[node.page] = out.get(node.page, 0) + 1
        return out

    def prefix_tokens(self, node: _Node) -> List[int]:
        """The full token run root -> ``node`` (an exact multiple of
        ``page_size`` tokens) — the identity a demoter needs to file
        the node's page under its chain key."""
        parts = []
        while node.parent is not None:
            parts.append(node.key)
            node = node.parent
        out: List[int] = []
        for key in reversed(parts):
            out.extend(key)
        return out

    def evict(self, need: int, demoter: Optional[Callable] = None) -> int:
        """Drop least-recently-used childless nodes nobody else
        references until ``need`` pages came free (or no candidates
        remain). Shared nodes (an active stream still references the
        page) are kept: unref'ing them frees nothing now and forfeits
        the share. Returns pages actually freed.

        ``demoter`` is THE single demote seam: when given, it is called
        as ``demoter(page, prefix_tokens)`` for every victim BEFORE the
        unref — the page still holds one live reference, so its device
        content may be gathered and filed in a colder tier
        (:class:`PageTierStore`). Eviction never releases a radix page
        any other way (``clear()`` runs only when the device pool is
        being re-initialized and the content is already dead), so a
        tiered engine routes every HBM->host demotion through here."""
        freed = 0
        while freed < need:
            leaves = [n for n in self._iter_nodes()
                      if not n.children
                      and self._pool.refcount(n.page) == 1]
            if not leaves:
                break
            victim = min(leaves, key=lambda x: x.stamp)
            del victim.parent.children[victim.key]
            if demoter is not None:
                demoter(victim.page, self.prefix_tokens(victim))
            self._pool.unref(victim.page)
            freed += 1
        return freed

    def clear(self) -> None:
        """Release every cached page (engine reset: the device pool is
        re-initialized, so cached K/V no longer exists)."""
        for node in list(self._iter_nodes()):
            self._pool.unref(node.page)
        self._root.children = {}


# ---------------------------------------------------------------------------
# demoted-page frames: the KV-span wire discipline applied to ONE page


_FRAME_MAGIC = b"KVPAGE1\0"
_FRAME_VERSION = 1


def _flatten_page_payload(payload: Dict[str, Any]
                          ) -> List[Tuple[str, np.ndarray]]:
    """One page's K/V payload as a flat (key, ndarray) list in a FIXED
    order — the frame layout (int8 pools carry q + scales per side).
    Mirrors ``models/disagg.py``'s span flattening; this module cannot
    import disagg (disagg imports the page hashes from here)."""
    out: List[Tuple[str, np.ndarray]] = []
    for side in ("k", "v"):
        val = payload[side]
        if isinstance(val, dict):
            out.append((f"{side}.q", np.asarray(val["q"])))
            out.append((f"{side}.s", np.asarray(val["s"])))
        else:
            out.append((side, np.asarray(val)))
    return out


def _frame_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def pack_page_frame(entry: Dict[str, Any]) -> bytes:
    """Frame ONE demoted KV page for the host/disk tiers:
    ``MAGIC | header_len | header JSON | raw array bytes`` — the span
    wire format's shape (``disagg.pack_span``) at page granularity. The
    header carries the chain key and per-page hash the frame is filed
    under plus a blake2s digest of the body, so a promote can prove the
    bytes it is about to install are exactly the bytes demoted — a
    bit-rotted disk frame or a frame filed under the wrong prefix dies
    in :func:`unpack_page_frame`, never on a live page table."""
    arrays = _flatten_page_payload(entry["payload"])
    body = b"".join(a.tobytes() for _, a in arrays)
    meta = {
        "version": _FRAME_VERSION,
        "chain": str(entry["chain"]),
        "page_hash": str(entry["page_hash"]),
        "kv_quant": bool(entry.get("kv_quant", False)),
        "arrays": [{"key": k, "shape": list(a.shape),
                    "dtype": a.dtype.name} for k, a in arrays],
    }
    # one digest over canonical header + body: a flipped bit in the
    # METADATA (chain, shapes, dtype) is as fatal as one in the KV
    # bytes — installing the right bytes under the wrong identity
    # corrupts the radix just the same
    meta["digest"] = hashlib.blake2s(
        json.dumps(meta, sort_keys=True).encode() + body).hexdigest()
    header = json.dumps(meta).encode()
    return _FRAME_MAGIC + struct.pack("<I", len(header)) + header + body


def unpack_page_frame(data: bytes,
                      chain: Optional[str] = None) -> Dict[str, Any]:
    """Parse + VERIFY a demoted-page frame: magic, version, body
    digest, and (when given) the chain key the caller is promoting —
    raises :class:`PageFrameError` on any mismatch so a corrupt tier
    entry is dropped holding zero pool pages."""
    if not data.startswith(_FRAME_MAGIC):
        raise PageFrameError("bad magic: not a KV page frame")
    off = len(_FRAME_MAGIC)
    if len(data) < off + 4:
        raise PageFrameError("truncated frame: no header length")
    (hlen,) = struct.unpack_from("<I", data, off)
    off += 4
    if len(data) < off + hlen:
        raise PageFrameError("truncated frame: header cut short")
    try:
        meta = json.loads(data[off:off + hlen])
    except ValueError as e:
        raise PageFrameError(f"bad header: {e}") from None
    off += hlen
    if meta.get("version") != _FRAME_VERSION:
        raise PageFrameError(f"frame version {meta.get('version')} != "
                             f"{_FRAME_VERSION}")
    if chain is not None and meta.get("chain") != chain:
        raise PageFrameError(f"frame filed under chain "
                             f"{meta.get('chain')!r}, wanted {chain!r}")
    body = data[off:]
    core = {k: v for k, v in meta.items() if k != "digest"}
    want = hashlib.blake2s(
        json.dumps(core, sort_keys=True).encode() + body).hexdigest()
    if want != meta.get("digest"):
        raise PageFrameError("digest mismatch: corrupt frame")
    arrays: Dict[str, np.ndarray] = {}
    pos = 0
    # past the digest everything below re-derives from verified bytes,
    # but a flipped bit can still yield VALID JSON with mangled specs —
    # any structural surprise is a corrupt frame, never a crash
    try:
        for spec in meta["arrays"]:
            dt = _frame_dtype(spec["dtype"])
            shape = tuple(int(d) for d in spec["shape"])
            nbytes = dt.itemsize * int(np.prod(shape))
            if pos + nbytes > len(body):
                raise PageFrameError(f"truncated body at {spec['key']!r}")
            arrays[spec["key"]] = np.frombuffer(
                body, dt, count=int(np.prod(shape)),
                offset=pos).reshape(shape)
            pos += nbytes
    except PageFrameError:
        raise
    except Exception as e:
        raise PageFrameError(f"bad array specs: {e}") from None
    payload: Dict[str, Any] = {}
    for side in ("k", "v"):
        if side in arrays:
            payload[side] = arrays[side]
        elif f"{side}.q" in arrays and f"{side}.s" in arrays:
            payload[side] = {"q": arrays[f"{side}.q"],
                             "s": arrays[f"{side}.s"]}
        else:
            raise PageFrameError(f"frame missing the {side!r} page")
    return {"version": meta["version"], "chain": meta["chain"],
            "page_hash": meta["page_hash"],
            "kv_quant": meta["kv_quant"], "payload": payload}


# ---------------------------------------------------------------------------
# host/disk page tiers


class PageTierStore:
    """Cold-page hierarchy under the HBM pool: demoted radix pages live
    here as packed, digest-checked frames — pinned host memory first,
    spilling to content-addressed files on disk when the host tier
    fills, dropping the LRU frame when disk fills too. Capacity is
    counted in PAGES on both tiers, so "2x the HBM pool at equal HBM"
    is literally ``host_pages + disk_pages >= pool.pages``.

    Ownership discipline (the ledger invariant, extended not weakened):

    * The store holds BYTE COPIES keyed by chain key
      (:func:`chain_keys`), never :class:`PagePool` page ids — a
      demoted page leaves the ledger entirely (demote gathers the
      bytes, files the frame, then unrefs), so ``check()`` /
      ``reconcile()`` stay exact over live owners with nothing new to
      prove about free pages.
    * :meth:`take` POPS: the caller becomes the frame's only owner.
      A promote racing a second promote — or racing an eviction that
      re-demotes the same chain — resolves to exactly one owner by
      construction; the loser misses and recomputes.
    * :meth:`discard` drops a chain the radix re-acquired (a retiring
      stream adopted the same prefix back into HBM): content lives in
      the radix XOR the tiers, never both, which the chaos
      ``kv-tier-owner`` invariant audits.

    A frame that fails verification at :meth:`take` (bit-rot,
    truncation — the ``kv_tier_corrupt`` chaos fault) is counted,
    dropped, and reported as a miss: the caller recomputes; corrupt
    bytes never reach a page table. Thread-safe — stats are scraped
    from HTTP threads while the engine thread demotes/promotes."""

    def __init__(self, host_pages: int = 0,
                 disk_dir: Optional[str] = None, disk_pages: int = 0):
        if host_pages < 0 or disk_pages < 0:
            raise ValueError("tier capacities must be >= 0")
        if disk_pages > 0 and not disk_dir:
            raise ValueError("disk_pages > 0 needs disk_dir")
        self.host_pages = int(host_pages)
        self.disk_pages = int(disk_pages) if disk_dir else 0
        self.disk_dir = disk_dir
        if disk_dir and self.disk_pages > 0:
            os.makedirs(disk_dir, exist_ok=True)
        self._lock = threading.Lock()
        self._host: "OrderedDict[str, bytes]" = OrderedDict()
        self._disk: "OrderedDict[str, str]" = OrderedDict()  # chain->path
        self.demoted_host = 0     # frames filed into the host tier
        self.demoted_disk = 0     # frames spilled host -> disk
        self.dropped = 0          # LRU frames dropped off the disk end
        self.host_hits = 0
        self.disk_hits = 0
        self.misses = 0
        self.corrupt_frames = 0   # frames rejected at take
        self.discarded = 0        # chains the radix re-acquired

    # ------------------------------------------------------------ queries

    def host_count(self) -> int:
        with self._lock:
            return len(self._host)

    def disk_count(self) -> int:
        with self._lock:
            return len(self._disk)

    def has(self, chain: str) -> bool:
        with self._lock:
            return chain in self._host or chain in self._disk

    def chains(self) -> List[str]:
        with self._lock:
            return list(self._host) + list(self._disk)

    # -------------------------------------------------------- transitions

    def put(self, chain: str, entry: Dict[str, Any]) -> None:
        """Demote: pack ``entry`` (``chain`` / ``page_hash`` /
        ``kv_quant`` / one-page ``payload``) and file it, displacing
        LRU frames down the hierarchy (host -> disk -> dropped). A
        re-demoted chain replaces its stale frame.

        All disk I/O happens OUTSIDE ``_lock`` (T4): a ``take()``
        racing a chain mid-spill sees a clean miss and recomputes —
        the documented contract — instead of every scrape and engine
        demote stalling behind a disk write."""
        frame = pack_page_frame(entry)
        spill: List[Tuple[str, bytes]] = []
        unlink: List[str] = []
        with self._lock:
            self._pop_locked(chain, unlink)
            if self.host_pages > 0:
                self._host[chain] = frame
                self.demoted_host += 1
                while len(self._host) > self.host_pages:
                    spill.append(self._host.popitem(last=False))
            else:
                spill.append((chain, frame))
        for old_chain, old_frame in spill:
            self._spill(old_chain, old_frame, unlink)
        for path in unlink:
            try:
                os.remove(path)
            except OSError:
                pass

    def _spill(self, chain: str, frame: bytes, unlink: List[str]) -> None:
        """File one frame host -> disk. Called with ``_lock`` RELEASED;
        the write lands first, the ledger entry commits under the lock
        after, and displaced paths are appended to ``unlink`` for the
        caller to remove (also outside the lock)."""
        if self.disk_pages <= 0:
            with self._lock:
                self.dropped += 1
            return
        path = os.path.join(self.disk_dir, f"{chain}.kvpage")
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(frame)
        os.replace(tmp, path)          # commit is atomic, like weights.py
        with self._lock:
            self._disk[chain] = path
            self._disk.move_to_end(chain)
            self.demoted_disk += 1
            while len(self._disk) > self.disk_pages:
                old_chain, old_path = self._disk.popitem(last=False)
                unlink.append(old_path)
                self.dropped += 1

    def take(self, chain: str) -> Optional[Dict[str, Any]]:
        """Promote: POP the chain's frame, verify it, and return the
        decoded entry — or None on a miss or a corrupt frame (counted;
        the frame is gone either way, so the caller that recomputes
        becomes the content's only owner). The pop is the ownership
        transfer and happens under ``_lock``; the disk read does not
        (T4) — the path left the ledger, so nobody else can reach it."""
        path: Optional[str] = None
        with self._lock:
            frame = self._host.pop(chain, None)
            from_host = frame is not None
            if frame is None:
                path = self._disk.pop(chain, None)
                if path is None:
                    self.misses += 1
                    return None
        if frame is None:
            try:
                with open(path, "rb") as f:
                    frame = f.read()
            except OSError:
                frame = b""
            try:
                os.remove(path)
            except OSError:
                pass
        try:
            entry = unpack_page_frame(frame, chain=chain)
        except PageFrameError:
            with self._lock:
                self.corrupt_frames += 1
                self.misses += 1
            return None
        with self._lock:
            if from_host:
                self.host_hits += 1
            else:
                self.disk_hits += 1
        return entry

    def discard(self, chain: str) -> bool:
        """Drop a chain without reading it — the radix owns the content
        again (a retiring stream re-adopted the prefix into HBM), so a
        stale tier copy would make two owners."""
        unlink: List[str] = []
        with self._lock:
            hit = self._pop_locked(chain, unlink)
            if hit:
                self.discarded += 1
        for path in unlink:
            try:
                os.remove(path)
            except OSError:
                pass
        return hit

    def _pop_locked(self, chain: str, unlink: List[str]) -> bool:
        """Drop ``chain`` from both tier ledgers; any orphaned disk
        path is appended to ``unlink`` for removal OUTSIDE the lock."""
        hit = self._host.pop(chain, None) is not None
        path = self._disk.pop(chain, None)
        if path is not None:
            hit = True
            unlink.append(path)
        return hit

    def clear(self) -> None:
        with self._lock:
            self._host.clear()
            paths = list(self._disk.values())
            self._disk.clear()
        for path in paths:
            try:
                os.remove(path)
            except OSError:
                pass

    # -------------------------------------------------------------- stats

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "host_pages": len(self._host),
                "host_capacity": self.host_pages,
                "disk_pages": len(self._disk),
                "disk_capacity": self.disk_pages,
                "demoted_host": self.demoted_host,
                "demoted_disk": self.demoted_disk,
                "dropped": self.dropped,
                "host_hits": self.host_hits,
                "disk_hits": self.disk_hits,
                "misses": self.misses,
                "corrupt_frames": self.corrupt_frames,
                "discarded": self.discarded,
            }


# ---------------------------------------------------------------------------
# fleet prefix directory


class PrefixDirectory:
    """Fleet-wide map of WHO holds WHICH cached prefix, keyed on the
    same chain identity the tiers use (:func:`chain_keys`, folded from
    the ``page_hashes`` the router's affinity ring already routes by).
    Replicas publish the chains their radix adopts; a replica that
    misses locally asks the directory for a sibling to ADOPT the
    prefix from over the span transport instead of recomputing it.

    Entries are hints, never truth: each carries the publish stamp and
    :meth:`lookup` drops entries older than ``max_age_s`` — a stale
    hint (the holder evicted, restarted, or died) costs the asker one
    failed fetch and a recompute fallback, never a wrong answer (the
    span transport digest-verifies what actually arrives). Thread-safe:
    the router and every replica's engine thread share one instance
    in-process."""

    def __init__(self, max_age_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        self.max_age_s = float(max_age_s)
        self._clock = clock
        self._lock = threading.Lock()
        # chain -> {replica: publish stamp}
        self._holders: Dict[str, Dict[str, float]] = {}
        self.publishes = 0
        self.hits = 0
        self.misses = 0
        self.stale_drops = 0

    def publish(self, replica: str, chains: Sequence[str]) -> None:
        now = self._clock()
        with self._lock:
            for chain in chains:
                self._holders.setdefault(chain, {})[replica] = now
                self.publishes += 1

    def forget(self, replica: str) -> None:
        """Drop every hint naming ``replica`` (it restarted or left the
        fleet — its radix is gone)."""
        with self._lock:
            for chain in list(self._holders):
                self._holders[chain].pop(replica, None)
                if not self._holders[chain]:
                    del self._holders[chain]

    def lookup(self, chain: str,
               exclude: Optional[str] = None) -> Optional[str]:
        """Freshest replica claiming ``chain`` (excluding the asker),
        or None. Stale claims are dropped on the way through."""
        horizon = self._clock() - self.max_age_s
        with self._lock:
            holders = self._holders.get(chain)
            if holders:
                for replica in [r for r, t in holders.items()
                                if t < horizon]:
                    del holders[replica]
                    self.stale_drops += 1
                if not holders:
                    del self._holders[chain]
                    holders = None
            if not holders:
                self.misses += 1
                return None
            best = max((r for r in holders if r != exclude),
                       key=lambda r: holders[r], default=None)
            if best is None:
                self.misses += 1
                return None
            self.hits += 1
            return best

    def holders(self, chain: str) -> List[str]:
        with self._lock:
            return sorted(self._holders.get(chain, ()))

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "chains": len(self._holders),
                "publishes": self.publishes,
                "hits": self.hits,
                "misses": self.misses,
                "stale_drops": self.stale_drops,
            }
