"""Host-side page ledger + prefix-sharing radix for the paged serving
engine (``models/serving.py:PagedServer``).

The device side of paged serving is a fixed pool of KV pages
(``llama.init_page_pool``) consulted through per-stream page tables;
this module is the HOST side — who owns which physical page, with the
same durability discipline as the scheduler's reservation ledger
(``state/reservation_store.py``): every page is either free or
refcounted, transitions are explicit (alloc/ref/unref), and
:meth:`PagePool.check` audits the whole ledger so the chaos invariant
checker can prove no page ever leaks or is double-booked across
abort/retire/reset.

Sharing model (vLLM/SGLang-style prefix caching, TPU-simplified):

* Only FULL pages of prompt tokens are hash-consed: a page whose every
  position is determined by the prompt (and its absolute positions —
  prefixes are position-aligned from 0) has bit-identical K/V across
  requests, so one physical copy serves them all behind a refcount.
* A retiring stream's full prompt pages are ADOPTED into the radix
  (one extra reference each); the radix evicts least-recently-used
  childless nodes under allocation pressure.
* The boundary partial page copies eagerly (copy-on-write at admission:
  the new stream gets a private copy of a cached page whose prefix
  matches its remaining prompt, then prefills only the tail). Pages a
  decode stream writes into are always private by construction, so the
  hot decode scatter needs no ownership check.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np


def page_hashes(prompt: Sequence[int], page_size: int) -> List[str]:
    """Content hash per FULL page of prompt tokens — THE prefix-hash
    every tier must agree on. One blake2s over the page's int32 bytes,
    truncated to 16 hex chars, one entry per full page, partial tail
    excluded (a partial page is never position-aligned shareable).

    Three consumers, one function, by design: the KV-span wire format
    (``models/disagg.py`` ``pack_span``/``unpack_span`` verify shipped
    pages against these), the fleet router's consistent-hash affinity
    key (``models/router.py`` ``route_key``), and this module's
    :class:`PrefixRadix` (whose hash-cons keys are the same full-page
    token runs these hashes summarize). If any of them hashed
    differently, requests would land on replicas whose radix holds
    nothing for them and affinity would silently degrade — the
    cross-module parity test in ``tests/test_router.py`` pins this.
    """
    out = []
    for j in range(len(prompt) // page_size):
        page = np.asarray(prompt[j * page_size:(j + 1) * page_size],
                          np.int32)
        out.append(hashlib.blake2s(page.tobytes()).hexdigest()[:16])
    return out


class PageLedgerError(RuntimeError):
    """A page transition that must never happen (double free, ref of a
    free page) — raised loudly rather than corrupting shared K/V."""


class PagePool:
    """Refcounted ledger over ``pages`` physical KV pages.

    Pure host bookkeeping — it never touches the device pool; the
    serving engine translates (alloc/unref) into page-table edits.
    """

    def __init__(self, pages: int, page_size: int):
        if pages < 1:
            raise ValueError(f"page pool needs >= 1 page, got {pages}")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.pages = pages
        self.page_size = page_size
        self._ref = [0] * pages
        # pop() from the tail -> ascending allocation order (determinism
        # across gang ranks matters: every rank must pick the same page)
        self._free = list(range(pages - 1, -1, -1))
        self.in_use_peak = 0

    # ------------------------------------------------------------ queries

    def free_count(self) -> int:
        return len(self._free)

    def in_use(self) -> int:
        return self.pages - len(self._free)

    def refcount(self, page: int) -> int:
        return self._ref[page]

    # -------------------------------------------------------- transitions

    def alloc(self, n: int = 1) -> Optional[List[int]]:
        """``n`` fresh pages at refcount 1, or None when fewer than ``n``
        are free (all-or-nothing: a partial grant would strand a stream
        mid-prefill with nowhere to write)."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        out = [self._free.pop() for _ in range(n)]
        for p in out:
            self._ref[p] = 1
        self.in_use_peak = max(self.in_use_peak, self.in_use())
        return out

    def ref(self, page: int) -> None:
        """One more reference to a live page (prefix sharing)."""
        if not 0 <= page < self.pages:
            raise PageLedgerError(f"ref of unknown page {page}")
        if self._ref[page] <= 0:
            raise PageLedgerError(
                f"ref of free page {page}: sharing a page nobody owns")
        self._ref[page] += 1

    def unref(self, page: int) -> None:
        """Drop one reference; the page returns to the free list at 0."""
        if not 0 <= page < self.pages:
            raise PageLedgerError(f"unref of unknown page {page}")
        if self._ref[page] <= 0:
            raise PageLedgerError(f"double free of page {page}")
        self._ref[page] -= 1
        if self._ref[page] == 0:
            self._free.append(page)

    # ---------------------------------------------------- audit + recovery

    def check(self, expected_refs: Optional[Mapping[int, int]] = None
              ) -> List[str]:
        """Ledger violations (empty == healthy).

        Structural: refcounts non-negative, the free list and the
        refcounts agree (a page is free iff refcount 0), no duplicate
        free-list entries. With ``expected_refs`` (page -> references
        actually held by live page tables + the radix) also cross-checks
        that no page leaked (counted but unreferenced) or is
        double-booked (referenced more times than counted).
        """
        out: List[str] = []
        free_set = set(self._free)
        if len(free_set) != len(self._free):
            dupes = sorted({p for p in self._free
                            if self._free.count(p) > 1})
            out.append(f"free list holds duplicates {dupes}: a double "
                       "free put the same page up for grabs twice")
        for p in range(self.pages):
            r = self._ref[p]
            if r < 0:
                out.append(f"page {p}: negative refcount {r}")
            elif r == 0 and p not in free_set:
                out.append(f"page {p}: leaked (refcount 0 but not in "
                           "the free list)")
            elif r > 0 and p in free_set:
                out.append(f"page {p}: double-booked (refcount {r} "
                           "while on the free list)")
        if expected_refs is not None:
            for p in range(self.pages):
                want = expected_refs.get(p, 0)
                if self._ref[p] != want:
                    out.append(
                        f"page {p}: refcount {self._ref[p]} != {want} "
                        "references held by live tables/radix")
        return out

    def reconcile(self, expected_refs: Mapping[int, int]) -> List[int]:
        """Crash-recovery sweep: force the ledger to the reference
        counts derivable from surviving state (live page tables + the
        radix) and rebuild the free list — the page analogue of the
        reservation ledger's orphan GC. Returns the reclaimed page ids
        (pages the crash left counted but unreferenced)."""
        reclaimed = []
        for p in range(self.pages):
            want = expected_refs.get(p, 0)
            if self._ref[p] > 0 and want == 0:
                reclaimed.append(p)
            self._ref[p] = want
        self._free = [p for p in range(self.pages - 1, -1, -1)
                      if self._ref[p] == 0]
        return reclaimed


class _Node:
    __slots__ = ("children", "page", "parent", "key", "stamp")

    def __init__(self, parent: Optional["_Node"],
                 key: Optional[tuple], page: Optional[int]):
        self.children: Dict[tuple, "_Node"] = {}
        self.parent = parent
        self.key = key
        self.page = page
        self.stamp = 0


class PrefixRadix:
    """Hash-consed radix of full prompt-prefix pages.

    Each edge is one page's worth of prompt tokens (the tuple is the
    hash-cons key); each non-root node owns ONE reference to a physical
    page in the :class:`PagePool`. Lookups reference matched pages on
    the caller's behalf; retirement adopts new pages via :meth:`insert`;
    :meth:`evict` trims least-recently-used unshared leaves when the
    pool runs dry.
    """

    def __init__(self, pool: PagePool):
        self._pool = pool
        self._root = _Node(None, None, None)
        self._clock = 0
        self.hits = 0            # lookups that shared >= 1 page
        self.shared_pages = 0    # pages served from the radix, cumulative

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    # ------------------------------------------------------------- lookup

    def lookup(self, prompt: List[int]) -> Tuple[List[int], _Node]:
        """Longest cached chain of full pages covering a PROPER prefix
        of ``prompt``; takes one pool reference per matched page on the
        caller's behalf. At least one prompt token is always left
        uncached so the final prefill chunk has a live position to take
        first-token logits from. Returns (pages, stop_node) — feed the
        stop node to :meth:`boundary` for the partial-page tail."""
        ps = self._pool.page_size
        n = len(prompt)
        node, pages = self._root, []
        j = 0
        while (j + 1) * ps < n:
            child = node.children.get(tuple(prompt[j * ps:(j + 1) * ps]))
            if child is None:
                break
            self._pool.ref(child.page)
            child.stamp = self._tick()
            pages.append(child.page)
            node = child
            j += 1
        if pages:
            self.hits += 1
            self.shared_pages += len(pages)
        return pages, node

    def boundary(self, node: _Node, prompt: List[int],
                 matched_tokens: int) -> Optional[Tuple[int, int]]:
        """Partial-page tail match under ``node``: a cached child whose
        page STARTS with the next (shareable) prompt tokens. Returns
        (src_page, valid_tokens) or None. The caller must COPY the page
        (eager copy-on-write) — the source stays owned by the radix, and
        positions past ``valid_tokens`` in the copy are garbage the
        caller's prefill/decode writes overwrite."""
        ps = self._pool.page_size
        valid = min(ps - 1, len(prompt) - 1 - matched_tokens)
        if valid <= 0:
            return None
        want = tuple(prompt[matched_tokens:matched_tokens + valid])
        for key, child in node.children.items():
            if key[:valid] == want:
                child.stamp = self._tick()
                return child.page, valid
        return None

    # ------------------------------------------------------------- insert

    def insert(self, prompt: List[int], pages: List[int]) -> int:
        """Adopt a retiring stream's full prompt pages (hash-consing:
        an existing node keeps ITS page and the stream's duplicate is
        simply not adopted; a new node takes one reference on the
        stream's page). Returns how many pages were newly adopted."""
        ps = self._pool.page_size
        node, adopted = self._root, 0
        full = min(len(prompt) // ps, len(pages))
        for j in range(full):
            key = tuple(prompt[j * ps:(j + 1) * ps])
            child = node.children.get(key)
            if child is None:
                child = _Node(node, key, pages[j])
                self._pool.ref(pages[j])
                node.children[key] = child
                adopted += 1
            child.stamp = self._tick()
            node = child
        return adopted

    # ----------------------------------------------------- evict + audit

    def _iter_nodes(self):
        stack = list(self._root.children.values())
        while stack:
            node = stack.pop()
            yield node
            stack.extend(node.children.values())

    def size(self) -> int:
        return sum(1 for _ in self._iter_nodes())

    def held(self) -> Dict[int, int]:
        """page -> references the radix holds (the invariant checker's
        input alongside the live page tables)."""
        out: Dict[int, int] = {}
        for node in self._iter_nodes():
            out[node.page] = out.get(node.page, 0) + 1
        return out

    def evict(self, need: int) -> int:
        """Drop least-recently-used childless nodes nobody else
        references until ``need`` pages came free (or no candidates
        remain). Shared nodes (an active stream still references the
        page) are kept: unref'ing them frees nothing now and forfeits
        the share. Returns pages actually freed."""
        freed = 0
        while freed < need:
            leaves = [n for n in self._iter_nodes()
                      if not n.children
                      and self._pool.refcount(n.page) == 1]
            if not leaves:
                break
            victim = min(leaves, key=lambda x: x.stamp)
            del victim.parent.children[victim.key]
            self._pool.unref(victim.page)
            freed += 1
        return freed

    def clear(self) -> None:
        """Release every cached page (engine reset: the device pool is
        re-initialized, so cached K/V no longer exists)."""
        for node in list(self._iter_nodes()):
            self._pool.unref(node.page)
        self._root.children = {}
