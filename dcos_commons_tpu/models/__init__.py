"""Model families for ``frameworks/jax`` workloads.

Mirrors the reference's shipped example frameworks (helloworld / cassandra /
hdfs under ``frameworks/``, SURVEY.md §2.3): here the "examples" are the
BASELINE.json configs — MNIST MLP (single chip), ResNet-50 (data parallel),
and the flagship Llama-style transformer (tensor/sequence/pipeline/expert
parallel via ``dcos_commons_tpu.parallel``).

All models are pure-functional JAX: params are pytrees of arrays, layers are
stacked and scanned (one compiled layer body regardless of depth), weights
ride in bf16 with fp32 master copies owned by the optimizer.
"""

from dcos_commons_tpu.models.mlp import MLPConfig
from dcos_commons_tpu.models.resnet import ResNetConfig
from dcos_commons_tpu.models.llama import LlamaConfig

__all__ = ["MLPConfig", "ResNetConfig", "LlamaConfig"]
