"""Train-step builders: optax optimizer + GSPMD sharding in one jit.

The training loop the ``frameworks/jax`` tasks run. Parallelism is purely
declarative: params carry `NamedSharding`s from `param_specs`, the batch is
sharded ("dp", ...) and XLA emits the gradient all-reduce over ICI — no
hand-written collectives in the step (SURVEY.md §2.4 "Collectives backend").
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_optimizer(lr: float = 3e-4, weight_decay: float = 0.01,
                   warmup: int = 100, decay_steps: int = 10000,
                   grad_clip: float = 1.0) -> optax.GradientTransformation:
    sched = optax.warmup_cosine_decay_schedule(0.0, lr, warmup, decay_steps)
    return optax.chain(optax.clip_by_global_norm(grad_clip),
                       optax.adamw(sched, weight_decay=weight_decay))


def make_train_step(loss_fn: Callable, optimizer: optax.GradientTransformation,
                    mesh: Optional[Mesh] = None,
                    param_spec_tree: Any = None,
                    batch_spec: Any = P("dp"),
                    has_aux_state: bool = False,
                    grad_accum: int = 1) -> Callable:
    """Build a jitted ``step(params, opt_state, batch[, aux]) -> ...``.

    ``loss_fn(params, batch)`` -> scalar loss (or ``(loss, (metric, aux))``
    when ``has_aux_state`` — the ResNet BN-state pattern).
    With a mesh, params/opt-state are pinned to ``param_spec_tree`` and the
    batch to ``batch_spec`` so GSPMD never resolves shardings ambiguously.

    ``grad_accum > 1`` microbatches the step: every batch leaf's leading
    axis is split into ``grad_accum`` equal slices and a ``lax.scan``
    runs backward passes sequentially, accumulating gradients in an
    fp32 carry (donated across iterations by XLA's scan buffer reuse)
    and applying ONE optimizer update on the average. Peak activation
    memory is one microbatch's, so the HBM headroom the fused loss frees
    converts into larger *effective* batch instead of OOM. Loss/metric
    are microbatch means — identical to the unmicrobatched step whenever
    per-microbatch token counts are equal (the unmasked LM case).
    """
    if grad_accum < 1:
        raise ValueError(f"grad_accum must be >= 1, got {grad_accum}")
    if grad_accum > 1 and has_aux_state:
        # BN-style aux threads state THROUGH the loss; sequential
        # microbatches would see stale state mid-step. No caller needs
        # the combination today — reject loudly rather than silently
        # training on stale statistics.
        raise NotImplementedError(
            "grad_accum > 1 with has_aux_state is not supported")

    def _grads_single(params, batch):
        (loss, metric), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, batch)
        return loss, metric, grads

    def _grads_accum(params, batch):
        def split(a):
            if a.shape[0] % grad_accum:
                raise ValueError(
                    f"batch leading dim {a.shape[0]} not divisible by "
                    f"grad_accum={grad_accum}")
            return a.reshape((grad_accum, a.shape[0] // grad_accum)
                             + a.shape[1:])

        micro = jax.tree.map(split, batch)

        def body(carry, mb):
            gsum, lsum, msum = carry
            loss, metric, grads = _grads_single(params, mb)
            gsum = jax.tree.map(
                lambda g, a: a + g.astype(jnp.float32), grads, gsum)
            return (gsum, lsum + loss, msum + metric), None

        zeros = jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        zf = jnp.zeros((), jnp.float32)
        (gsum, lsum, msum), _ = lax.scan(body, (zeros, zf, zf), micro)
        grads = jax.tree.map(
            lambda g, p: (g / grad_accum).astype(p.dtype), gsum, params)
        return lsum / grad_accum, msum / grad_accum, grads

    def step(params, opt_state, batch):
        if has_aux_state:
            (loss, (metric, aux)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
        elif grad_accum > 1:
            loss, metric, grads = _grads_accum(params, batch)
            aux = None
        else:
            loss, metric, grads = _grads_single(params, batch)
            aux = None
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        out = {"loss": loss, "metric": metric}
        if has_aux_state:
            return params, opt_state, aux, out
        return params, opt_state, out

    if mesh is None:
        return jax.jit(step, donate_argnums=(0, 1))

    def shardings_like(tree, spec_tree):
        if spec_tree is None:
            return None
        return jax.tree.map(lambda s: NamedSharding(mesh, s), spec_tree,
                            is_leaf=lambda s: isinstance(s, P))

    p_shard = shardings_like(None, param_spec_tree)
    b_shard = jax.tree.map(lambda s: NamedSharding(mesh, s), batch_spec,
                           is_leaf=lambda s: isinstance(s, P))
    if p_shard is None:
        return jax.jit(step, donate_argnums=(0, 1))

    # pin OUTPUT params to the same spec as the inputs: without this GSPMD
    # may resolve an output param to a different sharding, and the second
    # step call fails its in_shardings check (a one-step smoke never sees
    # this; any training loop does). The opt_state needs the same pinning
    # on BOTH sides — it is donated, and a moment leaf whose output
    # sharding GSPMD resolves differently from its input placement (e.g.
    # a replicated norm moment re-resolved tp-sharded) fails XLA's
    # donation aliasing check with a per-device size mismatch on step 1.
    # Its structure only exists once a real opt_state arrives, so the jit
    # is built lazily on the first call, with the opt-state leaves mapped
    # through the same shape -> spec table ``init_opt_state`` places by.
    jitted: Dict[str, Callable] = {}

    def _opt_shardings(params, opt_state):
        spec_by_shape: Dict[Tuple[int, ...], Any] = {}
        flat_p = jax.tree.leaves(params)
        flat_s = jax.tree.leaves(param_spec_tree,
                                 is_leaf=lambda s: isinstance(s, P))
        for leaf, spec in zip(flat_p, flat_s):
            spec_by_shape.setdefault(leaf.shape, spec)
        return jax.tree.map(
            lambda x: NamedSharding(
                mesh, spec_by_shape.get(getattr(x, "shape", None), P())),
            opt_state)

    def lazy_step(params, opt_state, batch):
        fn = jitted.get("fn")
        if fn is None:
            o_shard = _opt_shardings(params, opt_state)
            out_shardings = ((p_shard, o_shard, None, None)
                             if has_aux_state else (p_shard, o_shard, None))
            fn = jitted["fn"] = jax.jit(
                step, donate_argnums=(0, 1),
                in_shardings=(p_shard, o_shard, b_shard),
                out_shardings=out_shardings)
        return fn(params, opt_state, batch)

    return lazy_step


def init_opt_state(optimizer: optax.GradientTransformation, params,
                   mesh: Optional[Mesh] = None,
                   param_spec_tree: Any = None):
    """Init optimizer state; with a mesh, moments inherit param shardings."""
    opt_state = optimizer.init(params)
    if mesh is None or param_spec_tree is None:
        return opt_state
    spec_by_shape: Dict[Tuple[int, ...], Any] = {}
    flat_p = jax.tree.leaves(params)
    flat_s = jax.tree.leaves(param_spec_tree,
                             is_leaf=lambda s: isinstance(s, P))
    for leaf, spec in zip(flat_p, flat_s):
        spec_by_shape.setdefault(leaf.shape, spec)

    def place(x):
        if hasattr(x, "shape") and x.shape in spec_by_shape:
            return jax.device_put(x, NamedSharding(mesh,
                                                   spec_by_shape[x.shape]))
        return x
    return jax.tree.map(place, opt_state)
