"""HTTP front door for the continuous-batching serving engine.

The reference's services serve real client traffic on reserved, advertised
ports (``http/endpoints/EndpointsResource.java:22``; cassandra's client
ports in ``frameworks/cassandra/src/main/dist/svc.yml``). This is the
serving-workload analogue: a request ingress in front of
:class:`~dcos_commons_tpu.models.serving.SlotServer`, so a deployed
serving pod accepts work instead of draining synthetic bursts.

Design, TPU-first: HTTP handler threads never touch the device. They
validate, enqueue into a BOUNDED queue (back-pressure is a 503 +
Retry-After, not an unbounded pile-up in front of a fixed-throughput
chip), and wait on their request's stream. ONE engine thread owns the
SlotServer — submissions fill freed slots, one ``step()`` advances every
active slot, and freshly decoded tokens are fanned out to the per-request
streams with timestamps. That keeps every device dispatch on a single
thread (no lock around the cache pytree) and makes TTFT/TPOT measurable
per request at the ingress, where the serving benchmarks need them.

API (all JSON):

* ``POST /v1/generate``  ``{"prompt": [ints], "max_new": N, "stream": bool}``
  → ``{"tokens": [...], "ttft_ms", "tpot_ms", "queue_ms"}``; with
  ``stream`` true, chunked JSON lines ``{"token": t}`` … ``{"done": true}``.
* ``GET /v1/healthz`` → 200 once the engine thread accepts work (the
  serving.yml readiness gate).
* ``GET /v1/stats`` → request/token totals + TTFT/TPOT percentiles over
  the last window.
* ``POST /v1/prefix`` ``{"prompt": [ints]}`` → the longest radix-resident
  full-page prefix of the prompt as a packed KV span (octet-stream;
  404 when nothing is cached) — the fleet prefix-adoption fetch
  (``disagg.fetch_prefix`` is the client). Served through the engine
  thread: handlers enqueue a job and park, because the export gathers
  device pages with radix references held.
"""

from __future__ import annotations

import json
import queue
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from dcos_commons_tpu.metrics import MetricsRegistry
from dcos_commons_tpu.models.disagg import pack_span
from dcos_commons_tpu.models.serving import SlotServer
from dcos_commons_tpu.tracing import TRACE_HEADER, Tracer, parse_header


class _Pending:
    """One in-flight request: filled in by the engine thread, consumed by
    the handler thread that owns the HTTP connection."""

    __slots__ = ("prompt", "max_new", "stream", "tokens", "emitted",
                 "t_enqueue", "t_submit", "t_first", "t_done", "error",
                 "done", "events", "trace", "on_finish")

    def __init__(self, prompt: List[int], max_new: int, trace=None):
        self.prompt = prompt
        self.max_new = max_new
        self.tokens: List[int] = []
        self.emitted = 0                  # engine-side high-water mark
        self.t_enqueue = time.perf_counter()
        self.t_submit: Optional[float] = None
        self.t_first: Optional[float] = None
        self.t_done: Optional[float] = None
        self.error: Optional[str] = None
        self.done = threading.Event()
        # token stream for chunked responses: ints, then None sentinel
        self.events: "queue.Queue" = queue.Queue()
        # incoming X-Tpu-Trace context (None for untraced callers) and
        # the frontend's one-shot finalizer (spans + histograms)
        self.trace = trace
        self.on_finish = None

    def push(self, tokens: List[int]) -> None:
        now = time.perf_counter()
        for t in tokens:
            if self.t_first is None:
                self.t_first = now
            self.tokens.append(t)
            self.events.put(t)

    def finish(self, error: Optional[str] = None) -> None:
        self.error = error
        self.t_done = time.perf_counter()
        # one-shot: every finish path (normal retire, engine error,
        # shutdown) lands exactly one terminal span + histogram sample
        hook, self.on_finish = self.on_finish, None
        if hook is not None:
            try:
                hook(self)
            except Exception:
                pass
        self.events.put(None)
        self.done.set()

    def timings_ms(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        if self.t_submit is not None:
            out["queue_ms"] = round((self.t_submit - self.t_enqueue) * 1e3, 3)
        if self.t_first is not None:
            out["ttft_ms"] = round((self.t_first - self.t_enqueue) * 1e3, 3)
        if (self.t_done is not None and self.t_first is not None
                and len(self.tokens) > 1):
            out["tpot_ms"] = round(
                (self.t_done - self.t_first) / (len(self.tokens) - 1) * 1e3,
                3)
        return out


from dcos_commons_tpu.utils.stats import percentiles as _percentiles


class _Export:
    """One ``/v1/prefix`` job: the handler thread parks on ``done``
    while the engine thread — the sole engine driver — runs
    ``export_prefix`` at a step boundary and lands the span here. The
    gather copies pages to host, so once ``done`` fires the handler
    packs and writes the frame without touching the engine again."""

    __slots__ = ("prompt", "span", "error", "done")

    def __init__(self, prompt: List[int]):
        self.prompt = prompt
        self.span: Optional[dict] = None
        self.error: Optional[str] = None
        self.done = threading.Event()


class ServingFrontend:
    """Bounded-queue HTTP ingress over one :class:`SlotServer`."""

    def __init__(self, engine: SlotServer, port: int = 0,
                 host: str = "0.0.0.0", max_queue: int = 64,
                 request_timeout_s: float = 600.0,
                 idle_sleep_s: float = 0.001,
                 decode_window: int = 8,
                 window_s: float = 60.0,
                 metrics: Optional[MetricsRegistry] = None,
                 trace_store=None):
        self.engine = engine
        self.max_queue = max_queue
        # shared registry when the deployment passes one (the worker's
        # scheduler registry), else a private one — either way the
        # /v1/metrics endpoints below serve it
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self.tracer = Tracer("serve", trace_store)
        # the engine records per-chunk prefill/adopt spans when a tracer
        # is present (models/serving.py checks the attribute)
        if getattr(engine, "tracer", None) is None:
            engine.tracer = Tracer("engine", trace_store)
        self.request_timeout_s = request_timeout_s
        self._idle_sleep_s = idle_sleep_s
        # tokens decoded per device dispatch (SlotServer.step_many):
        # dispatch latency — not the chip — bounds TPOT on tunneled
        # backends, so the engine decodes a window per dispatch; new
        # requests wait at most one window for a slot
        self._decode_window = max(1, decode_window)
        self._queue: "queue.Queue[_Pending]" = queue.Queue(maxsize=max_queue)
        self._live: Dict[int, _Pending] = {}          # slot -> pending
        # drained from the queue but not yet admitted (a paged engine
        # admits a FIFO prefix when pages run short): retried FIRST on
        # the next fill so nothing is silently dropped
        self._backlog: List[_Pending] = []
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._lock = threading.Lock()                 # stats only
        # /v1/prefix jobs for the engine thread (bounded: a sibling
        # that can't get its export promptly recomputes — the fetch is
        # an optimization, never a dependency)
        self._exports: "queue.Queue[_Export]" = queue.Queue(maxsize=8)
        self.export_timeout_s = 30.0
        self._totals = {"requests": 0, "tokens": 0, "rejected": 0}
        # rolling-window load gauges (autoscaler input): completions and
        # sheds are stamped with time.monotonic() so load_gauges() can
        # report the last window_s seconds rather than lifetime totals —
        # point samples and lifetime counters both mislead a controller
        # (the former is noise, the latter never decays)
        self.window_s = window_s
        self._window: deque = deque(maxlen=1024)      # (t, ttft_ms, tpot_ms)
        self._sheds: deque = deque(maxlen=4096)       # t of each rejection
        self._engine_thread: Optional[threading.Thread] = None
        self._own_metrics = metrics is None
        # fold the rolling load gauges into the registry so one scrape
        # carries queue fill, shed rate, and window TTFT p95 alongside
        # the request histograms (suppliers run OUTSIDE the registry
        # lock — to_dict()'s contract — so reading self._lock is safe)
        for key in ("queue_depth", "queue_capacity", "completed", "shed",
                    "shed_rate", "ttft_p95_ms", "pages_free",
                    "pages_total", "kv_tier_host_pages",
                    "kv_tier_host_capacity", "kv_tier_disk_pages",
                    "kv_tier_disk_capacity", "kv_tier_hits",
                    "kv_tier_promoted", "kv_tier_demoted",
                    "spec_windows", "spec_proposed", "spec_accepted",
                    "spec_accept_rate", "spec_fallbacks"):
            self.metrics.gauge(f"ingress.{key}",
                               lambda k=key: self.load_gauges().get(k))
        frontend = self

        class Handler(BaseHTTPRequestHandler):
            # one request per connection keeps the thread pool honest
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):             # no stderr spam
                pass

            def _json(self, code: int, payload: dict,
                      extra_headers: Optional[dict] = None) -> None:
                body = (json.dumps(payload) + "\n").encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (extra_headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/v1/healthz":
                    self._json(200, frontend.health())
                elif self.path == "/v1/stats":
                    self._json(200, frontend.stats())
                elif self.path == "/v1/metrics":
                    self._json(200, frontend.metrics.to_dict())
                elif self.path == "/v1/metrics/prometheus":
                    body = frontend.metrics.to_prometheus().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path == "/v1/traces":
                    store = frontend.tracer.store
                    self._json(200, {
                        "trace_ids": store.trace_ids(),
                        "incomplete": store.incomplete_trace_ids()})
                elif self.path.startswith("/v1/trace/"):
                    trace_id = self.path[len("/v1/trace/"):].split("?")[0]
                    self._json(200, frontend.tracer.store.export(trace_id))
                else:
                    self._json(404, {"error": f"no route {self.path}"})

            def do_POST(self):
                if self.path == "/v1/prefix":
                    self._prefix()
                    return
                if self.path != "/v1/generate":
                    self._json(404, {"error": f"no route {self.path}"})
                    return
                try:
                    n = int(self.headers.get("Content-Length", "0"))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    prompt = req.get("prompt")
                    max_new = int(req.get("max_new", 32))
                    stream = bool(req.get("stream", False))
                    if (not isinstance(prompt, list) or not prompt
                            or not all(isinstance(t, int) for t in prompt)):
                        raise ValueError("prompt must be a non-empty "
                                         "list of ints")
                    if max_new < 1:
                        raise ValueError("max_new must be >= 1")
                    cfg = frontend.engine.cfg
                    if len(prompt) + max_new > cfg.max_seq:
                        raise ValueError(
                            f"prompt {len(prompt)} + max_new {max_new} "
                            f"exceeds the cache ({cfg.max_seq})")
                except (ValueError, json.JSONDecodeError) as e:
                    self._json(400, {"error": str(e)})
                    return
                ctx = parse_header(self.headers.get(TRACE_HEADER))
                pending = _Pending(prompt, max_new, trace=ctx)
                pending.on_finish = frontend._finalize
                if not frontend._enqueue(pending):
                    now = time.perf_counter()
                    frontend.tracer.record(
                        "serve.admission", now, now, parent=ctx,
                        terminal=True, status="shed")
                    self._json(503, {"error": "queue full"},
                               {"Retry-After": "1"})
                    return
                if stream:
                    self._stream(pending)
                else:
                    self._unary(pending)

            def _prefix(self) -> None:
                if not callable(getattr(frontend.engine,
                                        "export_prefix", None)):
                    self._json(404, {"error": "engine has no prefix "
                                              "export"})
                    return
                try:
                    n = int(self.headers.get("Content-Length", "0"))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    prompt = req.get("prompt")
                    if (not isinstance(prompt, list) or not prompt
                            or not all(isinstance(t, int)
                                       for t in prompt)):
                        raise ValueError("prompt must be a non-empty "
                                         "list of ints")
                except (ValueError, json.JSONDecodeError) as e:
                    self._json(400, {"error": str(e)})
                    return
                job = _Export([int(t) for t in prompt])
                try:
                    frontend._exports.put_nowait(job)
                except queue.Full:
                    self._json(503, {"error": "export queue full"},
                               {"Retry-After": "1"})
                    return
                frontend._wake.set()
                # an externally driven engine (start(drive=False)) never
                # drains exports; the wait bounds that to a 503, and the
                # asker's recompute fallback covers it
                if not job.done.wait(frontend.export_timeout_s):
                    self._json(503, {"error": "prefix export timed out"},
                               {"Retry-After": "1"})
                    return
                if job.error:
                    self._json(500, {"error": job.error})
                    return
                if job.span is None:
                    self._json(404, {"error": "no resident prefix"})
                    return
                body = pack_span(job.span)
                self.send_response(200)
                self.send_header("Content-Type",
                                 "application/octet-stream")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _unary(self, pending: _Pending) -> None:
                if not pending.done.wait(frontend.request_timeout_s):
                    self._json(504, {"error": "request timed out"})
                    return
                if pending.error:
                    self._json(500, {"error": pending.error})
                    return
                self._json(200, {"tokens": pending.tokens,
                                 **pending.timings_ms()})

            def _stream(self, pending: _Pending) -> None:
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()

                def chunk(obj: dict) -> None:
                    data = (json.dumps(obj) + "\n").encode()
                    self.wfile.write(f"{len(data):x}\r\n".encode()
                                     + data + b"\r\n")

                deadline = time.time() + frontend.request_timeout_s
                finished = False
                while time.time() < deadline:
                    try:
                        tok = pending.events.get(timeout=1.0)
                    except queue.Empty:
                        continue
                    if tok is None:
                        finished = True
                        break
                    chunk({"token": tok})
                if pending.error:
                    chunk({"done": True, "error": pending.error})
                elif not finished:
                    # a deadline-truncated stream must NOT read as a
                    # complete one (the unary path 504s here)
                    chunk({"done": True, "error": "request timed out"})
                else:
                    chunk({"done": True, **pending.timings_ms()})
                self.wfile.write(b"0\r\n\r\n")

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._http_thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ intake

    def _enqueue(self, pending: _Pending) -> bool:
        try:
            self._queue.put_nowait(pending)
        except queue.Full:
            with self._lock:
                self._totals["rejected"] += 1
                self._sheds.append(time.monotonic())
            self.metrics.counter("ingress.sheds")
            return False
        self._wake.set()
        return True

    def _finalize(self, pending: _Pending) -> None:
        """One-shot completion hook (``_Pending.finish``): land the
        request's latencies in the shared histograms and emit its spans
        retrospectively from the stored perf-counter stamps — queue wait,
        prefill-to-first-token, decode — chained under one terminal
        ``serve.request`` root so the trace reads end-to-end."""
        t_done = pending.t_done if pending.t_done is not None \
            else time.perf_counter()
        t_sub, t_first = pending.t_submit, pending.t_first
        m = self.metrics
        m.counter("ingress.requests_total")
        m.counter("ingress.tokens_total", len(pending.tokens))
        if pending.error:
            m.counter("ingress.request_errors")
        if t_sub is not None:
            m.observe("ingress.queue_seconds", t_sub - pending.t_enqueue)
        if t_first is not None:
            m.observe("ingress.ttft_seconds", t_first - pending.t_enqueue)
            if len(pending.tokens) > 1:
                m.observe("ingress.tpot_seconds",
                          (t_done - t_first) / (len(pending.tokens) - 1))
        status = "error" if pending.error else "ok"
        attrs = {"tokens": len(pending.tokens)}
        if pending.error:
            attrs["error"] = pending.error
        root = self.tracer.record(
            "serve.request", pending.t_enqueue, t_done,
            parent=pending.trace, terminal=True, status=status, **attrs)
        if t_sub is not None:
            self.tracer.record("serve.queue_wait", pending.t_enqueue,
                               t_sub, parent=root)
            if t_first is not None:
                self.tracer.record("serve.first_token", t_sub, t_first,
                                   parent=root)
                self.tracer.record("serve.decode", t_first, t_done,
                                   parent=root, tokens=len(pending.tokens))

    # ------------------------------------------------------- engine loop

    def _fill_slots(self) -> bool:
        filled = False
        while self.engine.free_slots():
            budget = len(self.engine.free_slots()) - len(self._backlog)
            batch = self._backlog + (self.drain_intake(budget)
                                     if budget > 0 else [])
            self._backlog = []
            if not batch:
                break
            now = time.perf_counter()
            items = []
            for pending in batch:
                if pending.t_submit is None:
                    pending.t_submit = now
                items.append({"prompt": pending.prompt,
                              "max_new": pending.max_new,
                              "request_id": pending})
            try:
                # batched admission: O(log n) prefill dispatches; the
                # engine's own predicate fails bad items ALONE
                # (validated at POST too, but one copy rules)
                placed = self.engine.submit_many(
                    items,
                    on_invalid=lambda item, reason:
                        item["request_id"].finish(reason))
                for slot, pending in placed:
                    self._live[slot] = pending
            except Exception as e:
                # dequeued but possibly not yet in _live: fail them
                # HERE or the clients hang to their timeout
                # (_fail_inflight only sees _live) — then re-raise so
                # _run_engine resets the engine (the dispatch may have
                # invalidated the cache)
                for item in items:
                    item["request_id"].finish(f"engine error: {e}")
                raise
            # unadmitted + not-failed items wait for capacity (pages or
            # slots), retried first next fill — NEVER dropped
            placed_ids = {id(p) for _, p in placed}
            self._backlog = [p for p in batch
                             if id(p) not in placed_ids
                             and not p.done.is_set()]
            self._sync()                # instant retire (max_new == 1)
            if not placed:
                break                    # no capacity: retry next tick
            filled = True
        return filled

    def _sync(self) -> None:
        """Fan freshly decoded tokens out to their request streams and
        resolve completions (engine thread only)."""
        for slot, pending in list(self._live.items()):
            r = self.engine.requests[slot]
            if r is not None and r.request_id is pending:
                if len(r.tokens) > pending.emitted:
                    pending.push(r.tokens[pending.emitted:])
                    pending.emitted = len(r.tokens)
                continue
            toks = self.engine.finished.pop(pending, None)
            if toks is not None and len(toks) > pending.emitted:
                pending.push(toks[pending.emitted:])
                pending.emitted = len(toks)
            del self._live[slot]
            # finish() first: timings_ms() only reports tpot once t_done
            # is stamped, so the stats window must read AFTER it
            pending.finish()
            with self._lock:
                self._totals["requests"] += 1
                self._totals["tokens"] += len(pending.tokens)
                t = pending.timings_ms()
                self._window.append((time.monotonic(), t.get("ttft_ms"),
                                     t.get("tpot_ms")))

    def _serve_exports(self) -> None:
        """Drain ``/v1/prefix`` jobs (engine thread only — the export
        gathers device pages with radix references held, so it runs
        where every other engine dispatch runs). Export is a pure read:
        a failure answers that one job and never resets the engine."""
        while True:
            try:
                job = self._exports.get_nowait()
            except queue.Empty:
                return
            try:
                job.span = self.engine.export_prefix(job.prompt)
                if job.span is not None:
                    self.metrics.counter("ingress.prefix_exports")
            except Exception as e:
                job.error = f"export error: {e}"
            finally:
                job.done.set()

    def _run_engine(self) -> None:
        while not self._stop.is_set():
            try:
                self._serve_exports()
                filled = self._fill_slots()
                if self.engine.requests_active():
                    self.engine.step_many(self._decode_window)
                    self._sync()
                elif not filled:
                    self._wake.wait(self._idle_sleep_s * 50)
                    self._wake.clear()
            except Exception as e:          # keep serving: only the
                # scheduler's health machinery should kill this task.
                # In-flight requests fail (their state is gone), the
                # engine RESETS (the jitted step donates the cache, so
                # after a failed dispatch the old buffer is invalid),
                # and the loop accepts new work.
                self._fail_inflight(f"engine error: {e}")

    def _fail_inflight(self, error: str) -> None:
        for pending in list(self._live.values()):
            pending.finish(error)
        self._live.clear()
        with self._lock:
            self._totals["errors"] = self._totals.get("errors", 0) + 1
        try:
            self.engine.reset()
        except Exception:
            # a reset failure leaves the engine unusable; surface via
            # health (engine thread exits -> ok: false -> readiness
            # fails -> the scheduler restarts the pod)
            raise

    # ---------------------------------------------------------- lifecycle

    def start(self, drive: bool = True) -> "ServingFrontend":
        """``drive=False`` starts the HTTP listener only — an external
        driver owns the engine (the multi-process gang loop,
        ``models/serving_gang.py``) and calls :meth:`mark_driven`."""
        if drive:
            self._engine_thread = threading.Thread(
                target=self._run_engine, daemon=True,
                name="serving-engine")
            self._engine_thread.start()
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="serving-http")
        self._http_thread.start()
        return self

    def mark_driven(self) -> None:
        """External drivers stamp liveness each iteration; health stays
        ok while the stamp is fresh (``driven_ttl_s`` — generous by
        default so a first-request compile inside one iteration does
        not flap health)."""
        self._driven_at = time.monotonic()

    driven_ttl_s: float = 600.0

    # ---- external-driver interface (the gang loop, serving_gang.py) ----

    def drain_intake(self, budget: int):
        """Pop up to ``budget`` queued requests for an external driver.
        Returns the pending objects; the driver submits them and calls
        :meth:`attach` with the slot each landed in."""
        out = []
        while len(out) < budget:
            try:
                out.append(self._queue.get_nowait())
            except queue.Empty:
                break
        return out

    def attach(self, slot: int, pending: "_Pending") -> None:
        """Bind a submitted request to its slot and fan out anything the
        submit already produced (first token / instant retire)."""
        self._live[slot] = pending
        self._sync()

    def sync(self) -> None:
        """Fan freshly decoded tokens out to request streams (public
        wrapper for external drivers)."""
        self._sync()

    def fail_inflight(self, error: str) -> None:
        """Fail every in-flight request and reset the engine (public
        wrapper for external drivers)."""
        self._fail_inflight(error)

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._engine_thread:
            self._engine_thread.join(timeout=10)
        # fail anything still queued or in flight so no client hangs
        while True:
            try:
                self._queue.get_nowait().finish("server stopped")
            except queue.Empty:
                break
        for pending in self._backlog:
            pending.finish("server stopped")
        self._backlog = []
        for pending in list(self._live.values()):
            pending.finish("server stopped")
        self._live.clear()
        if self._own_metrics:
            self.metrics.close()

    # ------------------------------------------------------------- status

    def health(self) -> dict:
        alive = (self._engine_thread is not None
                 and self._engine_thread.is_alive())
        driven_at = getattr(self, "_driven_at", None)
        if not alive and driven_at is not None:
            # externally-driven (gang loop): fresh stamp == serving
            alive = time.monotonic() - driven_at < self.driven_ttl_s
        out = {"ok": alive, "slots": self.engine.slots,
               "free": len(self.engine.free_slots()),
               "queued": self._queue.qsize()}
        if hasattr(self.engine, "pages_free"):
            # paged engines admit on pages: surface the real
            # utilization signal (autoscalers key off this, not slots)
            out["pages_free"] = self.engine.pages_free()
        out["load"] = self.load_gauges()
        return out

    def load_gauges(self) -> dict:
        """Time-windowed back-pressure signals over the last ``window_s``
        seconds — the autoscaler contract (``scheduler/elastic.py``
        ``backpressure()`` consumes exactly these keys). Served in the
        ``/v1/healthz`` body and under ``stats()["window"]``."""
        now = time.monotonic()
        horizon = now - self.window_s
        with self._lock:
            shed = sum(1 for t in self._sheds if t >= horizon)
            recent = [e for e in self._window if e[0] >= horizon]
        completed = len(recent)
        ttft = [t for _, t, _ in recent if t is not None]
        out = {
            "window_s": self.window_s,
            "queue_depth": self._queue.qsize(),
            "queue_capacity": self.max_queue,
            "completed": completed,
            "shed": shed,
            # fraction of window arrivals turned away at the door
            "shed_rate": shed / max(1, shed + completed),
            "ttft_p95_ms": _percentiles(ttft).get("p95"),
        }
        if hasattr(self.engine, "pages_free"):
            out["pages_free"] = self.engine.pages_free()
            ledger = getattr(self.engine, "ledger", None)
            if ledger is not None:
                out["pages_total"] = ledger.pages
        tiers = getattr(self.engine, "tiers", None)
        if tiers is not None:
            # tiered KV engine: surface host/disk occupancy + traffic so
            # the autoscaler's backpressure() and the router's spill
            # logic see cold-tier pressure, not just HBM pages
            ts = tiers.stats()
            out["kv_tier_host_pages"] = ts["host_pages"]
            out["kv_tier_host_capacity"] = ts["host_capacity"]
            out["kv_tier_disk_pages"] = ts["disk_pages"]
            out["kv_tier_disk_capacity"] = ts["disk_capacity"]
            out["kv_tier_hits"] = ts["host_hits"] + ts["disk_hits"]
            out["kv_tier_promoted"] = getattr(self.engine,
                                              "tier_promoted_pages", 0)
            out["kv_tier_demoted"] = getattr(self.engine,
                                             "tier_demoted_pages", 0)
        if getattr(self.engine, "spec_windows", 0) or \
                getattr(self.engine, "draft_k", 0):
            # speculative decode armed (or armed once and disarmed): the
            # accept rate is the engine's speed multiplier — tokens per
            # target pass is 1 + accept_rate * (k - 1) — so the
            # autoscaler/router must see it next to the queue gauges
            proposed = getattr(self.engine, "spec_proposed", 0)
            out["spec_windows"] = self.engine.spec_windows
            out["spec_proposed"] = proposed
            out["spec_accepted"] = getattr(self.engine, "spec_accepted", 0)
            out["spec_accept_rate"] = (
                out["spec_accepted"] / proposed if proposed else 0.0)
            out["spec_fallbacks"] = getattr(self.engine,
                                            "spec_fallbacks", 0)
        return out

    def stats(self) -> dict:
        with self._lock:
            totals = dict(self._totals)
            window = list(self._window)
        ttft = [t for _, t, _ in window if t is not None]
        tpot = [t for _, _, t in window if t is not None]
        return {**totals, "queued": self._queue.qsize(),
                "ttft_ms": _percentiles(ttft),
                "tpot_ms": _percentiles(tpot),
                "window": self.load_gauges()}
