"""MNIST MLP — the minimum end-to-end workload (BASELINE.json config #3).

Single chip, no collectives: this is the model the `frameworks/jax` service
deploys to prove the whole slice (spec -> plan -> match -> launch ->
bootstrap -> train) before any parallelism is involved (SURVEY.md §7 step 9a).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from dcos_commons_tpu.ops import softmax_cross_entropy

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class MLPConfig:
    in_dim: int = 784
    hidden: Tuple[int, ...] = (512, 256)
    n_classes: int = 10
    dtype: Any = jnp.bfloat16


def init_params(cfg: MLPConfig, key: jax.Array) -> Params:
    dims = (cfg.in_dim,) + cfg.hidden + (cfg.n_classes,)
    keys = jax.random.split(key, len(dims) - 1)
    return {
        f"layer{i}": {
            "w": (jax.random.normal(k, (din, dout), jnp.float32)
                  * (2.0 / din) ** 0.5).astype(cfg.dtype),
            "b": jnp.zeros((dout,), cfg.dtype),
        }
        for i, (k, din, dout) in enumerate(zip(keys, dims[:-1], dims[1:]))
    }


def forward(cfg: MLPConfig, params: Params, x: jnp.ndarray) -> jnp.ndarray:
    """x [B, in_dim] -> logits [B, n_classes] fp32."""
    x = x.astype(cfg.dtype)
    n = len(params)
    for i in range(n):
        lp = params[f"layer{i}"]
        x = x @ lp["w"] + lp["b"]
        if i < n - 1:
            x = jax.nn.relu(x)
    return x.astype(jnp.float32)


def loss_fn(cfg: MLPConfig, params: Params, batch: Tuple[jnp.ndarray,
            jnp.ndarray]) -> Tuple[jnp.ndarray, jnp.ndarray]:
    x, y = batch
    return softmax_cross_entropy(forward(cfg, params, x), y)
