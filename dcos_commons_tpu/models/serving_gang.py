"""Multi-process (gang) serving: rank-0 request broadcast.

A tensor-parallel gang spanning PROCESSES (one `jax.distributed` mesh
over many hosts) executes SPMD: every process must issue the SAME
engine calls with the SAME values, or the lock-step collectives
diverge. A per-process HTTP ingress therefore cannot drive the slot
engine directly — the round-4 verdict's "rank-0 request broadcast is
the missing piece". This module is that piece:

* Rank 0 runs the HTTP front door (``models/ingress.py``) WITHOUT its
  engine thread; every other rank runs nothing client-facing.
* All ranks run :class:`GangServingDriver.run`'s loop in lock-step.
  Each iteration: rank 0 drains up to ``min(free slots, max_intake)``
  queued requests into a FIXED-SHAPE int32 intake array; the array is
  ``broadcast_one_to_all``; every rank decodes it and makes identical
  ``engine.submit`` calls (slot choice is deterministic — first free
  slot), then one identical ``engine.step_many`` advances the pool.
  Rank 0 fans tokens back to its HTTP clients; peers discard.
* The broadcast is the rendezvous: idle iterations still broadcast an
  empty intake, so no rank ever waits on a collective the others
  skipped.

Determinism requirements (asserted in tests): greedy decoding, or a
sampler constructed with the same seed on every rank — the key stream
then advances identically inside the jitted steps, so retirements and
slot assignments stay rank-identical.

Paged engines ride the same loop unchanged, and that is what makes the
round-18 serving arithmetic gang-safe: a :class:`PagedServer` armed
with ``longctx_ring`` decides ring-vs-chunked prefill from
rank-identical host state (prompt length, prefill position, radix
share), so every rank enters the SAME ``prefill_ring`` collective in
the same iteration — the sequence-parallel prefill is just another
lock-step dispatch, and its ~seq/N per-host time is why the gang takes
it. Ring-prefilled K/V spans land in each member's LOCAL page pool via
the same install path the KVSPAN/``pack_span`` adoption channel uses,
so decode gathers never leave the host. MoE engines (``moe=``) need
nothing extra either: expert dispatch all-to-alls live inside the
step/chunk executables every rank already issues together. The one
sharp edge is fallback divergence — a rank that silently degraded to
chunked prefill while its peers ring would deadlock the gang — which
is why ``PagedServer._ring_prefill`` disqualifies on host-side config
checks that are pure functions of the broadcast intake, and why
:meth:`GangServingDriver.stats` surfaces ``longctx.fallbacks`` so a
nonzero count on any member is loud in the heartbeat stream.

Wire format (``encode_intake``/``decode_intake``): int32
``[max_intake, 2 + max_prompt]``; row = (prompt_len, max_new,
prompt..., 0 padding); prompt_len == 0 terminates.
"""

from __future__ import annotations

import time
from typing import List, Optional, Tuple

import numpy as np

from dcos_commons_tpu.models.ingress import ServingFrontend, _Pending
from dcos_commons_tpu.models.serving import SlotServer


def encode_intake(items: List[Tuple[List[int], int]], max_intake: int,
                  max_prompt: int) -> np.ndarray:
    """[(prompt, max_new), ...] -> fixed-shape int32 intake array."""
    if len(items) > max_intake:
        raise ValueError(f"{len(items)} submissions > max_intake "
                         f"{max_intake}")
    arr = np.zeros((max_intake, 2 + max_prompt), np.int32)
    for i, (prompt, max_new) in enumerate(items):
        n = len(prompt)
        if not 0 < n <= max_prompt:
            raise ValueError(f"prompt length {n} not in (0, {max_prompt}]")
        arr[i, 0] = n
        arr[i, 1] = max_new
        arr[i, 2:2 + n] = prompt
    return arr


def decode_intake(arr: np.ndarray) -> List[Tuple[List[int], int]]:
    out = []
    for row in np.asarray(arr):
        n = int(row[0])
        if n == 0:
            break
        out.append(([int(t) for t in row[2:2 + n]], int(row[1])))
    return out


class GangServingDriver:
    """Lock-step serving loop for one member of a multi-process gang.

    Rank 0 passes its :class:`ServingFrontend` (started with
    ``drive=False``); peers pass ``frontend=None``. Every rank passes
    an identically-configured :class:`SlotServer` (same seed) over the
    same global mesh.
    """

    def __init__(self, engine: SlotServer,
                 frontend: Optional[ServingFrontend], *,
                 num_processes: int, process_id: int,
                 decode_window: int = 8, max_intake: int = 4,
                 max_prompt: Optional[int] = None,
                 idle_sleep_s: float = 0.02):
        if (frontend is not None) != (process_id == 0):
            raise ValueError("exactly rank 0 owns the HTTP frontend")
        self.engine = engine
        self.frontend = frontend
        self.num_processes = num_processes
        self.process_id = process_id
        self.decode_window = max(1, decode_window)
        self.max_intake = max_intake
        # default: the full cache width — anything the POST validation
        # accepted (prompt + max_new <= max_seq) fits the wire format,
        # so no second, surprising limit exists
        self.max_prompt = (min(max_prompt, engine.cfg.max_seq - 1)
                           if max_prompt is not None
                           else engine.cfg.max_seq - 1)
        self._idle_sleep_s = idle_sleep_s
        self._stop = False
        self.iterations = 0
        self.errors = 0
        # rank 0 only: drained but unadmitted pendings (a paged engine
        # admits a FIFO prefix under page pressure) — re-broadcast FIRST
        # next iteration; every rank re-submits the same prefix, so the
        # gang stays deterministic and no client is silently dropped
        self._backlog: List[_Pending] = []

    # ------------------------------------------------------------- loop

    def _broadcast(self, arr: np.ndarray) -> np.ndarray:
        if self.num_processes <= 1:
            return arr
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.broadcast_one_to_all(arr))

    def run_iteration(self) -> bool:
        """One lock-step iteration; returns True if any work happened."""
        fe = self.frontend
        pendings: List[_Pending] = []
        if fe is not None:
            # stamp BEFORE the work: a first-request compile lives
            # inside this iteration and must not flap health
            fe.mark_driven()
            pendings.extend(self._backlog)
            self._backlog = []
            budget = (min(self.max_intake, len(self.engine.free_slots()))
                      - len(pendings))
            for p in fe.drain_intake(max(0, budget)):
                if len(p.prompt) > self.max_prompt:
                    # unreachable with the default (full cache width);
                    # a narrowed wire format fails loudly, not silently
                    p.finish(f"prompt exceeds gang max_prompt "
                             f"{self.max_prompt}")
                    continue
                pendings.append(p)
            arr = encode_intake([(p.prompt, p.max_new) for p in pendings],
                                self.max_intake, self.max_prompt)
        else:
            arr = np.zeros((self.max_intake, 2 + self.max_prompt),
                           np.int32)
        arr = self._broadcast(arr)
        items = decode_intake(arr)
        if items:
            now = time.perf_counter()
            subs = []
            for j, (prompt, max_new) in enumerate(items):
                rid = pendings[j] if fe is not None else object()
                if fe is not None:
                    pendings[j].t_submit = now
                subs.append({"prompt": prompt, "max_new": max_new,
                             "request_id": rid})
            # ONE batched admission on every rank: identical items in
            # identical order -> identical slot choices + dispatches.
            # Both engines admit a FIFO prefix of the batch, so
            # pendings[len(placed):] is exactly the unadmitted tail.
            placed = self.engine.submit_many(subs)
            if fe is not None:
                for slot, rid in placed:
                    fe.attach(slot, rid)         # incl. instant retire
                self._backlog = pendings[len(placed):]
        worked = bool(items)
        if self.engine.requests_active():
            self.engine.step_many(self.decode_window)
            if fe is not None:
                fe.sync()
            worked = True
        if fe is None:
            # peers have no frontend popping SlotServer.finished —
            # without this, every retired request leaks a host-side
            # entry forever on every non-zero rank
            self.engine.finished.clear()
        self.iterations += 1
        return worked

    def stats(self) -> dict:
        """Heartbeat payload: loop counters plus the engine's paged /
        MoE / longctx counters when the engine exposes them. Lock-step
        makes the engine numbers rank-identical, so any member's
        heartbeat describes the gang's shared schedule — EXCEPT
        ``pages.longctx.fallbacks`` / ``errors``, which are the
        per-member divergence canaries monitoring watches."""
        out = {"gang_iterations": self.iterations,
               "gang_errors": self.errors,
               "process_id": self.process_id,
               "backlog": len(self._backlog)}
        page_stats = getattr(self.engine, "page_stats", None)
        if callable(page_stats):
            out["pages"] = page_stats()
        if self.frontend is not None:
            out.update(self.frontend.stats())
        return out

    def run(self, max_iterations: Optional[int] = None,
            heartbeat_s: float = 0.0, on_heartbeat=None) -> None:
        """Drive until stopped (or ``max_iterations``, for tests).
        ``on_heartbeat(stats_dict)`` fires every ``heartbeat_s`` with
        :meth:`stats` (every rank; rank 0's payload includes the
        frontend counters)."""
        last_beat = time.monotonic()
        while not self._stop:
            if max_iterations is not None \
                    and self.iterations >= max_iterations:
                return
            try:
                worked = self.run_iteration()
            except Exception as e:   # keep serving: transient dispatch
                # failures must not tear the gang down. A failed
                # collective surfaces on EVERY rank (the transport
                # errors propagate), so each rank fails its in-flight
                # work, resets its engine to the empty pool, and meets
                # the others again at the next broadcast.
                self.errors += 1
                if self.frontend is not None:
                    self.frontend.fail_inflight(f"engine error: {e}")
                else:
                    self.engine.reset()
                worked = False
            if not worked:
                # the broadcast above is the rendezvous; idle ranks
                # sleep the same nominal interval and meet again
                time.sleep(self._idle_sleep_s)
            if heartbeat_s and on_heartbeat is not None \
                    and time.monotonic() - last_beat >= heartbeat_s:
                last_beat = time.monotonic()
                on_heartbeat(self.stats())

    def stop(self) -> None:
        self._stop = True
        for p in self._backlog:
            p.finish("server stopped")
        self._backlog = []
