"""Speculative decoding: a draft model proposes, the target verifies K
tokens per weight pass — greedy exact-match or sampled rejection
acceptance.

Decode at real model sizes is weight-streaming bound — every emitted
token streams the full weight set. Speculative decoding breaks that
coupling: a cheap draft decodes K candidate tokens autoregressively,
then the target consumes all K in ONE ``llama.extend_step`` forward
(weights stream once) and keeps the longest prefix it agrees with, plus
its own correction token. Per target weight pass the stream advances by
``1 + (accepted prefix)`` tokens; the output is **the target's greedy
stream no matter how bad the draft is** — acceptance only sets the
speed, never the text. (Precisely: token-exact wherever the argmax
margin exceeds the bf16 rounding difference between the K-wide verify
matmul and solo decode's 1-wide matmul — always, for peaked
trained-model logits; random-init near-uniform logits can flip a
near-tie, which the tests account for.)

Why rollback is free here: both models' caches are fixed ``max_seq``
buffers with masked reads (``kv_len``) — rows written for rejected
candidates sit beyond the live length, are never attended, and are
overwritten when decoding reaches them. Rejection is just "don't
advance the host-side position".

**Sampled acceptance** (``temperature > 0``) is Leviathan-style
rejection sampling: proposal ``x_i ~ q_i`` is accepted with probability
``min(1, p_i(x_i) / q_i(x_i))``; the first rejection resamples from the
residual ``normalize(max(p_i - q_i, 0))``, and a fully-accepted window
earns a bonus token from ``p_K``. The emitted marginal is EXACTLY the
target's (tempered) sampling distribution regardless of the draft — the
classic speculative-sampling theorem; :func:`rejection_step` is the
per-position primitive and is distribution-tested directly.

**Drafts that exist without a trained checkpoint**: ``llama.
truncate_layers`` (layer-skip self-speculation — near-chance acceptance
on an untrained target, included for the mechanism) and the int8
self-draft (same model, quantized weights: ~half the HBM bytes per
draft step, near-1 acceptance — tools/bench_speculative.py measures the
net tok/s).

The reference repo (a cluster scheduler) ships no serving stack; this
is workload-layer capability for BASELINE.json config #5 (the 8B
flagship is the intended target model, with a 400m-class draft).
"""

from __future__ import annotations

import functools
import json
import os
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dcos_commons_tpu.models import llama
from dcos_commons_tpu.ops import rope_frequencies

Params = llama.Params


def _softmax(logits: np.ndarray) -> np.ndarray:
    x = logits.astype(np.float64)
    x = x - x.max(axis=-1, keepdims=True)
    e = np.exp(x)
    return e / e.sum(axis=-1, keepdims=True)


def rejection_step(p: np.ndarray, q: np.ndarray, x: int,
                   rng: np.random.Generator) -> tuple[int, bool]:
    """One position of speculative rejection sampling.

    ``p``/``q``: target/draft probability rows over the vocab; ``x``:
    the draft's proposal (sampled from ``q``). Returns (token,
    accepted). The emitted token's marginal distribution is exactly
    ``p`` — accept w.p. min(1, p(x)/q(x)), else resample from the
    residual normalize(max(p - q, 0)) (Leviathan et al.; the theorem
    is distribution-tested in tests/test_speculative.py).
    """
    if rng.random() < min(1.0, float(p[x]) / max(float(q[x]), 1e-30)):
        return int(x), True
    resid = np.maximum(p - q, 0.0)
    total = resid.sum()
    probs = p if total <= 0.0 else resid / total
    return int(rng.choice(len(probs), p=probs)), False


class SpeculativeDecoder:
    """Speculative decoding for batch-1 serving (the latency case
    K-token verification exists for). ``temperature == 0`` (default) is
    greedy exact-match acceptance; ``temperature > 0`` is sampled
    rejection acceptance over the tempered distributions."""

    def __init__(self, cfg_t: llama.LlamaConfig, params_t: Params,
                 cfg_d: llama.LlamaConfig, params_d: Params, k: int = 4,
                 temperature: float = 0.0, seed: int = 0):
        if cfg_t.vocab_size != cfg_d.vocab_size:
            raise ValueError("draft and target must share a vocabulary")
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        if temperature < 0.0:
            raise ValueError(f"temperature must be >= 0, got {temperature}")
        self.cfg_t, self.params_t = cfg_t, params_t
        self.cfg_d, self.params_d = cfg_d, params_d
        self.k = k
        self.temperature = temperature
        self._rng = np.random.default_rng(seed)
        rope_t = rope_frequencies(cfg_t.head_dim, cfg_t.max_seq,
                                  cfg_t.rope_theta)
        rope_d = rope_frequencies(cfg_d.head_dim, cfg_d.max_seq,
                                  cfg_d.rope_theta)
        self._prefill_t = llama._stepwise_executables(cfg_t, None)[0]
        self._prefill_d = llama._stepwise_executables(cfg_d, None)[0]
        # the draft chunk runs k steps, consuming [cur, d_1..d_{k-1}]:
        # that writes the draft cache row for EVERY window position, so
        # a fully-accepted window leaves no K/V hole at pos+k-1 (the
        # k-th proposal itself is discarded — it exists to write d_{k-1}
        # into the cache). The verify window is [cur, d_1..d_{k-1}].
        # Sampled mode: the draft SAMPLES proposals from its tempered
        # distribution and returns the per-step logits (q_i for the
        # rejection test); the extra [k, V] output is noise next to the
        # weight streaming either path pays.
        if k > 1 and temperature > 0.0:
            from dcos_commons_tpu.ops.sampling import make_sampler
            sampler = make_sampler(temperature)
            self._draft_x = jax.jit(
                lambda p, c, pos, tok, key: llama.decode_chunk_logits(
                    self.cfg_d, p, c, pos, tok, self.k, rope=rope_d,
                    sampler=sampler, key=key))
        elif k > 1:
            self._draft_x = jax.jit(
                lambda p, c, pos, tok: llama.decode_chunk(
                    self.cfg_d, p, c, pos, tok, self.k, rope=rope_d))
        else:
            self._draft_x = None
        self._verify_x = jax.jit(lambda p, c, toks, pos: llama.extend_step(
            self.cfg_t, p, c, toks, pos, rope=rope_t))
        self._fused_x: Dict[int, Any] = {}     # steps -> one-dispatch loop

    def generate_fused(self, prompt: jnp.ndarray, steps: int
                       ) -> Tuple[jnp.ndarray, Dict[str, float]]:
        """Greedy speculative decoding as ONE device program.

        :meth:`generate` syncs with the host every verify pass (the
        accept decision), so on dispatch-heavy paths (tunneled
        backends: ~100 ms+ per round trip) the sync — not the chip —
        bounds throughput (measured: 24 tok/s vs 659 solo at 400m
        through the tunnel, at 0.69 acceptance). This variant runs
        draft + verify + acceptance inside a ``lax.while_loop``: the
        accept test is an argmax compare on device, emitted tokens land
        in a fixed [steps+k] buffer via ``dynamic_update_slice`` (a
        pass writes its whole window; only ``accepted+1`` of it is
        advanced over, and the next pass overwrites the rest), and the
        host syncs ONCE for the final buffer. Greedy only —
        sampled/rejection acceptance keeps the host loop.
        """
        if self.temperature > 0.0:
            raise ValueError("generate_fused is greedy-only; sampled "
                             "acceptance uses generate()")
        if self.k < 2:
            raise ValueError("generate_fused needs k >= 2")
        b, s = prompt.shape
        if b != 1:
            raise ValueError("speculative decoding is batch-1")
        need = s + steps + self.k
        if need > self.cfg_t.max_seq or need > self.cfg_d.max_seq:
            raise ValueError(
                f"prompt {s} + steps {steps} + k {self.k} exceeds "
                f"max_seq (target {self.cfg_t.max_seq}, draft "
                f"{self.cfg_d.max_seq})")
        x = self._fused_x.get(steps)
        if x is None:
            # both caches donated: they dominate HBM at real presets and
            # the while_loop works on its own copies — without donation
            # XLA holds input + working buffers live across the longest
            # dispatch in the system
            x = jax.jit(functools.partial(self._fused_loop, steps=steps),
                        donate_argnums=(2, 3))
            self._fused_x[steps] = x
        cache_t = llama.init_kv_cache(self.cfg_t, 1, self.cfg_t.max_seq)
        cache_d = llama.init_kv_cache(self.cfg_d, 1, self.cfg_d.max_seq)
        lt, cache_t = self._prefill_t(self.params_t, cache_t, prompt)
        _, cache_d = self._prefill_d(self.params_d, cache_d, prompt)
        out, n_out, passes = x(self.params_t, self.params_d, cache_t,
                               cache_d, lt, jnp.int32(s))
        toks = np.asarray(out)[:steps]              # the ONE host sync
        passes = int(passes)
        # n_out counts the prefill token (slot 0); pass emissions are
        # n_out - 1, of which one per pass is the target's own token
        proposed = passes * (self.k - 1)
        accepted = int(n_out) - 1 - passes
        stats = {"verify_passes": passes,
                 "tokens_per_pass": round(steps / max(passes, 1), 3),
                 "proposed": proposed, "accepted": accepted,
                 "accept_rate": round(accepted / max(proposed, 1), 4),
                 "temperature": 0.0, "k": self.k, "fused": True}
        return jnp.asarray([toks], jnp.int32), stats

    def _fused_loop(self, params_t, params_d, cache_t, cache_d,
                    prefill_logits, pos0, *, steps: int):
        """Traced body of :meth:`generate_fused`."""
        k = self.k
        cfg_t, cfg_d = self.cfg_t, self.cfg_d
        rope_t = rope_frequencies(cfg_t.head_dim, cfg_t.max_seq,
                                  cfg_t.rope_theta)
        rope_d = rope_frequencies(cfg_d.head_dim, cfg_d.max_seq,
                                  cfg_d.rope_theta)
        cur0 = jnp.argmax(prefill_logits, axis=-1).astype(jnp.int32)  # [1]
        out0 = jnp.zeros((steps + k,), jnp.int32)
        # the prefill's token is emission #1
        out0 = out0.at[0].set(cur0[0])

        def cond(c):
            return c[0] < steps

        def body(c):
            n_out, pos, cur, cache_t, cache_d, out, passes = c

            def dstep(carry, i):
                cache_d, tok = carry
                lg, cache_d = llama.decode_step(cfg_d, params_d,
                                                cache_d, pos + i, tok,
                                                rope=rope_d)
                nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
                return (cache_d, nxt), nxt

            (cache_d, _), dtoks = lax.scan(dstep, (cache_d, cur),
                                           jnp.arange(k))
            dtoks = dtoks[:, 0]                          # [k]
            window = jnp.concatenate([cur, dtoks[:k - 1]])[None, :]
            logits, cache_t = llama.extend_step(cfg_t, params_t,
                                                cache_t, window, pos,
                                                rope=rope_t)
            tgt = jnp.argmax(logits[0], axis=-1).astype(jnp.int32)  # [k]
            agree = jnp.cumprod(
                (dtoks[:k - 1] == tgt[:k - 1]).astype(jnp.int32))
            n_emit = jnp.sum(agree) + 1                  # 1..k
            out = lax.dynamic_update_slice(out, tgt, (n_out,))
            cur = lax.dynamic_index_in_dim(tgt, n_emit - 1,
                                           keepdims=True)
            return (n_out + n_emit, pos + n_emit, cur, cache_t,
                    cache_d, out, passes + 1)

        n_out, _, _, _, _, out, passes = lax.while_loop(
            cond, body, (jnp.int32(1), pos0, cur0, cache_t, cache_d,
                         out0, jnp.int32(0)))
        return out, n_out, passes

    def generate(self, prompt: jnp.ndarray, steps: int
                 ) -> Tuple[jnp.ndarray, Dict[str, float]]:
        """Decode ``steps`` tokens; returns (tokens [1, steps], stats).

        Greedy mode emits exactly ``llama.generate_stepwise``'s stream
        for the target model; sampled mode emits tokens whose marginal
        is the target's tempered sampling distribution (the rejection
        theorem) — acceptance only sets the speed, never the
        distribution."""
        b, s = prompt.shape
        if b != 1:
            raise ValueError("speculative decoding is batch-1")
        need = s + steps + self.k
        if need > self.cfg_t.max_seq or need > self.cfg_d.max_seq:
            raise ValueError(
                f"prompt {s} + steps {steps} + k {self.k} exceeds "
                f"max_seq (target {self.cfg_t.max_seq}, draft "
                f"{self.cfg_d.max_seq})")
        temp = self.temperature
        cache_t = llama.init_kv_cache(self.cfg_t, 1, self.cfg_t.max_seq)
        cache_d = llama.init_kv_cache(self.cfg_d, 1, self.cfg_d.max_seq)
        lt, cache_t = self._prefill_t(self.params_t, cache_t, prompt)
        _, cache_d = self._prefill_d(self.params_d, cache_d, prompt)
        if temp > 0.0:
            p0 = _softmax(np.asarray(lt[0], np.float32) / temp)
            cur = int(self._rng.choice(len(p0), p=p0))
        else:
            cur = int(jnp.argmax(lt, axis=-1)[0])
        out = [cur]
        pos = s                       # next write position (holds `cur`)
        passes = proposed = accepted = 0
        key = jax.random.key(int(self._rng.integers(2 ** 31)))
        while len(out) < steps:
            draft_logits = None
            if self._draft_x is None:
                draft_toks = []
            elif temp > 0.0:
                key, sub = jax.random.split(key)
                dtoks, dlogits, cache_d = self._draft_x(
                    self.params_d, cache_d, jnp.int32(pos),
                    jnp.asarray([cur], jnp.int32), sub)
                draft_toks = [int(t) for t in
                              np.asarray(dtoks[0])][:self.k - 1]
                draft_logits = np.asarray(dlogits[0],
                                          np.float32)[:self.k - 1]
            else:
                draft, cache_d = self._draft_x(
                    self.params_d, cache_d, jnp.int32(pos),
                    jnp.asarray([cur], jnp.int32))
                draft_toks = [int(t) for t in
                              np.asarray(draft[0])][:self.k - 1]
            window = jnp.asarray([[cur] + draft_toks], jnp.int32)
            logits, cache_t = self._verify_x(self.params_t, cache_t,
                                             window, jnp.int32(pos))
            passes += 1
            proposed += len(draft_toks)
            if temp > 0.0:
                # rejection acceptance over the tempered distributions;
                # replacement/bonus tokens land at the NEXT pass's write
                # position as `cur`, so both caches stay consistent
                p = _softmax(np.asarray(logits[0], np.float32) / temp)
                emitted = []
                for i, x in enumerate(draft_toks):
                    q = _softmax(draft_logits[i] / temp)
                    tok, ok = rejection_step(p[i], q, x, self._rng)
                    emitted.append(tok)
                    if not ok:
                        break
                    accepted += 1
                else:
                    # whole window accepted: bonus token from the
                    # target's distribution after the last proposal
                    emitted.append(int(self._rng.choice(
                        p.shape[1], p=p[len(draft_toks)])))
            else:
                target_toks = [int(t) for t in
                               np.asarray(jnp.argmax(logits[0], axis=-1))]
                # accept drafted tokens while the target agrees; the
                # token at the first disagreement is the target's own
                # choice, so every pass emits at least one
                # target-correct token
                emitted = []
                for i, t in enumerate(target_toks):
                    emitted.append(t)
                    if i >= len(draft_toks) or draft_toks[i] != t:
                        break
                accepted += len(emitted) - 1
            pos += len(emitted)
            cur = emitted[-1]
            out.extend(emitted)
        out = out[:steps]
        stats = {"verify_passes": passes,
                 "tokens_per_pass": round(len(out) / max(passes, 1), 3),
                 "proposed": proposed, "accepted": accepted,
                 "accept_rate": round(accepted / max(proposed, 1), 4),
                 "temperature": temp,
                 "k": self.k}
        return jnp.asarray([out], jnp.int32), stats


# ---------------------------------------------------------------------------
# draft artifacts: a trained draft as a loadable, compat-guarded unit

class DraftIncompatible(ValueError):
    """A draft checkpoint the serving engine must not arm, with a stable
    ``code`` the fallback path reports (``spec_fallback`` events and the
    chaos invariants key on it):

    * ``draft_config_missing`` — no ``draft_config.json`` beside the
      shards (not a draft artifact at all)
    * ``draft_manifest_stale`` — the shard manifest's digest no longer
      matches what :func:`save_draft` recorded (overwritten, truncated,
      or bit-rotted since training)
    * ``draft_vocab_mismatch`` / ``draft_rope_mismatch`` /
      ``draft_max_seq`` — the draft cannot speak for this target
    * ``draft_sampled_engine`` / ``draft_k`` — arm-time parameter
      rejections (:meth:`PagedServer.arm_draft`)

    Serving catches this and keeps decoding SOLO — a bad draft costs
    speed, never availability.
    """

    def __init__(self, code: str, msg: str):
        super().__init__(f"{code}: {msg}")
        self.code = code


_DRAFT_CFG_FIELDS = ("vocab_size", "dim", "n_layers", "n_heads",
                     "n_kv_heads", "ffn_dim", "max_seq", "rope_theta",
                     "norm_eps")


def _manifest_digest(step_dir: str) -> str:
    import hashlib
    with open(os.path.join(step_dir, "manifest.json"), "rb") as f:
        return hashlib.blake2s(f.read()).hexdigest()


def save_draft(out_dir: str, step: int, cfg_d: llama.LlamaConfig,
               params_d: Params,
               target_cfg: "llama.LlamaConfig | None" = None) -> str:
    """Persist a trained draft as a self-describing artifact: sharded
    params (``parallel.checkpoint`` format, per-shard digests) plus
    ``draft_config.json`` carrying the draft's architecture, the target
    it was distilled against, and the blake2s of the shard manifest —
    the staleness seal :func:`load_draft` verifies before serving ever
    touches the weights."""
    from dcos_commons_tpu.parallel.checkpoint import save_sharded
    step_dir = save_sharded(out_dir, step, {"params": params_d})
    meta = {
        "config": {f: getattr(cfg_d, f) for f in _DRAFT_CFG_FIELDS},
        "step": step,
        "manifest_digest": _manifest_digest(step_dir),
        "target": (None if target_cfg is None else
                   {"vocab_size": target_cfg.vocab_size,
                    "rope_theta": target_cfg.rope_theta,
                    "max_seq": target_cfg.max_seq,
                    "n_layers": target_cfg.n_layers,
                    "dim": target_cfg.dim}),
    }
    tmp = os.path.join(out_dir, ".draft_config.json.tmp")
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(meta, f, indent=2)
        f.flush()
        os.fsync(f.fileno())
    os.rename(tmp, os.path.join(out_dir, "draft_config.json"))
    return step_dir


def load_draft(path: str,
               cfg_t: "llama.LlamaConfig | None" = None
               ) -> Tuple[llama.LlamaConfig, Params, Dict[str, Any]]:
    """Load a :func:`save_draft` artifact, running every compatibility
    check that can fail BEFORE the weights reach an engine: the config
    sidecar must exist, the shard manifest must hash to the recorded
    digest (and every shard to the manifest's digests — the restore
    layer's own check), and when ``cfg_t`` is given the draft must share
    its vocabulary and rope and cover its positions. Raises
    :class:`DraftIncompatible` with a stable code on any failure;
    returns ``(cfg_d, params_d, meta)``."""
    from dcos_commons_tpu.parallel.checkpoint import (CheckpointCorrupt,
                                                      latest_step,
                                                      restore_sharded)
    cfg_path = os.path.join(path, "draft_config.json")
    if not os.path.exists(cfg_path):
        raise DraftIncompatible(
            "draft_config_missing",
            f"no draft_config.json under {path!r} — not a draft "
            "artifact")
    with open(cfg_path, encoding="utf-8") as f:
        meta = json.load(f)
    cfg_d = llama.LlamaConfig(**meta["config"])
    if cfg_t is not None:
        if cfg_d.vocab_size != cfg_t.vocab_size:
            raise DraftIncompatible(
                "draft_vocab_mismatch",
                f"draft vocab {cfg_d.vocab_size} != target "
                f"{cfg_t.vocab_size}")
        if cfg_d.rope_theta != cfg_t.rope_theta:
            raise DraftIncompatible(
                "draft_rope_mismatch",
                f"draft rope_theta {cfg_d.rope_theta} != target "
                f"{cfg_t.rope_theta}")
        if cfg_d.max_seq < cfg_t.max_seq:
            raise DraftIncompatible(
                "draft_max_seq",
                f"draft max_seq {cfg_d.max_seq} < target "
                f"{cfg_t.max_seq}")
    step = meta.get("step")
    if step is None or latest_step(path) != step:
        raise DraftIncompatible(
            "draft_manifest_stale",
            f"recorded step {step} is not the newest committed step "
            f"under {path!r} — the artifact was overwritten after "
            "save_draft sealed it")
    import jax as _jax
    pid = _jax.process_index()
    step_dir = os.path.join(path, f"step-{step:08d}-p{pid}")
    try:
        digest = _manifest_digest(step_dir)
    except OSError:
        raise DraftIncompatible(
            "draft_manifest_stale",
            f"shard manifest unreadable under {step_dir!r}") from None
    if digest != meta.get("manifest_digest"):
        raise DraftIncompatible(
            "draft_manifest_stale",
            "shard manifest digest does not match draft_config.json — "
            "the checkpoint changed after save_draft sealed it")
    template = {"params": llama.init_params(cfg_d, jax.random.key(0))}
    try:
        tree = restore_sharded(path, template, step)
    except (CheckpointCorrupt, FileNotFoundError) as e:
        raise DraftIncompatible(
            "draft_manifest_stale",
            f"draft shards failed restore: {e}") from None
    return cfg_d, tree["params"], meta
