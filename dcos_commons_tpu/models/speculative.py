"""Greedy speculative decoding: a draft model proposes, the target
verifies K tokens per weight pass.

Decode at real model sizes is weight-streaming bound — every emitted
token streams the full weight set. Speculative decoding breaks that
coupling: a cheap draft decodes K candidate tokens autoregressively,
then the target consumes all K in ONE ``llama.extend_step`` forward
(weights stream once) and keeps the longest prefix it agrees with, plus
its own correction token. Per target weight pass the stream advances by
``1 + (accepted prefix)`` tokens; the output is **the target's greedy
stream no matter how bad the draft is** — acceptance only sets the
speed, never the text. (Precisely: token-exact wherever the argmax
margin exceeds the bf16 rounding difference between the K-wide verify
matmul and solo decode's 1-wide matmul — always, for peaked
trained-model logits; random-init near-uniform logits can flip a
near-tie, which the tests account for.)

Why rollback is free here: both models' caches are fixed ``max_seq``
buffers with masked reads (``kv_len``) — rows written for rejected
candidates sit beyond the live length, are never attended, and are
overwritten when decoding reaches them. Rejection is just "don't
advance the host-side position".

The reference repo (a cluster scheduler) ships no serving stack; this
is workload-layer capability for BASELINE.json config #5 (the 8B
flagship is the intended target model, with a 400m-class draft).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dcos_commons_tpu.models import llama
from dcos_commons_tpu.ops import rope_frequencies

Params = llama.Params


class SpeculativeDecoder:
    """Greedy speculative decoding for batch-1 serving (the latency
    case K-token verification exists for)."""

    def __init__(self, cfg_t: llama.LlamaConfig, params_t: Params,
                 cfg_d: llama.LlamaConfig, params_d: Params, k: int = 4):
        if cfg_t.vocab_size != cfg_d.vocab_size:
            raise ValueError("draft and target must share a vocabulary")
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        self.cfg_t, self.params_t = cfg_t, params_t
        self.cfg_d, self.params_d = cfg_d, params_d
        self.k = k
        rope_t = rope_frequencies(cfg_t.head_dim, cfg_t.max_seq,
                                  cfg_t.rope_theta)
        rope_d = rope_frequencies(cfg_d.head_dim, cfg_d.max_seq,
                                  cfg_d.rope_theta)
        self._prefill_t = llama._stepwise_executables(cfg_t, None)[0]
        self._prefill_d = llama._stepwise_executables(cfg_d, None)[0]
        # the draft chunk runs k steps, consuming [cur, d_1..d_{k-1}]:
        # that writes the draft cache row for EVERY window position, so
        # a fully-accepted window leaves no K/V hole at pos+k-1 (the
        # k-th proposal itself is discarded — it exists to write d_{k-1}
        # into the cache). The verify window is [cur, d_1..d_{k-1}].
        self._draft_x = jax.jit(lambda p, c, pos, tok: llama.decode_chunk(
            self.cfg_d, p, c, pos, tok, self.k,
            rope=rope_d)) if k > 1 else None
        self._verify_x = jax.jit(lambda p, c, toks, pos: llama.extend_step(
            self.cfg_t, p, c, toks, pos, rope=rope_t))

    def generate(self, prompt: jnp.ndarray, steps: int
                 ) -> Tuple[jnp.ndarray, Dict[str, float]]:
        """Greedy-decode ``steps`` tokens; returns (tokens [1, steps],
        stats). Emits exactly ``llama.generate_stepwise``'s stream for
        the target model."""
        b, s = prompt.shape
        if b != 1:
            raise ValueError("speculative decoding is batch-1")
        need = s + steps + self.k
        if need > self.cfg_t.max_seq or need > self.cfg_d.max_seq:
            raise ValueError(
                f"prompt {s} + steps {steps} + k {self.k} exceeds "
                f"max_seq (target {self.cfg_t.max_seq}, draft "
                f"{self.cfg_d.max_seq})")
        cache_t = llama.init_kv_cache(self.cfg_t, 1, self.cfg_t.max_seq)
        cache_d = llama.init_kv_cache(self.cfg_d, 1, self.cfg_d.max_seq)
        lt, cache_t = self._prefill_t(self.params_t, cache_t, prompt)
        _, cache_d = self._prefill_d(self.params_d, cache_d, prompt)
        cur = int(jnp.argmax(lt, axis=-1)[0])
        out = [cur]
        pos = s                       # next write position (holds `cur`)
        passes = 0
        while len(out) < steps:
            if self._draft_x is not None:
                draft, cache_d = self._draft_x(
                    self.params_d, cache_d, jnp.int32(pos),
                    jnp.asarray([cur], jnp.int32))
                draft_toks = [int(t) for t in
                              np.asarray(draft[0])][:self.k - 1]
            else:
                draft_toks = []
            window = jnp.asarray([[cur] + draft_toks], jnp.int32)
            logits, cache_t = self._verify_x(self.params_t, cache_t,
                                             window, jnp.int32(pos))
            target_toks = [int(t) for t in
                           np.asarray(jnp.argmax(logits[0], axis=-1))]
            passes += 1
            # accept drafted tokens while the target agrees; the token
            # at the first disagreement is the target's own choice, so
            # every pass emits at least one target-correct token
            emitted = []
            for i, t in enumerate(target_toks):
                emitted.append(t)
                if i >= len(draft_toks) or draft_toks[i] != t:
                    break
            pos += len(emitted)
            cur = emitted[-1]
            out.extend(emitted)
        out = out[:steps]
        stats = {"verify_passes": passes,
                 "tokens_per_pass": round(len(out) / max(passes, 1), 3),
                 "k": self.k}
        return jnp.asarray([out], jnp.int32), stats
