"""Peer-to-peer weight transfer: a serving replica is a checkpoint CDN.

Scale-up cold boots were dominated by the weight load from shared
storage (``bench_r14/autoscale.jsonl`` receipts the A/B). But every
already-hot replica of a homogeneous decode tier holds the exact bytes
a booting sibling needs — committed ``parallel/checkpoint.py`` step
directories on its volume. This module moves them replica-to-replica
over the same span-channel idiom as ``models/disagg.py``:

* :class:`WeightServer` — PrefillWorker-style HTTP front door over one
  checkpoint directory. ``GET /v1/weights/manifest`` answers the newest
  committed step's manifest (per-shard blake2s digests included);
  ``GET /v1/weights/shard?step=N&file=F`` answers one shard as a
  digest-checked frame (``pack_frame``, the ``pack_span`` discipline:
  magic | header len | header JSON | body).
* :class:`PeerFetcher` — the booting replica's side: round-robin over
  the healthy peers (the ``DisaggCoordinator`` down-mark / re-probe
  rotation), per-shard retry on the next peer, every frame verified
  TWICE — the frame's own body digest (transport integrity) and the
  manifest digest the SAVING process wrote (end-to-end). Plugs straight
  into ``restore_sharded(reader=...)`` so fetched shards stream to
  device without a full-tree staging pass.
* :func:`restore_from_peers` — fetch + streaming restore in one call;
  raises :class:`WeightFetchError` when no peer can serve (callers
  degrade to the disk path, loudly — never crash the boot).
* :func:`mirror_from_peers` — optionally lands the fetched step as a
  committed local step directory (dot-tmp + rename, the checkpoint
  commit protocol) so the NEW replica immediately serves its siblings.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import struct
import threading
import time
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional

from ..metrics import MetricsRegistry
from ..parallel import checkpoint as ckpt

_MAGIC = b"WTSHARD1"
_WIRE_VERSION = 1


class WeightFetchError(RuntimeError):
    """A peer weight fetch that must not be trusted or retried in place:
    transport failure, framing, or digest verification failed."""


def pack_frame(meta: Dict[str, Any], body: bytes) -> bytes:
    """Frame one shard for the wire, ``pack_span``-style:
    ``MAGIC | header_len | header JSON | raw shard bytes``. The header
    carries the shard metadata plus a digest of the body."""
    header = dict(meta)
    header["version"] = _WIRE_VERSION
    header["body_digest"] = hashlib.blake2s(body).hexdigest()
    header["body_bytes"] = len(body)
    hdr = json.dumps(header).encode()
    return _MAGIC + struct.pack("<I", len(hdr)) + hdr + body


def unpack_frame(data: bytes) -> (dict, bytes):
    """Parse + VERIFY one shard frame; raises :class:`WeightFetchError`
    on bad magic, version, truncation, or body-digest mismatch — a
    mangled transfer dies here, before the restore path sees it."""
    if not data.startswith(_MAGIC):
        raise WeightFetchError("bad magic: not a weight shard frame")
    off = len(_MAGIC)
    if len(data) < off + 4:
        raise WeightFetchError("truncated frame: no header length")
    (hlen,) = struct.unpack_from("<I", data, off)
    off += 4
    try:
        meta = json.loads(data[off:off + hlen])
    except ValueError as e:
        raise WeightFetchError(f"bad header: {e}") from None
    off += hlen
    if meta.get("version") != _WIRE_VERSION:
        raise WeightFetchError(f"wire version {meta.get('version')} != "
                               f"{_WIRE_VERSION}")
    body = data[off:]
    if len(body) != meta.get("body_bytes"):
        raise WeightFetchError(
            f"truncated body: {len(body)} bytes, frame header says "
            f"{meta.get('body_bytes')}")
    if hashlib.blake2s(body).hexdigest() != meta.get("body_digest"):
        raise WeightFetchError("body digest mismatch: corrupt transfer")
    return meta, body


def _urlopen(req, timeout: float):
    """Same transport rule as ``disagg._transport_urlopen``: verified
    TLS through ``security/transport.py`` when importable; cleartext
    http:// falls back to urllib; https:// without the optional
    ``cryptography`` package is a hard error."""
    try:
        from ..security.transport import urlopen
    except ImportError:
        url = req.full_url if hasattr(req, "full_url") else str(req)
        if str(url).startswith("https://"):
            raise WeightFetchError(
                "https:// weight fetch needs security/transport.py "
                "(optional cryptography package not installed)")
        return urllib.request.urlopen(req, timeout=timeout)
    return urlopen(req, timeout=timeout)


class WeightServer:
    """One checkpoint directory behind HTTP — attach to any serving
    replica so its committed steps double as the fleet's weight source.

    Routes (GET): ``/v1/weights/manifest[?step=N]``,
    ``/v1/weights/shard?step=N&file=F``, plus the standard
    ``/v1/healthz`` / ``/v1/metrics`` / ``/v1/metrics/prometheus``
    trio every replica shape exposes. Only files named by the step's
    own manifest are served (no path traversal by construction).

    Round 19: the same routes can serve LIVE state — a training gang
    frozen at a step boundary publishes its in-memory export
    (``publish_live``: manifest + shard blobs + the GANGSTATE frame,
    see ``parallel/reshard.py``) and peers pull it with zero checkpoint
    I/O; ``/v1/weights/gangstate`` answers the raw frame. The live
    snapshot shadows committed disk steps while published and vanishes
    on ``clear_live``. ``_live_lock`` guards only the snapshot
    reference; response bodies are written after it is released (T4)."""

    def __init__(self, ckpt_dir: str, port: int = 0,
                 host: str = "0.0.0.0", pid: int = 0,
                 metrics: Optional[MetricsRegistry] = None):
        self.ckpt_dir = ckpt_dir
        self.pid = pid
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self._own_metrics = metrics is None
        self._live_lock = threading.Lock()
        self._live: Optional[dict] = None
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def _json(self, code: int, payload: dict) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                parsed = urllib.parse.urlparse(self.path)
                qs = urllib.parse.parse_qs(parsed.query)
                if parsed.path == "/v1/healthz":
                    self._json(200, {"ok": True, "role": "weights",
                                     "steps": server.steps()})
                elif parsed.path == "/v1/metrics":
                    self._json(200, server.metrics.to_dict())
                elif parsed.path == "/v1/metrics/prometheus":
                    body = server.metrics.to_prometheus().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif parsed.path == "/v1/weights/manifest":
                    step = qs.get("step", [None])[0]
                    try:
                        payload = server.manifest(
                            None if step is None else int(step))
                    except FileNotFoundError as e:
                        self._json(404, {"error": str(e)})
                        return
                    self._json(200, payload)
                elif parsed.path == "/v1/weights/shard":
                    try:
                        step = int(qs["step"][0])
                        fname = qs["file"][0]
                    except (KeyError, ValueError, IndexError):
                        self._json(400, {"error": "need step= and file="})
                        return
                    try:
                        frame = server.shard_frame(step, fname)
                    except FileNotFoundError as e:
                        self._json(404, {"error": str(e)})
                        return
                    server.metrics.counter("weights.shards_served")
                    server.metrics.counter("weights.bytes_served",
                                           len(frame))
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/octet-stream")
                    self.send_header("Content-Length", str(len(frame)))
                    self.end_headers()
                    self.wfile.write(frame)
                elif parsed.path == "/v1/weights/gangstate":
                    frame = server.gangstate_frame()
                    if frame is None:
                        self._json(404,
                                   {"error": "no live gang state published"})
                        return
                    server.metrics.counter("weights.gangstate_served")
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/octet-stream")
                    self.send_header("Content-Length", str(len(frame)))
                    self.end_headers()
                    self.wfile.write(frame)
                else:
                    self._json(404, {"error": f"no route {parsed.path}"})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    # -- live state (restart-free reshard, parallel/reshard.py) --------------

    def publish_live(self, step: int, manifest: dict,
                     blobs: Dict[str, bytes],
                     frame: Optional[bytes] = None) -> None:
        """Expose a frozen LIVE training state on the weight routes.
        ``blobs`` maps shard file names to raw bytes (the manifest's
        digests still verify end-to-end); ``frame`` is the opaque
        GANGSTATE frame served at ``/v1/weights/gangstate``. The
        snapshot is replaced wholesale — never mutated in place — so
        readers that copied the reference out of the lock stay
        coherent."""
        snap = {"step": int(step), "manifest": manifest,
                "blobs": dict(blobs), "frame": frame}
        with self._live_lock:
            self._live = snap
        self.metrics.counter("weights.live_published")

    def clear_live(self) -> None:
        with self._live_lock:
            self._live = None

    def _live_view(self, step: Optional[int] = None) -> Optional[dict]:
        with self._live_lock:
            live = self._live
        if live is None or (step is not None and live["step"] != step):
            return None
        return live

    def live_step(self) -> Optional[int]:
        live = self._live_view()
        return None if live is None else live["step"]

    def gangstate_frame(self) -> Optional[bytes]:
        live = self._live_view()
        return None if live is None else live.get("frame")

    # -- checkpoint surface --------------------------------------------------

    def steps(self) -> List[int]:
        return ckpt._local_steps(self.ckpt_dir, self.pid)

    def _step_dir(self, step: int) -> str:
        d = os.path.join(self.ckpt_dir, f"step-{step:08d}-p{self.pid}")
        if not os.path.isfile(os.path.join(d, "manifest.json")):
            raise FileNotFoundError(f"no committed step {step}")
        return d

    def manifest(self, step: Optional[int] = None) -> dict:
        live = self._live_view(step)
        if live is not None:
            steps = sorted(set(self.steps()) | {live["step"]})
            return {"step": live["step"], "steps": steps,
                    "manifest": live["manifest"], "live": True}
        steps = self.steps()
        if step is None:
            if not steps:
                raise FileNotFoundError(
                    f"no committed checkpoint under {self.ckpt_dir!r}")
            step = steps[-1]
        with open(os.path.join(self._step_dir(step), "manifest.json"),
                  encoding="utf-8") as f:
            manifest = json.load(f)
        return {"step": step, "steps": steps, "manifest": manifest}

    def shard_frame(self, step: int, fname: str) -> bytes:
        live = self._live_view(step)
        if live is not None:
            body = live["blobs"].get(fname)
            if body is None:
                raise FileNotFoundError(
                    f"live step {step} has no shard {fname!r}")
            return pack_frame({"step": step, "file": fname, "live": True},
                              body)
        step_d = self._step_dir(step)
        with open(os.path.join(step_d, "manifest.json"),
                  encoding="utf-8") as f:
            manifest = json.load(f)
        known = {s["file"] for e in manifest["leaves"].values()
                 for s in e["shards"]}
        if fname not in known:   # also forecloses path traversal
            raise FileNotFoundError(
                f"step {step} manifest names no shard {fname!r}")
        with open(os.path.join(step_d, fname), "rb") as f:
            body = f.read()
        return pack_frame({"step": step, "file": fname}, body)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "WeightServer":
        try:
            from ..security.transport import server_tls_from_env
            creds = server_tls_from_env()
            if creds is not None:
                from ..security.transport import wrap_server
                wrap_server(self._httpd, creds)
        except ImportError:
            pass
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="weights-http")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=10)
        if self._own_metrics:
            self.metrics.close()


class PeerFetcher:
    """Round-robin digest-checked shard fetch from already-hot peers.

    The rotation is the coordinator's (``disagg.DisaggCoordinator``):
    a failing peer is marked down and skipped until ``health_recheck_s``
    elapses and its ``/v1/healthz`` answers again; a shard fetch that
    fails on one peer retries on the NEXT healthy peer before the whole
    fetch gives up. ``reader`` satisfies ``restore_sharded``'s byte
    source contract, so fetched shards stream straight to device."""

    def __init__(self, peers, timeout_s: float = 120.0,
                 health_recheck_s: float = 5.0,
                 metrics: Optional[MetricsRegistry] = None):
        if isinstance(peers, str):
            self.peers = [p.strip() for p in peers.split(",") if p.strip()]
        else:
            self.peers = [str(p).strip() for p in (peers or ())
                          if str(p).strip()]
        self.timeout_s = timeout_s
        self.health_recheck_s = health_recheck_s
        self.metrics = metrics
        self._lock = threading.Lock()
        self._rr = 0
        self._down: Dict[str, float] = {}
        self.step: Optional[int] = None
        self._manifest: Optional[dict] = None
        self._by_file: Dict[str, dict] = {}
        self.shards_fetched = 0
        self.bytes_fetched = 0
        self.retries = 0

    # -- rotation ------------------------------------------------------------

    def _probe(self, peer: str) -> bool:
        try:
            req = urllib.request.Request(
                peer.rstrip("/") + "/v1/healthz")
            with _urlopen(req, timeout=5.0) as r:
                return bool(json.loads(r.read()).get("ok"))
        except Exception:
            return False

    def _mark_down(self, peer: str) -> None:
        with self._lock:
            self._down[peer] = time.monotonic()

    def _peer_ok(self, peer: str) -> bool:
        with self._lock:
            marked = self._down.get(peer)
            if marked is None:
                return True
            if time.monotonic() - marked < self.health_recheck_s:
                return False
        if self._probe(peer):
            with self._lock:
                self._down.pop(peer, None)
            return True
        self._mark_down(peer)
        return False

    def _order(self) -> List[str]:
        with self._lock:
            n = len(self.peers)
            if n == 0:
                return []
            start = self._rr % n
            self._rr += 1
            ordered = self.peers[start:] + self.peers[:start]
        return [p for p in ordered if self._peer_ok(p)]

    def _get(self, peer: str, path: str) -> bytes:
        req = urllib.request.Request(peer.rstrip("/") + path)
        with _urlopen(req, timeout=self.timeout_s) as r:
            return r.read()

    # -- fetch surface -------------------------------------------------------

    def manifest(self, step: Optional[int] = None) -> dict:
        """Resolve the step + manifest from the first healthy peer;
        pins ``self.step`` so every subsequent shard read is coherent
        (peers prune independently — mixing steps would be corrupt)."""
        last = "no healthy weight peer"
        q = f"?step={step}" if step is not None else ""
        for peer in self._order():
            try:
                payload = json.loads(
                    self._get(peer, f"/v1/weights/manifest{q}"))
            except Exception as e:
                last = f"{peer}: {e}"
                self._mark_down(peer)
                continue
            self.step = int(payload["step"])
            self._manifest = payload["manifest"]
            self._by_file = {
                s["file"]: s
                for e in self._manifest["leaves"].values()
                for s in e["shards"]}
            return self._manifest
        raise WeightFetchError(f"manifest fetch failed: {last}")

    def reader(self, fname: str) -> bytes:
        """``restore_sharded`` byte source: fetch one shard (or the
        manifest) from the rotation, verifying the frame digest AND the
        manifest digest the saving process wrote."""
        if fname == "manifest.json":
            if self._manifest is None:
                self.manifest()
            return json.dumps(self._manifest).encode()
        if self.step is None:
            self.manifest()
        q = (f"/v1/weights/shard?step={self.step}"
             f"&file={urllib.parse.quote(fname)}")
        last = "no healthy weight peer"
        first = True
        for peer in self._order():
            if not first:
                self.retries += 1
            first = False
            try:
                meta, body = unpack_frame(self._get(peer, q))
            except Exception as e:
                last = f"{peer}: {e}"
                self._mark_down(peer)
                continue
            if meta.get("file") != fname or meta.get("step") != self.step:
                self._mark_down(peer)
                last = f"{peer}: answered wrong shard {meta.get('file')!r}"
                continue
            want = self._by_file.get(fname, {}).get("digest")
            if want is not None \
                    and hashlib.blake2s(body).hexdigest() != want:
                # the peer's frame was self-consistent but does not
                # match the manifest: wrong bytes end-to-end
                self._mark_down(peer)
                last = f"{peer}: shard {fname!r} fails manifest digest"
                continue
            self.shards_fetched += 1
            self.bytes_fetched += len(body)
            if self.metrics is not None:
                self.metrics.counter("weights.shards_fetched")
                self.metrics.counter("weights.bytes_fetched", len(body))
            return body
        raise WeightFetchError(f"shard {fname!r}: {last}")

    def gangstate(self) -> bytes:
        """Fetch the raw GANGSTATE frame a frozen gang published for its
        live training state (``parallel/reshard.py`` verifies the whole
        frame ladder before anything is reserved)."""
        last = "no healthy weight peer"
        for peer in self._order():
            try:
                return self._get(peer, "/v1/weights/gangstate")
            except Exception as e:
                last = f"{peer}: {e}"
                self._mark_down(peer)
                continue
        raise WeightFetchError(f"gangstate fetch failed: {last}")

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            down = sorted(self._down)
        return {"peers": list(self.peers), "peers_down": down,
                "step": self.step, "shards_fetched": self.shards_fetched,
                "bytes_fetched": self.bytes_fetched,
                "retries": self.retries}


def restore_from_peers(peers, template, step: Optional[int] = None, *,
                       workers: Optional[int] = None,
                       timeout_s: float = 120.0,
                       metrics: Optional[MetricsRegistry] = None,
                       fetcher: Optional[PeerFetcher] = None) -> Any:
    """Boot-path weight load from an already-hot sibling: resolve the
    newest step a healthy peer serves, then stream its shards through
    ``restore_sharded`` (concurrent digest-checked fetches, device_put
    as they land). Raises :class:`WeightFetchError` when no peer can
    serve — the caller's contract is degrade-not-crash: fall back to
    the disk restore and count it."""
    f = fetcher if fetcher is not None else PeerFetcher(
        peers, timeout_s=timeout_s, metrics=metrics)
    if not f.peers:
        raise WeightFetchError("no weight peers configured")
    manifest = f.manifest(step)
    try:
        return ckpt.restore_sharded(None, template, workers=workers,
                                    reader=f.reader, manifest=manifest)
    except ckpt.CheckpointCorrupt as e:
        raise WeightFetchError(str(e)) from None


def mirror_from_peers(peers, out_dir: str,
                      step: Optional[int] = None, *,
                      pid: int = 0, timeout_s: float = 120.0,
                      fetcher: Optional[PeerFetcher] = None) -> int:
    """Land a peer's newest step as a committed LOCAL step directory
    (dot-tmp + ``os.rename``, the checkpoint commit protocol) so the
    freshly-booted replica immediately serves its own siblings.
    Returns the mirrored step number."""
    f = fetcher if fetcher is not None else PeerFetcher(
        peers, timeout_s=timeout_s)
    manifest = f.manifest(step)
    got = f.step
    final = os.path.join(out_dir, f"step-{got:08d}-p{pid}")
    tmp = os.path.join(out_dir, f".step-{got:08d}-p{pid}.tmp")
    if os.path.isdir(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    for entry in manifest["leaves"].values():
        for shard in entry["shards"]:
            body = f.reader(shard["file"])
            ckpt._verify_shard(shard, body, "peer")
            with open(os.path.join(tmp, shard["file"]), "wb") as fh:
                fh.write(body)
    with open(os.path.join(tmp, "manifest.json"), "w",
              encoding="utf-8") as fh:
        json.dump(manifest, fh)
    if os.path.isdir(final):
        shutil.rmtree(final)
    os.rename(tmp, final)
    return got
