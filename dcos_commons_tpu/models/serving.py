"""Continuous-batching serving engine for the llama decoder.

vLLM-style slot serving, built the TPU way — every shape static:

* A fixed pool of B **slots** shares one KV cache [L, B, max_seq, ...].
  Each slot holds one request at its own conversation length; one
  ``decode_step_slots`` dispatch advances every active slot (per-slot
  positions, per-slot cache writes, per-slot attention masks — and the
  pallas decode kernel's block skipping makes each slot's cost track its
  OWN length via the per-slot ``kv_len`` vector).
* New requests **fill freed slots without touching the others**: prefill
  runs as a bucketed [1, P] forward (prompt padded to the next
  power-of-two, so a handful of executables serve every prompt length)
  whose K/V scatter into the slot's cache rows. Padded positions are
  causally downstream of the live ones, so they perturb nothing, are
  masked by the slot's length, and are overwritten as decode advances.
* Retirement is host-side bookkeeping (budget exhausted, EOS, or cache
  full); retired slots keep decoding garbage rows that nothing reads —
  the batch never reshapes, so nothing recompiles.
* **Composes with tensor parallelism**: pass ``mesh`` and the cache
  shards over the KV-head axis next to the megatron weight shards; the
  per-slot decode runs the flash kernel per head shard
  (``flash_decode_tp``'s per-slot ``kv_len`` path) and bucketed prefill
  routes through the sharded flash prefill — continuous batching and a
  tp-sharded model are one engine, not alternatives.

The reference repo (a cluster scheduler) has no serving engine; this is
workload-layer capability for BASELINE.json config #5, layered on
``models/llama.py`` (``decode_step_slots``) and ``ops/flash_decode.py``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dcos_commons_tpu.models import llama
from dcos_commons_tpu.ops import rope_frequencies
from dcos_commons_tpu.ops.quant import QTensor, qmm, quantize


@dataclasses.dataclass
class _Request:
    request_id: Any
    prompt_len: int
    budget: int
    tokens: List[int]


def _bucket(n: int, lo: int = 8) -> int:
    b = lo
    while b < n:
        b *= 2
    return b


def _prefill_bucket(cfg, params, prompt, true_len, rope, mesh=None):
    """[1, P] causal forward: (last-live-position logits [1, V],
    ks/vs [L, 1, P, KV, D]). P is the padded bucket; positions >=
    true_len are causally downstream of the live ones and harmless.
    Shares :func:`llama.prefill_trunk` with solo prefill (flash routing
    — including the tp shard_map kernel — for lane-aligned buckets) —
    only the logits position and the cache landing differ."""
    x, ks, vs = llama.prefill_trunk(cfg, params, prompt, rope, mesh)
    last = lax.dynamic_index_in_dim(x, true_len - 1, axis=1,
                                    keepdims=False)
    logits = qmm(last, params["lm_head"]).astype(jnp.float32)
    return logits, ks, vs


def _prefill_bucket_many(cfg, params, prompts, true_lens, rope,
                         mesh=None):
    """[N, P] causal forward for N admitted requests in ONE dispatch:
    (per-row last-live-position logits [N, V], ks/vs [L, N, P, KV, D]).
    Rows are independent (batch-dim causal attention), so the math per
    row is exactly :func:`_prefill_bucket`'s — only the dispatch count
    changes (one per admission batch instead of one per request)."""
    x, ks, vs = llama.prefill_trunk(cfg, params, prompts, rope, mesh)
    last = jnp.take_along_axis(
        x, (true_lens - 1)[:, None, None], axis=1)[:, 0]
    logits = qmm(last, params["lm_head"]).astype(jnp.float32)
    return logits, ks, vs


def _shard_cache(cache, mesh):
    """Place the slot KV cache for tensor-parallel serving: shard over
    the KV-head axis (payload + scales) to sit next to the megatron
    weight shards; the SLOT axis stays unsharded — every shard serves
    every conversation, and attention is head-local."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    kvspec = NamedSharding(mesh, P(None, None, None, "tp", None))

    def place(c):
        if isinstance(c, QTensor):
            return QTensor(jax.device_put(c.q, kvspec),
                           jax.device_put(c.s, kvspec))
        return jax.device_put(c, kvspec)

    return {k: place(v) for k, v in cache.items()}


def _scatter_slot(cache, new, slot):
    """Write [L, 1, P, KV, D] prefill K/V into cache rows
    [:, slot, :P] (quantizing when the cache is int8)."""
    p = new.shape[2]
    if isinstance(cache, QTensor):
        nq = quantize(new, axis=-1)
        return QTensor(
            cache.q.at[:, slot, :p].set(nq.q[:, 0]),
            cache.s.at[:, slot, :p].set(nq.s[:, 0].astype(cache.s.dtype)))
    return cache.at[:, slot, :p].set(new[:, 0])


def _scatter_rows(cache, new, slots):
    """Write [L, N, P, KV, D] prefill K/V into cache rows
    [:, slots[i], :P] — slots are DISTINCT free slots, so the scatter
    has no duplicate-index ordering hazard."""
    p = new.shape[2]
    if isinstance(cache, QTensor):
        nq = quantize(new, axis=-1)
        return QTensor(
            cache.q.at[:, slots, :p].set(nq.q),
            cache.s.at[:, slots, :p].set(nq.s.astype(cache.s.dtype)))
    return cache.at[:, slots, :p].set(new)


class SlotServer:
    """Fixed-slot continuous batching over one resident weight set.

    ``submit()`` places a request in a free slot (prefill + first
    token); ``step()`` advances every active slot by one token in one
    dispatch; ``drain()`` loops until all requests finish. Greedy by
    default; pass ``sampler`` (``ops.sampling.make_sampler``) + ``key``
    for stochastic decoding.
    """

    def __init__(self, cfg: llama.LlamaConfig, params, slots: int = 8,
                 sampler=None, key: Optional[jax.Array] = None,
                 eos_id: Optional[int] = None, mesh=None):
        self.cfg = cfg
        self.params = params
        self.slots = slots
        self.sampler = sampler
        self.eos_id = eos_id
        self.mesh = mesh
        self.key = key if key is not None else jax.random.key(0)
        self.cache = llama.init_kv_cache(cfg, slots, cfg.max_seq)
        if mesh is not None and mesh.size > 1:
            # tensor-parallel serving: decode_step_slots runs the flash
            # kernel per head shard with the per-slot kv_len vector and
            # NO collectives until the out-projection
            self.cache = _shard_cache(self.cache, mesh)
        self.lengths = jnp.zeros((slots,), jnp.int32)
        self.cur_tok = jnp.zeros((slots,), jnp.int32)
        self.requests: List[Optional[_Request]] = [None] * slots
        self.finished: Dict[Any, List[int]] = {}
        rope = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
        self._prefill_x: Dict[int, Any] = {}   # bucket -> executable
        self._prefill_many_x: Dict[Any, Any] = {}   # (n, bucket) -> exe
        self._scatter_many_x: Dict[Any, Any] = {}   # (n, bucket) -> exe
        self._rope = rope
        # the cache is donated in BOTH jitted paths: it dominates HBM at
        # real presets (~1 GB+ at 8B) and every step/scatter returns a
        # same-shaped cache, so XLA aliases in-place instead of holding
        # two copies live across the update
        self._step_x = jax.jit(
            lambda p, c, ln, tok: llama.decode_step_slots(
                cfg, p, c, ln, tok, mesh=mesh, rope=rope),
            donate_argnums=(1,))
        self._stepk_x: Dict[int, Any] = {}     # window size -> executable
        self._scatter_x = jax.jit(
            lambda c, ks, vs, slot: {
                "k": _scatter_slot(c["k"], ks, slot),
                "v": _scatter_slot(c["v"], vs, slot)},
            donate_argnums=(0,))

    # ------------------------------------------------------------ intake

    def free_slots(self) -> List[int]:
        return [i for i, r in enumerate(self.requests) if r is None]

    def requests_active(self) -> bool:
        return any(r is not None for r in self.requests)

    def submit(self, prompt: List[int], max_new: int = 32,
               request_id: Any = None) -> Optional[int]:
        """Prefill ``prompt`` into a free slot; returns the slot index,
        or None when the pool is full (caller queues and retries after
        a step retires something)."""
        if not prompt:
            # must not alias the pool-full None: drain() would retry the
            # same item forever
            raise ValueError("empty prompt")
        free = self.free_slots()
        if not free:
            return None
        n = len(prompt)
        if n + max_new > self.cfg.max_seq:
            raise ValueError(
                f"prompt {n} + max_new {max_new} exceeds the cache "
                f"({self.cfg.max_seq}); raise max_seq or shrink the ask")
        slot = free[0]
        # a power-of-two bucket can overshoot a non-power-of-two
        # max_seq; the capacity check above already passed, so clamp —
        # padded positions are causally inert either way
        bucket = min(_bucket(n), self.cfg.max_seq)
        x = self._prefill_x.get(bucket)
        if x is None:
            cfg, rope, mesh = self.cfg, self._rope, self.mesh
            x = jax.jit(lambda p, toks, tl: _prefill_bucket(
                cfg, p, toks, tl, rope, mesh))
            self._prefill_x[bucket] = x
        arr = np.zeros((1, bucket), np.int32)
        arr[0, :n] = prompt                       # host-side assembly
        logits, ks, vs = x(self.params, jnp.asarray(arr), jnp.int32(n))
        self.cache = self._scatter_x(self.cache, ks, vs, jnp.int32(slot))
        tok = int(self._select(logits)[0])
        self.lengths = self.lengths.at[slot].set(n)
        self.cur_tok = self.cur_tok.at[slot].set(tok)
        rid = request_id if request_id is not None else object()
        self.requests[slot] = _Request(rid, n, max_new, [tok])
        self._maybe_retire(slot)
        return slot

    def _validate_item(self, item: Dict[str, Any]) -> Optional[str]:
        """None when admissible, else the rejection reason — the ONE
        copy of the admission predicate (the POST handler and callers
        defer to it via ``on_invalid``)."""
        prompt = item["prompt"]
        max_new = item.get("max_new", 32)
        if not prompt:
            return "empty prompt"
        if len(prompt) + max_new > self.cfg.max_seq:
            return (f"prompt {len(prompt)} + max_new {max_new} exceeds "
                    f"the cache ({self.cfg.max_seq}); raise max_seq or "
                    "shrink the ask")
        return None

    def submit_many(self, items: List[Dict[str, Any]],
                    on_invalid=None) -> List[Tuple[int, Any]]:
        """Admit up to ``len(free_slots())`` requests with O(log n)
        prefill DISPATCHES instead of one per request: items are taken
        in power-of-two batches (largest first), each batch prefilled
        as ONE [N, P] forward whose K/V scatter into N distinct slots.
        Each item: {"prompt": [...], "max_new": int, "request_id": any}.
        Returns [(slot, request_id), ...] for everything admitted;
        unadmitted items (pool full) are simply not in the result.
        Invalid items fail ALONE: with ``on_invalid(item, reason)`` they
        are reported and skipped (co-batched requests unaffected);
        without it the first invalid item raises BEFORE any dispatch.
        Power-of-two batch AND bucket sizes keep the executable count
        logarithmic in (slots x max_seq)."""
        admissible = []
        for item in items:
            reason = self._validate_item(item)
            if reason is None:
                admissible.append(item)
            elif on_invalid is not None:
                on_invalid(item, reason)
            else:
                raise ValueError(reason)
        placed: List[Tuple[int, Any]] = []
        remaining = admissible
        while remaining:
            free = self.free_slots()
            if not free:
                break
            n = min(len(remaining), len(free))
            k = 1 << (n.bit_length() - 1)          # largest pow2 <= n
            batch, remaining = remaining[:k], remaining[k:]
            placed.extend(self._submit_batch(batch, free[:k]))
        return placed

    def _submit_batch(self, batch: List[Dict[str, Any]],
                      slots: List[int]) -> List[Tuple[int, Any]]:
        k = len(batch)
        lens = [len(item["prompt"]) for item in batch]
        bucket = min(_bucket(max(lens)), self.cfg.max_seq)
        key = (k, bucket)
        x = self._prefill_many_x.get(key)
        if x is None:
            cfg, rope, mesh = self.cfg, self._rope, self.mesh
            x = jax.jit(lambda p, toks, tl: _prefill_bucket_many(
                cfg, p, toks, tl, rope, mesh))
            self._prefill_many_x[key] = x
        sx = self._scatter_many_x.get(key)
        if sx is None:
            sx = jax.jit(
                lambda c, ks, vs, sl: {
                    "k": _scatter_rows(c["k"], ks, sl),
                    "v": _scatter_rows(c["v"], vs, sl)},
                donate_argnums=(0,))
            self._scatter_many_x[key] = sx
        # assemble on the HOST: per-row device .at[].set would pay the
        # O(n) dispatches this path exists to remove
        arr = np.zeros((k, bucket), np.int32)
        for i, item in enumerate(batch):
            arr[i, :lens[i]] = item["prompt"]
        logits, ks, vs = x(self.params, jnp.asarray(arr),
                           jnp.asarray(lens, jnp.int32))
        slot_arr = jnp.asarray(slots, jnp.int32)
        self.cache = sx(self.cache, ks, vs, slot_arr)
        toks = self._select(logits)
        host_toks = [int(t) for t in np.asarray(toks)]
        placed = []
        for i, item in enumerate(batch):
            slot = slots[i]
            rid = item.get("request_id")
            rid = rid if rid is not None else object()
            self.lengths = self.lengths.at[slot].set(lens[i])
            self.cur_tok = self.cur_tok.at[slot].set(host_toks[i])
            self.requests[slot] = _Request(rid, lens[i],
                                           item.get("max_new", 32),
                                           [host_toks[i]])
            self._maybe_retire(slot)
            placed.append((slot, rid))
        return placed

    def _select(self, logits) -> jnp.ndarray:
        if self.sampler is None:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        self.key, sub = jax.random.split(self.key)
        return self.sampler(sub, logits).astype(jnp.int32)

    # ------------------------------------------------------------- decode

    def step(self) -> Dict[int, int]:
        """Advance every active slot one token; returns {slot: token}."""
        active = [i for i, r in enumerate(self.requests) if r is not None]
        if not active:
            return {}
        logits, self.cache = self._step_x(self.params, self.cache,
                                          self.lengths, self.cur_tok)
        toks = self._select(logits)
        # only active slots advance (a retired slot's write lands at its
        # frozen length — a row nothing reads until prefill rewrites it)
        mask = jnp.zeros((self.slots,), bool).at[
            jnp.asarray(active, jnp.int32)].set(True)
        self.lengths = jnp.where(mask, self.lengths + 1, self.lengths)
        self.cur_tok = jnp.where(mask, toks, self.cur_tok)
        out: Dict[int, int] = {}
        # ONE device->host transfer for the batch; per-element int(t)
        # would round-trip once per slot per step
        host_toks = [int(t) for t in np.asarray(toks)]
        for i in active:
            tok = host_toks[i]
            self.requests[i].tokens.append(tok)
            out[i] = tok
            self._maybe_retire(i)
        return out

    def step_many(self, k: int) -> Dict[int, List[int]]:
        """Advance every active slot ``k`` tokens in ONE dispatch (a
        ``lax.scan`` over :func:`llama.decode_step_slots`), returning
        ``{slot: [tokens...]}`` — each list truncated at the slot's
        retirement point.

        Why: per-token ``step()`` pays one host->device dispatch per
        emitted token, and on dispatch-heavy paths (tunneled backends:
        ~100 ms/dispatch measured) that — not the chip — bounds TPOT.
        One K-window amortizes the dispatch K-fold, the same trade
        ``generate_chunked`` makes for solo decode. Costs: a slot
        retiring mid-window wastes its remaining step-slots (bounded by
        K-1), and new requests wait up to one window for a slot. Retired
        slots are FROZEN inside the window (their length/token do not
        advance; the same dead row is rewritten), so nothing drifts.
        ``k == 1`` is exactly :meth:`step`.
        """
        if k <= 1:
            return {slot: [tok] for slot, tok in self.step().items()}
        active = [i for i, r in enumerate(self.requests) if r is not None]
        if not active:
            return {}
        x = self._stepk_x.get(k)
        if x is None:
            cfg, rope, mesh = self.cfg, self._rope, self.mesh

            def window(p, c, ln, tok, mask, key):
                def body(carry, _):
                    c, ln, tok, key = carry
                    logits, c = llama.decode_step_slots(
                        cfg, p, c, ln, tok, mesh=mesh, rope=rope)
                    key, sub = jax.random.split(key)
                    if self.sampler is None:
                        nxt = jnp.argmax(logits, axis=-1).astype(
                            jnp.int32)
                    else:
                        nxt = self.sampler(sub, logits).astype(jnp.int32)
                    nxt = jnp.where(mask, nxt, tok)
                    ln = jnp.where(mask, ln + 1, ln)
                    return (c, ln, nxt, key), nxt

                (c, ln, tok, key), toks = lax.scan(
                    body, (c, ln, tok, key), None, length=k)
                return c, ln, tok, key, toks          # toks [k, slots]

            x = jax.jit(window, donate_argnums=(1,))
            self._stepk_x[k] = x
        mask = jnp.zeros((self.slots,), bool).at[
            jnp.asarray(active, jnp.int32)].set(True)
        self.key, sub = jax.random.split(self.key)
        (self.cache, self.lengths, self.cur_tok, _, toks) = x(
            self.params, self.cache, self.lengths, self.cur_tok, mask,
            sub)
        host = np.asarray(toks)                       # ONE transfer
        out: Dict[int, List[int]] = {}
        for i in active:
            emitted: List[int] = []
            r = self.requests[i]
            for t in host[:, i]:
                emitted.append(int(t))
                r.tokens.append(int(t))
                self._maybe_retire(i)
                if self.requests[i] is None:
                    break   # retired mid-window: rest is dead compute
            out[i] = emitted
        return out

    def _maybe_retire(self, slot: int) -> None:
        r = self.requests[slot]
        if r is None:
            return
        done = (len(r.tokens) >= r.budget
                or (self.eos_id is not None
                    and r.tokens[-1] == self.eos_id)
                or r.prompt_len + len(r.tokens) >= self.cfg.max_seq)
        if done:
            self.finished[r.request_id] = r.tokens
            self.requests[slot] = None

    def reset(self) -> None:
        """Rebuild device state after a failed dispatch: the jitted step
        DONATES the cache, so an exception mid-step leaves ``self.cache``
        pointing at an invalidated buffer — re-init it (and the slot
        bookkeeping) rather than trying to serve through it. Weights are
        non-donated inputs and survive."""
        self.cache = llama.init_kv_cache(self.cfg, self.slots,
                                         self.cfg.max_seq)
        if self.mesh is not None and self.mesh.size > 1:
            self.cache = _shard_cache(self.cache, self.mesh)
        self.lengths = jnp.zeros((self.slots,), jnp.int32)
        self.cur_tok = jnp.zeros((self.slots,), jnp.int32)
        self.requests = [None] * self.slots
        self.finished.clear()

    def abort_active(self) -> int:
        """Drop every in-flight request without recording results (a
        failed drive loop resetting to a clean pool); returns how many
        were dropped. Slot cache rows need no cleanup — they are masked
        by length and rewritten by the next prefill."""
        dropped = 0
        for i, r in enumerate(self.requests):
            if r is not None:
                self.requests[i] = None
                dropped += 1
        return dropped

    # -------------------------------------------------------------- drive

    def drain(self, queue: List[Dict[str, Any]],
              decode_window: int = 1) -> Dict[Any, List[int]]:
        """Serve a whole workload: submit as slots free up, step until
        every request finishes. Each queue item: {"prompt": [...],
        "max_new": int, "request_id": any}. ``decode_window > 1``
        amortizes dispatch via :meth:`step_many` (greedy streams are
        identical — slots are independent)."""
        pending = list(queue)
        while pending or self.requests_active():
            placed = self.submit_many(pending)     # batched admission
            pending = pending[len(placed):]
            self.step_many(decode_window)
        return dict(self.finished)
