"""Continuous-batching serving engine for the llama decoder.

vLLM-style slot serving, built the TPU way — every shape static:

* A fixed pool of B **slots** shares one KV cache [L, B, max_seq, ...].
  Each slot holds one request at its own conversation length; one
  ``decode_step_slots`` dispatch advances every active slot (per-slot
  positions, per-slot cache writes, per-slot attention masks — and the
  pallas decode kernel's block skipping makes each slot's cost track its
  OWN length via the per-slot ``kv_len`` vector).
* New requests **fill freed slots without touching the others**: prefill
  runs as a bucketed [1, P] forward (prompt padded to the next
  power-of-two, so a handful of executables serve every prompt length)
  whose K/V scatter into the slot's cache rows. Padded positions are
  causally downstream of the live ones, so they perturb nothing, are
  masked by the slot's length, and are overwritten as decode advances.
* Retirement is host-side bookkeeping (budget exhausted, EOS, or cache
  full); retired slots keep decoding garbage rows that nothing reads —
  the batch never reshapes, so nothing recompiles.
* **Composes with tensor parallelism**: pass ``mesh`` and the cache
  shards over the KV-head axis next to the megatron weight shards; the
  per-slot decode runs the flash kernel per head shard
  (``flash_decode_tp``'s per-slot ``kv_len`` path) and bucketed prefill
  routes through the sharded flash prefill — continuous batching and a
  tp-sharded model are one engine, not alternatives.

The reference repo (a cluster scheduler) has no serving engine; this is
workload-layer capability for BASELINE.json config #5, layered on
``models/llama.py`` (``decode_step_slots``) and ``ops/flash_decode.py``.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dcos_commons_tpu.models import llama
from dcos_commons_tpu.models.paging import (PagePool, PageTierStore,
                                            PrefixDirectory, PrefixRadix,
                                            chain_keys, page_hashes)
from dcos_commons_tpu.ops import rope_frequencies
from dcos_commons_tpu.ops.quant import QTensor, qmm, quantize
from dcos_commons_tpu.parallel.ring_attention import ring_pad_len


@dataclasses.dataclass
class _Request:
    request_id: Any
    prompt_len: int
    budget: int
    tokens: List[int]


def _bucket(n: int, lo: int = 8) -> int:
    b = lo
    while b < n:
        b *= 2
    return b


def _prefill_bucket(cfg, params, prompt, true_len, rope, mesh=None):
    """[1, P] causal forward: (last-live-position logits [1, V],
    ks/vs [L, 1, P, KV, D]). P is the padded bucket; positions >=
    true_len are causally downstream of the live ones and harmless.
    Shares :func:`llama.prefill_trunk` with solo prefill (flash routing
    — including the tp shard_map kernel — for lane-aligned buckets) —
    only the logits position and the cache landing differ."""
    x, ks, vs = llama.prefill_trunk(cfg, params, prompt, rope, mesh)
    last = lax.dynamic_index_in_dim(x, true_len - 1, axis=1,
                                    keepdims=False)
    logits = qmm(last, params["lm_head"]).astype(jnp.float32)
    return logits, ks, vs


def _prefill_bucket_many(cfg, params, prompts, true_lens, rope,
                         mesh=None):
    """[N, P] causal forward for N admitted requests in ONE dispatch:
    (per-row last-live-position logits [N, V], ks/vs [L, N, P, KV, D]).
    Rows are independent (batch-dim causal attention), so the math per
    row is exactly :func:`_prefill_bucket`'s — only the dispatch count
    changes (one per admission batch instead of one per request)."""
    x, ks, vs = llama.prefill_trunk(cfg, params, prompts, rope, mesh)
    last = jnp.take_along_axis(
        x, (true_lens - 1)[:, None, None], axis=1)[:, 0]
    logits = qmm(last, params["lm_head"]).astype(jnp.float32)
    return logits, ks, vs


def _shard_cache(cache, mesh):
    """Place the slot KV cache for tensor-parallel serving: shard over
    the KV-head axis (payload + scales) to sit next to the megatron
    weight shards; the SLOT axis stays unsharded — every shard serves
    every conversation, and attention is head-local."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    kvspec = NamedSharding(mesh, P(None, None, None, "tp", None))

    def place(c):
        if isinstance(c, QTensor):
            return QTensor(jax.device_put(c.q, kvspec),
                           jax.device_put(c.s, kvspec))
        return jax.device_put(c, kvspec)

    return {k: place(v) for k, v in cache.items()}


def _scatter_slot(cache, new, slot):
    """Write [L, 1, P, KV, D] prefill K/V into cache rows
    [:, slot, :P] (quantizing when the cache is int8)."""
    p = new.shape[2]
    if isinstance(cache, QTensor):
        nq = quantize(new, axis=-1)
        return QTensor(
            cache.q.at[:, slot, :p].set(nq.q[:, 0]),
            cache.s.at[:, slot, :p].set(nq.s[:, 0].astype(cache.s.dtype)))
    return cache.at[:, slot, :p].set(new[:, 0])


def _scatter_rows(cache, new, slots):
    """Write [L, N, P, KV, D] prefill K/V into cache rows
    [:, slots[i], :P] — slots are DISTINCT free slots, so the scatter
    has no duplicate-index ordering hazard."""
    p = new.shape[2]
    if isinstance(cache, QTensor):
        nq = quantize(new, axis=-1)
        return QTensor(
            cache.q.at[:, slots, :p].set(nq.q),
            cache.s.at[:, slots, :p].set(nq.s.astype(cache.s.dtype)))
    return cache.at[:, slots, :p].set(new)


class SlotServer:
    """Fixed-slot continuous batching over one resident weight set.

    ``submit()`` places a request in a free slot (prefill + first
    token); ``step()`` advances every active slot by one token in one
    dispatch; ``drain()`` loops until all requests finish. Greedy by
    default; pass ``sampler`` (``ops.sampling.make_sampler``) + ``key``
    for stochastic decoding.
    """

    # set by the ingress/prefill tiers (dcos_commons_tpu.tracing.Tracer);
    # engine-level spans record only for requests that carry a trace ctx
    tracer = None

    def __init__(self, cfg: llama.LlamaConfig, params, slots: int = 8,
                 sampler=None, key: Optional[jax.Array] = None,
                 eos_id: Optional[int] = None, mesh=None):
        self.cfg = cfg
        self.params = params
        self.slots = slots
        self.sampler = sampler
        self.eos_id = eos_id
        self.mesh = mesh
        self.key = key if key is not None else jax.random.key(0)
        self.cache = llama.init_kv_cache(cfg, slots, cfg.max_seq)
        if mesh is not None and mesh.size > 1:
            # tensor-parallel serving: decode_step_slots runs the flash
            # kernel per head shard with the per-slot kv_len vector and
            # NO collectives until the out-projection
            self.cache = _shard_cache(self.cache, mesh)
        self.lengths = jnp.zeros((slots,), jnp.int32)
        self.cur_tok = jnp.zeros((slots,), jnp.int32)
        self.requests: List[Optional[_Request]] = [None] * slots
        self.finished: Dict[Any, List[int]] = {}
        # slot -> device scalar of the prefill's first token, awaiting
        # ONE batched host transfer (see _flush_pending)
        self._pending_first: Dict[int, jax.Array] = {}
        rope = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
        self._prefill_x: Dict[int, Any] = {}   # bucket -> executable
        self._prefill_many_x: Dict[Any, Any] = {}   # (n, bucket) -> exe
        self._scatter_many_x: Dict[Any, Any] = {}   # (n, bucket) -> exe
        self._rope = rope
        # the cache is donated in BOTH jitted paths: it dominates HBM at
        # real presets (~1 GB+ at 8B) and every step/scatter returns a
        # same-shaped cache, so XLA aliases in-place instead of holding
        # two copies live across the update
        self._step_x = jax.jit(
            lambda p, c, ln, tok: llama.decode_step_slots(
                cfg, p, c, ln, tok, mesh=mesh, rope=rope),
            donate_argnums=(1,))
        self._stepk_x: Dict[int, Any] = {}     # window size -> executable
        self._scatter_x = jax.jit(
            lambda c, ks, vs, slot: {
                "k": _scatter_slot(c["k"], ks, slot),
                "v": _scatter_slot(c["v"], vs, slot)},
            donate_argnums=(0,))

    # ------------------------------------------------------------ intake

    def free_slots(self) -> List[int]:
        return [i for i, r in enumerate(self.requests) if r is None]

    def requests_active(self) -> bool:
        return any(r is not None for r in self.requests)

    def submit(self, prompt: List[int], max_new: int = 32,
               request_id: Any = None) -> Optional[int]:
        """Prefill ``prompt`` into a free slot; returns the slot index,
        or None when the pool is full (caller queues and retries after
        a step retires something)."""
        if not prompt:
            # must not alias the pool-full None: drain() would retry the
            # same item forever
            raise ValueError("empty prompt")
        self._flush_pending()
        free = self.free_slots()
        if not free:
            return None
        n = len(prompt)
        if n + max_new > self.cfg.max_seq:
            raise ValueError(
                f"prompt {n} + max_new {max_new} exceeds the cache "
                f"({self.cfg.max_seq}); raise max_seq or shrink the ask")
        slot = free[0]
        # a power-of-two bucket can overshoot a non-power-of-two
        # max_seq; the capacity check above already passed, so clamp —
        # padded positions are causally inert either way
        bucket = min(_bucket(n), self.cfg.max_seq)
        x = self._prefill_x.get(bucket)
        if x is None:
            cfg, rope, mesh = self.cfg, self._rope, self.mesh
            x = jax.jit(lambda p, toks, tl: _prefill_bucket(
                cfg, p, toks, tl, rope, mesh))
            self._prefill_x[bucket] = x
        arr = np.zeros((1, bucket), np.int32)
        arr[0, :n] = prompt                       # host-side assembly
        logits, ks, vs = x(self.params, jnp.asarray(arr), jnp.int32(n))
        self.cache = self._scatter_x(self.cache, ks, vs, jnp.int32(slot))
        # the first token stays DEVICE-RESIDENT: int(...) here would
        # block on the prefill per admission (the r4 bench-slip lesson —
        # host syncs inside the hot loop, not the chip, set the pace).
        # _flush_pending materializes every deferred first token in ONE
        # transfer at the next engine-thread entry point.
        toks = self._select(logits)
        self.lengths = self.lengths.at[slot].set(n)
        self.cur_tok = self.cur_tok.at[slot].set(toks[0])
        rid = request_id if request_id is not None else object()
        self.requests[slot] = _Request(rid, n, max_new, [])
        self._pending_first[slot] = toks[0]
        return slot

    def _validate_item(self, item: Dict[str, Any]) -> Optional[str]:
        """None when admissible, else the rejection reason — the ONE
        copy of the admission predicate (the POST handler and callers
        defer to it via ``on_invalid``)."""
        prompt = item["prompt"]
        max_new = item.get("max_new", 32)
        if not prompt:
            return "empty prompt"
        if len(prompt) + max_new > self.cfg.max_seq:
            return (f"prompt {len(prompt)} + max_new {max_new} exceeds "
                    f"the cache ({self.cfg.max_seq}); raise max_seq or "
                    "shrink the ask")
        return None

    def submit_many(self, items: List[Dict[str, Any]],
                    on_invalid=None) -> List[Tuple[int, Any]]:
        """Admit up to ``len(free_slots())`` requests with O(log n)
        prefill DISPATCHES instead of one per request: items are taken
        in power-of-two batches (largest first), each batch prefilled
        as ONE [N, P] forward whose K/V scatter into N distinct slots.
        Each item: {"prompt": [...], "max_new": int, "request_id": any}.
        Returns [(slot, request_id), ...] for everything admitted;
        unadmitted items (pool full) are simply not in the result.
        Invalid items fail ALONE: with ``on_invalid(item, reason)`` they
        are reported and skipped (co-batched requests unaffected);
        without it the first invalid item raises BEFORE any dispatch.
        Power-of-two batch AND bucket sizes keep the executable count
        logarithmic in (slots x max_seq)."""
        admissible = []
        for item in items:
            reason = self._validate_item(item)
            if reason is None:
                admissible.append(item)
            elif on_invalid is not None:
                on_invalid(item, reason)
            else:
                raise ValueError(reason)
        self._flush_pending()
        placed: List[Tuple[int, Any]] = []
        remaining = admissible
        while remaining:
            free = self.free_slots()
            if not free:
                break
            n = min(len(remaining), len(free))
            k = 1 << (n.bit_length() - 1)          # largest pow2 <= n
            batch, remaining = remaining[:k], remaining[k:]
            placed.extend(self._submit_batch(batch, free[:k]))
        return placed

    def _submit_batch(self, batch: List[Dict[str, Any]],
                      slots: List[int]) -> List[Tuple[int, Any]]:
        k = len(batch)
        lens = [len(item["prompt"]) for item in batch]
        bucket = min(_bucket(max(lens)), self.cfg.max_seq)
        key = (k, bucket)
        x = self._prefill_many_x.get(key)
        if x is None:
            cfg, rope, mesh = self.cfg, self._rope, self.mesh
            x = jax.jit(lambda p, toks, tl: _prefill_bucket_many(
                cfg, p, toks, tl, rope, mesh))
            self._prefill_many_x[key] = x
        sx = self._scatter_many_x.get(key)
        if sx is None:
            sx = jax.jit(
                lambda c, ks, vs, sl: {
                    "k": _scatter_rows(c["k"], ks, sl),
                    "v": _scatter_rows(c["v"], vs, sl)},
                donate_argnums=(0,))
            self._scatter_many_x[key] = sx
        # assemble on the HOST: per-row device .at[].set would pay the
        # O(n) dispatches this path exists to remove
        arr = np.zeros((k, bucket), np.int32)
        for i, item in enumerate(batch):
            arr[i, :lens[i]] = item["prompt"]
        logits, ks, vs = x(self.params, jnp.asarray(arr),
                           jnp.asarray(lens, jnp.int32))
        slot_arr = jnp.asarray(slots, jnp.int32)
        self.cache = sx(self.cache, ks, vs, slot_arr)
        # first tokens stay device-resident (see submit); one batched
        # scatter updates cur_tok with NO host round-trip
        toks = self._select(logits)
        self.lengths = self.lengths.at[slot_arr].set(
            jnp.asarray(lens, jnp.int32))
        self.cur_tok = self.cur_tok.at[slot_arr].set(toks)
        placed = []
        for i, item in enumerate(batch):
            slot = slots[i]
            rid = item.get("request_id")
            rid = rid if rid is not None else object()
            self.requests[slot] = _Request(rid, lens[i],
                                           item.get("max_new", 32), [])
            self._pending_first[slot] = toks[i]
            placed.append((slot, rid))
        return placed

    def _select(self, logits) -> jnp.ndarray:
        if self.sampler is None:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        self.key, sub = jax.random.split(self.key)
        return self.sampler(sub, logits).astype(jnp.int32)

    def _flush_pending(self) -> None:
        """Materialize every deferred first token in ONE device->host
        transfer and run the retirement checks that waited on them.

        Called at the top of every engine-thread entry point that may
        observe request state (submit/submit_many/step/step_many) —
        NOT from ``free_slots``/``requests_active``, which the HTTP
        health thread reads concurrently and which must therefore stay
        pure host bookkeeping.
        """
        if not self._pending_first:
            return
        items = sorted(self._pending_first.items())
        self._pending_first.clear()
        vals = np.asarray(jnp.stack([t for _, t in items]))
        for (slot, _), tok in zip(items, vals):
            r = self.requests[slot]
            if r is None:
                continue                       # aborted before flush
            r.tokens.append(int(tok))
            self._maybe_retire(slot)

    # ------------------------------------------------------------- decode

    def step(self) -> Dict[int, int]:
        """Advance every active slot one token; returns {slot: token}."""
        self._flush_pending()
        active = [i for i, r in enumerate(self.requests) if r is not None]
        if not active:
            return {}
        logits, self.cache = self._step_x(self.params, self.cache,
                                          self.lengths, self.cur_tok)
        toks = self._select(logits)
        # only active slots advance (a retired slot's write lands at its
        # frozen length — a row nothing reads until prefill rewrites it)
        mask = jnp.zeros((self.slots,), bool).at[
            jnp.asarray(active, jnp.int32)].set(True)
        self.lengths = jnp.where(mask, self.lengths + 1, self.lengths)
        self.cur_tok = jnp.where(mask, toks, self.cur_tok)
        out: Dict[int, int] = {}
        # ONE device->host transfer for the batch; per-element int(t)
        # would round-trip once per slot per step
        host_toks = [int(t) for t in np.asarray(toks)]
        for i in active:
            tok = host_toks[i]
            self.requests[i].tokens.append(tok)
            out[i] = tok
            self._maybe_retire(i)
        return out

    def step_many(self, k: int) -> Dict[int, List[int]]:
        """Advance every active slot ``k`` tokens in ONE dispatch (a
        ``lax.scan`` over :func:`llama.decode_step_slots`), returning
        ``{slot: [tokens...]}`` — each list truncated at the slot's
        retirement point.

        Why: per-token ``step()`` pays one host->device dispatch per
        emitted token, and on dispatch-heavy paths (tunneled backends:
        ~100 ms/dispatch measured) that — not the chip — bounds TPOT.
        One K-window amortizes the dispatch K-fold, the same trade
        ``generate_chunked`` makes for solo decode. Costs: a slot
        retiring mid-window wastes its remaining step-slots (bounded by
        K-1), and new requests wait up to one window for a slot. Retired
        slots are FROZEN inside the window (their length/token do not
        advance; the same dead row is rewritten), so nothing drifts.
        ``k == 1`` is exactly :meth:`step`.
        """
        if k <= 1:
            return {slot: [tok] for slot, tok in self.step().items()}
        self._flush_pending()
        active = [i for i, r in enumerate(self.requests) if r is not None]
        if not active:
            return {}
        x = self._stepk_x.get(k)
        if x is None:
            cfg, rope, mesh = self.cfg, self._rope, self.mesh

            def window(p, c, ln, tok, mask, key):
                def body(carry, _):
                    c, ln, tok, key = carry
                    logits, c = llama.decode_step_slots(
                        cfg, p, c, ln, tok, mesh=mesh, rope=rope)
                    key, sub = jax.random.split(key)
                    if self.sampler is None:
                        nxt = jnp.argmax(logits, axis=-1).astype(
                            jnp.int32)
                    else:
                        nxt = self.sampler(sub, logits).astype(jnp.int32)
                    nxt = jnp.where(mask, nxt, tok)
                    ln = jnp.where(mask, ln + 1, ln)
                    return (c, ln, nxt, key), nxt

                (c, ln, tok, key), toks = lax.scan(
                    body, (c, ln, tok, key), None, length=k)
                return c, ln, tok, key, toks          # toks [k, slots]

            x = jax.jit(window, donate_argnums=(1,))
            self._stepk_x[k] = x
        mask = jnp.zeros((self.slots,), bool).at[
            jnp.asarray(active, jnp.int32)].set(True)
        self.key, sub = jax.random.split(self.key)
        (self.cache, self.lengths, self.cur_tok, _, toks) = x(
            self.params, self.cache, self.lengths, self.cur_tok, mask,
            sub)
        host = np.asarray(toks)                       # ONE transfer
        out: Dict[int, List[int]] = {}
        for i in active:
            emitted: List[int] = []
            r = self.requests[i]
            for t in host[:, i]:
                emitted.append(int(t))
                r.tokens.append(int(t))
                self._maybe_retire(i)
                if self.requests[i] is None:
                    break   # retired mid-window: rest is dead compute
            out[i] = emitted
        return out

    def _maybe_retire(self, slot: int) -> None:
        r = self.requests[slot]
        if r is None:
            return
        done = (len(r.tokens) >= r.budget
                or (self.eos_id is not None
                    and r.tokens[-1] == self.eos_id)
                or r.prompt_len + len(r.tokens) >= self.cfg.max_seq)
        if done:
            self.finished[r.request_id] = r.tokens
            self.requests[slot] = None

    def reset(self) -> None:
        """Rebuild device state after a failed dispatch: the jitted step
        DONATES the cache, so an exception mid-step leaves ``self.cache``
        pointing at an invalidated buffer — re-init it (and the slot
        bookkeeping) rather than trying to serve through it. Weights are
        non-donated inputs and survive."""
        self.cache = llama.init_kv_cache(self.cfg, self.slots,
                                         self.cfg.max_seq)
        if self.mesh is not None and self.mesh.size > 1:
            self.cache = _shard_cache(self.cache, self.mesh)
        self.lengths = jnp.zeros((self.slots,), jnp.int32)
        self.cur_tok = jnp.zeros((self.slots,), jnp.int32)
        self.requests = [None] * self.slots
        self.finished.clear()
        # deferred tokens reference pre-reset device state: drop them
        self._pending_first.clear()

    def abort_active(self) -> int:
        """Drop every in-flight request without recording results (a
        failed drive loop resetting to a clean pool); returns how many
        were dropped. Slot cache rows need no cleanup — they are masked
        by length and rewritten by the next prefill."""
        dropped = 0
        for i, r in enumerate(self.requests):
            if r is not None:
                self.requests[i] = None
                dropped += 1
        self._pending_first.clear()
        return dropped

    # -------------------------------------------------------------- drive

    def drain(self, queue: List[Dict[str, Any]],
              decode_window: int = 1) -> Dict[Any, List[int]]:
        """Serve a whole workload: submit as slots free up, step until
        every request finishes. Each queue item: {"prompt": [...],
        "max_new": int, "request_id": any}. ``decode_window > 1``
        amortizes dispatch via :meth:`step_many` (greedy streams are
        identical — slots are independent)."""
        pending = list(queue)
        while pending or self.requests_active():
            placed = self.submit_many(pending)     # batched admission
            pending = pending[len(placed):]
            self.step_many(decode_window)
        return dict(self.finished)


# ---------------------------------------------------------------------------
# block-paged engine


def _copy_page(cache, src, dst):
    """Copy pool page ``src`` -> ``dst`` across every layer (payload +
    scales for int8 pools) — the eager copy-on-write of a prefix-cached
    boundary page at admission."""
    if isinstance(cache, QTensor):
        return QTensor(cache.q.at[:, dst].set(cache.q[:, src]),
                       cache.s.at[:, dst].set(cache.s[:, src]))
    return cache.at[:, dst].set(cache[:, src])


def _install_pages(cache, payload, phys):
    """Write a shipped span's K/V pages ``payload``
    ``[L, N, page, KV, D]`` into pool pages ``phys`` ``[N]`` — the
    adoption half of the disaggregated prefill/decode shipping path
    (``models/disagg.py``). Payload and scales both land for int8
    pools; the write is a page-granular scatter, no reshaping."""
    if isinstance(cache, QTensor):
        return QTensor(cache.q.at[:, phys].set(payload.q),
                       cache.s.at[:, phys].set(payload.s))
    return cache.at[:, phys].set(payload)


def _payload_slice(side, a: int, b: int):
    """Span payload pages ``[a:b)`` as the device-ready value
    :func:`_install_pages` writes (QTensor for int8 pools)."""
    if isinstance(side, dict):
        return QTensor(jnp.asarray(side["q"][:, a:b]),
                       jnp.asarray(side["s"][:, a:b]))
    return jnp.asarray(side[:, a:b])


class PagedServer:
    """Block-paged, prefix-shared continuous batching — the vLLM-style
    successor to :class:`SlotServer`, same drive surface (``submit`` /
    ``submit_many`` / ``step`` / ``step_many`` / ``drain`` / ``reset`` /
    ``abort_active`` and the ``requests``/``finished``/``free_slots``
    seams ingress and the gang driver consume), different memory model:

    * **Pages, not rows.** One device pool of ``pages`` fixed
      ``(page_size, KV, D)`` K/V pages (+ one scratch page) serves every
      stream through a per-stream page table; a request holds
      ``ceil((prompt + max_new) / page_size)`` pages instead of pinning
      a whole ``max_seq`` row, so admission is gated on **pages free**
      (the host-side :class:`~dcos_commons_tpu.models.paging.PagePool`
      ledger) — a long request no longer blocks a fistful of short ones.
    * **Chunked prefill.** Prompts prefill in fixed ``prefill_chunk``
      slices, ONE chunk per ``step``/``step_many`` call, interleaved
      with the decode dispatch — running streams keep emitting while a
      long prompt works through the queue, and one chunk executable
      replaces the slot engine's per-bucket prefill matrix.
    * **Prefix sharing.** Full prompt-prefix pages are hash-consed in a
      radix (:class:`~dcos_commons_tpu.models.paging.PrefixRadix`):
      identical system prompts across requests occupy ONE physical copy
      behind refcounts; the partial boundary page copies eagerly at
      admission (copy-on-write), so every page a stream *writes* is
      private by construction and the hot paths need no ownership mask.
    * **Scratch-page discipline.** Streams that are inactive, still
      prefilling, or retired mid-window have their table rows pointed at
      the scratch page for the decode dispatch, and padded chunk
      positions write there too — garbage never lands on a live
      (possibly shared) page.

    Greedy tokens are EXACTLY the slot engine's: the gathered page view
    reassembles the cache in logical order, so masked attention reduces
    in the same order over the same values.
    """

    # set by the ingress/prefill tiers (dcos_commons_tpu.tracing.Tracer);
    # engine-level spans record only for requests that carry a trace ctx
    tracer = None

    def __init__(self, cfg: llama.LlamaConfig, params, slots: int = 8,
                 pages: Optional[int] = None, page_size: int = 64,
                 prefill_chunk: int = 64, sampler=None,
                 key: Optional[jax.Array] = None,
                 eos_id: Optional[int] = None, mesh=None,
                 prefix_cache: bool = True, compile_cache=None,
                 tiers: Optional[PageTierStore] = None,
                 directory: Optional[PrefixDirectory] = None,
                 replica_id: str = "", peer_fetch=None,
                 moe=None, longctx_ring: int = 0,
                 ring_threshold: Optional[int] = None):
        if page_size < 1 or cfg.max_seq % page_size:
            raise ValueError(
                f"page_size {page_size} must divide max_seq "
                f"{cfg.max_seq} (the page table is fixed-width)")
        if prefill_chunk < 1:
            raise ValueError(f"prefill_chunk must be >= 1, got "
                             f"{prefill_chunk}")
        # ------------------------------------------------ MoE decode path
        # `moe` (a parallel.moe.MoEConfig) swaps every model executable's
        # FFN for the routed expert layer via llama.make_moe_ffn: on an
        # `ep` mesh the dispatch all-to-alls carry capacity-bounded
        # [E, C, D] buffers (the analysis hot-path budget); single-host
        # engines run the bitwise-equal local path. Paged KV is untouched
        # — routing happens entirely inside the FFN residual step.
        if moe is not None and "router" not in params["layers"]:
            raise ValueError(
                "moe config given but params carry no router; build "
                "them with llama.init_moe_params")
        if moe is None and "router" in params["layers"]:
            raise ValueError(
                "params carry a router but no moe config; pass "
                "moe=MoEConfig(...) so routing is explicit")
        self.moe = moe
        self._ffn = (llama.make_moe_ffn(cfg, moe, mesh)
                     if moe is not None else None)
        # --------------------------------------- sequence-parallel prefill
        # `longctx_ring` > 1 arms ring prefill: a prompt at/over
        # `ring_threshold` tokens prefills in ONE tick via
        # llama.prefill_ring across the mesh's `sp` axis (~seq/N per-host
        # time) and its K/V span lands page-aligned in the local pool —
        # decode gathers stay local. Anything that disqualifies a stream
        # (resumed prefix, over-long pad, missing axis) degrades to the
        # chunked path and counts a coded fallback, never drops a stream.
        self._ring_sp = int(longctx_ring)
        if self._ring_sp > 1:
            sp_have = mesh.shape.get("sp", 1) if mesh is not None else 1
            if sp_have != self._ring_sp:
                raise ValueError(
                    f"longctx_ring={longctx_ring} needs a mesh with an "
                    f"'sp' axis of that size; got "
                    f"{dict(mesh.shape) if mesh is not None else None}")
            if cfg.max_seq % self._ring_sp:
                raise ValueError(
                    f"longctx_ring={longctx_ring} must divide max_seq "
                    f"{cfg.max_seq} so padded prompts stay in-table")
            if cfg.kv_quant:
                raise ValueError(
                    "ring prefill installs bf16 K/V spans; kv_quant "
                    "pools are not supported with longctx_ring")
        self.ring_threshold = (int(ring_threshold)
                               if ring_threshold is not None
                               else 2 * prefill_chunk)
        self.cfg = cfg
        self.params = params
        self.slots = slots                     # concurrent stream cap
        self.page_size = page_size
        self.pages_per_stream = cfg.max_seq // page_size
        self.total_pages = (int(pages) if pages is not None
                            else slots * self.pages_per_stream)
        if self.total_pages < 1:
            raise ValueError(f"page pool needs >= 1 page, got "
                             f"{self.total_pages}")
        self.prefill_chunk = prefill_chunk
        self.sampler = sampler
        self.eos_id = eos_id
        self.mesh = mesh
        self.key = key if key is not None else jax.random.key(0)
        # physical index total_pages is the SCRATCH page: never in the
        # ledger, never read unmasked — inactive streams' decode writes
        # and padded chunk positions land there
        self.scratch = self.total_pages
        self.pool = llama.init_page_pool(cfg, self.total_pages + 1,
                                         page_size)
        if (mesh is not None and mesh.size > 1
                and mesh.shape.get("tp", 1) > 1):
            # same rank-5 layout as the slot cache (KV heads at axis 3),
            # so the slot engine's placement applies verbatim; the page
            # axis stays unsharded like the slot axis. ep/sp-only meshes
            # keep the pool replicated: expert parallelism shards the
            # FFN weights, ring prefill shards activations — each gang
            # member's pages are its own local pool
            self.pool = _shard_cache(self.pool, mesh)
        self.ledger = PagePool(self.total_pages, page_size)
        self.radix = PrefixRadix(self.ledger) if prefix_cache else None
        self._tables = np.full((slots, self.pages_per_stream),
                               self.scratch, np.int32)
        self.lengths = jnp.zeros((slots,), jnp.int32)
        self.cur_tok = jnp.zeros((slots,), jnp.int32)
        self.requests: List[Optional[_Request]] = [None] * slots
        self.finished: Dict[Any, List[int]] = {}
        self._pending_first: Dict[int, jax.Array] = {}
        self._stream_pages: List[List[int]] = [[] for _ in range(slots)]
        self._prompts: List[Optional[List[int]]] = [None] * slots
        self._prefill_pos = [0] * slots        # next position to prefill
        self._prefill_q: "deque[int]" = deque()
        self._decoding = [False] * slots       # prefill finished?
        rope = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
        self._rope = rope
        scratch = self.scratch
        # greedy engines at an identical (config, topology, geometry)
        # key share ONE set of jitted wrappers through the AOT cache —
        # XLA's executable cache is per wrapper object, so the second
        # homogeneous replica decodes without a re-trace/re-compile;
        # sampled engines bypass it (the window lambda closes over
        # self.sampler, which is engine-private)
        ns = None
        if compile_cache is not None and sampler is None:
            from ..parallel.aot import engine_key
            extra: Dict[str, Any] = {}
            if moe is not None:
                # routing identity is executable identity: a different
                # expert count / capacity / router traces different HLO
                extra.update(moe_experts=moe.num_experts,
                             moe_capacity=moe.capacity_factor,
                             moe_routing=moe.routing)
            if self._ring_sp > 1:
                extra.update(ring=self._ring_sp)
            ns = compile_cache.namespace(engine_key(
                cfg, mesh, kind="paged", slots=slots,
                pages=self.total_pages, page_size=page_size,
                prefill_chunk=prefill_chunk, **extra))
        if ns:
            self._step_x = ns["step"]
            self._stepk_x = ns["stepk"]
            self._chunk_x = ns["chunk"]
            self._copy_x = ns["copy"]
            self._adopt_x = ns["adopt"]
        else:
            # pool donated everywhere it flows through jit, like the
            # slot cache: it dominates HBM and every executable returns
            # a same-shaped pool
            ffn = self._ffn
            self._step_x = jax.jit(
                lambda p, c, tbl, ln, tok: llama.decode_step_paged(
                    cfg, p, c, tbl, ln, tok, mesh=mesh, rope=rope,
                    ffn_override=ffn),
                donate_argnums=(1,))
            self._stepk_x: Dict[int, Any] = {}
            self._chunk_x = jax.jit(
                lambda p, c, tbl, toks, st, tl, li:
                    llama.prefill_chunk_paged(cfg, p, c, tbl, toks, st,
                                              tl, li, scratch, mesh=mesh,
                                              rope=rope,
                                              ffn_override=ffn),
                donate_argnums=(1,))
            self._copy_x = jax.jit(
                lambda c, src, dst: {"k": _copy_page(c["k"], src, dst),
                                     "v": _copy_page(c["v"], src, dst)},
                donate_argnums=(0,))
            # adoption scatter executables, one per installed-page count
            self._adopt_x: Dict[int, Any] = {}
            if ns is not None:
                ns.update(step=self._step_x, stepk=self._stepk_x,
                          chunk=self._chunk_x, copy=self._copy_x,
                          adopt=self._adopt_x)
        # disaggregation counters (page_stats): spans this engine
        # prefilled for shipment / adopted from a peer / pages the
        # radix deduped at adoption (shipped system prompts)
        self.shipped_spans = 0
        self.adopted_spans = 0
        self.adopt_shared_pages = 0
        # live-migration counters (models/migrate.py): streams this
        # engine drained away after a confirmed adoption / resumed from
        # a peer's exported decode state
        self.migrated_out = 0
        self.migrated_in = 0
        # ------------------------------------------------- KV hierarchy
        # cold radix pages demote HBM -> host -> disk through `tiers`
        # (every eviction routes through the ONE demote seam,
        # PrefixRadix.evict's demoter); `directory` + `peer_fetch`
        # (replica_id, prompt_prefix) -> span let a miss adopt a
        # fleet-hot prefix from a sibling instead of recomputing.
        # Promotion is ASYNCHRONOUS: admission only plans it, the plan
        # lands in _tier_tick at the top of the next step — the hit
        # stream sits out exactly one step, the decode batch never
        # blocks on a host/disk/peer read.
        self.tiers = tiers
        self.directory = directory
        self.replica_id = replica_id or f"paged-{id(self):x}"
        self.peer_fetch = peer_fetch
        self._pending_tier: List[Dict[str, Any]] = []
        self.tier_demoted_pages = 0    # pages gathered out on eviction
        self.tier_promoted_pages = 0   # pages installed back from tiers
        self.tier_fallbacks = 0        # planned promotes that recomputed
        self.tier_promote_s = 0.0      # cumulative promote-install time
        self.directory_hits = 0        # admissions served by a sibling
        self.directory_fallbacks = 0   # stale hints -> recompute
        self.adopted_prefix_pages = 0  # pages installed from siblings
        self.exported_prefixes = 0     # prefix spans served to siblings
        # ---------------------------------------------- speculative decode
        # armed via arm_draft(): the decode dispatch becomes ONE fused
        # draft-scan + paged-verify window per step_many call. The draft
        # keeps its KV in a private SLOT cache (it is orders cheaper than
        # the target, so monolithic rows cost nothing that matters) and
        # its executables stay engine-private — draft identity is not in
        # the AOT engine key, so they must never enter the shared
        # namespace. Disarmed (the default), nothing below is touched
        # and every path is bitwise the solo engine.
        self._draft: Optional[Tuple[llama.LlamaConfig, Any]] = None
        self._draft_cache = None
        self._draft_rope = None
        self.draft_k = 0
        self.metrics = None            # optional shared MetricsRegistry
        self._spec_x = None            # the fused window executable
        self._draft_prefill_x: Dict[int, Any] = {}   # padded len -> exe
        self.spec_windows = 0          # fused draft+verify dispatches
        self.spec_proposed = 0         # draft tokens offered to verify
        self.spec_accepted = 0         # draft tokens the target kept
        self.spec_fallbacks = 0        # windows degraded to solo decode
        self.spec_draft_prefill_s = 0.0
        self.spec_window_s = 0.0
        # ------------------------------------------- long-context counters
        # ring prefill executables are keyed on the PADDED prompt length
        # (each distinct s_pad traces its own HLO; prompts pad to
        # lcm(sp, page_size) multiples so the working set stays small)
        self._ring_x: Dict[int, Any] = {}
        self.ring_prefills = 0         # prompts prefilled via the ring
        self.ring_prefilled_tokens = 0
        self.ring_prefill_s = 0.0      # cumulative ring-prefill time
        self.longctx_fallbacks = 0     # ring attempts degraded to chunks

    # the engine-thread-only helpers are identical to the slot engine's
    _select = SlotServer._select
    drain = SlotServer.drain

    # ----------------------------------------------- speculative decoding

    def arm_draft(self, cfg_d: llama.LlamaConfig, params_d, k: int = 4,
                  metrics=None, warmup: bool = True) -> None:
        """Arm the speculative decode path: ``step_many`` windows run
        draft-propose + paged-verify fused in one dispatch, advancing
        every stream by ``1 + accepted`` tokens per target weight pass.

        Compatibility is checked HERE, before any live stream exists
        (:class:`~dcos_commons_tpu.models.speculative.DraftIncompatible`
        with a stable ``code`` on mismatch — the serving path catches it
        and keeps decoding solo), and ``warmup`` traces + compiles the
        fused window against scratch state so a draft the compiler
        rejects also fails at arm time, not mid-stream. Greedy engines
        only: acceptance is an argmax compare, so a sampled engine must
        keep its host-loop semantics."""
        from dcos_commons_tpu.models.speculative import DraftIncompatible
        if self.sampler is not None:
            raise DraftIncompatible(
                "draft_sampled_engine",
                "speculative decode is greedy-only; this engine samples")
        if self._ffn is not None:
            raise DraftIncompatible(
                "draft_moe_engine",
                "speculative decode is not supported on MoE engines: the "
                "K-wide verify pass routes a k-token group while the "
                "accepted history was routed one token at a time, so "
                "verify logits would not match the committed path")
        if k < 2:
            raise DraftIncompatible("draft_k", f"draft k must be >= 2, "
                                               f"got {k}")
        if cfg_d.vocab_size != self.cfg.vocab_size:
            raise DraftIncompatible(
                "draft_vocab_mismatch",
                f"draft vocab {cfg_d.vocab_size} != target "
                f"{self.cfg.vocab_size}")
        if cfg_d.rope_theta != self.cfg.rope_theta:
            raise DraftIncompatible(
                "draft_rope_mismatch",
                f"draft rope_theta {cfg_d.rope_theta} != target "
                f"{self.cfg.rope_theta}")
        if cfg_d.max_seq < self.cfg.max_seq:
            raise DraftIncompatible(
                "draft_max_seq",
                f"draft max_seq {cfg_d.max_seq} < target "
                f"{self.cfg.max_seq}: the draft cannot cover every "
                "position this engine serves")
        # the draft cache stays bf16 whatever the target pool does —
        # int8 KV pays off on the model that dominates HBM, not here.
        # Execution policy follows the engine: a sealed draft artifact
        # records architecture only, so a loaded cfg carries DEFAULTS
        # for the rest — attn_impl "auto" (would resolve its own
        # attention path independently of the engine's) and remat True
        # (per-layer jax.checkpoint: pure recompute overhead in a path
        # that never backprops)
        cfg_d = dataclasses.replace(cfg_d, kv_quant=False,
                                    attn_impl=self.cfg.attn_impl,
                                    remat=False, remat_policy=None)
        self._draft = (cfg_d, params_d)
        self.draft_k = int(k)
        self.metrics = metrics
        self._draft_rope = rope_frequencies(cfg_d.head_dim, cfg_d.max_seq,
                                            cfg_d.rope_theta)
        self._draft_cache = llama.init_kv_cache(cfg_d, self.slots,
                                                cfg_d.max_seq)
        self._spec_x = self._build_spec_x()
        self._draft_prefill_x.clear()
        if warmup:
            # full-width table: the live path truncates columns per
            # window (_window_mp), but compiling the widest shape here
            # surfaces any compiler rejection of THIS draft at arm time
            mask = jnp.zeros((self.slots,), bool)
            tbl = jnp.full((self.slots, self.pages_per_stream),
                           self.scratch, jnp.int32)
            ones = jnp.ones((self.slots,), jnp.int32)
            zeros = jnp.zeros((self.slots,), jnp.int32)
            out = self._spec_x(self.params, params_d, self.pool,
                               self._draft_cache, tbl, ones, zeros, mask)
            (self.pool, self._draft_cache, tgt, n_emit) = out[:4]
            jax.block_until_ready(tgt)

    def disarm_draft(self) -> None:
        """Back to solo decode; the draft cache is dropped. Counters
        survive — a fallback must stay visible after it happens."""
        self._draft = None
        self._draft_cache = None
        self._spec_x = None
        self._draft_prefill_x.clear()
        self.draft_k = 0

    def _build_spec_x(self):
        """ONE jitted program per armed draft: k-step draft scan (slot
        cache, greedy) -> K-wide paged verify -> on-device acceptance.
        Pool and draft cache are donated — together they dominate HBM
        and both return same-shaped."""
        cfg, mesh, rope = self.cfg, self.mesh, self._rope
        cfg_d, _ = self._draft
        rope_d = self._draft_rope
        k = self.draft_k

        def window(p, pd, pool, cache_d, tbl, ln, tok, mask):
            def dstep(carry, j):
                cache_d, cur = carry
                lg, cache_d = llama.decode_step_slots(
                    cfg_d, pd, cache_d, ln + j, cur, rope=rope_d)
                nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
                nxt = jnp.where(mask, nxt, cur)
                return (cache_d, nxt), nxt

            # k draft steps consuming [cur, d_1..d_{k-1}]: the k-th
            # proposal is discarded but its step writes d_{k-1}'s K/V,
            # so a fully-accepted window leaves no draft-cache hole
            # (models/speculative.py's window discipline, verbatim)
            (cache_d, _), dtoks = lax.scan(dstep, (cache_d, tok),
                                           jnp.arange(k))
            window_toks = jnp.concatenate(
                [tok[:, None], dtoks[:k - 1].T], axis=1)     # [B, k]
            logits, pool = llama.verify_step_paged(
                cfg, p, pool, tbl, ln, window_toks, mesh=mesh, rope=rope)
            tgt = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, k]
            agree = jnp.cumprod(
                (dtoks[:k - 1].T == tgt[:, :k - 1]).astype(jnp.int32),
                axis=1)
            n_emit = jnp.where(mask, jnp.sum(agree, axis=1) + 1, 0)
            new_ln = ln + n_emit
            new_cur = jnp.take_along_axis(
                tgt, jnp.maximum(n_emit - 1, 0)[:, None], axis=1)[:, 0]
            new_cur = jnp.where(mask, new_cur, tok)
            return pool, cache_d, tgt, n_emit, new_ln, new_cur

        return jax.jit(window, donate_argnums=(2, 3))

    def _draft_prefill(self, slot: int, prompt: List[int]) -> None:
        """Write the draft's K/V for a freshly-prefilled stream: one
        whole-prompt forward (the draft is cheap enough that chunking
        buys nothing), padded to the engine's prefill_chunk granularity
        so a handful of executables serve every prompt length. Padded
        tail rows are causally downstream garbage the masked reads never
        see and decode overwrites before they become readable."""
        cfg_d, params_d = self._draft
        n = len(prompt)
        c = self.prefill_chunk
        padded = -(-n // c) * c
        x = self._draft_prefill_x.get(padded)
        if x is None:
            rope_d = self._draft_rope

            def run(pd, cache_d, toks, slot_i):
                _, ks, vs = llama.prefill_trunk(cfg_d, pd, toks, rope_d)
                at = (0, slot_i, 0, 0, 0)
                return {"k": lax.dynamic_update_slice(
                            cache_d["k"], ks.astype(cache_d["k"].dtype),
                            at),
                        "v": lax.dynamic_update_slice(
                            cache_d["v"], vs.astype(cache_d["v"].dtype),
                            at)}

            x = jax.jit(run, donate_argnums=(1,))
            self._draft_prefill_x[padded] = x
        buf = np.zeros((1, padded), np.int32)
        buf[0, :n] = prompt
        t0 = time.perf_counter()
        self._draft_cache = x(params_d, self._draft_cache,
                              jnp.asarray(buf), jnp.int32(slot))
        dt = time.perf_counter() - t0
        self.spec_draft_prefill_s += dt
        if self.metrics is not None:
            self.metrics.observe("serving.spec.draft_prefill_seconds", dt)

    def warmup(self, widths=(1,)) -> Dict[str, float]:
        """Pre-trace + compile the serving executables BEFORE admission
        — the cold-start ``compile`` phase, made a receipted number: one
        prefill chunk plus one decode step per decode-table width in
        ``widths``, every write landing on the scratch page so no live
        state is touched. With a shared ``compile_cache`` namespace the
        same call costs only executable lookups. ``widths`` should cover
        the page-window widths expected at admission (a width not warmed
        compiles lazily on first use, exactly as before). Returns
        ``{phase: seconds}``."""
        timings: Dict[str, float] = {}
        t0 = time.perf_counter()
        row = np.full((self.pages_per_stream,), self.scratch, np.int32)
        c = self.prefill_chunk
        logits, self.pool = self._chunk_x(
            self.params, self.pool, jnp.asarray(row),
            jnp.zeros((1, c), jnp.int32), jnp.int32(0), jnp.int32(c),
            jnp.int32(c - 1))
        jax.block_until_ready(logits)
        timings["chunk"] = time.perf_counter() - t0
        ones = jnp.ones((self.slots,), jnp.int32)
        zeros = jnp.zeros((self.slots,), jnp.int32)
        for w in widths:
            t1 = time.perf_counter()
            tbl = jnp.full((self.slots, int(w)), self.scratch, jnp.int32)
            logits, self.pool = self._step_x(self.params, self.pool,
                                             tbl, ones, zeros)
            jax.block_until_ready(logits)
            timings[f"step_w{int(w)}"] = time.perf_counter() - t1
        return timings

    def _flush_pending(self) -> None:
        """:meth:`SlotServer._flush_pending`, plus decode ACTIVATION:
        a stream joins the decode batch only once its first token is in
        ``r.tokens`` — order and EOS/budget checks then see tokens in
        emission order."""
        if not self._pending_first:
            return
        items = sorted(self._pending_first.items())
        self._pending_first.clear()
        vals = np.asarray(jnp.stack([t for _, t in items]))
        for (slot, _), tok in zip(items, vals):
            r = self.requests[slot]
            if r is None:
                continue                       # aborted before flush
            r.tokens.append(int(tok))
            self._decoding[slot] = True
            self._maybe_retire(slot)

    # ------------------------------------------------------------ intake

    def free_slots(self) -> List[int]:
        return [i for i, r in enumerate(self.requests) if r is None]

    def requests_active(self) -> bool:
        return any(r is not None for r in self.requests)

    def pages_free(self) -> int:
        return self.ledger.free_count()

    def _validate_item(self, item: Dict[str, Any]) -> Optional[str]:
        prompt = item["prompt"]
        max_new = item.get("max_new", 32)
        if not prompt:
            return "empty prompt"
        if len(prompt) + max_new > self.cfg.max_seq:
            return (f"prompt {len(prompt)} + max_new {max_new} exceeds "
                    f"the cache ({self.cfg.max_seq}); raise max_seq or "
                    "shrink the ask")
        need = -(-(len(prompt) + max_new) // self.page_size)
        if need > self.total_pages:
            # permanently infeasible (no amount of retirement frees
            # enough): reject loudly like over-max_seq, never queue
            return (f"prompt {len(prompt)} + max_new {max_new} needs "
                    f"{need} pages but the pool holds "
                    f"{self.total_pages}; raise SERVE_PAGES or shrink "
                    "the ask")
        return None

    def submit(self, prompt: List[int], max_new: int = 32,
               request_id: Any = None) -> Optional[int]:
        """Admit ``prompt``: reserve its FULL page span (prompt +
        max_new — the table stays constant through decode), share any
        cached full-prefix pages, copy the boundary page, and queue the
        uncached tail for chunked prefill. Returns the stream index, or
        None when streams or pages are exhausted (caller re-offers
        later). No device forward happens here — prefill is paid one
        chunk per step, interleaved with decode."""
        reason = self._validate_item({"prompt": prompt,
                                      "max_new": max_new})
        if reason is not None:
            raise ValueError(reason)
        self._flush_pending()
        return self._admit(list(prompt), max_new, request_id)

    # ------------------------------------------------------- KV hierarchy

    def _evict(self, need: int) -> int:
        """THE single release path for radix pages under pressure:
        every eviction flows through here so a tiered engine demotes
        each victim's bytes to host/disk BEFORE its reference drops —
        the page either stays accounted in the ledger or its content
        moves wholly into the tier store, never a leaked in-between."""
        demoter = self._demote if self.tiers is not None else None
        return self.radix.evict(need, demoter=demoter)

    def _demote(self, page: int, prefix_tokens: List[int]) -> None:
        """Demoter callback (``PrefixRadix.evict``): gather the
        victim's device bytes and file them under the prefix's chain
        key while the page still holds its last reference. Best-effort
        — a failed gather just loses the cold copy, never the evict."""
        try:
            hs = page_hashes(prefix_tokens, self.page_size)
            ck = chain_keys(prefix_tokens, self.page_size)
            self.tiers.put(ck[-1], {
                "chain": ck[-1], "page_hash": hs[-1],
                "kv_quant": bool(self.cfg.kv_quant),
                "payload": self._gather_span([page])})
            self.tier_demoted_pages += 1
        except Exception:
            pass

    def _radix_adopt(self, prompt: List[int], pages: List[int]) -> int:
        """THE single adopt path into the radix: insert, then resolve
        ownership fleet-wide — tier frames for the re-acquired chains
        are discarded (content lives in HBM XOR the tiers, the
        single-owner rule a promote racing an evict relies on) and the
        chains are published to the prefix directory so siblings can
        adopt them instead of recomputing."""
        adopted = self.radix.insert(prompt, pages)
        full = min(len(prompt) // self.page_size, len(pages))
        if full and (self.tiers is not None or self.directory is not None):
            cks = chain_keys(prompt[:full * self.page_size],
                             self.page_size)
            if self.tiers is not None:
                for ck in cks:
                    self.tiers.discard(ck)
            if self.directory is not None:
                self.directory.publish(self.replica_id, cks)
        return adopted

    def _tier_plan(self, prompt: List[int],
                   matched_pages: int) -> Optional[Dict[str, Any]]:
        """Plan covering full prompt pages PAST the radix match from
        colder sources: consecutive demoted frames in the tier store
        first, else the longest fleet-hot prefix a directory sibling
        claims. Returns the pending-promote record (landed later by
        :meth:`_tier_tick`) or None when only recompute remains. At
        least one prompt token always stays uncovered, mirroring the
        radix lookup's first-token rule."""
        if self.tiers is None and self.directory is None:
            return None
        ps = self.page_size
        max_cover = (len(prompt) - 1) // ps
        if matched_pages >= max_cover:
            return None
        cks = chain_keys(prompt[:max_cover * ps], ps)
        if self.tiers is not None:
            cover = matched_pages
            while cover < max_cover and self.tiers.has(cks[cover]):
                cover += 1
            if cover > matched_pages:
                return {"kind": "tier", "base": matched_pages,
                        "chains": cks[matched_pages:cover]}
        if self.directory is not None and self.peer_fetch is not None:
            for j in range(max_cover, matched_pages, -1):
                holder = self.directory.lookup(cks[j - 1],
                                               exclude=self.replica_id)
                if holder is not None:
                    return {"kind": "fleet", "base": matched_pages,
                            "holder": holder, "cover": j}
        return None

    def _admit(self, prompt: List[int], max_new: int,
               request_id: Any) -> Optional[int]:
        free = self.free_slots()
        if not free:
            return None
        slot = free[0]
        n = len(prompt)
        ps = self.page_size
        total = -(-(n + max_new) // ps)
        shared: List[int] = []
        node = None
        if self.radix is not None:
            shared, node = self.radix.lookup(prompt)
        plan = (self._tier_plan(prompt, len(shared))
                if self.radix is not None else None)
        own_needed = total - len(shared)
        pages = self.ledger.alloc(own_needed)
        if pages is None and self.radix is not None:
            # under pressure the radix gives back LRU unshared pages
            self._evict(own_needed - self.ledger.free_count())
            pages = self.ledger.alloc(own_needed)
        if pages is None:
            for p in shared:                   # undo the lookup refs
                self.ledger.unref(p)
            return None
        matched = len(shared) * ps
        start = matched
        if node is not None and plan is None:
            b = self.radix.boundary(node, prompt, matched)
            if b is not None:
                src, valid = b
                # eager COW: the cached page's first `valid` rows are
                # bit-identical K/V for our positions; copy it into our
                # first private page and prefill only past them (the
                # copy's garbage tail is overwritten / never read)
                self.pool = self._copy_x(self.pool, jnp.int32(src),
                                         jnp.int32(pages[0]))
                start = matched + valid
        stream_pages = shared + pages
        row = self._tables[slot]
        row[:] = self.scratch
        row[:total] = stream_pages
        self._stream_pages[slot] = stream_pages
        self._prompts[slot] = prompt
        self._prefill_pos[slot] = start
        self._decoding[slot] = False
        rid = request_id if request_id is not None else object()
        self.requests[slot] = _Request(rid, n, max_new, [])
        if plan is not None:
            # async promote: the stream defers ONE step (it joins the
            # prefill queue when _tier_tick lands or abandons the plan)
            # so the decode gather never blocks on a cold-tier read
            plan["slot"] = slot
            plan["req"] = self.requests[slot]
            self._pending_tier.append(plan)
        else:
            self._prefill_q.append(slot)
        return slot

    def submit_many(self, items: List[Dict[str, Any]],
                    on_invalid=None) -> List[Tuple[int, Any]]:
        """Admit a FIFO PREFIX of ``items`` (first stream or page
        exhaustion stops intake — pages behind the blocked head would
        starve it forever under sustained load). Admission is pure host
        bookkeeping (+ at most one page-copy dispatch per prefix hit),
        so there is nothing to batch the way the slot engine batches
        prefill — the device work happens chunk-by-chunk in step()."""
        admissible = []
        for item in items:
            reason = self._validate_item(item)
            if reason is None:
                admissible.append(item)
            elif on_invalid is not None:
                on_invalid(item, reason)
            else:
                raise ValueError(reason)
        self._flush_pending()
        placed: List[Tuple[int, Any]] = []
        for item in admissible:
            slot = self._admit(list(item["prompt"]),
                               item.get("max_new", 32),
                               item.get("request_id"))
            if slot is None:
                break
            placed.append((slot, self.requests[slot].request_id))
        return placed

    # ----------------------------------------------------- disaggregation

    def prefill_span(self, prompt: List[int],
                     trace=None) -> Optional[Dict[str, Any]]:
        """Prefill-only engine mode: run ``prompt`` through chunked
        prefill FLAT-OUT — every chunk back to back, no decode
        interleave, no slot occupied — and return the finished span:
        the prompt's K/V pages pulled to host plus the first generated
        token. This is the prefill tier's entire job in disaggregated
        serving (``models/disagg.py``): the span ships to a decode tier
        and is installed there by :meth:`adopt_pages`.

        The span's full prompt pages are adopted into THIS engine's
        radix before its working references drop, so a repeated system
        prompt skips the prefill compute on the next call (the prefill
        tier keeps its own prefix cache). Returns None when the pool is
        exhausted (transient — spans release right after extraction, so
        the caller retries / sheds), raises ValueError for prompts this
        engine can never prefill. ``trace`` is an optional incoming
        trace context (``X-Tpu-Trace``): the flat-out prefill records
        one span under it."""
        t_pre0 = time.perf_counter()
        prompt = list(prompt)
        n = len(prompt)
        if not prompt:
            raise ValueError("empty prompt")
        if n + 1 > self.cfg.max_seq:
            # the decode side must have room for >= 1 generated token
            raise ValueError(f"prompt {n} leaves no decode room in "
                             f"max_seq {self.cfg.max_seq}")
        ps = self.page_size
        span_pages = -(-n // ps)
        if span_pages > self.total_pages:
            raise ValueError(f"prompt {n} needs {span_pages} pages but "
                             f"the pool holds {self.total_pages}")
        shared: List[int] = []
        node = None
        if self.radix is not None:
            shared, node = self.radix.lookup(prompt)
        own_needed = span_pages - len(shared)
        pages = self.ledger.alloc(own_needed)
        if pages is None and self.radix is not None:
            self._evict(own_needed - self.ledger.free_count())
            pages = self.ledger.alloc(own_needed)
        if pages is None:
            for p in shared:
                self.ledger.unref(p)
            return None
        matched = len(shared) * ps
        start = matched
        if node is not None:
            b = self.radix.boundary(node, prompt, matched)
            if b is not None:
                src, valid = b
                self.pool = self._copy_x(self.pool, jnp.int32(src),
                                         jnp.int32(pages[0]))
                start = matched + valid
        stream_pages = shared + pages
        row = np.full((self.pages_per_stream,), self.scratch, np.int32)
        row[:span_pages] = stream_pages
        tbl = jnp.asarray(row)
        c = self.prefill_chunk
        while True:
            end = min(start + c, n)
            chunk = np.zeros((1, c), np.int32)
            chunk[0, :end - start] = prompt[start:end]
            last = end >= n
            li = (n - 1 - start) if last else 0
            logits, self.pool = self._chunk_x(
                self.params, self.pool, tbl, jnp.asarray(chunk),
                jnp.int32(start), jnp.int32(n), jnp.int32(li))
            start = end
            if last:
                break
        first = int(self._select(logits)[0])
        payload = self._gather_span(stream_pages)
        if self.radix is not None:
            self._radix_adopt(prompt, stream_pages)
        for p in stream_pages:
            self.ledger.unref(p)
        self.shipped_spans += 1
        tracer = self.tracer
        if tracer is not None and trace is not None:
            tracer.record("engine.prefill_span", t_pre0,
                          time.perf_counter(), parent=trace,
                          prompt_len=n, pages=span_pages,
                          shared_pages=len(shared))
        return {"version": 1, "prompt": prompt, "first_token": first,
                "page_size": ps, "kv_quant": bool(self.cfg.kv_quant),
                "payload": payload}

    def _gather_span(self, pages: List[int]) -> Dict[str, Any]:
        """Pull the span's pages to host in logical order —
        ``[L, N, page, KV, D]`` per side (q + scales as a dict for int8
        pools). One device->host transfer per side; this IS the bytes
        the shipper puts on the wire."""
        idx = jnp.asarray(pages, jnp.int32)

        def take(side):
            if isinstance(side, QTensor):
                return {"q": np.asarray(side.q[:, idx]),
                        "s": np.asarray(side.s[:, idx])}
            return np.asarray(side[:, idx])

        return {"k": take(self.pool["k"]), "v": take(self.pool["v"])}

    def adopt_pages(self, span: Dict[str, Any], max_new: int = 32,
                    request_id: Any = None) -> Optional[int]:
        """Install a foreign prefill span (:meth:`prefill_span` on a
        peer engine, possibly shipped across the wire by
        ``models/disagg.py``) under the normal refcount/ledger
        discipline and start the stream decode-active at its first
        token.

        Admission is gated on **pages free** exactly like
        :meth:`submit` — returns the stream index, or None when slots
        or pages are exhausted (the caller re-offers later). The radix
        dedupes shipped content: full prompt pages already cached
        (repeated system prompts) are shared by reference and their
        payload slices are never written. Raises ValueError for spans
        this engine can never admit (config mismatch, over-capacity) —
        checked BEFORE any reservation; a failure AFTER pages are
        reserved unwinds every reservation before re-raising, so
        ``check()``/``reconcile()`` hold across aborted adoptions."""
        t_adopt0 = time.perf_counter()
        prompt = list(span["prompt"])
        n = len(prompt)
        first = int(span["first_token"])
        if int(span.get("page_size", self.page_size)) != self.page_size:
            raise ValueError(
                f"span page_size {span.get('page_size')} != pool page "
                f"size {self.page_size}; tiers must agree")
        if bool(span.get("kv_quant")) != bool(self.cfg.kv_quant):
            raise ValueError("span/pool kv_quant mismatch: shipped "
                             "pages are raw pool bytes, tiers must "
                             "run the same KV dtype")
        reason = self._validate_item({"prompt": prompt,
                                      "max_new": max_new})
        if reason is not None:
            raise ValueError(reason)
        ps = self.page_size
        span_pages = -(-n // ps)
        payload = span["payload"]

        def _shape(x):
            return tuple((x["q"] if isinstance(x, dict) else x).shape)

        want = (self.cfg.n_layers, span_pages, ps, self.cfg.n_kv_heads,
                self.cfg.head_dim)
        if _shape(payload["k"]) != want or _shape(payload["v"]) != want:
            raise ValueError(f"span payload shape "
                             f"{_shape(payload['k'])} != pool page "
                             f"shape {want}")
        self._flush_pending()
        free = self.free_slots()
        if not free:
            return None
        slot = free[0]
        total = -(-(n + max_new) // ps)
        shared: List[int] = []
        if self.radix is not None:
            shared, _ = self.radix.lookup(prompt)
        own_needed = total - len(shared)
        pages = self.ledger.alloc(own_needed)
        if pages is None and self.radix is not None:
            self._evict(own_needed - self.ledger.free_count())
            pages = self.ledger.alloc(own_needed)
        if pages is None:
            for p in shared:
                self.ledger.unref(p)
            return None
        matched = len(shared)
        try:
            # write the shipped K/V for prompt pages past the radix
            # match; decode-tail pages (past span_pages) start blank
            # like any stream's — the decode loop fills them
            install = span_pages - matched
            if install > 0:
                self.pool = self._adopt_exec(install)(
                    self.pool,
                    _payload_slice(payload["k"], matched, span_pages),
                    _payload_slice(payload["v"], matched, span_pages),
                    jnp.asarray(pages[:install], jnp.int32))
        except Exception:
            # aborted transfer: every reservation unwinds, the ledger
            # reconciles clean (chaos invariant "kv-ship")
            for p in shared:
                self.ledger.unref(p)
            for p in pages:
                self.ledger.unref(p)
            raise
        stream_pages = shared + pages
        row = self._tables[slot]
        row[:] = self.scratch
        row[:total] = stream_pages
        self._stream_pages[slot] = stream_pages
        self._prompts[slot] = prompt
        self._prefill_pos[slot] = n
        self._decoding[slot] = True
        self.lengths = self.lengths.at[slot].set(n)
        self.cur_tok = self.cur_tok.at[slot].set(first)
        rid = request_id if request_id is not None else object()
        self.requests[slot] = _Request(rid, n, max_new, [first])
        self.adopted_spans += 1
        self.adopt_shared_pages += matched
        tracer = self.tracer
        if tracer is not None:
            ctx = getattr(rid, "trace", None)
            if ctx is not None:
                tracer.record("engine.adopt_span", t_adopt0,
                              time.perf_counter(), parent=ctx,
                              pages=span_pages, shared_pages=matched)
        self._maybe_retire(slot)
        return slot

    def _adopt_exec(self, n: int):
        x = self._adopt_x.get(n)
        if x is None:
            x = jax.jit(
                lambda c, kp, vp, ph: {
                    "k": _install_pages(c["k"], kp, ph),
                    "v": _install_pages(c["v"], vp, ph)},
                donate_argnums=(0,))
            self._adopt_x[n] = x
        return x

    # --------------------------------------------------- tier promotion

    def _tier_tick(self) -> None:
        """Land every pending promote/adoption plan queued at
        admission: install the cold bytes into the stream's own pages,
        adopt the covered prefix into the radix, and ONLY THEN let the
        stream enter the prefill queue — the one-step deferral that
        keeps the decode dispatch from ever blocking on a host/disk
        read or a peer fetch. A plan whose frames went missing or
        corrupt (or whose directory hint went stale) falls back to
        recomputing from the radix-matched position; the stream loses
        the shortcut, never tokens."""
        if not self._pending_tier:
            return
        plans, self._pending_tier = self._pending_tier, []
        for plan in plans:
            slot = plan["slot"]
            if self.requests[slot] is not plan["req"]:
                continue                       # aborted while deferred
            t0 = time.perf_counter()
            if plan["kind"] == "tier":
                ok = self._promote_from_tier(plan)
                if not ok:
                    self.tier_fallbacks += 1
            else:
                ok = self._adopt_from_fleet(plan)
                if not ok:
                    self.directory_fallbacks += 1
            self.tier_promote_s += time.perf_counter() - t0
            self._prefill_q.append(slot)

    @staticmethod
    def _concat_pages(sides: List[Any]):
        """Stack per-page payloads ``[L, 1, page, KV, D]`` into one
        span payload along the page axis (QTensor dict for int8)."""
        if isinstance(sides[0], dict):
            return {"q": np.concatenate([s["q"] for s in sides], axis=1),
                    "s": np.concatenate([s["s"] for s in sides], axis=1)}
        return np.concatenate(sides, axis=1)

    def _promote_from_tier(self, plan: Dict[str, Any]) -> bool:
        """Install the longest verified run of demoted frames for the
        plan's chains. ``take`` POPS each frame — this promote is the
        content's single owner the instant it holds the bytes, so an
        eviction re-demoting the same chain mid-flight can only file a
        NEW copy, which :meth:`_radix_adopt` discards when the chain
        re-enters HBM (exactly-one-owner, the chaos ``kv-tier-owner``
        invariant)."""
        slot = plan["slot"]
        prompt = self._prompts[slot]
        ps = self.page_size
        entries = []
        for ck in plan["chains"]:
            e = self.tiers.take(ck)
            if (e is None
                    or bool(e.get("kv_quant")) != bool(self.cfg.kv_quant)):
                break                          # missing/corrupt: stop run
            entries.append(e)
        if not entries:
            return False
        m = len(entries)
        base = plan["base"]
        payload = {
            "k": self._concat_pages([e["payload"]["k"] for e in entries]),
            "v": self._concat_pages([e["payload"]["v"] for e in entries])}
        want = (self.cfg.n_layers, m, ps, self.cfg.n_kv_heads,
                self.cfg.head_dim)

        def _shape(x):
            return tuple((x["q"] if isinstance(x, dict) else x).shape)

        if _shape(payload["k"]) != want or _shape(payload["v"]) != want:
            return False                       # foreign geometry: recompute
        phys = self._stream_pages[slot][base:base + m]
        self.pool = self._adopt_exec(m)(
            self.pool,
            _payload_slice(payload["k"], 0, m),
            _payload_slice(payload["v"], 0, m),
            jnp.asarray(phys, jnp.int32))
        self._prefill_pos[slot] = (base + m) * ps
        self.tier_promoted_pages += m
        self._radix_adopt(prompt[:(base + m) * ps],
                          self._stream_pages[slot][:base + m])
        return True

    def _adopt_from_fleet(self, plan: Dict[str, Any]) -> bool:
        """Fetch the fleet-hot prefix from the directory's hinted
        sibling (span transport, digest-verified on the wire) and
        install it like a tier promote. Any failure — the holder died,
        evicted the prefix, or shipped something that does not verify —
        is a recompute fallback, never an error: directory entries are
        hints and the prefill path is always there."""
        slot = plan["slot"]
        prompt = self._prompts[slot]
        ps = self.page_size
        try:
            span = self.peer_fetch(plan["holder"],
                                   prompt[:plan["cover"] * ps])
        except Exception:
            span = None
        if span is None:
            return False
        got = list(span.get("prompt", []))
        if (int(span.get("page_size", ps)) != ps
                or bool(span.get("kv_quant")) != bool(self.cfg.kv_quant)
                or len(got) % ps
                or got != prompt[:len(got)]):
            return False
        cover = min(plan["cover"], len(got) // ps)
        base = plan["base"]
        if cover <= base:
            return False
        payload = span["payload"]
        want = (self.cfg.n_layers, len(got) // ps, ps,
                self.cfg.n_kv_heads, self.cfg.head_dim)

        def _shape(x):
            return tuple((x["q"] if isinstance(x, dict) else x).shape)

        if _shape(payload["k"]) != want or _shape(payload["v"]) != want:
            return False
        m = cover - base
        phys = self._stream_pages[slot][base:cover]
        self.pool = self._adopt_exec(m)(
            self.pool,
            _payload_slice(payload["k"], base, cover),
            _payload_slice(payload["v"], base, cover),
            jnp.asarray(phys, jnp.int32))
        self._prefill_pos[slot] = cover * ps
        self.directory_hits += 1
        self.adopted_prefix_pages += m
        self._radix_adopt(prompt[:cover * ps],
                          self._stream_pages[slot][:cover])
        return True

    def export_prefix(self, prompt: List[int]) -> Optional[Dict[str, Any]]:
        """Serve a sibling's prefix-adoption fetch: the longest
        radix-cached full-page chain of ``prompt``, gathered to host as
        a span the peer installs with the adoption machinery. The span
        covers CACHED pages only (``first_token`` is the ``-1``
        prefix-span sentinel — the asker still prefills its tail), and
        the gather runs with the lookup's references held, so a
        concurrent eviction cannot free the pages mid-read. Returns
        None when nothing is cached — the asker recomputes."""
        if self.radix is None:
            return None
        prompt = list(prompt)
        # lookup only ever covers a PROPER prefix; pad one sentinel
        # token so a prompt of exactly k full pages can match all k
        shared, _ = self.radix.lookup(prompt + [-1])
        if not shared:
            return None
        try:
            payload = self._gather_span(shared)
        finally:
            for p in shared:
                self.ledger.unref(p)
        self.exported_prefixes += 1
        return {"version": 1,
                "prompt": prompt[:len(shared) * self.page_size],
                "first_token": -1, "page_size": self.page_size,
                "kv_quant": bool(self.cfg.kv_quant), "payload": payload}

    # ------------------------------------------------------ live migration

    def export_stream(self, slot: int) -> Optional[Dict[str, Any]]:
        """Freeze the decode stream in ``slot`` at a step boundary and
        return its portable state: the KV pages covering every position
        written so far PLUS the sampler/stream state a destination needs
        to resume token-exact — prompt, every generated token, the
        remaining budget, and the engine RNG key. Export is a pure READ:
        the victim keeps all its pages and bookkeeping and keeps decoding
        until :meth:`release_stream` confirms the adoption elsewhere, so
        a failed migration leaves the stream untouched.

        Returns None for an empty slot or a stream still prefilling
        (nothing decoded yet — the caller re-submits the prompt on the
        destination instead of shipping pages).

        Positions: the device KV holds ``prompt_len + len(tokens) - 1``
        written positions (the LAST emitted token's K/V lands on the
        destination's next decode step, exactly as it would here), so
        that — not the full reserved span — is what ships. The final
        shipped page may be partial; its garbage tail is overwritten as
        decode continues, like an adopted boundary page."""
        self._flush_pending()          # a step boundary, never mid-flush
        if not (0 <= slot < self.slots):
            return None
        r = self.requests[slot]
        if r is None or not self._decoding[slot] or not r.tokens:
            return None
        n = r.prompt_len
        ps = self.page_size
        kv_len = n + len(r.tokens) - 1
        span_pages = -(-kv_len // ps)
        pages = self._stream_pages[slot][:span_pages]
        try:
            rng = np.asarray(jax.random.key_data(self.key))
        except Exception:              # raw uint32 key arrays
            rng = np.asarray(self.key)
        return {"version": 1, "prompt": list(self._prompts[slot]),
                "tokens": list(r.tokens), "max_new": int(r.budget),
                "page_size": ps, "kv_quant": bool(self.cfg.kv_quant),
                "rng_key": rng, "payload": self._gather_span(pages)}

    def import_stream(self, state: Dict[str, Any], request_id: Any = None,
                      adopt_rng: bool = False) -> Optional[int]:
        """Adopt a migrated decode stream (:meth:`export_stream` on the
        victim, possibly shipped as a ``DECSTATE`` frame by
        ``models/migrate.py``) and resume it mid-stream: the stream
        joins the decode batch at position ``prompt + generated - 1``
        with its token history, remaining budget, and identity intact —
        under greedy decode the continuation is token-exact.

        The transaction discipline is :meth:`adopt_pages`'s: config and
        shape mismatches raise ValueError BEFORE any reservation; slot
        or page exhaustion returns None; a failure after pages are
        reserved unwinds every reservation before re-raising — in every
        non-success case the victim (which still holds the stream) loses
        nothing. ``adopt_rng`` additionally installs the shipped engine
        RNG key — an engine-global, so only sensible when the
        destination carries no other sampled streams."""
        t_mig0 = time.perf_counter()
        prompt = list(state["prompt"])
        tokens = [int(t) for t in state["tokens"]]
        n = len(prompt)
        max_new = int(state["max_new"])
        if not tokens:
            raise ValueError("decode state carries no generated tokens; "
                             "ship a prefill span instead")
        if int(state.get("page_size", self.page_size)) != self.page_size:
            raise ValueError(
                f"stream page_size {state.get('page_size')} != pool page "
                f"size {self.page_size}; tiers must agree")
        if bool(state.get("kv_quant")) != bool(self.cfg.kv_quant):
            raise ValueError("stream/pool kv_quant mismatch: shipped "
                             "pages are raw pool bytes, tiers must run "
                             "the same KV dtype")
        reason = self._validate_item({"prompt": prompt,
                                      "max_new": max_new})
        if reason is not None:
            raise ValueError(reason)
        if len(tokens) >= max_new or n + len(tokens) >= self.cfg.max_seq:
            raise ValueError("stream already complete; nothing to "
                             "resume — deliver its tokens instead")
        ps = self.page_size
        kv_len = n + len(tokens) - 1
        span_pages = -(-kv_len // ps)
        payload = state["payload"]

        def _shape(x):
            return tuple((x["q"] if isinstance(x, dict) else x).shape)

        want = (self.cfg.n_layers, span_pages, ps, self.cfg.n_kv_heads,
                self.cfg.head_dim)
        if _shape(payload["k"]) != want or _shape(payload["v"]) != want:
            raise ValueError(f"stream payload shape "
                             f"{_shape(payload['k'])} != pool page "
                             f"shape {want}")
        self._flush_pending()
        free = self.free_slots()
        if not free:
            return None
        slot = free[0]
        total = -(-(n + max_new) // ps)
        shared: List[int] = []
        if self.radix is not None:
            # full PROMPT pages dedupe exactly as at adoption — every
            # shared page is covered by the shipped span (kv_len >= n)
            shared, _ = self.radix.lookup(prompt)
        own_needed = total - len(shared)
        pages = self.ledger.alloc(own_needed)
        if pages is None and self.radix is not None:
            self._evict(own_needed - self.ledger.free_count())
            pages = self.ledger.alloc(own_needed)
        if pages is None:
            for p in shared:
                self.ledger.unref(p)
            return None
        matched = len(shared)
        try:
            install = span_pages - matched
            if install > 0:
                self.pool = self._adopt_exec(install)(
                    self.pool,
                    _payload_slice(payload["k"], matched, span_pages),
                    _payload_slice(payload["v"], matched, span_pages),
                    jnp.asarray(pages[:install], jnp.int32))
        except Exception:
            # aborted install: every reservation unwinds, the victim
            # still holds the stream — it resumes untouched
            for p in shared:
                self.ledger.unref(p)
            for p in pages:
                self.ledger.unref(p)
            raise
        stream_pages = shared + pages
        row = self._tables[slot]
        row[:] = self.scratch
        row[:total] = stream_pages
        self._stream_pages[slot] = stream_pages
        self._prompts[slot] = prompt
        self._prefill_pos[slot] = n
        self._decoding[slot] = True
        self.lengths = self.lengths.at[slot].set(kv_len)
        self.cur_tok = self.cur_tok.at[slot].set(tokens[-1])
        rid = request_id if request_id is not None else object()
        self.requests[slot] = _Request(rid, n, max_new, list(tokens))
        if adopt_rng and state.get("rng_key") is not None:
            try:
                self.key = jax.random.wrap_key_data(
                    jnp.asarray(state["rng_key"]))
            except Exception:
                self.key = jnp.asarray(state["rng_key"])
        self.migrated_in += 1
        tracer = self.tracer
        if tracer is not None:
            ctx = getattr(rid, "trace", None)
            if ctx is not None:
                tracer.record("engine.import_stream", t_mig0,
                              time.perf_counter(), parent=ctx,
                              pages=span_pages, shared_pages=matched,
                              generated=len(tokens))
        self._maybe_retire(slot)
        return slot

    def release_stream(self, slot: int) -> bool:
        """Confirm a migration: drop the victim's copy of the stream —
        every page unrefs, full prompt pages adopt into the radix (the
        prompt finished prefilling, so they hold prompt-determined K/V,
        the retirement reasoning) — WITHOUT recording a result; the
        destination owns the stream now. Only call after the adoption
        committed; until then the stream keeps decoding here."""
        if not (0 <= slot < self.slots) or self.requests[slot] is None:
            return False
        decoded = self._decoding[slot]
        self.requests[slot] = None
        self._pending_first.pop(slot, None)
        self._release(slot, adopt=decoded)
        self.migrated_out += 1
        return True

    # ---------------------------------------- sequence-parallel prefill

    def _ring_exec(self, s_pad: int):
        """Jitted ring-prefill program for padded prompt length
        ``s_pad``: one :func:`llama.prefill_ring` forward (~seq/sp
        per-host time), last-position logits through the lm_head, and a
        page-granular scatter of the whole K/V span into the pool —
        the adoption install path (:func:`_install_pages`) reused for
        locally-computed pages."""
        x = self._ring_x.get(s_pad)
        if x is None:
            cfg, mesh, rope = self.cfg, self.mesh, self._rope
            ffn = self._ffn
            ps = self.page_size
            n_pages = s_pad // ps

            def ring(p, pool, prompt, li, phys):
                hidden, ks, vs = llama.prefill_ring(
                    cfg, p, prompt, mesh, rope=rope, ffn_override=ffn)
                h_last = lax.dynamic_slice_in_dim(hidden, li, 1,
                                                  axis=1)[:, 0]
                logits = qmm(h_last, p["lm_head"]).astype(jnp.float32)
                kp = ks[:, 0].reshape(cfg.n_layers, n_pages, ps,
                                      cfg.n_kv_heads, cfg.head_dim)
                vp = vs[:, 0].reshape(cfg.n_layers, n_pages, ps,
                                      cfg.n_kv_heads, cfg.head_dim)
                pool = {"k": _install_pages(pool["k"], kp, phys),
                        "v": _install_pages(pool["v"], vp, phys)}
                return logits, pool

            x = jax.jit(ring, donate_argnums=(1,))
            self._ring_x[s_pad] = x
        return x

    def _ring_prefill(self, slot: int) -> bool:
        """Prefill the WHOLE prompt of ``slot`` in one sequence-parallel
        tick. Returns True when the stream is decode-ready; any
        disqualification (padded length over ``max_seq``, missing sp
        axis at trace time, compiler rejection) counts a coded
        ``longctx_fallback`` and returns False — the caller falls back
        to the chunked path, the stream is never dropped.

        Only runs from position 0: a radix-resumed stream's leading
        pages are SHARED (other streams read them), and the ring path
        writes the full span — clobbering shared pages with
        ring-numerics K/V is exactly the aliasing the COW discipline
        exists to prevent, so those streams stay on chunks."""
        prompt = self._prompts[slot]
        n = len(prompt)
        ps = self.page_size
        try:
            s_pad = ring_pad_len(n, self._ring_sp, ps)
            if s_pad > self.cfg.max_seq:
                raise ValueError(
                    f"prompt {n} pads to {s_pad} for sp="
                    f"{self._ring_sp}, over max_seq {self.cfg.max_seq}")
            n_pages = s_pad // ps
            own = -(-n // ps)          # pages actually covering the prompt
            phys = np.full((n_pages,), self.scratch, np.int32)
            phys[:own] = self._tables[slot][:own]
            # pad pages land on scratch: their K/V is causally
            # downstream of every live position and masked by kv_len,
            # so the duplicate-index scatter is sacrificial by design
            padded = np.zeros((1, s_pad), np.int32)
            padded[0, :n] = prompt
            t0 = time.perf_counter()
            logits, self.pool = self._ring_exec(s_pad)(
                self.params, self.pool, jnp.asarray(padded),
                jnp.int32(n - 1), jnp.asarray(phys))
        except Exception:
            self.longctx_fallbacks += 1
            return False
        toks = self._select(logits)
        self.lengths = self.lengths.at[slot].set(n)
        self.cur_tok = self.cur_tok.at[slot].set(toks[0])
        self._pending_first[slot] = toks[0]
        self._prefill_pos[slot] = n
        self.ring_prefills += 1
        self.ring_prefilled_tokens += n
        self.ring_prefill_s += time.perf_counter() - t0
        tracer = self.tracer
        if tracer is not None:
            ctx = getattr(self.requests[slot].request_id, "trace", None)
            if ctx is not None:
                tracer.record("engine.prefill_ring", t0,
                              time.perf_counter(), parent=ctx,
                              prompt_len=n, padded=s_pad,
                              ring=self._ring_sp)
        if self._draft is not None:
            self._draft_prefill(slot, prompt)
        return True

    # ------------------------------------------------------------- decode

    def _prefill_tick(self) -> None:
        """Run ONE fixed-shape prefill chunk for the stream at the head
        of the prefill queue. This is the chunked-prefill interleave:
        every step()/step_many() pays at most one chunk before its
        decode dispatch, so running streams never stall behind a long
        prompt. With ``longctx_ring`` armed, a long-enough prompt
        starting from position 0 prefills WHOLE in one sequence-parallel
        tick instead (:meth:`_ring_prefill`); on any disqualification it
        degrades to this chunked path."""
        while self._prefill_q and self.requests[self._prefill_q[0]] is None:
            self._prefill_q.popleft()          # aborted mid-prefill
        if not self._prefill_q:
            return
        slot = self._prefill_q[0]
        prompt = self._prompts[slot]
        n = len(prompt)
        if (self._ring_sp > 1 and self._prefill_pos[slot] == 0
                and n >= self.ring_threshold):
            if self._ring_prefill(slot):
                self._prefill_q.popleft()
                return
        c = self.prefill_chunk
        start = self._prefill_pos[slot]
        end = min(start + c, n)
        chunk = np.zeros((1, c), np.int32)
        chunk[0, :end - start] = prompt[start:end]
        last = end >= n
        li = (n - 1 - start) if last else 0
        t0 = time.perf_counter()
        logits, self.pool = self._chunk_x(
            self.params, self.pool, jnp.asarray(self._tables[slot]),
            jnp.asarray(chunk), jnp.int32(start), jnp.int32(n),
            jnp.int32(li))
        tracer = self.tracer
        if tracer is not None:
            ctx = getattr(self.requests[slot].request_id, "trace", None)
            if ctx is not None:
                tracer.record("engine.prefill_chunk", t0,
                              time.perf_counter(), parent=ctx,
                              start=start, end=end, prompt_len=n)
        self._prefill_pos[slot] = end
        if last:
            toks = self._select(logits)
            self.lengths = self.lengths.at[slot].set(n)
            self.cur_tok = self.cur_tok.at[slot].set(toks[0])
            # the first token stays device-resident; the stream turns
            # decode-active at the FLUSH (next engine call's top), never
            # in this same call — otherwise the decode window appends
            # tokens BEFORE the first token lands in r.tokens (order
            # corruption) and an EOS/budget-1 first token would decode
            # steps it should not
            self._pending_first[slot] = toks[0]
            self._prefill_q.popleft()
            if self._draft is not None:
                # the draft sees the WHOLE prompt (including any pages
                # the radix adopted for the target — the draft cache has
                # no prefix sharing); streams that enter decode without
                # passing here (migration adoption) start with a cold
                # draft row, which costs acceptance, never correctness:
                # the verify pass consults only the target pool
                self._draft_prefill(slot, prompt)

    def _decode_tables(self) -> np.ndarray:
        """Tables for the decode dispatch: any stream not actively
        decoding (idle, still prefilling, retired) points at the scratch
        page, so its garbage write cannot land on a live page."""
        mask = np.array(
            [self._decoding[i] and self.requests[i] is not None
             for i in range(self.slots)])
        return np.where(mask[:, None], self._tables,
                        np.int32(self.scratch))

    def _window_mp(self, active: List[int], k: int) -> int:
        """Leading table columns a ``k``-step decode window can touch.

        The host mirror of the device ``lengths`` is
        ``prompt_len + len(tokens) - 1``, so the highest position any
        active stream writes or reads this window is that + ``k`` — the
        dispatch only needs the tables (and the attention gather behind
        them) over ``ceil(.../page_size)`` LEADING pages, not the full
        ``max_seq`` span. This is a paging-only win: the attention read
        scales with the longest live stream while the slot engine's
        fixed rows always pay ``max_seq`` width. Frozen rows (masked,
        all-scratch tables) may carry lengths past the truncated span;
        their clipped writes land on scratch and their outputs are
        discarded, exactly as with full-width tables."""
        top = max(self.requests[i].prompt_len
                  + len(self.requests[i].tokens) for i in active)
        return min(self.pages_per_stream,
                   (top + k - 2) // self.page_size + 1)

    def step(self) -> Dict[int, int]:
        """One prefill chunk (if queued) + one decode step for every
        decode-active stream; returns {stream: token}."""
        self._flush_pending()
        self._tier_tick()
        self._prefill_tick()
        active = [i for i in range(self.slots)
                  if self.requests[i] is not None and self._decoding[i]]
        if not active:
            return {}
        mp = self._window_mp(active, 1)
        tbl = jnp.asarray(self._decode_tables()[:, :mp])
        logits, self.pool = self._step_x(self.params, self.pool, tbl,
                                         self.lengths, self.cur_tok)
        toks = self._select(logits)
        mask = jnp.zeros((self.slots,), bool).at[
            jnp.asarray(active, jnp.int32)].set(True)
        self.lengths = jnp.where(mask, self.lengths + 1, self.lengths)
        self.cur_tok = jnp.where(mask, toks, self.cur_tok)
        out: Dict[int, int] = {}
        host_toks = [int(t) for t in np.asarray(toks)]   # ONE transfer
        for i in active:
            tok = host_toks[i]
            self.requests[i].tokens.append(tok)
            out[i] = tok
            self._maybe_retire(i)
        return out

    def step_many(self, k: int) -> Dict[int, List[int]]:
        """Up to ``k`` prefill chunks + a ``k``-step decode window in
        ONE dispatch (same scan-window trade as the slot engine — the
        page table is fixed for the window, which the upfront full-span
        allocation at admission makes safe). Prefill is paced to decode
        exactly as in :meth:`step` (one chunk per decode step): a single
        chunk per WINDOW would starve admission under sustained load —
        1/k the prefill throughput — while an unbounded drain would
        spike running streams' TPOT by the whole backlog. The loop stops
        early when the queue empties, so an idle queue costs nothing."""
        if self._draft is not None:
            return self._spec_step_many(k)
        if k <= 1:
            return {slot: [tok] for slot, tok in self.step().items()}
        self._flush_pending()
        self._tier_tick()
        for _ in range(k):
            self._prefill_tick()
            if not self._prefill_q:
                break
        active = [i for i in range(self.slots)
                  if self.requests[i] is not None and self._decoding[i]]
        if not active:
            return {}
        x = self._stepk_x.get(k)
        if x is None:
            cfg, rope, mesh = self.cfg, self._rope, self.mesh
            ffn = self._ffn

            def window(p, c, tbl, ln, tok, mask, key):
                def body(carry, _):
                    c, ln, tok, key = carry
                    logits, c = llama.decode_step_paged(
                        cfg, p, c, tbl, ln, tok, mesh=mesh, rope=rope,
                        ffn_override=ffn)
                    key, sub = jax.random.split(key)
                    if self.sampler is None:
                        nxt = jnp.argmax(logits, axis=-1).astype(
                            jnp.int32)
                    else:
                        nxt = self.sampler(sub, logits).astype(jnp.int32)
                    nxt = jnp.where(mask, nxt, tok)
                    ln = jnp.where(mask, ln + 1, ln)
                    return (c, ln, nxt, key), nxt

                (c, ln, tok, key), toks = lax.scan(
                    body, (c, ln, tok, key), None, length=k)
                return c, ln, tok, key, toks

            x = jax.jit(window, donate_argnums=(1,))
            self._stepk_x[k] = x
        mask = jnp.zeros((self.slots,), bool).at[
            jnp.asarray(active, jnp.int32)].set(True)
        self.key, sub = jax.random.split(self.key)
        mp = self._window_mp(active, k)
        tbl = jnp.asarray(self._decode_tables()[:, :mp])
        (self.pool, self.lengths, self.cur_tok, _, toks) = x(
            self.params, self.pool, tbl, self.lengths, self.cur_tok,
            mask, sub)
        host = np.asarray(toks)                          # ONE transfer
        out: Dict[int, List[int]] = {}
        for i in active:
            emitted: List[int] = []
            r = self.requests[i]
            for t in host[:, i]:
                emitted.append(int(t))
                r.tokens.append(int(t))
                self._maybe_retire(i)
                if self.requests[i] is None:
                    break
            out[i] = emitted
        return out

    def _spec_step_many(self, k: int) -> Dict[int, List[int]]:
        """The armed decode dispatch: ONE fused draft-scan + paged-verify
        window per call, advancing every active stream by ``1 +
        accepted`` target-verified tokens (1 .. draft_k). The solo
        window's host discipline carries over unchanged — pacing up to
        ``k`` prefill chunks first, committing per stream until
        retirement breaks the loop, lengths frozen for masked slots.
        The page ledger is untouched by the window itself (the verify
        writes only through tables already allocated at admission), so
        ledger hygiene under speculation is the admission/retire story
        it always was.

        Any failure inside the fused dispatch disarms the draft before
        re-raising: the caller's existing reset()/retry path then runs
        SOLO — a broken draft degrades throughput, never liveness."""
        self._flush_pending()
        self._tier_tick()
        for _ in range(k):
            self._prefill_tick()
            if not self._prefill_q:
                break
        active = [i for i in range(self.slots)
                  if self.requests[i] is not None and self._decoding[i]]
        if not active:
            return {}
        kd = self.draft_k
        mask = jnp.zeros((self.slots,), bool).at[
            jnp.asarray(active, jnp.int32)].set(True)
        mp = self._window_mp(active, kd)
        tbl = jnp.asarray(self._decode_tables()[:, :mp])
        _, params_d = self._draft
        t0 = time.perf_counter()
        try:
            (self.pool, self._draft_cache, tgt, n_emit, self.lengths,
             self.cur_tok) = self._spec_x(
                self.params, params_d, self.pool, self._draft_cache,
                tbl, self.lengths, self.cur_tok, mask)
            host_tgt = np.asarray(tgt)                   # [B, kd]
            host_n = np.asarray(n_emit)                  # [B]
        except Exception:
            self.spec_fallbacks += 1
            if self.metrics is not None:
                self.metrics.counter("serving.spec.fallbacks")
            self.disarm_draft()
            raise
        dt = time.perf_counter() - t0
        self.spec_windows += 1
        self.spec_window_s += dt
        out: Dict[int, List[int]] = {}
        for i in active:
            n = int(host_n[i])
            self.spec_proposed += kd - 1
            self.spec_accepted += n - 1
            emitted: List[int] = []
            for t in host_tgt[i, :n]:
                emitted.append(int(t))
                self.requests[i].tokens.append(int(t))
                self._maybe_retire(i)
                if self.requests[i] is None:
                    break
            out[i] = emitted
        if self.metrics is not None:
            self.metrics.counter("serving.spec.windows")
            self.metrics.counter("serving.spec.proposed",
                                 float(len(active) * (kd - 1)))
            self.metrics.counter(
                "serving.spec.accepted",
                float(sum(int(host_n[i]) - 1 for i in active)))
            self.metrics.observe("serving.spec.window_seconds", dt)
        return out

    # --------------------------------------------------------- retirement

    def _maybe_retire(self, slot: int) -> None:
        r = self.requests[slot]
        if r is None or not r.tokens:
            return
        done = (len(r.tokens) >= r.budget
                or (self.eos_id is not None
                    and r.tokens[-1] == self.eos_id)
                or r.prompt_len + len(r.tokens) >= self.cfg.max_seq)
        if done:
            self.finished[r.request_id] = r.tokens
            self.requests[slot] = None
            self._release(slot, adopt=True)

    def _release(self, slot: int, adopt: bool) -> None:
        """Give a stream's pages back: optionally adopt its full prompt
        pages into the prefix radix (adoption takes its own references
        BEFORE the stream's drop, so shared content survives), then drop
        the stream's reference on every page and point the table row at
        scratch."""
        pages = self._stream_pages[slot]
        prompt = self._prompts[slot]
        if adopt and self.radix is not None and prompt is not None:
            # full prompt pages hold prompt-determined K/V only (decode
            # writes start at position len(prompt)), so they are safe to
            # share; a mid-window garbage write can only land in the
            # final allocated page, which is never a full prompt page
            self._radix_adopt(prompt, pages)
        for p in pages:
            self.ledger.unref(p)
        self._stream_pages[slot] = []
        self._prompts[slot] = None
        self._prefill_pos[slot] = 0
        self._decoding[slot] = False
        self._tables[slot, :] = self.scratch

    def abort_active(self) -> int:
        """Drop every in-flight request and return EVERY page it held
        (mid-prefill pages may hold partial garbage, so nothing is
        adopted into the radix); returns how many were dropped."""
        dropped = 0
        for i, r in enumerate(self.requests):
            if r is not None:
                self.requests[i] = None
                self._release(i, adopt=False)
                dropped += 1
        self._prefill_q.clear()
        self._pending_first.clear()
        return dropped

    def reset(self) -> None:
        """Rebuild device + host state after a failed dispatch (the
        jitted paths donate the pool, so its buffer may be invalid).
        The radix is rebuilt too: its cached K/V lived in the old pool.
        """
        self.pool = llama.init_page_pool(self.cfg, self.total_pages + 1,
                                         self.page_size)
        if (self.mesh is not None and self.mesh.size > 1
                and self.mesh.shape.get("tp", 1) > 1):
            self.pool = _shard_cache(self.pool, self.mesh)
        self.ledger = PagePool(self.total_pages, self.page_size)
        self.radix = (PrefixRadix(self.ledger)
                      if self.radix is not None else None)
        self._tables[:] = self.scratch
        self.lengths = jnp.zeros((self.slots,), jnp.int32)
        self.cur_tok = jnp.zeros((self.slots,), jnp.int32)
        self.requests = [None] * self.slots
        self.finished.clear()
        self._pending_first.clear()
        self._stream_pages = [[] for _ in range(self.slots)]
        self._prompts = [None] * self.slots
        self._prefill_pos = [0] * self.slots
        self._prefill_q.clear()
        self._decoding = [False] * self.slots
        # pending promote plans die with the streams; the TIER FRAMES
        # survive — they are content-addressed host/disk byte copies,
        # still bit-valid for the rebuilt pool, so a reset engine keeps
        # its cold cache warm
        self._pending_tier.clear()
        if self._draft is not None:
            # the spec window donates the draft cache alongside the
            # pool, so it is just as suspect after a failed dispatch
            cfg_d, _ = self._draft
            self._draft_cache = llama.init_kv_cache(cfg_d, self.slots,
                                                    cfg_d.max_seq)

    # -------------------------------------------------------------- audit

    def expected_refs(self) -> Dict[int, int]:
        """page -> references actually held (live stream tables + the
        radix) — the invariant checker's cross-check input."""
        expected: Dict[int, int] = {}
        for pages in self._stream_pages:
            for p in pages:
                expected[p] = expected.get(p, 0) + 1
        if self.radix is not None:
            for p, cnt in self.radix.held().items():
                expected[p] = expected.get(p, 0) + cnt
        return expected

    def ledger_violations(self) -> List[str]:
        """Empty when the page ledger is healthy (chaos invariant)."""
        return self.ledger.check(self.expected_refs())

    def page_stats(self) -> Dict[str, Any]:
        return {
            "pages": self.total_pages,
            "page_size": self.page_size,
            "pages_free": self.ledger.free_count(),
            "pages_in_use": self.ledger.in_use(),
            "pages_in_use_peak": self.ledger.in_use_peak,
            "prefix_hits": self.radix.hits if self.radix else 0,
            "prefix_shared_pages": (self.radix.shared_pages
                                    if self.radix else 0),
            "shipped_spans": self.shipped_spans,
            "adopted_spans": self.adopted_spans,
            "adopt_shared_pages": self.adopt_shared_pages,
            "migrated_out": self.migrated_out,
            "migrated_in": self.migrated_in,
            "tier_demoted_pages": self.tier_demoted_pages,
            "tier_promoted_pages": self.tier_promoted_pages,
            "tier_fallbacks": self.tier_fallbacks,
            "tier_promote_s": self.tier_promote_s,
            "directory_hits": self.directory_hits,
            "directory_fallbacks": self.directory_fallbacks,
            "adopted_prefix_pages": self.adopted_prefix_pages,
            "exported_prefixes": self.exported_prefixes,
            "tiers": self.tiers.stats() if self.tiers is not None else None,
            "directory": (self.directory.stats()
                          if self.directory is not None else None),
            "spec": {
                "armed": self._draft is not None,
                "k": self.draft_k,
                "windows": self.spec_windows,
                "proposed": self.spec_proposed,
                "accepted": self.spec_accepted,
                "accept_rate": (self.spec_accepted / self.spec_proposed
                                if self.spec_proposed else 0.0),
                "fallbacks": self.spec_fallbacks,
                "draft_prefill_s": self.spec_draft_prefill_s,
                "window_s": self.spec_window_s,
            },
            "moe": ({
                "experts": self.moe.num_experts,
                "capacity_factor": self.moe.capacity_factor,
                "routing": self.moe.routing,
            } if self.moe is not None else None),
            "longctx": {
                "ring": self._ring_sp,
                "threshold": self.ring_threshold,
                "ring_prefills": self.ring_prefills,
                "ring_prefilled_tokens": self.ring_prefilled_tokens,
                "ring_prefill_s": self.ring_prefill_s,
                "fallbacks": self.longctx_fallbacks,
            },
        }
