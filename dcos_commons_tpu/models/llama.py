"""Llama-style decoder-only transformer — the flagship model.

TPU-first design decisions (vs. a torch port):

* **Stacked layers + ``lax.scan``** — one compiled layer body, O(1) HLO size
  in depth; XLA pipelines the per-layer matmuls onto the MXU.
* **GSPMD sharding via `PartitionSpec`s** (`param_specs`): weights shard over
  the ``tp`` mesh axis megatron-style (column-parallel in-proj, row-parallel
  out-proj), activations over ``dp`` (batch) and ``sp`` (sequence); XLA
  inserts the all-reduces on ICI.
* **Swappable attention**: ``dense`` (GSPMD, any mesh), ``ring``
  (`parallel.ring_attention`, long-context over an ICI ring), or ``ulysses``
  (`parallel.ulysses`, all-to-all head scatter) — same [B, S, H, D] layout.
* bf16 weights/activations, fp32 softmax/norm/logits.

Reference parity: this is BASELINE.json config #5 ("Llama-3-8B inference,
scheduler-placed model-parallel shards"); the reference repo itself ships no
models (SURVEY.md §2.4).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dcos_commons_tpu.ops import (apply_rope, apply_rope_at,
                                  apply_rope_at_many,
                                  apply_rope_positions,
                                  fused_linear_cross_entropy,
                                  gqa_attention, repeat_kv,
                                  rms_norm, rope_frequencies,
                                  softmax_cross_entropy)
from dcos_commons_tpu.ops.flash_decode import (flash_decode,
                                               flash_decode_paged,
                                               flash_decode_paged_tp,
                                               flash_decode_tp)
from dcos_commons_tpu.ops.quant import (QTensor, dequantize, qmm, qtake,
                                        quantize)
from dcos_commons_tpu.parallel.ring_attention import make_ring_attention
from dcos_commons_tpu.parallel.ulysses import make_ulysses_attention

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_dim: int = 14336
    max_seq: int = 8192
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    attn_impl: str = "auto"         # auto | dense | flash | ring | ulysses
    # ring attention block order: "contiguous" (shard i holds positions
    # [i*S/R, (i+1)*S/R); causal hops behind the diagonal skip compute
    # but the live work is imbalanced across the ring) or "zigzag"
    # (shard i holds chunks (i, 2R-1-i) of 2R chunks — balanced causal
    # skipping; training lays tokens out via
    # parallel.ring_attention.zigzag_indices, loss_fn handles it)
    ring_layout: str = "contiguous"
    dtype: Any = jnp.bfloat16
    remat: bool = True              # jax.checkpoint each layer (training)
    # selective-checkpoint policy name from jax.checkpoint_policies
    # (e.g. "dots_with_no_batch_dims_saveable": save matmul outputs,
    # recompute only cheap elementwise ops — most of full remat's memory
    # relief at a fraction of its recompute FLOPs); None = save nothing
    remat_policy: Optional[str] = None
    # int8 KV cache (per-position/per-head scales): halves decode's
    # cache traffic and doubles the batch x seq that fits HBM next to
    # the weights; the convert rides the attention matmul's operand
    # load the same way weight dequant does (ops/quant.py)
    kv_quant: bool = False
    # decode-step attention: auto | dense | flash | flash_interpret.
    # auto = the pallas decode kernel (ops/flash_decode.py) on unsharded
    # TPU when shapes are lane-aligned, else the dense path; flash
    # forces it; flash_interpret runs it in interpret mode (CPU tests)
    decode_attn: str = "auto"
    # fused linear-cross-entropy on the train loss head
    # (ops/losses.py): the lm_head projection runs inside the
    # sequence-chunked loss loop, so the [B, S, V] fp32 logits tensor —
    # ~4 GB of HBM traffic per step at B=8/S=1024/V=128256 — never
    # materializes in either direction. Identical math; the off switch
    # exists for A/B receipts and paranoia rollbacks.
    fused_ce: bool = True
    # sequence chunk of the fused loss: peak logits scratch is
    # [B, fused_ce_block, V] fp32 (S need not divide it)
    fused_ce_block: int = 512

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @classmethod
    def llama3_8b(cls, **kw) -> "LlamaConfig":
        return cls(**kw)

    @classmethod
    def llama_400m(cls, **kw) -> "LlamaConfig":
        """The mid-size bench/operator preset (~306M params): fits any
        chip comfortably, compiles in seconds — ONE definition so the
        worker preset and every bench tool measure the same shape."""
        defaults = dict(vocab_size=32000, dim=1536, n_layers=8,
                        n_heads=12, n_kv_heads=6, ffn_dim=4096,
                        max_seq=512, remat=False)
        defaults.update(kw)
        return cls(**defaults)

    @classmethod
    def tiny(cls, **kw) -> "LlamaConfig":
        """4-layer toy config for tests and the multi-chip dry run."""
        defaults = dict(vocab_size=256, dim=64, n_layers=4, n_heads=8,
                        n_kv_heads=4, ffn_dim=128, max_seq=128,
                        remat=False)
        defaults.update(kw)
        return cls(**defaults)


# ---------------------------------------------------------------------------
# parameters

def init_params(cfg: LlamaConfig, key: jax.Array) -> Params:
    """Scaled-normal init; stacked [L, ...] layer weights."""
    k = jax.random.split(key, 10)
    d, f, L = cfg.dim, cfg.ffn_dim, cfg.n_layers
    qd = cfg.n_heads * cfg.head_dim
    kvd = cfg.n_kv_heads * cfg.head_dim
    dt = cfg.dtype

    def norm2(key, *shape, scale=None):
        scale = scale if scale is not None else (shape[-2] ** -0.5)
        return (jax.random.normal(key, shape, jnp.float32) * scale).astype(dt)

    return {
        "embed": norm2(k[0], cfg.vocab_size, d, scale=d ** -0.5),
        "layers": {
            "attn_norm": jnp.ones((L, d), dt),
            "wq": norm2(k[1], L, d, qd),
            "wk": norm2(k[2], L, d, kvd),
            "wv": norm2(k[3], L, d, kvd),
            "wo": norm2(k[4], L, qd, d, scale=(qd ** -0.5) / (2 * L) ** 0.5),
            "ffn_norm": jnp.ones((L, d), dt),
            "w_gate": norm2(k[5], L, d, f),
            "w_up": norm2(k[6], L, d, f),
            "w_down": norm2(k[7], L, f, d, scale=(f ** -0.5) / (2 * L) ** 0.5),
        },
        "norm": jnp.ones((d,), dt),
        "lm_head": norm2(k[8], d, cfg.vocab_size),
    }


def param_specs(cfg: LlamaConfig) -> Params:
    """Megatron-style tp sharding: column-parallel in-projections,
    row-parallel out-projections; embeddings sharded over vocab."""
    return {
        "embed": P("tp", None),
        "layers": {
            "attn_norm": P(),
            "wq": P(None, None, "tp"),
            "wk": P(None, None, "tp"),
            "wv": P(None, None, "tp"),
            "wo": P(None, "tp", None),
            "ffn_norm": P(),
            "w_gate": P(None, None, "tp"),
            "w_up": P(None, None, "tp"),
            "w_down": P(None, "tp", None),
        },
        "norm": P(),
        "lm_head": P(None, "tp"),
    }


def _scale_spec(spec: P, s_shape: Tuple[int, ...]) -> P:
    """Sharding for a QTensor's scales: the weight's spec with the
    collapsed (size-1) axes unsharded — so e.g. a row-parallel ``wo``
    keeps its scales replicated while a column-parallel ``wq`` shards
    them along tp with the payload's out-channel axis."""
    entries = list(spec) + [None] * (len(s_shape) - len(spec))
    return P(*[None if s_shape[i] == 1 else entries[i]
               for i in range(len(s_shape))])


def shard_params(params: Params, mesh: Mesh, cfg: LlamaConfig) -> Params:
    specs = param_specs(cfg)

    def put(x, sp):
        return jax.device_put(x, NamedSharding(mesh, sp))

    def put_leaf(p, sp):
        if isinstance(p, QTensor):
            return QTensor(put(p.q, sp),
                           put(p.s, _scale_spec(sp, p.s.shape)))
        return put(p, sp)

    return jax.tree.map(put_leaf, params, specs,
                        is_leaf=lambda x: isinstance(x, QTensor))


def quantize_params(params: Params) -> Params:
    """Weight-only int8 (``ops.quant``) for the DENSE decoder's serving
    path: matmul weights quantize per-out-channel (reduction axis -2),
    the embedding table per row; norm gains stay high-precision — a
    negligible byte count and numerically load-bearing. MoE trees are
    rejected: the expert banks feed ``parallel.moe`` einsums that consume
    raw arrays (EP serving shards experts across hosts instead of
    squeezing one chip, so quantizing them buys nothing today)."""
    if "router" in params["layers"]:
        raise ValueError(
            "quantize_params supports the dense decoder only; "
            "MoE expert banks are not quantizable (parallel.moe)")
    keep = ("attn_norm", "ffn_norm")
    layers = {k: (v if k in keep else quantize(v, axis=-2))
              for k, v in params["layers"].items()}
    return {"embed": quantize(params["embed"], axis=-1),
            "layers": layers,
            "norm": params["norm"],
            "lm_head": quantize(params["lm_head"], axis=-2)}


def init_quantized_params(cfg: LlamaConfig, key: jax.Array,
                          device=None) -> Params:
    """Initialize + quantize WITHOUT materializing bf16 weights on the
    accelerator: generation and quantization run on the host CPU backend,
    only int8 payloads + scales transfer. An 8B config lands at ~8 GB
    on-device; a device-side init-then-quantize would need bf16 + int8
    resident at once (~24 GB) and cannot fit a 16 GB v5e chip."""
    try:
        cpu = jax.devices("cpu")[0]
    except RuntimeError as e:
        raise RuntimeError(
            "init_quantized_params needs the host cpu backend to stream "
            "weights (set JAX_PLATFORMS to include cpu, e.g. 'tpu,cpu'): "
            f"{e}") from e
    with jax.default_device(cpu):
        qparams = quantize_params(init_params(cfg, key))
        # force host materialization before any transfer below
        qparams = jax.block_until_ready(qparams)
    if device is not None:
        qparams = jax.tree.map(lambda x: jax.device_put(x, device),
                               qparams)
    return qparams


# ---------------------------------------------------------------------------
# forward

def _make_attn_fn(cfg: LlamaConfig, mesh: Optional[Mesh]) -> Callable:
    """Returns f(q, k, v): q [B, S, H, D], k/v [B, S, KV, D].

    All impls take k/v with the spec's KV head count; GQA expansion is each
    impl's own business (the flash kernel reads KV groups through its index
    map with no HBM repeat at all; dense repeats internally; ring/ulysses
    repeat here because their head sharding wants H == n_heads).
    """
    impl = cfg.attn_impl
    if impl == "auto":
        # unsharded TPU -> pallas flash kernel (measured faster than both
        # XLA dense and the upstream reference pallas kernel on v5e,
        # ops/flash_attention.py). Sharded meshes stay on XLA dense —
        # ring/ulysses change the collective pattern and are explicit
        # opt-ins per model config (frameworks/jax scenarios set them).
        impl = ("flash" if mesh is None
                and jax.default_backend() == "tpu" else "dense")
    if impl == "flash":
        from ..ops.flash_attention import flash_attention, supports

        def attn(q, k, v):
            if supports(q, k):
                return flash_attention(q, k, v, causal=True)
            return gqa_attention(q, k, v, causal=True)

        return attn
    if impl == "dense" or mesh is None:
        return lambda q, k, v: gqa_attention(q, k, v, causal=True)
    if impl == "ring":
        # RAW kv heads cross the ring: the GQA broadcast happens inside
        # the tile einsum (parallel/ring_attention.py), so the hops move
        # H/KV-times fewer ICI bytes and no repeated copy lands in HBM.
        # When the tp axis does NOT divide the kv heads (but does divide
        # the query heads — the pre-round-5 working envelope), fall back
        # to rotating the expanded heads rather than failing the gang.
        tp_size = mesh.shape.get("tp", 1) if mesh is not None else 1
        if tp_size > 1 and cfg.n_kv_heads % tp_size:
            rep = cfg.n_heads // cfg.n_kv_heads
            ring = make_ring_attention(mesh, causal=True,
                                       layout=cfg.ring_layout)
            return lambda q, k, v: ring(q, repeat_kv(k, rep),
                                        repeat_kv(v, rep))
        return make_ring_attention(mesh, causal=True,
                                   layout=cfg.ring_layout)
    n_rep = cfg.n_heads // cfg.n_kv_heads
    if impl == "ulysses":
        uly = make_ulysses_attention(mesh, causal=True)
        return lambda q, k, v: uly(q, repeat_kv(k, n_rep),
                                   repeat_kv(v, n_rep))
    raise ValueError(f"unknown attn_impl {cfg.attn_impl!r}")


def _constrain(x, mesh: Optional[Mesh], *spec):
    if mesh is None:
        return x
    return lax.with_sharding_constraint(x, NamedSharding(mesh, P(*spec)))


def attention_block(cfg: LlamaConfig, x: jnp.ndarray, lp: Params,
                    rope, attn_fn: Callable,
                    return_kv: bool = False):
    """Pre-norm attention residual step on x [B, S, D]. With
    ``return_kv`` also returns the rope'd K/V (the prefill cache
    contract, identical to what ``decode_step`` writes)."""
    b, s, _ = x.shape
    h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
    q = qmm(h, lp["wq"]).reshape(b, s, cfg.n_heads, cfg.head_dim)
    k = qmm(h, lp["wk"]).reshape(b, s, cfg.n_kv_heads, cfg.head_dim)
    v = qmm(h, lp["wv"]).reshape(b, s, cfg.n_kv_heads, cfg.head_dim)
    q = apply_rope(q, rope)
    k = apply_rope(k, rope)
    o = attn_fn(q, k, v)  # GQA expansion is the impl's business
    out = x + qmm(o.reshape(b, s, -1), lp["wo"])
    if return_kv:
        return out, k, v
    return out


def ffn_block(cfg: LlamaConfig, x: jnp.ndarray, lp: Params) -> jnp.ndarray:
    """Pre-norm SwiGLU residual step on x [B, S, D]."""
    h = rms_norm(x, lp["ffn_norm"], cfg.norm_eps)
    gate = jax.nn.silu(qmm(h, lp["w_gate"]).astype(jnp.float32))
    up = qmm(h, lp["w_up"]).astype(jnp.float32)
    return x + qmm((gate * up).astype(cfg.dtype), lp["w_down"])


def apply_layer(cfg: LlamaConfig, x: jnp.ndarray, lp: Params,
                rope, attn_fn: Callable,
                mesh: Optional[Mesh] = None) -> jnp.ndarray:
    """One decoder layer on activations x [B, S, D] (shared by the dense
    forward's scan and the pipeline-parallel stage bodies)."""
    x = attention_block(cfg, x, lp, rope, attn_fn)
    x = ffn_block(cfg, x, lp)
    return _constrain(x, mesh, "dp", "sp", None)


def _maybe_checkpoint(fn, cfg: LlamaConfig):
    """Per-layer rematerialization: full (save nothing) or selective via a
    named ``jax.checkpoint_policies`` policy (``cfg.remat_policy``)."""
    if not cfg.remat:
        return fn
    if cfg.remat_policy:
        policy = getattr(jax.checkpoint_policies, cfg.remat_policy)
        return jax.checkpoint(fn, policy=policy)
    return jax.checkpoint(fn)


def forward(cfg: LlamaConfig, params: Params, tokens: jnp.ndarray,
            mesh: Optional[Mesh] = None,
            positions: Optional[jnp.ndarray] = None,
            return_hidden: bool = False) -> jnp.ndarray:
    """tokens [B, S] int32 -> logits [B, S, V] fp32.

    ``positions`` (optional [S] int32): the global position of each
    sequence slot, for layouts where slot != position (the zigzag ring
    layout) — rope reads the gathered table; attention impls that mask
    by position (ring) derive the same map from their layout.

    ``return_hidden`` returns the final-norm hidden states [B, S, D]
    instead of projecting through the lm_head — the fused-loss contract
    (``loss_fn`` feeds them to ``fused_linear_cross_entropy`` so the
    [B, S, V] logits tensor never materializes).
    """
    rope = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
    if positions is not None:
        rope = rope[:, jnp.asarray(positions)]
    attn_fn = _make_attn_fn(cfg, mesh)

    x = qtake(params["embed"], tokens, cfg.dtype)
    x = _constrain(x, mesh, "dp", "sp", None)

    def layer(x, lp):
        return apply_layer(cfg, x, lp, rope, attn_fn, mesh), None

    body = _maybe_checkpoint(layer, cfg)
    x, _ = lax.scan(body, x, params["layers"])
    x = rms_norm(x, params["norm"], cfg.norm_eps)
    if return_hidden:
        return _constrain(x, mesh, "dp", "sp", None)
    logits = qmm(x, params["lm_head"]).astype(jnp.float32)
    return _constrain(logits, mesh, "dp", "sp", None)


def stack_pipeline_params(params: Params, pp: int) -> Params:
    """Reshape stacked layer weights [L, ...] -> [pp, L/pp, ...] for the
    ``make_pipeline`` stage axis; embed/norm/lm_head stay replicated."""
    layers = jax.tree.map(
        lambda a: a.reshape(pp, a.shape[0] // pp, *a.shape[1:]),
        params["layers"])
    return {**params, "layers": layers}


def pipeline_param_specs(cfg: LlamaConfig) -> Params:
    """Sharding for the pipelined layout: layer stacks over ``pp``."""
    return {
        "embed": P(),
        "layers": jax.tree.map(lambda _: P("pp"),
                               param_specs(cfg)["layers"]),
        "norm": P(),
        "lm_head": P(),
    }


def forward_pipelined(cfg: LlamaConfig, params: Params, tokens: jnp.ndarray,
                      mesh: Mesh, n_micro: int,
                      return_hidden: bool = False) -> jnp.ndarray:
    """Pipeline-parallel forward (SURVEY.md §2.4 PP): the decoder trunk is
    stage-sharded over the ``pp`` mesh axis and microbatches stream through
    the GPipe fill/drain schedule (``parallel.pipeline``); embed / final
    norm / lm_head run replicated outside the pipeline.

    ``params`` must be in the :func:`stack_pipeline_params` layout with
    ``cfg.n_layers %% pp == 0`` and ``B %% n_micro == 0``.
    ``return_hidden`` skips the lm_head (the fused-loss contract, as in
    :func:`forward`).
    """
    from dcos_commons_tpu.parallel.pipeline import make_pipeline

    b, s = tokens.shape
    rope = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
    attn_fn = lambda q, k, v: gqa_attention(q, k, v, causal=True)  # noqa: E731

    x = params["embed"].astype(cfg.dtype)[tokens]
    xm = x.reshape(n_micro, b // n_micro, s, -1)

    def stage_fn(stage_layers, x_mb):
        def body(x_, lp):
            return apply_layer(cfg, x_, lp, rope, attn_fn), None
        out, _ = lax.scan(_maybe_checkpoint(body, cfg),
                          x_mb, stage_layers)
        return out

    pipe = make_pipeline(mesh, stage_fn)
    x = pipe(params["layers"], xm).reshape(b, s, -1)
    x = rms_norm(x, params["norm"], cfg.norm_eps)
    if return_hidden:
        return x
    return (x @ params["lm_head"]).astype(jnp.float32)


def loss_fn_pipelined(cfg: LlamaConfig, params: Params, tokens: jnp.ndarray,
                      mesh: Mesh, n_micro: int):
    if cfg.fused_ce:
        x = forward_pipelined(cfg, params, tokens[:, :-1], mesh, n_micro,
                              return_hidden=True)
        return fused_linear_cross_entropy(
            x, params["lm_head"], tokens[:, 1:], z_loss=1e-4,
            block_size=cfg.fused_ce_block)
    logits = forward_pipelined(cfg, params, tokens[:, :-1], mesh, n_micro)
    return softmax_cross_entropy(logits, tokens[:, 1:], z_loss=1e-4)


# ---------------------------------------------------------------------------
# mixture-of-experts variant (expert parallelism over the ep mesh axis)

def init_moe_params(cfg: LlamaConfig, num_experts: int,
                    key: jax.Array) -> Params:
    """Like :func:`init_params` but the dense FFN is replaced by a routed
    expert bank: ``router [L, D, E]``, ``w_in [L, E, D, F]``,
    ``w_out [L, E, F, D]`` (SURVEY.md §2.4 EP)."""
    params = init_params(cfg, key)
    d, f, L, E = cfg.dim, cfg.ffn_dim, cfg.n_layers, num_experts
    k = jax.random.split(jax.random.fold_in(key, 1), 3)
    layers = dict(params["layers"])
    for dense_key in ("w_gate", "w_up", "w_down"):
        layers.pop(dense_key)
    layers["router"] = (jax.random.normal(k[0], (L, d, E), jnp.float32)
                        * d ** -0.5).astype(jnp.float32)
    layers["w_in"] = (jax.random.normal(k[1], (L, E, d, f), jnp.float32)
                      * d ** -0.5).astype(cfg.dtype)
    layers["w_out"] = (jax.random.normal(k[2], (L, E, f, d), jnp.float32)
                       * (f ** -0.5) / (2 * L) ** 0.5).astype(cfg.dtype)
    return {**params, "layers": layers}


def moe_param_specs(cfg: LlamaConfig) -> Params:
    """Experts sharded over ``ep``; everything else replicated."""
    return {
        "embed": P(),
        "layers": {
            "attn_norm": P(), "wq": P(), "wk": P(), "wv": P(), "wo": P(),
            "ffn_norm": P(),
            "router": P(),
            "w_in": P(None, "ep"),
            "w_out": P(None, "ep"),
        },
        "norm": P(),
        "lm_head": P(),
    }


def forward_moe(cfg: LlamaConfig, params: Params, tokens: jnp.ndarray,
                mesh: Mesh, moe_cfg,
                return_hidden: bool = False
                ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """MoE decoder forward: attention as usual, FFN replaced by the GShard
    top-2 expert layer with all-to-all dispatch over ``ep``
    (``parallel.moe``). Returns (logits, mean auxiliary load-balance loss);
    ``return_hidden`` gives final-norm hidden states instead of logits
    (the fused-loss contract, as in :func:`forward`).
    """
    from dcos_commons_tpu.parallel.moe import make_moe

    b, s = tokens.shape
    rope = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
    attn_fn = lambda q, k, v: gqa_attention(q, k, v, causal=True)  # noqa: E731
    moe_fn = make_moe(mesh, moe_cfg)

    x = params["embed"].astype(cfg.dtype)[tokens]

    def layer(carry, lp):
        x, aux_sum = carry
        x = attention_block(cfg, x, lp, rope, attn_fn)
        h = rms_norm(x, lp["ffn_norm"], cfg.norm_eps)
        out, aux = moe_fn(h.reshape(b * s, -1), lp["router"],
                          lp["w_in"], lp["w_out"])
        x = x + out.reshape(b, s, -1).astype(cfg.dtype)
        return (x, aux_sum + aux.astype(jnp.float32)), None

    (x, aux_sum), _ = lax.scan(
        _maybe_checkpoint(layer, cfg),
        (x, jnp.float32(0.0)), params["layers"])
    x = rms_norm(x, params["norm"], cfg.norm_eps)
    if return_hidden:
        return x, aux_sum / cfg.n_layers
    logits = (x @ params["lm_head"]).astype(jnp.float32)
    return logits, aux_sum / cfg.n_layers


def loss_fn_moe(cfg: LlamaConfig, params: Params, tokens: jnp.ndarray,
                mesh: Mesh, moe_cfg, aux_weight: float = 0.01):
    if cfg.fused_ce:
        x, aux = forward_moe(cfg, params, tokens[:, :-1], mesh, moe_cfg,
                             return_hidden=True)
        loss, metric = fused_linear_cross_entropy(
            x, params["lm_head"], tokens[:, 1:], z_loss=1e-4,
            block_size=cfg.fused_ce_block)
        return loss + aux_weight * aux, metric
    logits, aux = forward_moe(cfg, params, tokens[:, :-1], mesh, moe_cfg)
    loss, metric = softmax_cross_entropy(logits, tokens[:, 1:], z_loss=1e-4)
    return loss + aux_weight * aux, metric


def make_moe_ffn(cfg: LlamaConfig, moe_cfg,
                 mesh: Optional[Mesh] = None) -> Callable:
    """Build the ``ffn_override`` that routes :func:`_decode_body`'s FFN
    step through ``parallel.moe`` — the MoE serving hook.

    On a mesh with an ``ep`` axis > 1 the layer runs
    :func:`~dcos_commons_tpu.parallel.moe.moe_apply` under shard_map:
    each shard computes only its E/ep experts' FLOPs and the two
    ``all_to_all`` collectives carry the capacity-bounded [E, C, D]
    dispatch buffers (the analysis hot path budget). Anywhere else
    (single host, decode smoke, the parity reference) it runs
    :func:`~dcos_commons_tpu.parallel.moe.moe_apply_local` — the same
    contractions expert-by-expert, so both paths agree bitwise for the
    same token group. The auxiliary load-balance loss is dead weight at
    inference and is dropped."""
    from dcos_commons_tpu.parallel import moe as _moe

    if mesh is not None and mesh.shape.get("ep", 1) > 1:
        if moe_cfg.num_experts % mesh.shape["ep"]:
            raise ValueError(
                f"num_experts={moe_cfg.num_experts} not divisible by "
                f"ep={mesh.shape['ep']}")

        def inner(flat, rw, wi, wo):
            out, _ = _moe.moe_apply(flat, rw, wi, wo, moe_cfg)
            return out

        apply = jax.shard_map(
            inner, mesh=mesh,
            in_specs=(P(), P(), P("ep"), P("ep")),
            out_specs=P(), check_vma=False)
    else:
        def apply(flat, rw, wi, wo):
            out, _ = _moe.moe_apply_local(flat, rw, wi, wo, moe_cfg)
            return out

    def ffn(x: jnp.ndarray, lp: Params) -> jnp.ndarray:
        b, s, d = x.shape
        h = rms_norm(x, lp["ffn_norm"], cfg.norm_eps)
        out = apply(h.reshape(b * s, d), lp["router"],
                    lp["w_in"], lp["w_out"])
        return x + out.reshape(b, s, d).astype(cfg.dtype)

    return ffn


_MOE_STEPWISE_CACHE: dict = {}


def _moe_stepwise_executables(cfg: LlamaConfig, moe_cfg,
                              mesh: Optional[Mesh]):
    """Jitted MoE prefill/decode-step callables, cached per
    (cfg, moe_cfg, mesh) like :func:`_stepwise_executables`."""
    key = (cfg, moe_cfg, mesh)
    hit = _MOE_STEPWISE_CACHE.get(key)
    if hit is None:
        rope = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
        ffn = make_moe_ffn(cfg, moe_cfg, mesh)
        hit = (
            jax.jit(lambda p, c, pr: extend_step(
                cfg, p, c, pr, jnp.int32(0), rope=rope,
                ffn_override=ffn)),
            jax.jit(lambda p, c, pos, tok: decode_step(
                cfg, p, c, pos, tok, mesh, rope=rope,
                ffn_override=ffn)),
        )
        _MOE_STEPWISE_CACHE[key] = hit
    return hit


def generate_stepwise_moe(cfg: LlamaConfig, params: Params,
                          prompt: jnp.ndarray, steps: int, moe_cfg,
                          mesh: Optional[Mesh] = None) -> jnp.ndarray:
    """Greedy MoE generation — the serving parity REFERENCE (what
    :func:`generate_stepwise` is to the dense paged engine).

    Whole-prompt prefill via :func:`extend_step` + one stepwise decode
    executable, both with the :func:`make_moe_ffn` override. Note the
    grouping contract: the paged engine routes each prefill chunk /
    decode batch as its own dispatch group, this reference routes the
    whole prompt then one token at a time — the two agree token-exactly
    ONLY under dropless capacity (``parallel.moe.dropless``), where
    per-token routing is independent of the token grouping."""
    b, s = prompt.shape
    _check_capacity(cfg, s, steps)
    cache = init_kv_cache(cfg, b, cfg.max_seq)
    prefill_x, step_x = _moe_stepwise_executables(cfg, moe_cfg, mesh)
    logits, cache = prefill_x(params, cache, prompt)
    logits = logits[:, -1]             # extend_step returns every position
    toks = []
    for i in range(steps):
        tok = jnp.argmax(logits, axis=-1).astype(prompt.dtype)
        logits, cache = step_x(params, cache, jnp.int32(s + i), tok)
        toks.append(tok)
    if not toks:
        return jnp.zeros((b, 0), prompt.dtype)
    return jnp.stack(toks, axis=1)                         # [B, steps]


def loss_fn(cfg: LlamaConfig, params: Params, tokens: jnp.ndarray,
            mesh: Optional[Mesh] = None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Next-token LM loss over tokens [B, S] -> (loss, accuracy).

    With the zigzag ring layout, inputs AND targets are permuted into
    the layout order (the shift into input/target pairs happens FIRST,
    in natural order) — cross entropy is permutation-invariant under a
    consistent pairing, so the loss equals the natural-order loss while
    the ring's causal work stays balanced.

    With ``cfg.fused_ce`` (the default) the lm_head projection runs
    inside ``fused_linear_cross_entropy``'s sequence-chunked loop, so
    the full [B, S, V] fp32 logits tensor never materializes — same
    math, a fraction of the loss head's HBM traffic
    (docs/performance.md "HBM traffic on the loss head")."""
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    if (cfg.attn_impl == "ring" and cfg.ring_layout == "zigzag"
            and mesh is not None):
        from dcos_commons_tpu.parallel.ring_attention import zigzag_indices
        perm = jnp.asarray(zigzag_indices(inputs.shape[1],
                                          mesh.shape["sp"]))
        if cfg.fused_ce:
            x = forward(cfg, params, inputs[:, perm], mesh,
                        positions=perm, return_hidden=True)
            return fused_linear_cross_entropy(
                x, params["lm_head"], targets[:, perm], z_loss=1e-4,
                block_size=cfg.fused_ce_block)
        logits = forward(cfg, params, inputs[:, perm], mesh,
                         positions=perm)
        return softmax_cross_entropy(logits, targets[:, perm],
                                     z_loss=1e-4)
    if cfg.fused_ce:
        x = forward(cfg, params, inputs, mesh, return_hidden=True)
        return fused_linear_cross_entropy(
            x, params["lm_head"], targets, z_loss=1e-4,
            block_size=cfg.fused_ce_block)
    logits = forward(cfg, params, inputs, mesh)
    return softmax_cross_entropy(logits, targets, z_loss=1e-4)


# ---------------------------------------------------------------------------
# KV-cache decode (inference path; BASELINE.json config #5)

def init_kv_cache(cfg: LlamaConfig, batch: int, max_seq: int) -> Params:
    shape = (cfg.n_layers, batch, max_seq, cfg.n_kv_heads, cfg.head_dim)
    if cfg.kv_quant:
        sshape = shape[:-1] + (1,)
        return {"k": QTensor(jnp.zeros(shape, jnp.int8),
                             jnp.zeros(sshape, jnp.bfloat16)),
                "v": QTensor(jnp.zeros(shape, jnp.int8),
                             jnp.zeros(sshape, jnp.bfloat16))}
    return {"k": jnp.zeros(shape, cfg.dtype), "v": jnp.zeros(shape, cfg.dtype)}


def _cache_update(cache, new: jnp.ndarray, pos, axis: int, dtype
                  ) -> Tuple[Any, jnp.ndarray]:
    """Write ``new`` (bf16 K or V rows) into the cache at ``pos`` along
    ``axis``; returns (updated cache, attention-readable view).

    Quantized caches round the new rows to int8 with a per-row scale
    (``quantize`` along head_dim) and update payload + scales in step;
    the dequantized read is an elementwise producer XLA fuses into the
    attention matmul's operand load — no bf16 cache copy lands in HBM.
    """
    if isinstance(cache, QTensor):
        nq = quantize(new, axis=-1)
        cache = QTensor(
            lax.dynamic_update_slice_in_dim(cache.q, nq.q, pos, axis=axis),
            lax.dynamic_update_slice_in_dim(
                cache.s, nq.s.astype(cache.s.dtype), pos, axis=axis))
        return cache, dequantize(cache, dtype)
    cache = lax.dynamic_update_slice_in_dim(cache, new, pos, axis=axis)
    return cache, cache


def cache_specs() -> Params:
    return {"k": P(None, "dp", None, "tp", None),
            "v": P(None, "dp", None, "tp", None)}


def _tp_only(mesh: Optional[Mesh]) -> bool:
    """True when the mesh shards nothing but the ``tp`` axis — the
    head-local sharding the flash-decode shard_map wrapper serves."""
    return (mesh is not None and "tp" in mesh.shape
            and all(n == 1 for ax, n in mesh.shape.items()
                    if ax != "tp"))


def _use_flash_decode(cfg: LlamaConfig, mesh: Optional[Mesh]) -> bool:
    """Route decode_step's attention: the pallas kernel on TPU with
    lane-aligned shapes (head_dim and max_seq % 128) — unsharded, or
    tp-only meshes whose axis divides the KV heads (attention is
    head-local, so tp shards run the kernel via shard_map with no
    collectives); dense elsewhere."""
    def mesh_ok(m):
        # a one-device mesh shards nothing, whatever its axes are named
        return (m is None or m.size == 1
                or (_tp_only(m)
                    and cfg.n_kv_heads % m.shape["tp"] == 0))

    if cfg.decode_attn in ("flash", "flash_interpret"):
        if not mesh_ok(mesh):
            # forcing flash on a mesh the kernel cannot serve must be
            # loud, not a silent dense run or a KeyError downstream
            raise ValueError(
                f"decode_attn={cfg.decode_attn!r} needs an unsharded "
                "or tp-only mesh whose axis divides the KV heads; got "
                f"{dict(mesh.shape)}")
        return True
    if cfg.decode_attn == "dense":
        return False
    if cfg.decode_attn != "auto":
        # a typo'd mode must not silently measure the dense path
        raise ValueError(
            f"decode_attn={cfg.decode_attn!r}: expected one of "
            "'auto', 'dense', 'flash', 'flash_interpret'")
    if jax.default_backend() != "tpu" \
            or cfg.head_dim % 128 or cfg.max_seq % 128:
        return False
    return mesh_ok(mesh)


def _decode_body(cfg: LlamaConfig, params: Params, cache: Params,
                 tokens: jnp.ndarray, flash: bool, rope_fn, cache_write,
                 kv_len, causal: bool = False, q_offset=0,
                 all_positions: bool = False,
                 mesh: Optional[Mesh] = None,
                 attn_override=None, logit_index=None,
                 ffn_override=None
                 ) -> Tuple[jnp.ndarray, Params]:
    """The cache-consuming forward shared by :func:`decode_step` (one
    scalar position), :func:`decode_step_slots` (per-slot positions),
    :func:`extend_step` (a K-token window), and the paged serving paths
    (:func:`decode_step_paged` / :func:`prefill_chunk_paged`). The
    callers differ ONLY in how rope is applied, where the cache rows
    land, and the attention mask — everything else must stay ONE body
    or the serving engine / speculative verify silently diverge from
    solo decode.

    ``tokens`` [B, S] (S == 1 for decode steps); ``causal``/``q_offset``
    shape the within-window mask for S > 1; ``all_positions`` returns
    logits [B, S, V] instead of the last position's [B, V].
    ``attn_override(q, k_cache, v_cache)`` replaces the attention read
    entirely (the paged paths gather through a page table / run the
    paged pallas kernel — the cache layout is theirs to interpret);
    ``logit_index`` takes logits at a DYNAMIC position instead of the
    last (a padded prefill chunk's last live token).
    ``ffn_override(x, lp) -> x`` replaces the whole pre-norm FFN
    residual step (the MoE serving path routes through
    ``parallel.moe`` here); None keeps the dense SwiGLU bitwise.
    """
    b, s = tokens.shape
    x = qtake(params["embed"], tokens, cfg.dtype)              # [B, S, D]

    def layer(carry, inputs):
        x, layer_idx = carry
        lp, k_cache, v_cache = inputs
        h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        q = qmm(h, lp["wq"]).reshape(b, s, cfg.n_heads, cfg.head_dim)
        k = qmm(h, lp["wk"]).reshape(b, s, cfg.n_kv_heads, cfg.head_dim)
        v = qmm(h, lp["wv"]).reshape(b, s, cfg.n_kv_heads, cfg.head_dim)
        q = rope_fn(q)
        k = rope_fn(k)
        k_cache, k_read = cache_write(k_cache, k)
        v_cache, v_read = cache_write(v_cache, v)
        if attn_override is not None:
            o = attn_override(q, k_cache, v_cache)
        elif flash:
            # the pallas kernel consumes the cache in storage form (int8
            # payload + scales dequantize in VMEM); the dense read above
            # is dead code XLA eliminates on this branch. tp meshes run
            # the kernel per head shard (shard_map, no collectives).
            interp = cfg.decode_attn == "flash_interpret"
            if mesh is not None and mesh.shape.get("tp", 1) > 1:
                o = flash_decode_tp(q, k_cache, v_cache, kv_len, mesh,
                                    interpret=interp)
            else:
                # no real tp sharding (no mesh, or a one-device /
                # tp=1 mesh): the plain kernel call partitions trivially
                o = flash_decode(q, k_cache, v_cache, kv_len,
                                 interpret=interp)
        else:
            o = gqa_attention(q, k_read, v_read, causal=causal,
                              q_offset=q_offset, kv_len=kv_len)
        x = x + qmm(o.reshape(b, s, -1), lp["wo"])
        if ffn_override is not None:
            x = ffn_override(x, lp)
        else:
            h = rms_norm(x, lp["ffn_norm"], cfg.norm_eps)
            gate = jax.nn.silu(qmm(h, lp["w_gate"]).astype(jnp.float32))
            up = qmm(h, lp["w_up"]).astype(jnp.float32)
            x = x + qmm((gate * up).astype(cfg.dtype), lp["w_down"])
        return (x, layer_idx + 1), (k_cache, v_cache)

    (x, _), (k_new, v_new) = lax.scan(
        layer, (x, 0), (params["layers"], cache["k"], cache["v"]))
    x = rms_norm(x, params["norm"], cfg.norm_eps)
    if all_positions:
        pass
    elif logit_index is not None:
        x = lax.dynamic_index_in_dim(x, logit_index, axis=1,
                                     keepdims=False)
    else:
        x = x[:, -1, :]
    logits = qmm(x, params["lm_head"]).astype(jnp.float32)
    return logits, {"k": k_new, "v": v_new}


def decode_step(cfg: LlamaConfig, params: Params, cache: Params,
                pos: jnp.ndarray, token: jnp.ndarray,
                mesh: Optional[Mesh] = None,
                rope: Optional[jnp.ndarray] = None,
                ffn_override=None
                ) -> Tuple[jnp.ndarray, Params]:
    """One greedy-decode step.

    token [B] int32, pos scalar int32 (current length). Returns
    (logits [B, V], updated cache). Static shapes: the cache is a fixed
    [max] ring written at ``pos`` via dynamic_update_slice, masked reads.
    Pass a precomputed ``rope`` table (``rope_frequencies`` output,
    [2, max_seq, head_dim//2]) when calling from inside a scan —
    materializing that constant inside every nested scan body explodes
    TPU compile time (generate() hoists it once).
    """
    if rope is None:
        rope = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
    return _decode_body(
        cfg, params, cache, token[:, None], _use_flash_decode(cfg, mesh),
        rope_fn=lambda t: apply_rope(t, rope, pos),
        cache_write=lambda c, new: _cache_update(c, new, pos, 1,
                                                 cfg.dtype),
        kv_len=pos + 1, mesh=mesh, ffn_override=ffn_override)


def extend_step(cfg: LlamaConfig, params: Params, cache: Params,
                tokens: jnp.ndarray, pos: jnp.ndarray,
                rope: Optional[jnp.ndarray] = None,
                ffn_override=None
                ) -> Tuple[jnp.ndarray, Params]:
    """Consume K tokens in ONE forward: ``tokens`` [B, K] occupy
    positions ``pos..pos+K-1``; returns (logits [B, K, V] at every
    position, updated cache).

    The verify pass of speculative decoding (``models/speculative.py``)
    and the chunked-prefill building block: the whole window's K/V
    writes land first, then each query attends causally within the
    window (``q_offset=pos``) and to the live cache prefix — so the
    weights stream ONCE per K tokens instead of once per token.
    Single-chip (no mesh parameter): sharded serving decodes through
    ``decode_step`` / ``generate_*`` instead.
    """
    kk = tokens.shape[1]
    if rope is None:
        rope = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
    return _decode_body(
        cfg, params, cache, tokens, flash=False,
        rope_fn=lambda t: apply_rope(t, rope, pos),
        cache_write=lambda c, new: _cache_update(c, new, pos, 1,
                                                 cfg.dtype),
        kv_len=pos + kk, causal=True, q_offset=pos, all_positions=True,
        ffn_override=ffn_override)


def _cache_update_slots(cache, new: jnp.ndarray, lengths: jnp.ndarray,
                        dtype) -> Tuple[Any, jnp.ndarray]:
    """Per-slot cache write: row ``b`` of ``new`` [B, 1, KV, D] lands at
    position ``lengths[b]`` (scatter). Same contract as
    :func:`_cache_update` otherwise."""
    b = new.shape[0]
    rows = jnp.arange(b)
    if isinstance(cache, QTensor):
        nq = quantize(new, axis=-1)
        cache = QTensor(
            cache.q.at[rows, lengths].set(nq.q[:, 0]),
            cache.s.at[rows, lengths].set(nq.s[:, 0].astype(
                cache.s.dtype)))
        return cache, dequantize(cache, dtype)
    cache = cache.at[rows, lengths].set(new[:, 0])
    return cache, cache


def decode_step_slots(cfg: LlamaConfig, params: Params, cache: Params,
                      lengths: jnp.ndarray, tokens: jnp.ndarray,
                      mesh: Optional[Mesh] = None,
                      rope: Optional[jnp.ndarray] = None
                      ) -> Tuple[jnp.ndarray, Params]:
    """One decode step with PER-SLOT positions — the continuous-batching
    kernel of :class:`~dcos_commons_tpu.models.serving.SlotServer`.

    ``tokens`` [B] int32, ``lengths`` [B] int32 (each slot's live
    length; its new K/V row is written at that position and it attends
    to ``lengths[b] + 1`` slots). Identical math to :func:`decode_step`
    per row — a batch of conversations at different positions decodes
    in one dispatch.
    """
    if rope is None:
        rope = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
    return _decode_body(
        cfg, params, cache, tokens[:, None],
        _use_flash_decode(cfg, mesh),
        rope_fn=lambda t: apply_rope_at(t, rope, lengths),
        cache_write=lambda c, new: _cache_update_slots(c, new, lengths,
                                                       cfg.dtype),
        kv_len=lengths + 1, mesh=mesh)


# ---------------------------------------------------------------------------
# block-paged KV (PagedServer): a fixed pool of pages + per-stream
# indirection tables instead of per-slot max_seq rows

def init_page_pool(cfg: LlamaConfig, pages: int, page_size: int) -> Params:
    """KV page pool [L, pages, page_size, KV, D] (QTensor payload +
    per-position scales under ``cfg.kv_quant``, like
    :func:`init_kv_cache`). One physical pool serves every stream; who
    owns which page is host bookkeeping (``models/paging.PagePool``)."""
    shape = (cfg.n_layers, pages, page_size, cfg.n_kv_heads, cfg.head_dim)
    if cfg.kv_quant:
        sshape = shape[:-1] + (1,)
        return {"k": QTensor(jnp.zeros(shape, jnp.int8),
                             jnp.zeros(sshape, jnp.bfloat16)),
                "v": QTensor(jnp.zeros(shape, jnp.int8),
                             jnp.zeros(sshape, jnp.bfloat16))}
    return {"k": jnp.zeros(shape, cfg.dtype),
            "v": jnp.zeros(shape, cfg.dtype)}


def page_pool_specs() -> Params:
    """Sharding for the page pool under tensor parallelism: the KV-head
    axis shards next to the megatron weight shards (as the slot cache
    does); the PAGE axis stays unsharded — every shard holds every
    page, and attention is head-local."""
    return {"k": P(None, None, None, "tp", None),
            "v": P(None, None, None, "tp", None)}


def _gather_pages(cache, table: jnp.ndarray, dtype) -> jnp.ndarray:
    """Reassemble a per-layer pool [P, ps, KV, D] into logical-order
    views [B, MP*ps, KV, D] through ``table`` [B, MP] — the dense-path
    attention read. Position ``p`` of stream ``b`` lands at view index
    ``p`` exactly (the table maps logical page p//ps to its physical
    page), so masked attention over the view reduces in the SAME order
    as over a monolithic cache row — greedy parity with the slot engine
    is exact, not approximate."""
    if isinstance(cache, QTensor):
        view = dequantize(QTensor(cache.q[table], cache.s[table]), dtype)
    else:
        view = cache[table]
    b, mp, ps, kv, d = view.shape
    return view.reshape(b, mp * ps, kv, d)


def _page_write(cache, rows: jnp.ndarray, phys: jnp.ndarray,
                offs: jnp.ndarray):
    """Scatter K/V ``rows`` [N, KV, D] into a per-layer pool at
    (``phys[i]``, ``offs[i]``), quantizing when the pool is int8.
    Callers guarantee writable target pages are PRIVATE to their stream
    (prefix-shared pages are read-only; the boundary page copies at
    admission), so the scatter needs no ownership mask."""
    if isinstance(cache, QTensor):
        nq = quantize(rows, axis=-1)
        return QTensor(
            cache.q.at[phys, offs].set(nq.q),
            cache.s.at[phys, offs].set(nq.s.astype(cache.s.dtype)))
    return cache.at[phys, offs].set(rows)


def _use_flash_decode_paged(cfg: LlamaConfig, mesh: Optional[Mesh],
                            page_size: int) -> bool:
    """Route the paged decode step's attention: the same gate as
    :func:`_use_flash_decode` with the lane-alignment condition on the
    PAGE — the paged kernel's k-blocks tile pages, not max_seq rows."""
    if not _use_flash_decode(cfg, mesh):
        return False
    if page_size % 128:
        if cfg.decode_attn in ("flash", "flash_interpret"):
            raise ValueError(
                f"decode_attn={cfg.decode_attn!r} needs page_size % 128 "
                f"== 0 for the paged pallas kernel; got {page_size}")
        return False
    return True


def decode_step_paged(cfg: LlamaConfig, params: Params, pool: Params,
                      table: jnp.ndarray, lengths: jnp.ndarray,
                      tokens: jnp.ndarray, mesh: Optional[Mesh] = None,
                      rope: Optional[jnp.ndarray] = None,
                      ffn_override=None
                      ) -> Tuple[jnp.ndarray, Params]:
    """One decode step against the PAGED pool — per-row math identical
    to :func:`decode_step_slots`, only the cache landing differs.

    ``tokens``/``lengths`` [B] int32; ``table`` [B, MP] int32 maps each
    stream's logical page to a physical pool page. Each stream's new
    K/V row scatters into (table[b, lengths[b]//ps], lengths[b] %% ps);
    attention reads the pool through the table (gather-based dense, or
    the paged pallas kernel when lane-aligned). Inactive streams must
    point their table rows at a scratch page the engine never
    allocates — their frozen-position writes land there harmlessly.
    """
    if rope is None:
        rope = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
    kq = pool["k"].q if isinstance(pool["k"], QTensor) else pool["k"]
    ps = kq.shape[2]
    mp = table.shape[1]
    # clip: a retired stream's length can run past the table mid-window
    # (the slot engine's frozen-row behaviour); its row is all scratch
    page_idx = jnp.clip(lengths // ps, 0, mp - 1)
    phys = jnp.take_along_axis(table, page_idx[:, None], axis=1)[:, 0]
    offs = lengths % ps
    flash = _use_flash_decode_paged(cfg, mesh, ps)
    interp = cfg.decode_attn == "flash_interpret"

    def cache_write(c, new):
        return _page_write(c, new[:, 0], phys, offs), None

    def attn_override(q, k_cache, v_cache):
        if flash:
            if mesh is not None and mesh.shape.get("tp", 1) > 1:
                return flash_decode_paged_tp(q, k_cache, v_cache, table,
                                             lengths + 1, mesh,
                                             interpret=interp)
            return flash_decode_paged(q, k_cache, v_cache, table,
                                      lengths + 1, interpret=interp)
        k_read = _gather_pages(k_cache, table, cfg.dtype)
        v_read = _gather_pages(v_cache, table, cfg.dtype)
        return gqa_attention(q, k_read, v_read, causal=False,
                             kv_len=lengths + 1)

    return _decode_body(
        cfg, params, pool, tokens[:, None], False,
        rope_fn=lambda t: apply_rope_at(t, rope, lengths),
        cache_write=cache_write, kv_len=lengths + 1, mesh=mesh,
        attn_override=attn_override, ffn_override=ffn_override)


def verify_step_paged(cfg: LlamaConfig, params: Params, pool: Params,
                      table: jnp.ndarray, lengths: jnp.ndarray,
                      tokens: jnp.ndarray, mesh: Optional[Mesh] = None,
                      rope: Optional[jnp.ndarray] = None,
                      ffn_override=None
                      ) -> Tuple[jnp.ndarray, Params]:
    """Consume a K-token window PER STREAM against the paged pool — the
    speculative-verify counterpart of :func:`extend_step`, batched over
    streams at independent positions.

    ``tokens`` [B, K] occupy positions ``lengths[b]..lengths[b]+K-1``
    of each stream; returns (logits [B, K, V] at every window position,
    pool). Row (b, j)'s K/V scatters through ``table`` [B, MP] exactly
    like :func:`decode_step_paged`'s single row would at that position,
    so a fully-accepted window leaves the pool bitwise as K successive
    solo steps would have — acceptance never forks the cache contents.
    Attention is causal WITHIN the window with per-stream offsets
    (query j of stream b sees positions <= lengths[b]+j), which is why
    greedy argmax over these logits reproduces solo decode's stream
    token-exactly (modulo the K-wide-vs-1-wide bf16 reduction caveat
    ``models/speculative.py`` documents).

    Rejection rollback is free by the same masked-cache argument as the
    monolithic verify: rejected rows sit beyond the live length the
    host keeps, are never attended (every future read masks at the
    ADVANCED length), and are overwritten in place when decode reaches
    them. Writes land only in pages the stream's table row maps — the
    full-span allocation at admission — with overflow past the
    allocated span clipping onto the engine's scratch page rows exactly
    like a frozen stream's writes; tokens the host can still commit
    (within the stream's max_new budget) attend only in-span positions,
    so the shared-scratch collisions stay confined to discarded tail
    tokens. The page ledger never hears about any of this: no page is
    allocated or released by a verify window, which is what keeps
    check()/reconcile() trivially clean under speculative serving.
    """
    if rope is None:
        rope = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
    b, kk = tokens.shape
    kq = pool["k"].q if isinstance(pool["k"], QTensor) else pool["k"]
    ps = kq.shape[2]
    mp = table.shape[1]
    positions = lengths[:, None] + jnp.arange(kk, dtype=jnp.int32)[None]
    page_idx = jnp.clip(positions // ps, 0, mp - 1)
    phys = jnp.take_along_axis(table, page_idx, axis=1)      # [B, K]
    offs = positions % ps
    rope_pos = jnp.clip(positions, 0, rope.shape[1] - 1)

    def cache_write(c, new):
        # new [B, K, KV, D] -> flat scatter of every (stream, window) row
        flat = new.reshape((b * kk,) + new.shape[2:])
        return _page_write(c, flat, phys.reshape(-1),
                           offs.reshape(-1)), None

    def attn_override(q, k_cache, v_cache):
        k_read = _gather_pages(k_cache, table, cfg.dtype)
        v_read = _gather_pages(v_cache, table, cfg.dtype)
        return gqa_attention(q, k_read, v_read, causal=True,
                             q_offset=lengths, kv_len=lengths + kk)

    return _decode_body(
        cfg, params, pool, tokens, False,
        rope_fn=lambda t: apply_rope_at_many(t, rope, rope_pos),
        cache_write=cache_write, kv_len=lengths + kk, causal=True,
        mesh=mesh, attn_override=attn_override, all_positions=True,
        ffn_override=ffn_override)


def prefill_chunk_paged(cfg: LlamaConfig, params: Params, pool: Params,
                        table: jnp.ndarray, tokens: jnp.ndarray,
                        start: jnp.ndarray, true_len: jnp.ndarray,
                        logit_index: jnp.ndarray, scratch_page: int,
                        mesh: Optional[Mesh] = None,
                        rope: Optional[jnp.ndarray] = None,
                        ffn_override=None
                        ) -> Tuple[jnp.ndarray, Params]:
    """One CHUNK of paged prefill for a single stream: ``tokens``
    [1, C] occupy positions ``start..start+C-1``, K/V landing through
    ``table`` [MP]. Returns (logits [1, V] at ``logit_index`` — the
    chunk-relative last live position; garbage for non-final chunks —
    and the pool).

    This is how long prompts stop stalling running decode streams: the
    engine interleaves ONE fixed-shape chunk per tick with the decode
    dispatch, so a 4096-token prompt costs many small stalls instead of
    one huge one, and one executable serves every prompt length (vs the
    slot engine's per-bucket prefill matrix).

    Padded positions at/after ``true_len`` redirect their writes to
    ``scratch_page`` (live queries are causally upstream of them, so
    they perturb nothing and nothing reads them). Attention gathers the
    stream's pages in logical order — per-position math identical to
    full-prompt prefill, chunk boundaries included, because causal
    attention at position p sees exactly positions <= p either way.
    """
    if rope is None:
        rope = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
    kq = pool["k"].q if isinstance(pool["k"], QTensor) else pool["k"]
    ps = kq.shape[2]
    mp = table.shape[0]
    c = tokens.shape[1]
    positions = start + jnp.arange(c, dtype=jnp.int32)
    live = positions < true_len
    phys = jnp.where(live,
                     table[jnp.clip(positions // ps, 0, mp - 1)],
                     jnp.int32(scratch_page))
    offs = positions % ps
    table_b = table[None]                                    # [1, MP]
    # a RESUMED chunk (radix hit / tier promote / fleet adoption) can
    # start so late that start + C overruns the rope table; rotate by
    # per-lane gather, NOT apply_rope's dynamic_slice, whose clamped
    # start would mis-rotate the live head of the chunk (tokens past
    # true_len are dead either way — their clipped rope is never read)
    rope_pos = jnp.clip(positions, 0, rope.shape[1] - 1)

    def cache_write(cache, new):
        return _page_write(cache, new[0], phys, offs), None

    def attn_override(q, k_cache, v_cache):
        k_read = _gather_pages(k_cache, table_b, cfg.dtype)
        v_read = _gather_pages(v_cache, table_b, cfg.dtype)
        return gqa_attention(q, k_read, v_read, causal=True,
                             q_offset=start, kv_len=start + c)

    return _decode_body(
        cfg, params, pool, tokens, False,
        rope_fn=lambda t: apply_rope_positions(t, rope, rope_pos),
        cache_write=cache_write, kv_len=start + c, causal=True,
        q_offset=start, mesh=mesh, attn_override=attn_override,
        logit_index=logit_index, ffn_override=ffn_override)


def prefill(cfg: LlamaConfig, params: Params, cache: Params,
            prompt: jnp.ndarray, mesh: Optional[Mesh] = None,
            rope: Optional[jnp.ndarray] = None
            ) -> Tuple[jnp.ndarray, Params]:
    """Parallel prefill: ONE forward over the whole prompt, writing every
    layer's K/V into the cache at positions ``[0, S)``.

    Returns (last-position logits [B, V], cache). Replaces the old
    token-by-token prefill (S sequential decode steps): same cache
    contents, but the sequence dimension runs in parallel on the MXU and
    the compiled graph is the train forward's — which both halves
    ``generate``'s compile time (the dominant cost at 400m+ through
    tunneled backends, docs/performance.md) and makes prompt processing
    O(1) dispatches instead of O(S).
    """
    if rope is None:
        rope = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
    x, ks, vs = prefill_trunk(cfg, params, prompt, rope, mesh)
    logits = qmm(x[:, -1, :], params["lm_head"]).astype(jnp.float32)
    cache = {
        "k": _cache_update(cache["k"], ks, 0, 2, cfg.dtype)[0],
        "v": _cache_update(cache["v"], vs, 0, 2, cfg.dtype)[0],
    }
    return logits, cache


def prefill_trunk(cfg: LlamaConfig, params: Params, prompt: jnp.ndarray,
                  rope: jnp.ndarray, mesh: Optional[Mesh] = None
                  ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """The prefill forward shared by :func:`prefill` and the serving
    engine's bucketed slot prefill: (normed hidden states [B, S, D],
    ks/vs [L, B, S, KV, D]) — callers pick which position's logits they
    need and where the K/V land.

    NOT _make_attn_fn: the cache contract matches decode_step exactly,
    and ring/ulysses shard_map impls require sp-divisible sequence
    lengths — prompts are arbitrary. Long aligned prompts route to the
    pallas flash kernel (same serving gate as decode): the dense path
    materializes [B, H, S, S] fp32 scores, a 26 GB transient at
    batch 8 x seq 4096 that simply does not fit; flash streams them
    through VMEM tiles.
    """
    s = prompt.shape[1]
    # flash prefill routes like flash decode (_use_flash_decode):
    # unsharded runs the plain kernel; tp-only meshes whose axis divides
    # the KV heads run it per head shard via shard_map
    # (ops.flash_attention.flash_attention_tp — attention is head-local,
    # no collectives). Anything else keeps the dense path, which
    # partitions under GSPMD but pays the [B, H, S, S] fp32 transient.
    if _use_flash_decode(cfg, mesh) and s % 128 == 0 \
            and cfg.head_dim <= 256:
        from dcos_commons_tpu.ops.flash_attention import (
            flash_attention, flash_attention_tp)
        interp = cfg.decode_attn == "flash_interpret"
        if mesh is not None and mesh.shape.get("tp", 1) > 1:
            attn_fn = (lambda q, k, v: flash_attention_tp(
                q, k, v, mesh, causal=True, interpret=interp))
        else:
            attn_fn = (lambda q, k, v: flash_attention(
                q, k, v, causal=True, interpret=interp))
    else:
        attn_fn = (lambda q, k, v: gqa_attention(q, k, v, causal=True))
    x = qtake(params["embed"], prompt, cfg.dtype)
    x = _constrain(x, mesh, "dp", None, None)

    def layer(x, lp):
        x, k, v = attention_block(cfg, x, lp, rope, attn_fn,
                                  return_kv=True)
        x = ffn_block(cfg, x, lp)
        return _constrain(x, mesh, "dp", None, None), (k, v)

    x, (ks, vs) = lax.scan(layer, x, params["layers"])
    return rms_norm(x, params["norm"], cfg.norm_eps), ks, vs


def prefill_ring(cfg: LlamaConfig, params: Params, prompt: jnp.ndarray,
                 mesh: Mesh, rope: Optional[jnp.ndarray] = None,
                 ffn_override=None
                 ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Sequence-parallel prefill: :func:`prefill_trunk`'s contract —
    (normed hidden [B, S, D], ks/vs [L, B, S, KV, D]) — computed with
    ``parallel.ring_attention`` over the ``sp`` mesh axis, so each gang
    member pays ~S/sp of the attention work and a 128k prompt prefills
    in ~seq/N wall-clock instead of serially on one host.

    The caller pads the prompt to an sp-divisible length
    (``ring_attention.ring_pad_len``); padded positions compute garbage
    K/V that the serving engine routes to scratch or never reads. The
    layout is always ``contiguous`` — K/V must land in natural position
    order for the page-table install; the zigzag layout's balance win
    only matters for TRAINING throughput, and its permuted cache order
    would corrupt the paged decode gather. Requires S <= cfg.max_seq
    (rope table bound) and a real sp axis; callers degrade to chunked
    prefill on ValueError (the ``longctx_fallback`` discipline)."""
    s = prompt.shape[1]
    sp = mesh.shape.get("sp", 1) if mesh is not None else 1
    if sp <= 1:
        raise ValueError("prefill_ring needs an 'sp' mesh axis > 1; "
                         f"got {dict(mesh.shape) if mesh else None}")
    if s % sp:
        raise ValueError(
            f"ring prefill needs S ({s}) % sp ({sp}) == 0; pad the "
            "prompt with ring_attention.ring_pad_len")
    if s > cfg.max_seq:
        raise ValueError(f"padded prompt {s} exceeds max_seq "
                         f"{cfg.max_seq}")
    if rope is None:
        rope = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
    ring = make_ring_attention(mesh, causal=True, layout="contiguous",
                               spec=P(None, "sp", None, None),
                               kv_spec=P(None, "sp", None, None))
    x = qtake(params["embed"], prompt, cfg.dtype)
    x = _constrain(x, mesh, None, "sp", None)

    def layer(x, lp):
        x, k, v = attention_block(cfg, x, lp, rope, ring,
                                  return_kv=True)
        if ffn_override is not None:
            x = ffn_override(x, lp)
        else:
            x = ffn_block(cfg, x, lp)
        return _constrain(x, mesh, None, "sp", None), (k, v)

    x, (ks, vs) = lax.scan(layer, x, params["layers"])
    return rms_norm(x, params["norm"], cfg.norm_eps), ks, vs


def _check_capacity(cfg: LlamaConfig, prompt_len: int, steps: int) -> None:
    """Reject requests that would write past the cache: dynamic_update_slice
    CLAMPS out-of-range positions, so an oversized ask silently smears
    writes onto the last cache row and returns corrupted tokens instead of
    failing (SlotServer.submit and SpeculativeDecoder.generate carry the
    same guard)."""
    if prompt_len + steps > cfg.max_seq:
        raise ValueError(
            f"prompt {prompt_len} + steps {steps} exceeds the cache "
            f"({cfg.max_seq}); raise max_seq or shrink the ask")


def generate(cfg: LlamaConfig, params: Params, prompt: jnp.ndarray,
             steps: int, mesh: Optional[Mesh] = None) -> jnp.ndarray:
    """Greedy generation: parallel prefill, then scan decode steps."""
    b, s = prompt.shape
    _check_capacity(cfg, s, steps)
    cache = init_kv_cache(cfg, b, cfg.max_seq)
    # hoisted once: inside the scan it would be re-materialized per body
    rope = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
    logits, cache = prefill(cfg, params, cache, prompt, mesh, rope=rope)

    def step(carry, i):
        cache, logits = carry
        tok = jnp.argmax(logits, axis=-1).astype(prompt.dtype)
        logits, cache = decode_step(cfg, params, cache, s + i, tok, mesh,
                                    rope=rope)
        return (cache, logits), tok

    (_, _), toks = lax.scan(step, (cache, logits), jnp.arange(steps))
    return jnp.swapaxes(toks, 0, 1)                        # [B, steps]


def _select(sampler, key, logits: jnp.ndarray, dtype) -> jnp.ndarray:
    """Next token from logits: the sampler (ops/sampling.py) when given,
    else greedy argmax."""
    if sampler is None:
        return jnp.argmax(logits, axis=-1).astype(dtype)
    return sampler(key, logits).astype(dtype)


def decode_chunk(cfg: LlamaConfig, params: Params, cache: Params,
                 pos: jnp.ndarray, token: jnp.ndarray, steps: int,
                 mesh: Optional[Mesh] = None,
                 rope: Optional[jnp.ndarray] = None,
                 sampler=None, key: Optional[jax.Array] = None
                 ) -> Tuple[jnp.ndarray, Params]:
    """``steps`` greedy decode steps in ONE executable.

    Consumes ``token`` [B] at position ``pos`` and returns
    (toks [B, steps], cache): the argmax continuation. The middle ground
    between :func:`decode_step` (one dispatch per token — dispatch
    latency dominates small-model decode; measured 2.7 ms/token vs
    ~0.8 ms of chip time at 400m batch 1 through a tunneled backend) and
    :func:`generate` (one program for prefill + all steps — best
    dispatch amortization, pathological compile through remote compile
    helpers). The scan body compiles once regardless of ``steps``, so
    the compile cost is one decode_step's; dispatch cost is /steps.
    """
    toks, _, cache = decode_chunk_logits(cfg, params, cache, pos, token,
                                         steps, mesh, rope=rope,
                                         sampler=sampler, key=key)
    # the unused per-step logits stack is dead code XLA eliminates
    # under the caller's jit — ONE scan body serves both entry points
    return toks, cache                                     # [B, steps]


def decode_chunk_logits(cfg: LlamaConfig, params: Params, cache: Params,
                        pos: jnp.ndarray, token: jnp.ndarray, steps: int,
                        mesh: Optional[Mesh] = None,
                        rope: Optional[jnp.ndarray] = None,
                        sampler=None, key: Optional[jax.Array] = None
                        ) -> Tuple[jnp.ndarray, jnp.ndarray, Params]:
    """:func:`decode_chunk` that ALSO returns every step's logits
    [B, steps, V] — the draft side of sampled speculative decoding needs
    q_i(x_i) for the rejection test, not just the sampled tokens."""
    if rope is None:
        rope = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
    if key is None:
        key = jax.random.key(0)

    def step(carry, i):
        cache, tok, k = carry
        logits, cache = decode_step(cfg, params, cache, pos + i, tok,
                                    mesh, rope=rope)
        k, sub = jax.random.split(k)
        nxt = _select(sampler, sub, logits, tok.dtype)
        return (cache, nxt, k), (nxt, logits)

    (cache, _, _), (toks, logits) = lax.scan(step, (cache, token, key),
                                             jnp.arange(steps))
    return (jnp.swapaxes(toks, 0, 1),
            jnp.swapaxes(logits, 0, 1), cache)     # [B,steps],[B,steps,V]


def truncate_layers(cfg: LlamaConfig, params: Params, n_layers: int
                    ) -> Tuple[LlamaConfig, Params]:
    """A layer-skip draft: the target's FIRST ``n_layers`` decoder layers
    with the embed/final-norm/lm_head shared (self-speculation a la
    layer-skip / draft-&-verify). Zero extra weights to store — the
    stacked [L, ...] layout makes the cut a view. Works on quantized
    trees. (On an UNTRAINED target the truncation agrees near-chance;
    real acceptance needs a trained/distilled stack — the int8
    self-draft in tools/bench_speculative.py is the measurable-here
    alternative.)"""
    if not 1 <= n_layers <= cfg.n_layers:
        raise ValueError(
            f"draft layers {n_layers} not in [1, {cfg.n_layers}]")
    dcfg = dataclasses.replace(cfg, n_layers=n_layers)

    def cut(x):
        if isinstance(x, QTensor):
            return QTensor(x.q[:n_layers], x.s[:n_layers])
        return x[:n_layers]

    layers = jax.tree.map(cut, params["layers"],
                          is_leaf=lambda x: isinstance(x, QTensor))
    return dcfg, {**params, "layers": layers}


_STEPWISE_CACHE: dict = {}


def _stepwise_executables(cfg: LlamaConfig, mesh: Optional[Mesh]):
    """Jitted prefill/decode-step callables, cached per (cfg, mesh) so
    repeat ``generate_stepwise`` calls re-trace and re-compile nothing
    (jax.jit caches per wrapper object — a fresh lambda per call would
    silently recompile every time)."""
    key = (cfg, mesh)
    hit = _STEPWISE_CACHE.get(key)
    if hit is None:
        rope = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
        hit = (
            jax.jit(lambda p, c, pr: prefill(cfg, p, c, pr, mesh,
                                             rope=rope)),
            jax.jit(lambda p, c, pos, tok: decode_step(cfg, p, c, pos,
                                                       tok, mesh,
                                                       rope=rope)),
        )
        _STEPWISE_CACHE[key] = hit
    return hit


def generate_stepwise(cfg: LlamaConfig, params: Params,
                      prompt: jnp.ndarray, steps: int,
                      mesh: Optional[Mesh] = None) -> jnp.ndarray:
    """Greedy generation compiling only ``prefill`` + ONE ``decode_step``
    executable, driven by a host loop.

    Same outputs as :func:`generate`, different compile/dispatch trade:
    the fused scan program amortizes dispatch but its nested-scan graph
    takes minutes to compile at 400m+ through tunneled PJRT backends
    (docs/performance.md); this variant compiles in seconds — decode at
    real model sizes is HBM-bound streaming the weights every token, so
    per-step dispatch overhead is hidden at 400m+ anyway.
    """
    b, s = prompt.shape
    _check_capacity(cfg, s, steps)
    cache = init_kv_cache(cfg, b, cfg.max_seq)
    prefill_x, step_x = _stepwise_executables(cfg, mesh)
    logits, cache = prefill_x(params, cache, prompt)
    toks = []
    for i in range(steps):
        tok = jnp.argmax(logits, axis=-1).astype(prompt.dtype)
        logits, cache = step_x(params, cache, jnp.int32(s + i), tok)
        toks.append(tok)
    if not toks:
        return jnp.zeros((b, 0), prompt.dtype)
    return jnp.stack(toks, axis=1)                         # [B, steps]


_CHUNKED_CACHE: dict = {}


def generate_chunked(cfg: LlamaConfig, params: Params,
                     prompt: jnp.ndarray, steps: int, chunk: int = 16,
                     mesh: Optional[Mesh] = None, sampler=None,
                     key: Optional[jax.Array] = None) -> jnp.ndarray:
    """Generation via :func:`decode_chunk`: prefill + one K-step
    executable driven by a host loop every K tokens.

    Greedy by default; pass ``sampler`` (``ops.sampling.make_sampler``,
    built ONCE — the compiled executable is cached per sampler object)
    and ``key`` for stochastic decoding. Emits the same tokens as
    :func:`generate_stepwise` (first token from the prefill logits, then
    chunks of the continuation), with 1 + ceil((steps-1)/chunk)
    dispatches instead of 1 + steps. ``steps`` is rounded up to whole
    chunks internally and trimmed, so one executable serves every
    requested length.
    (Chunk-rounding overshoot past ``steps`` is safe even at the capacity
    boundary: overshoot writes clamp onto the last row strictly AFTER
    every kept token was computed, and their outputs are trimmed.)
    """
    b, s = prompt.shape
    _check_capacity(cfg, s, steps)
    cache = init_kv_cache(cfg, b, cfg.max_seq)
    if key is None:
        key = jax.random.key(0)
    # prefill depends on neither chunk nor sampler: share the stepwise
    # cache's executable so varying chunk sizes / fresh sampler objects
    # never recompile it (at 8b a prefill compile is minutes on tunnels)
    prefill_x = _stepwise_executables(cfg, mesh)[0]
    cache_key = (cfg, mesh, chunk, sampler)
    chunk_x = _CHUNKED_CACHE.get(cache_key)
    if chunk_x is None:
        rope = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
        chunk_x = jax.jit(lambda p, c, pos, tok, k: decode_chunk(
            cfg, p, c, pos, tok, chunk, mesh, rope=rope,
            sampler=sampler, key=k))
        _CHUNKED_CACHE[cache_key] = chunk_x
    logits, cache = prefill_x(params, cache, prompt)
    key, sub = jax.random.split(key)
    tok = _select(sampler, sub, logits, prompt.dtype)
    out = [tok[:, None]]
    emitted = 1
    pos = s
    while emitted < steps:
        key, sub = jax.random.split(key)
        toks, cache = chunk_x(params, cache, jnp.int32(pos), tok, sub)
        out.append(toks)
        tok = toks[:, -1]
        emitted += chunk
        pos += chunk
    return jnp.concatenate(out, axis=1)[:, :steps]         # [B, steps]
