"""Zero-drop elasticity: live migration of in-flight decode streams.

Scale events — an ``Autoscaler`` shrink, a ``Preemptor`` reclaim — used
to be the one place the serving stack still dropped work: every live
decode stream on the victim replica either died or re-prefilled from
scratch. PR 7's span channel already proved the hard half (KV pages
move between replicas mid-flight under a digest-checked wire format
with transactional adoption); this module generalizes that channel
from prefill→decode handoff to decode→decode **drain**:

* :func:`pack_decstate` / :func:`unpack_decstate` — the ``DECSTATE``
  wire frame: the KVSPAN layout (``MAGIC | header_len | header JSON |
  raw pages``) extended with the sampler/stream state a destination
  needs to resume mid-stream token-exact — generated tokens, remaining
  budget, engine RNG key, QoS/tenant identity, trace context. Same
  verification discipline: magic, version, blake2s body digest, and
  the prompt's prefix-page hashes are all checked BEFORE the decode
  tier goes near its ledger; any mismatch raises
  :class:`DecStateError` holding zero destination pages.
* :class:`MigrationManager` — the drain protocol. On a scale-down or
  preemption decision it walks the victim's live streams; per stream
  it freezes at a step boundary (``PagedServer.export_stream`` — a
  pure read), picks surviving destinations in router-ring preference
  order (the same ``route_key`` affinity the fleet router uses, so
  the stream usually lands where its prefix pages are already
  cached), round-trips the state through the DECSTATE frame, and
  adopts transactionally (``import_stream``: reserve → install → join
  the decode batch). Only after the adoption commits does the victim
  release its copy (``release_stream``); any failure — frame
  verification, capacity, a dead peer — unwinds the destination and
  leaves the victim resuming untouched. Streams still mid-prefill
  have no decode state to ship: their prompt re-submits on the
  destination (still zero-drop — nothing was emitted yet).
* :class:`MigrateReceiver` — ``POST /v1/migrate`` over one engine:
  the destination's front door for cross-process drains, with the
  same lazy opt-in TLS hook as ``disagg.PrefillWorker`` (the env
  contract + optional ``cryptography`` package), so migrated KV moves
  under the same transport guarantees as shipped spans.

``scheduler/elastic.py`` triggers the drain (drain-before-reclaim on
both the autoscaler and the preemptor grace window);
``models/router.py`` learns the resulting "migrated-to" redirects so
relays follow the stream; the chaos tier injects ``migrate_mid_stream``
faults and audits a token-exact-continuation invariant over the
migration receipts. See docs/fault-tolerance.md "Live migration".
"""

from __future__ import annotations

import hashlib
import json
import struct
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..tracing import TRACE_HEADER, Tracer, parse_header
from .disagg import (PageShipError, _flatten_payload, _transport_urlopen,
                     _wire_dtype)
from .paging import page_hashes

_DEC_MAGIC = b"DECSTAT1"
_DEC_VERSION = 1


class DecStateError(PageShipError):
    """A DECSTATE frame that must not be adopted: framing, digest,
    version, or prefix-hash verification failed."""


# --------------------------------------------------------------- wire format


def pack_decstate(state: Dict[str, Any], tenant: Optional[str] = None,
                  qos: Optional[str] = None,
                  trace: Optional[str] = None,
                  request_id: Optional[Any] = None) -> bytes:
    """Frame a ``PagedServer.export_stream()`` result for the wire:
    ``MAGIC | header_len | header JSON | raw page bytes``. The header
    carries everything :func:`unpack_decstate` verifies plus the full
    stream identity — prompt, generated tokens, budget, the engine RNG
    key (hex — it is a few dozen bytes), tenant/QoS labels, and the
    trace context header — so the destination resumes the stream as
    the SAME request, not a lookalike."""
    arrays = _flatten_payload(state["payload"])
    body = b"".join(a.tobytes() for _, a in arrays)
    rng = state.get("rng_key")
    rng_meta = None
    if rng is not None:
        rng = np.asarray(rng)
        rng_meta = {"shape": list(rng.shape), "dtype": rng.dtype.name,
                    "hex": rng.tobytes().hex()}
    meta = {
        "version": _DEC_VERSION,
        "prompt": [int(t) for t in state["prompt"]],
        "tokens": [int(t) for t in state["tokens"]],
        "max_new": int(state["max_new"]),
        "page_size": int(state["page_size"]),
        "kv_quant": bool(state["kv_quant"]),
        "rng_key": rng_meta,
        "tenant": tenant,
        "qos": qos,
        "trace": trace,
        "request_id": (request_id if request_id is None
                       or isinstance(request_id, (str, int))
                       else str(request_id)),
        "page_hashes": page_hashes(state["prompt"], state["page_size"]),
        "body_digest": hashlib.blake2s(body).hexdigest(),
        "arrays": [{"key": k, "shape": list(a.shape),
                    "dtype": a.dtype.name} for k, a in arrays],
    }
    header = json.dumps(meta).encode()
    # the header carries the stream identity (tokens, budget, RNG key)
    # that no page hash covers — it gets its own digest in the frame so
    # a bit flip anywhere dies in verification, not in a resumed stream
    hdig = hashlib.blake2s(header, digest_size=8).digest()
    return _DEC_MAGIC + struct.pack("<I", len(header)) + hdig + header + body


def unpack_decstate(data: bytes) -> Dict[str, Any]:
    """Parse + VERIFY a DECSTATE frame: magic, version, body digest,
    prefix hashes against the shipped prompt, and per-array bounds.
    Raises :class:`DecStateError` on any mismatch — a truncated,
    bit-flipped, or version-skewed transfer dies here, before the
    destination reserves anything. Returns the dict
    ``PagedServer.import_stream`` consumes, plus the identity fields
    (``tenant``/``qos``/``trace``)."""
    if not data.startswith(_DEC_MAGIC):
        raise DecStateError("bad magic: not a DECSTATE frame")
    off = len(_DEC_MAGIC)
    if len(data) < off + 12:
        raise DecStateError("truncated frame: no header length")
    (hlen,) = struct.unpack_from("<I", data, off)
    off += 4
    hdig, off = data[off:off + 8], off + 8
    header = data[off:off + hlen]
    if len(header) < hlen:
        raise DecStateError("truncated frame: short header")
    if hashlib.blake2s(header, digest_size=8).digest() != hdig:
        raise DecStateError("header digest mismatch: corrupt transfer")
    try:
        meta = json.loads(header)
    except ValueError as e:
        raise DecStateError(f"bad header: {e}") from None
    off += hlen
    if not isinstance(meta, dict):
        raise DecStateError("bad header: not an object")
    if meta.get("version") != _DEC_VERSION:
        raise DecStateError(f"DECSTATE version {meta.get('version')} != "
                            f"{_DEC_VERSION}")
    body = data[off:]
    if hashlib.blake2s(body).hexdigest() != meta["body_digest"]:
        raise DecStateError("body digest mismatch: corrupt transfer")
    prompt = [int(t) for t in meta["prompt"]]
    tokens = [int(t) for t in meta["tokens"]]
    if not tokens:
        raise DecStateError("DECSTATE frame carries no generated tokens")
    if page_hashes(prompt, meta["page_size"]) != meta["page_hashes"]:
        raise DecStateError("prefix-hash mismatch: prompt and pages "
                            "disagree")
    arrays: Dict[str, np.ndarray] = {}
    pos = 0
    for spec in meta["arrays"]:
        try:
            dt = _wire_dtype(spec["dtype"])
        except (TypeError, AttributeError):
            raise DecStateError(
                f"unknown wire dtype {spec['dtype']!r} at "
                f"{spec['key']!r}") from None
        shape = tuple(spec["shape"])
        nbytes = dt.itemsize * int(np.prod(shape))
        if pos + nbytes > len(body):
            raise DecStateError(f"truncated body at {spec['key']!r}")
        arrays[spec["key"]] = np.frombuffer(
            body, dt, count=int(np.prod(shape)), offset=pos).reshape(shape)
        pos += nbytes
    payload: Dict[str, Any] = {}
    for side in ("k", "v"):
        if side in arrays:
            payload[side] = arrays[side]
        elif f"{side}.q" in arrays and f"{side}.s" in arrays:
            payload[side] = {"q": arrays[f"{side}.q"],
                             "s": arrays[f"{side}.s"]}
        else:
            raise DecStateError(f"frame missing the {side!r} pages")
    rng = None
    rm = meta.get("rng_key")
    if rm is not None:
        try:
            rng = np.frombuffer(bytes.fromhex(rm["hex"]),
                                _wire_dtype(rm["dtype"])).reshape(
                                    tuple(rm["shape"]))
        except (TypeError, ValueError, AttributeError, KeyError):
            raise DecStateError("mangled rng_key in header") from None
    return {"version": meta["version"], "prompt": prompt,
            "tokens": tokens, "max_new": meta["max_new"],
            "page_size": meta["page_size"],
            "kv_quant": meta["kv_quant"], "rng_key": rng,
            "tenant": meta.get("tenant"), "qos": meta.get("qos"),
            "trace": meta.get("trace"),
            "request_id": meta.get("request_id"), "payload": payload}


# ------------------------------------------------------------ the wire hop


def ship_stream(peer: str, frame: bytes, timeout_s: float = 30.0,
                trace: Optional[str] = None) -> Dict[str, Any]:
    """POST one DECSTATE frame to ``peer``'s :class:`MigrateReceiver`.
    Moves through ``security/transport.py`` when importable (the same
    opt-in TLS contract as KV-span shipping). Raises
    :class:`DecStateError` on transport failure, a peer 503 (capacity
    back-pressure), or a rejected frame."""
    headers = {"Content-Type": "application/octet-stream"}
    if trace:
        headers[TRACE_HEADER] = trace
    req = urllib.request.Request(peer.rstrip("/") + "/v1/migrate",
                                 data=frame, headers=headers)
    try:
        with _transport_urlopen(req, timeout=timeout_s) as r:
            body = json.loads(r.read())
    except PageShipError:
        raise
    except Exception as e:
        raise DecStateError(f"peer {peer}: {e}") from None
    if not body.get("ok"):
        raise DecStateError(f"peer {peer}: {body.get('error', 'rejected')}")
    return body


class RemoteReplica:
    """A destination behind HTTP: presents the in-process importer
    surface (``import_stream``/``submit``) over a peer's
    :class:`MigrateReceiver`, so :class:`MigrationManager` drains to a
    remote replica through the exact code path it uses locally. A
    capacity 503 maps to None (the manager tries the next candidate),
    every other failure raises."""

    def __init__(self, peer: str, timeout_s: float = 30.0):
        self.peer = peer.rstrip("/")
        self.timeout_s = timeout_s

    def import_stream(self, state: Dict[str, Any],
                      request_id: Any = None) -> Optional[int]:
        trace = getattr(request_id, "trace", None)
        frame = pack_decstate(
            state, tenant=getattr(request_id, "tenant", None),
            qos=getattr(request_id, "qos", None),
            trace=trace.header() if hasattr(trace, "header") else None,
            request_id=request_id)
        try:
            body = ship_stream(self.peer, frame, timeout_s=self.timeout_s)
        except DecStateError as e:
            if "503" in str(e) or "exhausted" in str(e):
                return None
            raise
        return int(body.get("slot", 0))

    def submit(self, prompt: List[int], max_new: int = 32,
               request_id: Any = None) -> Optional[int]:
        # a still-prefilling stream has no decode state to ship: the
        # remote drain path has no generic /v1/generate here, so the
        # manager re-submits through the front door instead — signal
        # "not handled" and let the caller fall back
        return None


# --------------------------------------------------------------- the manager


class MigrationManager:
    """The decode→decode drain protocol, one victim replica at a time.

    ``drain(victim, dests)`` walks every live stream on the victim and
    for each one: freeze at a step boundary (``export_stream`` — pure
    read), pick destinations in ring-preference order over the
    survivors (prefix affinity — the stream lands where its prompt
    pages are likely cached), round-trip through the DECSTATE frame
    (so the in-process path exercises the same verification the wire
    does), transactionally adopt (``import_stream``), and only then
    release the victim's copy. Any failure leaves the victim stream
    untouched and tries the next candidate; a stream no destination
    accepts stays on the victim (``failed`` in the receipt) rather
    than dying. Streams still prefilling re-submit their prompt.

    ``ring`` is any object with ``preference(key) -> [name, ...]``
    (``router.HashRing``); without one, destinations are tried in the
    order given. ``on_redirect(src, dst)`` fires per migrated stream —
    the router wires ``note_migration`` here so relays follow.
    """

    def __init__(self, enable: bool = True, timeout_s: float = 30.0,
                 max_inflight: int = 2, ring=None, page_size: int = 64,
                 affinity_pages: int = 1, tracer: Optional[Tracer] = None,
                 on_redirect=None):
        if timeout_s <= 0:
            raise ValueError(f"timeout_s must be > 0, got {timeout_s}")
        if max_inflight < 1:
            raise ValueError(f"max_inflight must be >= 1, "
                             f"got {max_inflight}")
        self.enable = enable
        self.timeout_s = timeout_s
        self.max_inflight = max_inflight
        self.ring = ring
        self.page_size = page_size
        self.affinity_pages = affinity_pages
        self.tracer = tracer
        self.on_redirect = on_redirect
        self._lock = threading.Lock()
        self.started = 0
        self.migrated = 0
        self.resubmitted = 0
        self.failed = 0
        self.pause_ms: List[float] = []
        # (victim, dest, request_id repr, generated tokens) newest last
        self.moves: List[Tuple[str, str, str, int]] = []

    # ------------------------------------------------------------ planning

    def destination_order(self, prompt: Sequence[int],
                          names: Sequence[str]) -> List[str]:
        """Surviving destinations in ring-preference order for this
        stream's affinity key; survivors the ring does not know append
        in given order (never silently unreachable)."""
        names = list(names)
        if self.ring is None or not names:
            return names
        from .router import route_key
        key = route_key(prompt, self.page_size, self.affinity_pages)
        pref = [n for n in self.ring.preference(key) if n in names]
        return pref + [n for n in names if n not in pref]

    # -------------------------------------------------------------- drain

    def migrate_stream(self, victim, slot: int, victim_name: str,
                       dests: Sequence[Tuple[str, Any]]) -> Optional[str]:
        """Move ONE stream; returns the destination name, or None when
        every candidate refused (victim keeps the stream)."""
        r = victim.requests[slot]
        if r is None:
            return None
        t0 = time.perf_counter()
        with self._lock:
            self.started += 1
        state = victim.export_stream(slot)
        rid = r.request_id
        trace = getattr(rid, "trace", None)
        if state is None:
            # still prefilling: nothing emitted yet — re-submitting the
            # prompt on a survivor is already token-exact
            prompt = victim._prompts[slot]
            for name, dest in dests:
                if time.perf_counter() - t0 > self.timeout_s:
                    break
                try:
                    s = dest.submit(list(prompt), r.budget,
                                    request_id=rid)
                except Exception:
                    continue
                if s is None:
                    continue
                victim.release_stream(slot)
                self._done(t0, victim_name, name, rid, 0, resubmit=True)
                return name
            with self._lock:
                self.failed += 1
            return None
        frame = pack_decstate(
            state, tenant=getattr(rid, "tenant", None),
            qos=getattr(rid, "qos", None),
            trace=trace.header() if hasattr(trace, "header") else None)
        for name, dest in dests:
            if time.perf_counter() - t0 > self.timeout_s:
                break
            try:
                # the in-process hop round-trips the REAL frame so the
                # local path exercises exactly the wire's verification
                new_slot = dest.import_stream(unpack_decstate(frame),
                                              request_id=rid)
            except Exception:
                continue                       # dest unwound; try next
            if new_slot is None:
                continue                       # capacity; try next
            victim.release_stream(slot)
            self._done(t0, victim_name, name, rid, len(state["tokens"]))
            return name
        with self._lock:
            self.failed += 1
        return None

    def _done(self, t0: float, src: str, dst: str, rid: Any,
              generated: int, resubmit: bool = False) -> None:
        pause = (time.perf_counter() - t0) * 1e3
        with self._lock:
            if resubmit:
                self.resubmitted += 1
            else:
                self.migrated += 1
            self.pause_ms.append(pause)
            self.moves.append((src, dst, repr(rid), generated))
        if self.on_redirect is not None:
            self.on_redirect(src, dst)
        if self.tracer is not None:
            ctx = getattr(rid, "trace", None)
            if ctx is not None:
                self.tracer.record("migrate.stream", t0,
                                   time.perf_counter(), parent=ctx,
                                   src=src, dst=dst, generated=generated,
                                   resubmit=resubmit)

    def drain(self, victim, victim_name: str,
              dests: Sequence[Tuple[str, Any]]) -> Dict[str, Any]:
        """Drain EVERY live stream off ``victim`` onto the surviving
        ``dests`` (``[(name, engine_or_RemoteReplica), ...]``); the
        per-stream candidate order is ring preference over the given
        names. Returns the drain receipt. With ``enable=False`` this is
        a no-op returning a zero receipt — the scale event proceeds as
        before (and drops whatever it drops); the A/B the bench
        measures."""
        receipt = {"victim": victim_name, "live": 0, "migrated": 0,
                   "resubmitted": 0, "failed": 0}
        if not self.enable:
            return receipt
        by_name = dict(dests)
        for slot in range(victim.slots):
            r = victim.requests[slot]
            if r is None:
                continue
            receipt["live"] += 1
            prompt = victim._prompts[slot] or []
            order = self.destination_order(prompt, [n for n, _ in dests])
            ranked = [(n, by_name[n]) for n in order]
            moved = self.migrate_stream(victim, slot, victim_name, ranked)
            if moved is None:
                receipt["failed"] += 1
            elif victim.requests[slot] is None and r.tokens:
                receipt["migrated"] += 1
            else:
                receipt["resubmitted"] += 1
        return receipt

    # -------------------------------------------------------------- stats

    def stats(self) -> Dict[str, Any]:
        from ..utils.stats import percentiles
        with self._lock:
            return {
                "enable": self.enable,
                "timeout_s": self.timeout_s,
                "max_inflight": self.max_inflight,
                "started": self.started,
                "migrated": self.migrated,
                "resubmitted": self.resubmitted,
                "failed": self.failed,
                "pause_ms": percentiles(list(self.pause_ms)),
                "moves": list(self.moves[-32:]),
            }


# ------------------------------------------------------------ the receiver


class MigrateReceiver:
    """The destination's front door for cross-process drains: one
    engine behind ``POST /v1/migrate`` taking a raw DECSTATE frame.
    Exactly ONE request runs the engine at a time (the donation
    contract — same lock discipline as ``disagg.PrefillWorker``).
    Capacity exhaustion is a 503 (the manager tries the next
    survivor); a frame that fails verification or engine validation is
    a 400 holding zero pages. ``start()`` applies the same lazy opt-in
    TLS contract as every other control-plane server: wrapped when the
    ``TPU_TLS_*`` env asks for it AND the optional ``cryptography``
    package is present."""

    def __init__(self, engine, port: int = 0, host: str = "0.0.0.0",
                 trace_store=None):
        self.engine = engine
        self._lock = threading.Lock()
        self.tracer = Tracer("migrate", trace_store)
        receiver = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def _json(self, code: int, payload: dict) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/v1/healthz":
                    st = receiver.engine.page_stats()
                    self._json(200, {"ok": True, "role": "migrate",
                                     "pages_free": st["pages_free"],
                                     "migrated_in": st["migrated_in"],
                                     "migrated_out": st["migrated_out"]})
                else:
                    self._json(404, {"error": f"no route {self.path}"})

            def do_POST(self):
                if self.path != "/v1/migrate":
                    self._json(404, {"error": f"no route {self.path}"})
                    return
                n = int(self.headers.get("Content-Length", 0))
                data = self.rfile.read(n)
                ctx = parse_header(self.headers.get(TRACE_HEADER))
                t0 = time.perf_counter()
                try:
                    state = unpack_decstate(data)
                except DecStateError as e:
                    self._json(400, {"error": str(e)})
                    return
                try:
                    with receiver._lock:
                        slot = receiver.engine.import_stream(
                            state, request_id=state.get("request_id"))
                except ValueError as e:
                    self._json(400, {"error": str(e)})
                    return
                except Exception as e:
                    self._json(500, {"error": f"import failed: {e}"})
                    return
                if slot is None:
                    self._json(503, {"error": "pages exhausted"})
                    return
                if ctx is not None:
                    receiver.tracer.record(
                        "migrate.import", t0, time.perf_counter(),
                        parent=ctx, generated=len(state["tokens"]))
                self._json(200, {"ok": True, "slot": int(slot),
                                 "generated": len(state["tokens"])})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "MigrateReceiver":
        try:
            # the PrefillWorker lazy TLS hook, followed through onto the
            # migration path (ROADMAP 5c)
            from dcos_commons_tpu.security.transport import (
                server_tls_from_env)
            creds = server_tls_from_env()
            if creds is not None:
                from dcos_commons_tpu.security.transport import wrap_server
                wrap_server(self._httpd, creds)
        except ImportError:
            pass
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="migrate-http")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=10)


# ----------------------------------------------------------------- env knobs


def manager_from_env(env: Optional[dict] = None, **kw) -> MigrationManager:
    """Build a :class:`MigrationManager` from the ``MIGRATE_*`` env
    contract (docs/yaml-reference.md): ``MIGRATE_ENABLE`` (default on),
    ``MIGRATE_TIMEOUT_S`` (per-stream freeze→resume budget),
    ``MIGRATE_MAX_INFLIGHT`` (concurrent drains)."""
    import os
    e = os.environ if env is None else env
    enable = (e.get("MIGRATE_ENABLE") or "1").strip().lower() not in (
        "0", "false", "no", "off")
    timeout = float(e.get("MIGRATE_TIMEOUT_S") or 30.0)
    inflight = int(float(e.get("MIGRATE_MAX_INFLIGHT") or 2))
    return MigrationManager(enable=enable, timeout_s=timeout,
                            max_inflight=inflight, **kw)
