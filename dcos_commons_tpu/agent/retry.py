"""Bounded retry + jittered backoff around the agent transport's verbs.

Reference: the Mesos driver retried nothing — ``driver.acceptOffers`` either
reached the master or the framework got a new offer cycle. Our transport has
no offer market to re-drive a failed instruction, so the scheduler side
hardens the launch/kill/destroy paths itself: a transient enqueue failure
(replicated-state hiccup behind ``RemoteCluster``, a transport raising on a
momentarily unreachable backend) is retried a bounded number of times with
full jitter, capped by a per-call deadline. A call that exhausts the budget
re-raises the last error — the caller's crash-don't-corrupt handling
(``runner.CycleDriver``) still applies to genuine outages.

``FakeCluster`` is never wrapped (tests talk to it directly), and wrapping
any always-succeeding client is behavior-identical: the first attempt is
invoked exactly as before, with zero added latency.
"""

from __future__ import annotations

import logging
import random
import time
from typing import Callable, Optional, Sequence

from .client import StatusCallback
from .inventory import AgentInfo

log = logging.getLogger(__name__)


class RetryingAgentClient:
    """Wraps any AgentClient; retries the *instruction* verbs only.

    Read verbs (``agents``, ``running_task_ids``) pass straight through —
    a stale read is re-taken next cycle anyway, and retrying them would
    just add tail latency to every cycle. Unknown attributes delegate to
    the inner client, so transport-specific surface (``RemoteCluster``'s
    ``register``/``poll``/``async_status_ok``) keeps working through the
    wrapper.
    """

    def __init__(self, inner, max_attempts: int = 3,
                 base_delay_s: float = 0.05, max_delay_s: float = 2.0,
                 call_timeout_s: float = 10.0,
                 rng: Optional[random.Random] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 clock: Callable[[], float] = time.monotonic):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self._inner = inner
        self._max_attempts = max_attempts
        self._base_delay_s = base_delay_s
        self._max_delay_s = max_delay_s
        self._call_timeout_s = call_timeout_s
        self._rng = rng or random.Random()
        self._sleep = sleep
        self._clock = clock

    # -- retry core --------------------------------------------------------

    def _retry(self, what: str, fn: Callable[[], None]) -> None:
        deadline = self._clock() + self._call_timeout_s
        attempt = 0
        while True:
            attempt += 1
            try:
                fn()
                return
            except Exception as e:
                if attempt >= self._max_attempts:
                    raise
                # full jitter (0..cap]: decorrelates a fleet of schedulers
                # hammering a recovering backend; cap doubles per attempt
                cap = min(self._max_delay_s,
                          self._base_delay_s * (2 ** (attempt - 1)))
                delay = self._rng.uniform(0, cap) or cap
                if self._clock() + delay > deadline:
                    # the per-call deadline beats the attempt budget: a
                    # verb must never stall the cycle longer than promised
                    raise
                log.warning("%s failed (attempt %d/%d), retrying in "
                            "%.3fs: %s", what, attempt, self._max_attempts,
                            delay, e)
                self._sleep(delay)

    # -- AgentClient -------------------------------------------------------

    def agents(self) -> Sequence[AgentInfo]:
        return self._inner.agents()

    def launch(self, plan) -> None:
        # idempotent to retry: the WAL is already written and the agent
        # executes a launch command once per task id (a duplicate enqueue
        # surfaces as a dup status, which ingestion dedupes)
        self._retry(f"launch on {plan.agent.agent_id}",
                    lambda: self._inner.launch(plan))

    def kill(self, agent_id: str, task_id: str,
             grace_period_s: float = 0.0) -> None:
        self._retry(f"kill {task_id}",
                    lambda: self._inner.kill(agent_id, task_id,
                                             grace_period_s))

    def destroy_volumes(self, agent_id: str, pod_instance_name: str) -> None:
        self._retry(f"destroy_volumes {pod_instance_name}",
                    lambda: self._inner.destroy_volumes(agent_id,
                                                        pod_instance_name))

    def running_task_ids(self, agent_id: str) -> Sequence[str]:
        return self._inner.running_task_ids(agent_id)

    def set_status_callback(self, callback: StatusCallback) -> None:
        self._inner.set_status_callback(callback)

    def __getattr__(self, name: str):
        return getattr(self._inner, name)
