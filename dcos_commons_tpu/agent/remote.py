"""RemoteCluster — the scheduler side of the agent transport.

Replaces the reference's Mesos driver boundary (``framework/
SchedulerDriverFactory.java:27``, C++ ``libmesos`` via JNI): per-host agent
daemons (the C++ ``tpu-agent`` under ``native/agent``) register and poll the
scheduler over HTTP; the scheduler queues launch/kill commands per agent and
ingests status updates from the poll body. Agent-initiated polling keeps the
daemon dependency-free and NAT-friendly; the poll interval bounds command
latency the way offer-cycle cadence did in Mesos.

Liveness: an agent missing ``expiry_s`` of polls is dropped from
:meth:`agents`, which makes its tasks eligible for LOST synthesis in
``ServiceScheduler.reconcile`` — the Mesos agent-failover analogue.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Dict, List, Optional, Sequence

from .client import StatusCallback
from .inventory import AgentInfo, PortRange, TpuInventory
from ..matching.evaluator import LaunchPlan
from ..state.tasks import TaskState, TaskStatus

log = logging.getLogger(__name__)


def _now() -> float:
    return time.time()


class RemoteCluster:
    """AgentClient implementation backed by polling remote agents."""

    # a freshly-(re)started scheduler sees zero agents until they poll and
    # re-register; without this grace every task would be declared LOST and
    # relaunched on scheduler restart (ServiceScheduler.reconcile)
    default_agent_grace_s = 30.0

    # statuses arrive on HTTP worker threads: the scheduler may persist
    # them here but defer the plan feed to its cycle thread, so a poll
    # never queues behind a whole-fleet match pass (core.py
    # handle_status_nowait; p99 tail in docs/performance.md)
    async_status_ok = True

    def __init__(self, expiry_s: float = 30.0, poll_interval_s: float = 1.0):
        self._lock = threading.Lock()
        self._expiry_s = expiry_s
        self.poll_interval_s = poll_interval_s
        self._agents: Dict[str, AgentInfo] = {}
        self._last_seen: Dict[str, float] = {}
        self._queues: Dict[str, List[dict]] = {}
        self._running: Dict[str, List[str]] = {}
        # agent_id -> live chip count, present only while it disagrees with
        # the registered inventory (chip fell off the bus / probe error)
        self._tpu_chips_now: Dict[str, int] = {}
        self._callback: Optional[StatusCallback] = None

    # -- AgentClient interface --------------------------------------------

    def agents(self) -> Sequence[AgentInfo]:
        with self._lock:
            cutoff = _now() - self._expiry_s
            out = []
            for aid, a in self._agents.items():
                if self._last_seen.get(aid, 0) < cutoff:
                    continue
                chips_now = self._tpu_chips_now.get(aid)
                if chips_now is not None:
                    a = dataclasses.replace(a, tpu=dataclasses.replace(
                        a.tpu, chips=chips_now, degraded=True))
                out.append(a)
            return out

    def launch(self, plan: LaunchPlan) -> None:
        command = {"type": "launch", "tasks": [
            {
                "task_name": l.task_name,
                "task_id": l.task_id,
                "cmd": l.cmd,
                "env": dict(l.env),
                "goal": l.goal,
                "config_templates": [
                    {"name": n, "dest": d, "template": t}
                    for n, d, t in l.config_templates],
                "health_check_cmd": l.health_check_cmd,
                "health_interval_s": l.health_interval_s,
                "health_grace_s": l.health_grace_s,
                "health_max_failures": l.health_max_failures,
                "health_timeout_s": l.health_timeout_s,
                "health_delay_s": l.health_delay_s,
                "kill_grace_s": l.kill_grace_s,
                "readiness_check_cmd": l.readiness_check_cmd,
                "readiness_interval_s": l.readiness_interval_s,
                "readiness_timeout_s": l.readiness_timeout_s,
                "uris": list(l.uris),
                "files": [{"dest": d, "content_b64": c} for d, c in l.files],
                "pod_instance": l.pod_instance,
                "volumes": list(l.volumes),
                "host_volumes": [list(hv) for hv in l.host_volumes],
                "rlimits": [{"name": n, "soft": s, "hard": h}
                            for n, s, h in l.rlimits],
                "seccomp_unconfined": l.seccomp_unconfined,
                "seccomp_profile": l.seccomp_profile,
                "ipc_mode": l.ipc_mode,
                "shm_size_mb": l.shm_size_mb,
            } for l in plan.launches]}
        with self._lock:
            self._queues.setdefault(plan.agent.agent_id, []).append(command)

    def kill(self, agent_id: str, task_id: str,
             grace_period_s: float = 0.0) -> None:
        with self._lock:
            self._queues.setdefault(agent_id, []).append(
                {"type": "kill", "task_id": task_id,
                 "grace_period_s": grace_period_s})

    def destroy_volumes(self, agent_id: str, pod_instance_name: str) -> None:
        with self._lock:
            self._queues.setdefault(agent_id, []).append(
                {"type": "destroy_volumes",
                 "pod_instance": pod_instance_name})

    def running_task_ids(self, agent_id: str) -> Sequence[str]:
        with self._lock:
            return list(self._running.get(agent_id, []))

    def set_status_callback(self, callback: StatusCallback) -> None:
        self._callback = callback

    # -- transport side (called by the HTTP routes) ------------------------

    def register(self, payload: dict) -> dict:
        """POST /v1/agents/register body -> AgentInfo."""
        tpu = payload.get("tpu") or {}
        coords = tpu.get("coords")
        info = AgentInfo(
            agent_id=payload["agent_id"],
            hostname=payload.get("hostname", payload["agent_id"]),
            cpus=float(payload.get("cpus", 0)),
            memory_mb=int(payload.get("memory_mb", 0)),
            disk_mb=int(payload.get("disk_mb", 0)),
            ports=tuple(PortRange(int(lo), int(hi))
                        for lo, hi in payload.get("ports", [[10000, 20000]])),
            tpu=TpuInventory(
                chips=int(tpu.get("chips", 0)),
                slice_id=tpu.get("slice_id"),
                topology=tpu.get("topology"),
                coords=tuple(coords) if coords else None,
                worker_index=tpu.get("worker_index"),
            ),
            attributes=dict(payload.get("attributes", {})),
            zone=payload.get("zone"),
            region=payload.get("region"),
            volume_profiles=tuple(payload.get("volume_profiles", ())),
            roles=tuple(payload.get("roles") or ("*",)),
        )
        with self._lock:
            self._agents[info.agent_id] = info
            self._last_seen[info.agent_id] = _now()
            self._queues.setdefault(info.agent_id, [])
            # fresh registration advertises fresh inventory: whatever the
            # agent reports now IS the truth, clear any stale health mark
            self._tpu_chips_now.pop(info.agent_id, None)
        return {"ok": True, "poll_interval_s": self.poll_interval_s}

    def poll(self, agent_id: str, payload: dict) -> dict:
        """POST /v1/agents/<id>/poll: heartbeat + statuses -> commands.

        Statuses are parsed and dispatched *before* the command queue is
        drained: a malformed status or a callback error must not lose
        launch/kill commands the scheduler already WAL'd.
        """
        with self._lock:
            if agent_id not in self._agents:
                # unknown/expired agent must re-register (it keeps its
                # queued statuses and resends them after registering)
                return {"ok": False, "reregister": True, "commands": []}
            self._last_seen[agent_id] = _now()
            self._running[agent_id] = list(payload.get("running_task_ids",
                                                       []))
            health = payload.get("tpu_health")
            if health is not None:
                # chip-level health (SURVEY.md §5): the agent re-probes
                # /dev/accel* every poll; losing chips vs the registered
                # inventory (or a probe error, chips < 0) degrades the host.
                # A later poll reporting the full count clears the mark
                # (driver reload) — agents() reflects whichever is current.
                registered = self._agents[agent_id].tpu.chips
                chips_now = int(health.get("chips", registered))
                if health.get("error") or chips_now < registered:
                    if self._tpu_chips_now.get(agent_id) != chips_now:
                        log.warning(
                            "agent %s TPU-degraded: %d/%d chips%s",
                            agent_id, max(chips_now, 0), registered,
                            f" ({health['error']})"
                            if health.get("error") else "")
                    self._tpu_chips_now[agent_id] = max(chips_now, 0)
                else:
                    if agent_id in self._tpu_chips_now:
                        log.warning("agent %s TPU health recovered "
                                    "(%d chips)", agent_id, chips_now)
                    self._tpu_chips_now.pop(agent_id, None)
        callback = self._callback
        for s in payload.get("statuses", []):
            try:
                status = TaskStatus(
                    task_id=s["task_id"],
                    state=TaskState(s["state"]),
                    message=s.get("message", ""),
                    timestamp=float(s.get("timestamp") or _now()),
                    readiness_passed=bool(s.get("readiness_passed", False)),
                    agent_id=agent_id,
                )
                if callback is not None:
                    callback(s["task_name"], status)
            except Exception:
                log.exception("dropping bad status from agent %s: %r",
                              agent_id, s)
        with self._lock:
            commands, self._queues[agent_id] = self._queues.get(agent_id,
                                                                []), []
        return {"ok": True, "commands": commands,
                "poll_interval_s": self.poll_interval_s}
