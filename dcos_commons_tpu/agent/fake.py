"""Fake in-process cluster — the simulation backend.

Reference: the tier-2 test harness substrate (``sdk/testing`` mocks the
SchedulerDriver and synthesizes offers/statuses). Our fake cluster plays the
*agent* side of the AgentClient protocol: tasks "launch" instantly and emit
scripted status sequences, so a whole service (plans, matcher, recovery,
state) runs end-to-end in-process with no hardware and no sleeps.

Behavior modes per task (set via ``script``):
* AUTO_RUN (default): STAGING -> RUNNING (readiness passed) immediately.
* AUTO_FINISH: STAGING -> RUNNING -> FINISHED (for ONCE/FINISH tasks the
  mode is chosen automatically from the launch's goal).
* MANUAL: emit nothing; the test drives statuses via ``send_status``.
* CRASH: STAGING -> FAILED (crash-loop simulation).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, replace
from typing import Dict, List, Optional, Sequence

from ..matching.evaluator import LaunchPlan, TaskLaunch
from ..state.tasks import TaskState, TaskStatus
from .client import StatusCallback
from .inventory import AgentInfo


class TaskBehavior(enum.Enum):
    AUTO_RUN = "auto-run"
    AUTO_FINISH = "auto-finish"
    MANUAL = "manual"
    CRASH = "crash"


@dataclass
class FakeTask:
    launch: TaskLaunch
    agent_id: str
    state: TaskState = TaskState.STAGING

    @property
    def task_id(self) -> str:
        return self.launch.task_id

    @property
    def task_name(self) -> str:
        return self.launch.task_name


class FakeCluster:
    """Implements :class:`~dcos_commons_tpu.agent.client.AgentClient`."""

    def __init__(self, agents: Sequence[AgentInfo]):
        self._agents: Dict[str, AgentInfo] = {a.agent_id: a for a in agents}
        self._tasks: Dict[str, FakeTask] = {}  # task_id -> FakeTask
        self._callback: Optional[StatusCallback] = None
        # task_spec_name or task_name -> behavior override
        self._script: Dict[str, TaskBehavior] = {}
        self._launch_log: List[LaunchPlan] = []
        self._kill_log: List[str] = []
        # (agent_id, pod_instance_name) destroy-volume commands, for tests
        self.destroyed_volumes: List[tuple] = []
        # pre-degrade TPU inventory per agent, restored by heal_tpu
        self._healthy_tpu: Dict[str, object] = {}
        # opt-in SIGTERM modeling (elastic soak / preemption tests): a
        # kill WITH a grace period parks the task in _term_pending — it
        # keeps running until the harness calls finish_graceful_kill
        # (clean checkpoint-flush exit) or a grace-0 kill escalates.
        # Default off: every existing test keeps instant KILLED kills.
        self.graceful_kills = False
        self._term_pending: Dict[str, float] = {}  # task_id -> grace_s

    # -- test scripting ----------------------------------------------------

    def script(self, task_name: str, behavior: TaskBehavior) -> None:
        """Override behavior for a task (matched by full instance name first,
        then by spec-level task name)."""
        self._script[task_name] = behavior

    @property
    def launch_log(self) -> List[LaunchPlan]:
        return self._launch_log

    @property
    def kill_log(self) -> List[str]:
        return self._kill_log

    def add_agent(self, agent: AgentInfo) -> None:
        self._agents[agent.agent_id] = agent

    def degrade_tpu(self, agent_id: str, chips_now: int) -> None:
        """Simulate a chip falling off the bus mid-run: the agent stays
        live and its tasks keep running, but its TPU inventory reports
        ``chips_now`` with ``degraded=True`` — what ``RemoteCluster``
        synthesizes when a real agent's re-probe loses chips."""
        a = self._agents[agent_id]
        self._healthy_tpu.setdefault(agent_id, a.tpu)
        self._agents[agent_id] = replace(
            a, tpu=replace(a.tpu, chips=chips_now, degraded=True))

    def heal_tpu(self, agent_id: str) -> None:
        """Inverse of :meth:`degrade_tpu` — the agent's re-probe reports the
        full registered chip count again (driver reload / chip re-seated),
        matching ``RemoteCluster.poll`` clearing ``_tpu_chips_now``."""
        if agent_id not in self._agents:
            return  # keep the healthy record; the agent may re-register
        healthy = self._healthy_tpu.pop(agent_id, None)
        if healthy is not None:
            self._agents[agent_id] = replace(self._agents[agent_id],
                                             tpu=healthy)

    def live_tasks(self) -> List[FakeTask]:
        """Every task the fake agents consider alive (non-terminal), for
        harness-side invariants (e.g. no two live launches may share a
        task name after recovery churn)."""
        return [t for t in self._tasks.values() if not t.state.terminal]

    def remove_agent(self, agent_id: str) -> List[FakeTask]:
        """Simulate host loss: agent gone, its tasks implicitly dead (no
        status is emitted — the scheduler must detect via reconciliation,
        like a Mesos agent partition)."""
        self._agents.pop(agent_id, None)
        lost = [t for t in self._tasks.values() if t.agent_id == agent_id]
        for t in lost:
            del self._tasks[t.task_id]
            self._term_pending.pop(t.task_id, None)
        return lost

    def task(self, task_name: str) -> Optional[FakeTask]:
        for t in self._tasks.values():
            if t.task_name == task_name:
                return t
        return None

    def send_status(self, task_id: str, state: TaskState, message: str = "",
                    readiness_passed: bool = False) -> None:
        task = self._tasks.get(task_id)
        task_name = task.task_name if task else task_id.rsplit("__", 1)[0]
        if task is not None:
            task.state = state
            if state.terminal:
                del self._tasks[task_id]
                # a task that died any other way (crash, agent op) while
                # TERM-pending can no longer answer its SIGTERM
                self._term_pending.pop(task_id, None)
        if self._callback is not None:
            self._callback(task_name, TaskStatus.now(
                task_id, state, message=message,
                readiness_passed=readiness_passed,
                agent_id=task.agent_id if task else None))

    # -- AgentClient -------------------------------------------------------

    def agents(self) -> Sequence[AgentInfo]:
        return list(self._agents.values())

    def set_status_callback(self, callback: StatusCallback) -> None:
        self._callback = callback

    def launch(self, plan: LaunchPlan) -> None:
        if plan.agent.agent_id not in self._agents:
            raise RuntimeError(f"launch on unknown agent {plan.agent.agent_id}")
        self._launch_log.append(plan)
        for launch in plan.launches:
            task = FakeTask(launch=launch, agent_id=plan.agent.agent_id)
            self._tasks[launch.task_id] = task
            behavior = self._behavior(launch)
            self.send_status(launch.task_id, TaskState.STAGING)
            if behavior is TaskBehavior.MANUAL:
                continue
            if behavior is TaskBehavior.CRASH:
                self.send_status(launch.task_id, TaskState.FAILED, message="crash")
            elif behavior is TaskBehavior.AUTO_FINISH:
                self.send_status(launch.task_id, TaskState.RUNNING)
                self.send_status(launch.task_id, TaskState.FINISHED)
            else:
                self.send_status(launch.task_id, TaskState.RUNNING,
                                 readiness_passed=True)

    def _behavior(self, launch: TaskLaunch) -> TaskBehavior:
        if launch.task_name in self._script:
            return self._script[launch.task_name]
        if launch.task_spec_name in self._script:
            return self._script[launch.task_spec_name]
        if launch.goal in ("FINISH", "ONCE"):
            return TaskBehavior.AUTO_FINISH
        return TaskBehavior.AUTO_RUN

    def kill(self, agent_id: str, task_id: str, grace_period_s: float = 0.0) -> None:
        self._kill_log.append(task_id)
        if task_id not in self._tasks:
            return  # unknown task: scheduler already considers it dead
        if self.graceful_kills and grace_period_s > 0:
            # SIGTERM delivered: the task is now draining/flushing. A
            # repeat TERM while pending is idempotent (schedulers re-fire
            # kill steps every cycle until the terminal status lands).
            self._term_pending.setdefault(task_id, grace_period_s)
            return
        escalated = self._term_pending.pop(task_id, None) is not None
        self.send_status(task_id, TaskState.KILLED,
                         message="killed by scheduler (grace expired)"
                         if escalated else "killed by scheduler")

    def pending_term_tasks(self) -> List[str]:
        """Task ids holding a delivered-but-unanswered SIGTERM, sorted
        (harness drives their flush via :meth:`finish_graceful_kill`)."""
        return sorted(t for t in self._term_pending if t in self._tasks)

    def finish_graceful_kill(self, task_id: str, message: str =
                             "exit 143: checkpoint flushed") -> bool:
        """The task answered its SIGTERM: checkpoint flushed, clean exit
        143 (the sentinel contract, ``frameworks/jax/sentinel.py``).
        Returns False if the task was not TERM-pending (already escalated,
        crashed, or its agent vanished)."""
        if self._term_pending.pop(task_id, None) is None \
                or task_id not in self._tasks:
            return False
        self.send_status(task_id, TaskState.KILLED, message=message)
        return True

    def destroy_volumes(self, agent_id: str, pod_instance_name: str) -> None:
        self.destroyed_volumes.append((agent_id, pod_instance_name))

    def running_task_ids(self, agent_id: str) -> Sequence[str]:
        return [t.task_id for t in self._tasks.values()
                if t.agent_id == agent_id and not t.state.terminal]
