"""Agent layer: inventory model, transport interface, fake in-process cluster.

``client``/``fake`` are re-exported lazily: ``matching.evaluator`` imports
``agent.inventory`` while ``specification`` is still initializing, and the
eager chain (client -> state -> specification) would close an import cycle.
"""

from .inventory import AgentInfo, PortRange, TaskRecord, TpuInventory  # noqa: F401

from .._lazy import lazy_exports

__getattr__, __dir__ = lazy_exports(__name__, {
    "AgentClient": "client", "StatusCallback": "client",
    "FakeCluster": "fake", "FakeTask": "fake", "TaskBehavior": "fake",
    "RemoteCluster": "remote",
    "RetryingAgentClient": "retry",
}, globals())
