from .client import AgentClient, StatusCallback
from .fake import FakeCluster, FakeTask, TaskBehavior
from .inventory import AgentInfo, PortRange, TaskRecord, TpuInventory
