from .inventory import AgentInfo, PortRange, TaskRecord, TpuInventory
