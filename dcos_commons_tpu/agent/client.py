"""Scheduler-side agent transport interface.

Reference: the Mesos scheduler driver boundary
(``framework/SchedulerDriverFactory.java:27`` — C++ JNI libmesos or V1 HTTP)
collapsed to the three verbs this SDK actually needs once the offer market
is gone: launch, kill, reconcile. Implementations:

* :class:`~dcos_commons_tpu.agent.fake.FakeCluster` — in-process agents for
  tests/simulation (tier-2 harness, reference ``sdk/testing``).
* the C++ agent daemon speaking gRPC (``native/``), wrapped by a Python
  client with the same interface.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Callable, Protocol, Sequence

from ..state.tasks import TaskStatus
from .inventory import AgentInfo

if TYPE_CHECKING:  # break specification -> matching -> agent import cycle
    from ..matching.evaluator import LaunchPlan

StatusCallback = Callable[[str, TaskStatus], None]  # (task_name, status)


class AgentClient(Protocol):
    # default grace before tasks on an unregistered agent are declared LOST
    # (reference: Mesos agent-reregistration-timeout). In-process fakes keep
    # 0 (agents exist from construction); remote transports override — a
    # restarted scheduler must give live agents time to re-register before
    # relaunching everything they run.
    default_agent_grace_s: float = 0.0

    def agents(self) -> Sequence[AgentInfo]:
        """Current inventory of registered, healthy agents."""

    def launch(self, plan: LaunchPlan) -> None:
        """Start the plan's tasks on its agent. Must be preceded by the
        launch WAL write (StoredTasks + reservations)."""

    def kill(self, agent_id: str, task_id: str, grace_period_s: float = 0.0) -> None:
        """Kill one task; a terminal status will be delivered."""

    def destroy_volumes(self, agent_id: str, pod_instance_name: str) -> None:
        """Delete the pod instance's persistent volumes on the agent
        (reference: Mesos DESTROY of persistent volumes — pod replace and
        uninstall must not leak the failed instance's data to its
        replacement)."""

    def running_task_ids(self, agent_id: str) -> Sequence[str]:
        """Explicit reconciliation: what is actually running on the agent
        (reference ``ExplicitReconciler``/``ImplicitReconciler``)."""

    def set_status_callback(self, callback: StatusCallback) -> None:
        """Register the scheduler's status-update sink."""
