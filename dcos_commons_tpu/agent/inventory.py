"""Agent inventory model — what a per-host agent advertises to the scheduler.

Reference analogue: a Mesos *offer* (``offer/MesosResourcePool.java:24``
pools an offer's reserved/unreserved/atomic resources). We collapse the offer
market into an **inventory** model: agents continuously advertise their total
resources plus current reservations; the matcher computes availability
directly (SURVEY.md section 7 design stance — no decline/revive/suppress).

TPU fields: each agent reports its local chip count and, when part of a pod
slice, the slice id and its ICI coordinates — this is what the reference's
``bootstrap`` (``sdk/bootstrap/main.go``) never had and our matcher's gang
placement consumes.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Mapping, Optional, Tuple


@dataclass(frozen=True)
class TpuInventory:
    """Local TPU chips as inventoried by the agent (``/dev/accel*`` probe in
    the C++ agent; synthetic in the fake agent)."""

    chips: int = 0
    slice_id: Optional[str] = None       # e.g. "slice-0" — one ICI domain
    topology: Optional[str] = None       # e.g. "v4-32", "4x4x4"
    coords: Optional[Tuple[int, ...]] = None  # this host's coords in the slice
    worker_index: Optional[int] = None   # stable host index within the slice
    # chip-level health (SURVEY.md §5): the agent re-probes its chips on
    # every poll; fewer chips than registered (or a probe error) marks the
    # host degraded — the matcher refuses NEW TPU work on it and the
    # scheduler proactively re-forms gangs that have a member here, instead
    # of waiting for the task to crash. ``chips`` reflects the live count.
    degraded: bool = False


@dataclass(frozen=True)
class PortRange:
    begin: int
    end: int  # inclusive

    def __contains__(self, port: int) -> bool:
        return self.begin <= port <= self.end


@dataclass(frozen=True)
class AgentInfo:
    """One host's advertised inventory + identity."""

    agent_id: str
    hostname: str
    cpus: float
    memory_mb: int
    disk_mb: int = 0
    ports: Tuple[PortRange, ...] = (PortRange(10000, 20000),)
    tpu: TpuInventory = field(default_factory=TpuInventory)
    attributes: Mapping[str, str] = field(default_factory=dict)
    zone: Optional[str] = None
    region: Optional[str] = None
    # mount-disk profiles this host offers (reference: DC/OS disk profiles
    # consumed by profile-mount-volumes); empty = plain disk only
    volume_profiles: Tuple[str, ...] = ()
    # reservation roles this host serves (reference pre-reserved-role pools
    # like "slave_public"); "*" = the default shared pool
    roles: Tuple[str, ...] = ("*",)


@dataclass(frozen=True)
class TaskRecord:
    """Where a launched task lives — the matcher's view of cluster state used
    by placement rules (reference rules read ``Collection<TaskInfo>`` +
    stored offer attributes via ``offer/taskdata/TaskLabelReader``)."""

    task_name: str          # "<pod>-<idx>-<task>"
    pod_type: str
    pod_index: int
    agent_id: str
    hostname: str
    zone: Optional[str] = None
    region: Optional[str] = None
    permanently_failed: bool = False  # reference FailureUtils label
    # agent attributes captured at launch (reference AuxLabelAccess stores
    # offer attributes into TaskInfo labels for attribute-counting rules)
    attributes: Mapping[str, str] = field(default_factory=dict)

    @property
    def pod_instance_name(self) -> str:
        return f"{self.pod_type}-{self.pod_index}"
