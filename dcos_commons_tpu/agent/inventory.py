"""Agent inventory model — what a per-host agent advertises to the scheduler.

Reference analogue: a Mesos *offer* (``offer/MesosResourcePool.java:24``
pools an offer's reserved/unreserved/atomic resources). We collapse the offer
market into an **inventory** model: agents continuously advertise their total
resources plus current reservations; the matcher computes availability
directly (SURVEY.md section 7 design stance — no decline/revive/suppress).

TPU fields: each agent reports its local chip count and, when part of a pod
slice, the slice id and its ICI coordinates — this is what the reference's
``bootstrap`` (``sdk/bootstrap/main.go``) never had and our matcher's gang
placement consumes.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Mapping, Optional, Tuple


@dataclass(frozen=True)
class TpuInventory:
    """Local TPU chips as inventoried by the agent (``/dev/accel*`` probe in
    the C++ agent; synthetic in the fake agent)."""

    chips: int = 0
    slice_id: Optional[str] = None       # e.g. "slice-0" — one ICI domain
    topology: Optional[str] = None       # e.g. "v4-32", "4x4x4"
    coords: Optional[Tuple[int, ...]] = None  # this host's coords in the slice
    worker_index: Optional[int] = None   # stable host index within the slice
    # chip-level health (SURVEY.md §5): the agent re-probes its chips on
    # every poll; fewer chips than registered (or a probe error) marks the
    # host degraded — the matcher refuses NEW TPU work on it and the
    # scheduler proactively re-forms gangs that have a member here, instead
    # of waiting for the task to crash. ``chips`` reflects the live count.
    degraded: bool = False


@dataclass(frozen=True)
class PortRange:
    begin: int
    end: int  # inclusive

    def __contains__(self, port: int) -> bool:
        return self.begin <= port <= self.end


@dataclass(frozen=True)
class AgentInfo:
    """One host's advertised inventory + identity."""

    agent_id: str
    hostname: str
    cpus: float
    memory_mb: int
    disk_mb: int = 0
    ports: Tuple[PortRange, ...] = (PortRange(10000, 20000),)
    tpu: TpuInventory = field(default_factory=TpuInventory)
    attributes: Mapping[str, str] = field(default_factory=dict)
    zone: Optional[str] = None
    region: Optional[str] = None
    # mount-disk profiles this host offers (reference: DC/OS disk profiles
    # consumed by profile-mount-volumes); empty = plain disk only
    volume_profiles: Tuple[str, ...] = ()
    # reservation roles this host serves (reference pre-reserved-role pools
    # like "slave_public"); "*" = the default shared pool
    roles: Tuple[str, ...] = ("*",)


@dataclass(frozen=True)
class TaskRecord:
    """Where a launched task lives — the matcher's view of cluster state used
    by placement rules (reference rules read ``Collection<TaskInfo>`` +
    stored offer attributes via ``offer/taskdata/TaskLabelReader``)."""

    task_name: str          # "<pod>-<idx>-<task>"
    pod_type: str
    pod_index: int
    agent_id: str
    hostname: str
    zone: Optional[str] = None
    region: Optional[str] = None
    permanently_failed: bool = False  # reference FailureUtils label
    # agent attributes captured at launch (reference AuxLabelAccess stores
    # offer attributes into TaskInfo labels for attribute-counting rules)
    attributes: Mapping[str, str] = field(default_factory=dict)

    @property
    def pod_instance_name(self) -> str:
        return f"{self.pod_type}-{self.pod_index}"


class TaskRecords(list):
    """An immutable-by-convention snapshot of TaskRecords with secondary
    indexes, so matcher passes that previously scanned the whole fleet per
    candidate (sibling lookups, gang votes, coordinator discovery) answer
    in O(result). Consumers must treat it as frozen — only the OWNER (the
    scheduler's generation-keyed cache) may mutate it, and only through
    ``patch()``, which keeps every index consistent at O(changed) cost so
    a launch no longer forces an O(fleet) rebuild. Plain ``list``/
    ``Sequence`` callers keep working: the evaluator duck-types on the
    index methods and falls back to scans."""

    def __init__(self, records=()):
        super().__init__(records)
        self._by_pod: dict = {}
        self._by_type: dict = {}        # pod_type -> {task_name: record}
        self._coordinators: dict = {}   # pod_type -> first record at index 0
        self._by_name: dict = {}        # task_name -> record
        self._pos: dict = {}            # task_name -> index in the list
        for i, r in enumerate(self):
            self._by_name[r.task_name] = r
            self._pos[r.task_name] = i
            self._by_pod.setdefault(r.pod_instance_name, []).append(r)
            self._by_type.setdefault(r.pod_type, {})[r.task_name] = r
            if r.pod_index == 0:
                self._coordinators.setdefault(r.pod_type, r)

    def for_pod_instance(self, name: str) -> list:
        return self._by_pod.get(name, [])

    def for_pod_type(self, pod_type: str) -> list:
        return list(self._by_type.get(pod_type, {}).values())

    def coordinator(self, pod_type: str) -> Optional[TaskRecord]:
        """The record of ``<pod_type>-0`` (any task of it), if launched."""
        return self._coordinators.get(pod_type)

    # -- owner-only incremental maintenance --------------------------------

    def _drop(self, name: str) -> None:
        r = self._by_name.pop(name, None)
        if r is None:
            return
        # O(1) list removal: swap the record with the tail and pop
        i = self._pos.pop(name)
        last = super().pop()
        if last is not r:
            self[i] = last
            self._pos[last.task_name] = i
        bucket = self._by_pod.get(r.pod_instance_name)
        if bucket is not None:   # short list: one pod instance's tasks
            bucket.remove(r)
            if not bucket:
                del self._by_pod[r.pod_instance_name]
        by_type = self._by_type.get(r.pod_type)
        if by_type is not None:
            by_type.pop(name, None)
            if not by_type:
                del self._by_type[r.pod_type]
        if self._coordinators.get(r.pod_type) is r:
            # re-elect from the remaining index-0 records of the type
            # (rare: only when the coordinator record itself changes)
            del self._coordinators[r.pod_type]
            for cand in (by_type or {}).values():
                if cand.pod_index == 0:
                    self._coordinators[r.pod_type] = cand
                    break

    def patch(self, updates, deletes=()) -> None:
        """Replace/insert ``updates`` records and drop ``deletes`` names,
        keeping every index consistent — O(changed), not O(fleet). This is
        how the scheduler's cache absorbs a mid-cycle launch; nobody else
        may mutate the snapshot."""
        for name in deletes:
            self._drop(name)
        for r in updates:
            self._drop(r.task_name)
            self._by_name[r.task_name] = r
            self._pos[r.task_name] = len(self)
            self.append(r)
            self._by_pod.setdefault(r.pod_instance_name, []).append(r)
            self._by_type.setdefault(r.pod_type, {})[r.task_name] = r
            if r.pod_index == 0:
                self._coordinators.setdefault(r.pod_type, r)
