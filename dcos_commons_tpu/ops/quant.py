"""Weight-only int8 quantization for TPU serving.

The standard v5e serving recipe: weights stored as int8 with per-channel
bf16 scales, activations stay bf16. Decode at real model sizes is
HBM-bandwidth bound (every token streams the full weight set), so halving
the bytes per weight is ~2x decode throughput — and it is what lets an
8B-parameter model (16 GB in bf16) fit a single 16 GB-HBM chip at all
(~8 GB quantized + KV cache).

Design:

* :class:`QTensor` — a registered pytree node ``(q: int8, s: scale)``.
  Because it is a pytree container, quantized weights flow through
  ``lax.scan`` (the stacked-layer decode loop slices the leading L axis
  of both payload and scales), ``jax.device_put``, and the sharded
  checkpoint engine without special cases.
* Symmetric per-channel scales with ``keepdims``: the scale tensor has
  the same rank as the weight with the quantized (reduction) axis size 1,
  so it broadcasts against matmul *outputs* — ``x @ dequant(w)`` equals
  ``(x @ w.q) * w.s`` exactly when ``s`` is per-out-channel, which keeps
  the matmul itself on the MXU in bf16 with the int8->bf16 convert fused
  into the weight load by XLA (no dequantized copy ever materializes in
  HBM).
* :func:`qmm` / :func:`qtake` accept plain arrays too, so model code has
  ONE path for quantized and unquantized weights.

Reference parity: the reference repo (Java control plane) ships no
quantization; this is the execute-side half of BASELINE.json config #5
("Llama-3-8B inference") on single-chip v5e hardware.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Union

import jax
import jax.numpy as jnp

Array = jnp.ndarray


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class QTensor:
    """Int8 payload + broadcastable scales; quantized axis has size 1 in
    ``s``. Behaves as a pytree container of (q, s)."""

    q: Array  # int8, original weight shape
    s: Array  # scale, same rank, quantized axis collapsed to 1

    def tree_flatten(self):
        return (self.q, self.s), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):
        # the *logical* dtype models compute in, not the storage dtype
        return self.s.dtype


QArray = Union[Array, QTensor]


def quantize(w: Array, axis: int = -2,
             scale_dtype: Any = jnp.bfloat16) -> QTensor:
    """Symmetric per-channel int8: ``axis`` is the axis folded into each
    scale group (the matmul reduction axis for ``x @ w`` weights; the
    embedding dim for gather tables)."""
    wf = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=axis, keepdims=True)
    s = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(wf / s), -127, 127).astype(jnp.int8)
    return QTensor(q=q, s=s.astype(scale_dtype))


def dequantize(w: QTensor, dtype: Any = None) -> Array:
    dtype = dtype or w.s.dtype
    return (w.q.astype(jnp.float32)
            * w.s.astype(jnp.float32)).astype(dtype)


def qmm(x: Array, w: QArray) -> Array:
    """``x @ w`` for plain or quantized ``w``.

    Quantized path: matmul against the int8 payload cast to ``x.dtype``
    (XLA fuses the convert into the weight load), then scale the output —
    exact for per-out-channel scales, since the scale is constant along
    the reduction axis. Under GSPMD a row-sharded (reduction-axis) ``w``
    all-reduces the partial products *before* the scale multiply, which
    is the mathematically correct order.
    """
    if isinstance(w, QTensor):
        # s is [..., 1, out]; drop the collapsed reduction axis so it
        # broadcasts against the matmul output's trailing [out] dim
        return (x @ w.q.astype(x.dtype)) * jnp.squeeze(
            w.s, axis=-2).astype(x.dtype)
    return x @ w


def qtake(w: QArray, idx: Array, dtype: Any) -> Array:
    """Embedding lookup ``w[idx]`` for plain or quantized tables (tables
    quantize per *row*, so the gathered rows carry their own scales)."""
    if isinstance(w, QTensor):
        return w.q[idx].astype(dtype) * w.s[idx].astype(dtype)
    return w.astype(dtype)[idx]
