"""TPU-first primitive ops shared by the model families.

Everything here is shape-static, jit-traceable, and bf16-in/fp32-accumulate
so XLA can tile the matmuls onto the MXU and fuse the elementwise tails.
"""

from dcos_commons_tpu.ops.norms import rms_norm, layer_norm
from dcos_commons_tpu.ops.rotary import (rope_frequencies, apply_rope,
                                          apply_rope_at,
                                          apply_rope_at_many,
                                          apply_rope_positions)
from dcos_commons_tpu.ops.attention import gqa_attention, repeat_kv
from dcos_commons_tpu.ops.losses import (fused_linear_cross_entropy,
                                         softmax_cross_entropy)

__all__ = [
    "rms_norm", "layer_norm",
    "rope_frequencies", "apply_rope", "apply_rope_at",
    "apply_rope_at_many", "apply_rope_positions",
    "gqa_attention", "repeat_kv",
    "softmax_cross_entropy", "fused_linear_cross_entropy",
]
