"""Token sampling for the serving path: temperature, top-k, nucleus.

The decode loops (``models/llama.py``: ``generate`` / ``generate_chunked``
/ ``generate_stepwise``) are greedy by default; a sampler built here drops
in wherever the argmax was. Everything is shape-static and branch-free so
samplers compile into the decode scan unchanged:

* top-k uses ``lax.top_k`` (k is a Python int, so the threshold — the
  k-th largest logit — is a static-shape reduction);
* top-p sorts the row (V ~ 32k sorts fine on TPU), takes the softmax
  cumsum, and masks every token whose *preceding* cumulative mass already
  reached p — the standard nucleus rule that always keeps the top token;
* filtering composes by masking to ``-inf`` before
  ``jax.random.categorical`` (Gumbel-max over the surviving logits).

The reference scheduler has no serving stack (Java control plane; see
SURVEY §2.4) — this is workload-layer capability for BASELINE.json
config #5's inference path.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

Array = jnp.ndarray

# sample(key, logits [B, V]) -> tokens [B] int32
Sampler = Callable[[jax.Array, Array], Array]

_NEG_INF = float("-inf")


def top_k_mask(logits: Array, k: int) -> Array:
    """Keep the k largest logits per row, -inf elsewhere (ties at the
    threshold all survive, matching the usual implementation)."""
    kth = lax.top_k(logits, k)[0][..., -1:]
    return jnp.where(logits < kth, _NEG_INF, logits)


def top_p_mask(logits: Array, p: float) -> Array:
    """Nucleus filtering: keep the smallest prefix of the
    probability-sorted vocabulary whose mass reaches ``p``."""
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits.astype(jnp.float32), axis=-1)
    # mass strictly BEFORE each position: position 0 is always kept
    before = jnp.cumsum(probs, axis=-1) - probs
    cut = jnp.sum(before < p, axis=-1, keepdims=True)      # tokens kept
    threshold = jnp.take_along_axis(sorted_logits, cut - 1, axis=-1)
    return jnp.where(logits < threshold, _NEG_INF, logits)


@dataclasses.dataclass(frozen=True)
class ConfiguredSampler:
    """A callable sampler that hashes/compares by its settings, so decode
    executable caches keyed on the sampler object (``generate_chunked``)
    hit across equal-config instances — building a fresh sampler per
    request must not recompile."""

    temperature: float
    top_k: int
    top_p: float

    def __call__(self, key: jax.Array, logits: Array) -> Array:
        # temperature first: nucleus membership is conventionally decided
        # on the TEMPERED distribution (HF/vLLM warper order). top-k is
        # order-insensitive (scaling is monotonic), top-p is not.
        x = logits.astype(jnp.float32) / self.temperature
        if self.top_k:
            x = top_k_mask(x, self.top_k)
        if 0.0 < self.top_p < 1.0:
            x = top_p_mask(x, self.top_p)
        return jax.random.categorical(key, x, axis=-1)


def make_sampler(temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 0.0) -> Optional[Sampler]:
    """Build a sampler, or ``None`` for greedy (temperature 0).

    Filters apply in the conventional order (top-k, then top-p over the
    survivors), then Gumbel-max categorical over ``logits/temperature``.
    """
    if temperature == 0.0:
        return None
    if temperature < 0.0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    if not 0.0 <= top_p <= 1.0:
        raise ValueError(f"top_p must be in [0, 1], got {top_p}")
    return ConfiguredSampler(temperature, top_k, top_p)
