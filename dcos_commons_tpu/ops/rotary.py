"""Rotary position embeddings (RoPE), decode-offset aware."""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def rope_frequencies(head_dim: int, max_seq: int,
                     theta: float = 10000.0) -> jnp.ndarray:
    """Precompute [max_seq, head_dim//2] complex-free cos/sin table.

    Returns a stacked [2, max_seq, head_dim//2] fp32 array (cos, sin) so the
    table lives in one buffer and slices cleanly under jit.
    """
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2,
                                      dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_seq, dtype=jnp.float32)
    freqs = jnp.outer(t, inv)                       # [S, D/2]
    return jnp.stack([jnp.cos(freqs), jnp.sin(freqs)])


def apply_rope(x: jnp.ndarray, table: jnp.ndarray,
               offset: int | jnp.ndarray = 0) -> jnp.ndarray:
    """Rotate [B, S, H, D] by positions ``offset..offset+S``.

    ``offset`` may be a traced scalar (decode step); the slice uses
    ``lax.dynamic_slice_in_dim`` so shapes stay static.
    """
    seq = x.shape[1]
    half = x.shape[-1] // 2
    cos = lax.dynamic_slice_in_dim(table[0], offset, seq)[None, :, None, :]
    sin = lax.dynamic_slice_in_dim(table[1], offset, seq)[None, :, None, :]
    return _rotate(x, cos, sin, half)


def apply_rope_positions(x: jnp.ndarray, table: jnp.ndarray,
                         pos: jnp.ndarray) -> jnp.ndarray:
    """Rotate [B, S, H, D] at explicit positions ``pos`` [S] shared by
    every batch row — the chunked-prefill path, where a resumed chunk's
    window ``start..start+S`` may overrun the table (its tail past
    ``true_len`` is dead padding). :func:`apply_rope` must not be used
    there: ``dynamic_slice`` clamps the START when the slice would run
    off the table, silently mis-rotating the LIVE head of the chunk;
    the gather here clamps per lane, so only dead tail lanes saturate.
    Callers pass ``pos`` pre-clipped to the table."""
    half = x.shape[-1] // 2
    cos = table[0][pos][None, :, None, :]           # [1, S, 1, D/2]
    sin = table[1][pos][None, :, None, :]
    return _rotate(x, cos, sin, half)


def apply_rope_at(x: jnp.ndarray, table: jnp.ndarray,
                  pos: jnp.ndarray) -> jnp.ndarray:
    """Rotate a single decode position PER SLOT: x [B, 1, H, D], pos [B]
    (each batch row at its own sequence position — the continuous-
    batching decode step, where slots advance independently)."""
    half = x.shape[-1] // 2
    cos = table[0][pos][:, None, None, :]           # [B, 1, 1, D/2]
    sin = table[1][pos][:, None, None, :]
    return _rotate(x, cos, sin, half)


def apply_rope_at_many(x: jnp.ndarray, table: jnp.ndarray,
                       pos: jnp.ndarray) -> jnp.ndarray:
    """Rotate a K-token window PER STREAM: x [B, K, H, D], pos [B, K]
    (stream ``b``'s window occupies its own positions — the paged
    speculative verify, where every stream sits at a different length).
    Callers pass ``pos`` pre-clipped to the table, same contract as
    :func:`apply_rope_positions`."""
    half = x.shape[-1] // 2
    cos = table[0][pos][:, :, None, :]              # [B, K, 1, D/2]
    sin = table[1][pos][:, :, None, :]
    return _rotate(x, cos, sin, half)


def _rotate(x, cos, sin, half):
    x32 = x.astype(jnp.float32)
    x1, x2 = x32[..., :half], x32[..., half:]
    out = jnp.concatenate([x1 * cos - x2 * sin,
                           x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)
