"""Pallas TPU flash attention (fused forward AND backward).

The hot op of the workload layer (``frameworks/jax`` llama training/serving):
online-softmax blockwise attention that never materializes the [Sq, Sk]
score matrix in HBM — scores live in VMEM one (block_q, block_k) tile at a
time, with running max/denominator carried in VMEM scratch across the
sequential k-block grid axis (TPU grids iterate sequentially, so the
innermost axis doubles as the flash accumulation loop).

Backward is the FlashAttention-2 recomputation scheme: the forward saves
only O and the per-row logsumexp; two kernels (dK/dV over k-blocks, dQ
over q-blocks) recompute P tile by tile — again never materializing the
score matrix — with ``D = rowsum(dO * O)`` precomputed in XLA.

GQA comes free through the BlockSpec index map: each query head reads its
kv-group's K/V block directly — no ``repeat_kv`` materialization at all
(the dense path pays that broadcast in HBM). Backward computes per-q-head
dK/dV and group-sums once in XLA.

Layout matches ``ops.attention``: q [B, Sq, H, D], k/v [B, Sk, KV, D].
Causal masking is positional (``q_offset`` shifts query positions); blocks
entirely above the diagonal are skipped, not just masked.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dcos_commons_tpu import _jax_compat  # noqa: F401  (installs renames)

_NEG = -1e30
_LANES = 128


def _fit_block(requested: int, seq: int) -> int:
    """Largest power-of-two block <= requested that divides seq (callers
    guarantee seq % 8 == 0 via ``supports``)."""
    b = 1 << (min(requested, seq).bit_length() - 1)  # floor to power of two
    while b > 8 and seq % b:
        b //= 2
    return b


def _causal_mask(iq, ik, block_q, block_k, q_offset, shape, transpose=False):
    q_axis, k_axis = (1, 0) if transpose else (0, 1)
    q_pos = (q_offset + iq * block_q
             + jax.lax.broadcasted_iota(jnp.int32, shape, q_axis))
    k_pos = (ik * block_k
             + jax.lax.broadcasted_iota(jnp.int32, shape, k_axis))
    return q_pos >= k_pos


# --------------------------------------------------------------------------
# forward

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, sm_scale: float, causal: bool, q_offset: int,
                block_q: int, block_k: int):
    iq = pl.program_id(2)
    ik = pl.program_id(3)
    n_k = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # causal: a k-block strictly above this q-block's last row contributes
    # nothing — skip its compute entirely (the win over masked-dense)
    q_last = q_offset + (iq + 1) * block_q - 1
    live = jnp.logical_or(not causal, ik * block_k <= q_last)

    @pl.when(live)
    def _body():
        # matmuls run in the input dtype (bf16 rides the MXU at full rate)
        # with f32 accumulation; softmax statistics stay f32 throughout
        q = q_ref[0, 0]                                  # [bq, d]
        k = k_ref[0, 0]                                  # [bk, d]
        v = v_ref[0, 0]                                  # [bk, d]
        s = jax.lax.dot_general(                         # [bq, bk] f32
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        mask = None
        if causal:
            mask = _causal_mask(iq, ik, block_q, block_k, q_offset, s.shape)
            s = jnp.where(mask, s, _NEG)

        m_prev = m_scr[:, :1]                            # [bq, 1]
        l_prev = l_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)                  # [bq, 1]
        p = jnp.exp(s - m_new)                           # [bq, bk]
        if mask is not None:
            # a fully-masked row has m_new == _NEG == its masked scores, so
            # exp(s - m_new) would be 1, not 0 — zero p explicitly so such
            # rows keep l == 0 and finish as 0 output, not mean-of-V
            p = jnp.where(mask, p, 0.0)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ik == n_k - 1)
    def _finish():
        # fully-masked rows (possible with q_offset < 0 padding) get 0, not
        # NaN: guard the 1/l; their logsumexp is recorded as _NEG so the
        # backward recomputation also zeroes them
        l = l_scr[:, :1]
        safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scr[:] / safe).astype(o_ref.dtype)
        # lse rides in an [8, block_q] tile (8 identical sublanes): TPU
        # block shapes need the second-to-last dim divisible by 8
        lse = jnp.where(l[:, 0] == 0.0, _NEG,
                        m_scr[:, 0] + jnp.log(safe[:, 0]))
        lse_ref[0, 0] = jnp.broadcast_to(lse[None, :], lse_ref[0, 0].shape)


def _flash_forward(q, k, v, causal, sm_scale, q_offset, block_q, block_k,
                   interpret):
    """Returns (o [B,Sq,H,D], lse [B,H,Sq] fp32)."""
    b, s_q, h, d = q.shape
    _, s_k, kv, _ = k.shape
    assert h % kv == 0, (h, kv)
    n_rep = h // kv
    block_q = _fit_block(block_q, s_q)
    block_k = _fit_block(block_k, s_k)
    assert s_q % block_q == 0 and s_k % block_k == 0, (s_q, s_k)
    scale = sm_scale if sm_scale is not None else d ** -0.5

    # [B, S, H, D] -> [B, H, S, D]: block maps want heads outermost
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    grid = (b, h, s_q // block_q, s_k // block_k)
    kernel = functools.partial(
        _fwd_kernel, sm_scale=scale, causal=causal, q_offset=q_offset,
        block_q=block_q, block_k=block_k)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, qi, ki, n_rep=n_rep:
                         (bi, hi // n_rep, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, qi, ki, n_rep=n_rep:
                         (bi, hi // n_rep, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, 8, block_q),
                         lambda bi, hi, qi, ki: (bi, hi, 0, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s_q, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, 8, s_q), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),   # running max
            pltpu.VMEM((block_q, _LANES), jnp.float32),   # running denom
            pltpu.VMEM((block_q, d), jnp.float32),        # output acc
        ],
        compiler_params=pltpu.CompilerParams(
            # only the k axis carries state; batch/head/q-block tiles are
            # independent, letting Mosaic pipeline them
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"),
        ),
        interpret=interpret,
    )(qt, kt, vt)
    return out.transpose(0, 2, 1, 3), lse  # lse: [b, h, 8, sq] (8 copies)


# --------------------------------------------------------------------------
# backward (FlashAttention-2 recomputation)

def _recompute_p(q, k, lse_rows, iq, ik, block_q, block_k, causal, q_offset,
                 sm_scale, transpose):
    """P tile from saved logsumexp. ``transpose``: [bk, bq] layout."""
    if transpose:
        s = jax.lax.dot_general(k, q, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * sm_scale - lse_rows[None, :]
    else:
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * sm_scale - lse_rows[:, None]
    p = jnp.exp(s)
    if causal:
        mask = _causal_mask(iq, ik, block_q, block_k, q_offset, s.shape,
                            transpose=transpose)
        p = jnp.where(mask, p, 0.0)
    # rows whose lse is the _NEG sentinel are fully masked: exp(s + 1e30)
    # would explode, so zero them explicitly. f32 multiply, not a bool
    # where: Mosaic can't insert a minor dim on 1-bit vectors
    alive = (lse_rows > _NEG / 2).astype(jnp.float32)
    p = p * (alive[None, :] if transpose else alive[:, None])
    return p


def _bwd_dkdv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                     dk_ref, dv_ref, dk_scr, dv_scr, *,
                     sm_scale, causal, q_offset, block_q, block_k):
    ik = pl.program_id(2)
    iq = pl.program_id(3)
    n_q = pl.num_programs(3)

    @pl.when(iq == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    q_last = q_offset + (iq + 1) * block_q - 1
    live = jnp.logical_or(not causal, ik * block_k <= q_last)

    @pl.when(live)
    def _body():
        q = q_ref[0, 0]                  # [bq, d]
        k = k_ref[0, 0]                  # [bk, d]
        v = v_ref[0, 0]                  # [bk, d]
        do = do_ref[0, 0]                # [bq, d]
        lse = lse_ref[0, 0][0]           # [bq] f32 (row 0 of the 8 copies)
        delta = delta_ref[0, 0][0]       # [bq] f32 (rowsum(dO*O))
        p_t = _recompute_p(q, k, lse, iq, ik, block_q, block_k, causal,
                           q_offset, sm_scale, transpose=True)   # [bk, bq]
        dv_scr[:] += jax.lax.dot_general(
            p_t.astype(do.dtype), do, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp_t = jax.lax.dot_general(      # [bk, bq]
            v, do, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds_t = p_t * (dp_t - delta[None, :]) * sm_scale
        dk_scr[:] += jax.lax.dot_general(
            ds_t.astype(q.dtype), q, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(iq == n_q - 1)
    def _finish():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_scr, *,
                   sm_scale, causal, q_offset, block_q, block_k):
    iq = pl.program_id(2)
    ik = pl.program_id(3)
    n_k = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    q_last = q_offset + (iq + 1) * block_q - 1
    live = jnp.logical_or(not causal, ik * block_k <= q_last)

    @pl.when(live)
    def _body():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0][0]
        delta = delta_ref[0, 0][0]
        p = _recompute_p(q, k, lse, iq, ik, block_q, block_k, causal,
                         q_offset, sm_scale, transpose=False)    # [bq, bk]
        dp = jax.lax.dot_general(        # [bq, bk]
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * sm_scale
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ik == n_k - 1)
    def _finish():
        dq_ref[0, 0] = dq_scr[:].astype(dq_ref.dtype)


def _flash_backward(q, k, v, o, lse, g, causal, sm_scale, q_offset,
                    block_q, block_k, interpret):
    b, s_q, h, d = q.shape
    _, s_k, kv, _ = k.shape
    n_rep = h // kv
    block_q = _fit_block(block_q, s_q)
    block_k = _fit_block(block_k, s_k)
    scale = sm_scale if sm_scale is not None else d ** -0.5

    qt = q.transpose(0, 2, 1, 3)          # [b, h, sq, d]
    kt = k.transpose(0, 2, 1, 3)          # [b, kv, sk, d]
    vt = v.transpose(0, 2, 1, 3)
    dot = g.transpose(0, 2, 1, 3)         # [b, h, sq, d]
    ot = o.transpose(0, 2, 1, 3)
    # D = rowsum(dO * O): cheap elementwise+reduce, left to XLA; broadcast
    # into the same [b, h, 8, sq] sublane layout as lse
    delta = jnp.sum(dot.astype(jnp.float32) * ot.astype(jnp.float32),
                    axis=-1)              # [b, h, sq] f32
    delta = jnp.broadcast_to(delta[:, :, None, :], lse.shape)

    common = dict(sm_scale=scale, causal=causal, q_offset=q_offset,
                  block_q=block_q, block_k=block_k)

    # ---- dK/dV: grid (b, h, k-blocks, q-blocks), q innermost ----
    dkdv = pl.pallas_call(
        functools.partial(_bwd_dkdv_kernel, **common),
        grid=(b, h, s_k // block_k, s_q // block_q),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, ki, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, ki, qi, n_rep=n_rep:
                         (bi, hi // n_rep, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, ki, qi, n_rep=n_rep:
                         (bi, hi // n_rep, ki, 0)),
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, ki, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, 8, block_q),
                         lambda bi, hi, ki, qi: (bi, hi, 0, qi)),
            pl.BlockSpec((1, 1, 8, block_q),
                         lambda bi, hi, ki, qi: (bi, hi, 0, qi)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, ki, qi: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, ki, qi: (bi, hi, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s_k, d), k.dtype),  # per q-head
            jax.ShapeDtypeStruct((b, h, s_k, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(qt, kt, vt, dot, lse, delta)
    dk_ph, dv_ph = dkdv
    # GQA: group-sum per-q-head grads down to the kv heads
    dk = dk_ph.reshape(b, kv, n_rep, s_k, d).sum(axis=2)
    dv = dv_ph.reshape(b, kv, n_rep, s_k, d).sum(axis=2)

    # ---- dQ: grid (b, h, q-blocks, k-blocks), k innermost ----
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, **common),
        grid=(b, h, s_q // block_q, s_k // block_k),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, qi, ki, n_rep=n_rep:
                         (bi, hi // n_rep, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, qi, ki, n_rep=n_rep:
                         (bi, hi // n_rep, ki, 0)),
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, 8, block_q),
                         lambda bi, hi, qi, ki: (bi, hi, 0, qi)),
            pl.BlockSpec((1, 1, 8, block_q),
                         lambda bi, hi, qi, ki: (bi, hi, 0, qi)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d),
                               lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, s_q, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(qt, kt, vt, dot, lse, delta)

    return (dq.transpose(0, 2, 1, 3),
            dk.transpose(0, 2, 1, 3),
            dv.transpose(0, 2, 1, 3))


# --------------------------------------------------------------------------
# custom VJP plumbing

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, causal, sm_scale, q_offset, block_q, block_k, interpret):
    out, _ = _flash_forward(q, k, v, causal, sm_scale, q_offset, block_q,
                            block_k, interpret)
    return out


def _flash_fwd(q, k, v, causal, sm_scale, q_offset, block_q, block_k,
               interpret):
    out, lse = _flash_forward(q, k, v, causal, sm_scale, q_offset, block_q,
                              block_k, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, sm_scale, q_offset, block_q, block_k, interpret,
               res, g):
    q, k, v, o, lse = res
    return _flash_backward(q, k, v, o, lse, g, causal, sm_scale, q_offset,
                           block_q, block_k, interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "sm_scale", "q_offset", "block_q", "block_k",
                     "interpret"))
def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                    causal: bool = True,
                    sm_scale: Optional[float] = None,
                    q_offset: int = 0,
                    block_q: int = 512,
                    block_k: int = 512,
                    interpret: bool = False) -> jnp.ndarray:
    """Drop-in for ``ops.attention.gqa_attention`` on full sequences.

    q: [B, Sq, H, D]; k/v: [B, Sk, KV, D], H % KV == 0. Requires
    Sq % 8 == 0 and Sk % 128 == 0 (see ``supports``); blocks self-fit to
    the largest power-of-two divisor, so no caller-side padding is needed.
    Fully differentiable: both directions run fused pallas kernels.
    """
    return _flash(q, k, v, causal, sm_scale, q_offset, block_q, block_k,
                  interpret)


def flash_attention_tp(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                       mesh, *, axis: str = "tp", causal: bool = True,
                       sm_scale: Optional[float] = None, q_offset: int = 0,
                       block_q: int = 512, block_k: int = 512,
                       interpret: bool = False) -> jnp.ndarray:
    """:func:`flash_attention` under tensor parallelism (the prefill
    mirror of ``ops.flash_decode.flash_decode_tp``).

    Attention is head-local, so megatron-sharded prefill (heads split
    over the ``tp`` mesh axis) runs the kernel independently per shard
    on its local head group — ``shard_map`` with head-axis specs and NO
    collectives. This is what removes the dense path's [B, H, S, S]
    fp32 score transient from SHARDED long-context prefill (26 GB at
    batch 8 x seq 4096 — the single-chip wall the flash kernel already
    removed, commit 11f24f6). Requires the KV head count to divide
    evenly across the axis.
    """
    from jax.sharding import PartitionSpec as P

    tp = mesh.shape[axis]
    kv_heads = k.shape[2]
    if kv_heads % tp:
        raise ValueError(
            f"flash_attention_tp: {kv_heads} KV heads do not divide "
            f"over {axis}={tp}")
    hspec = P(None, None, axis, None)

    def shard(q_l, k_l, v_l):
        return flash_attention(q_l, k_l, v_l, causal=causal,
                               sm_scale=sm_scale, q_offset=q_offset,
                               block_q=block_q, block_k=block_k,
                               interpret=interpret)

    # check_vma=False: pallas_call's out_shape carries no varying-mesh-
    # axes annotation, and the body is collective-free by construction
    return jax.shard_map(shard, mesh=mesh, in_specs=(hspec, hspec, hspec),
                         out_specs=hspec, check_vma=False)(q, k, v)


def supports(q: jnp.ndarray, k: jnp.ndarray, *, kv_len=None) -> bool:
    """Whether the flash path can serve this call (else dense fallback)."""
    s_q, s_k = q.shape[1], k.shape[1]
    if kv_len is not None:
        return False  # padded decode caches use the dense path
    if q.shape[-1] > 256:
        return False  # head_dim beyond a VMEM-friendly tile
    # q blocks self-fit to any multiple of 8 (see _fit_block); k blocks
    # must stay lane-width multiples — an s_k with small odd factors would
    # degrade to 8-wide tiles and lose to the dense path it replaces
    return s_q % 8 == 0 and s_k % 128 == 0
