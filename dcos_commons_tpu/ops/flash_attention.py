"""Pallas TPU flash attention (forward).

The hot op of the workload layer (``frameworks/jax`` llama training/serving):
online-softmax blockwise attention that never materializes the [Sq, Sk]
score matrix in HBM — scores live in VMEM one (block_q, block_k) tile at a
time, with running max/denominator carried in VMEM scratch across the
sequential k-block grid axis (TPU grids iterate sequentially, so the
innermost axis doubles as the flash accumulation loop).

GQA comes free through the BlockSpec index map: each query head reads its
kv-group's K/V block directly — no ``repeat_kv`` materialization at all
(the dense path pays that broadcast in HBM).

Layout matches ``ops.attention``: q [B, Sq, H, D], k/v [B, Sk, KV, D].
Causal masking is positional (``q_offset`` shifts query positions); blocks
entirely above the diagonal are skipped, not just masked.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG = -1e30
_LANES = 128


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
                  sm_scale: float, causal: bool, q_offset: int,
                  block_q: int, block_k: int):
    iq = pl.program_id(2)
    ik = pl.program_id(3)
    n_k = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # causal: a k-block strictly above this q-block's last row contributes
    # nothing — skip its compute entirely (the win over masked-dense)
    q_last = q_offset + (iq + 1) * block_q - 1
    k_first = ik * block_k
    live = jnp.logical_or(not causal, k_first <= q_last)

    @pl.when(live)
    def _body():
        # matmuls run in the input dtype (bf16 rides the MXU at full rate)
        # with f32 accumulation; softmax statistics stay f32 throughout
        q = q_ref[0, 0]                                  # [bq, d]
        k = k_ref[0, 0]                                  # [bk, d]
        v = v_ref[0, 0]                                  # [bk, d]
        s = jax.lax.dot_general(                         # [bq, bk] f32
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        mask = None
        if causal:
            q_pos = (q_offset + iq * block_q
                     + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0))
            k_pos = (ik * block_k
                     + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1))
            mask = q_pos >= k_pos
            s = jnp.where(mask, s, _NEG)

        m_prev = m_scr[:, :1]                            # [bq, 1]
        l_prev = l_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)                  # [bq, 1]
        p = jnp.exp(s - m_new)                           # [bq, bk]
        if mask is not None:
            # a fully-masked row has m_new == _NEG == its masked scores, so
            # exp(s - m_new) would be 1, not 0 — zero p explicitly so such
            # rows keep l == 0 and finish as 0 output, not mean-of-V
            p = jnp.where(mask, p, 0.0)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ik == n_k - 1)
    def _finish():
        # fully-masked rows (possible with q_offset < 0 padding) get 0, not
        # NaN: guard the 1/l
        l = l_scr[:, :1]
        safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scr[:] / safe).astype(o_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, causal, sm_scale, q_offset, block_q, block_k, interpret):
    return _flash_forward(q, k, v, causal, sm_scale, q_offset, block_q,
                          block_k, interpret)


def _flash_fwd(q, k, v, *nondiff):
    return _flash(q, k, v, *nondiff), (q, k, v)


def _flash_bwd(causal, sm_scale, q_offset, block_q, block_k, interpret,
               res, g):
    # Backward recomputes through the (differentiable) dense reference —
    # identical math, so gradients are exact; the flash win applies to the
    # forward/serving path while training remains correct everywhere.
    # (A fused flash backward kernel is the natural next optimization.)
    from .attention import gqa_attention
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: gqa_attention(
            q_, k_, v_, causal=causal, sm_scale=sm_scale, q_offset=q_offset),
        q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "sm_scale", "q_offset", "block_q", "block_k",
                     "interpret"))
def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                    causal: bool = True,
                    sm_scale: Optional[float] = None,
                    q_offset: int = 0,
                    block_q: int = 128,
                    block_k: int = 128,
                    interpret: bool = False) -> jnp.ndarray:
    """Drop-in for ``ops.attention.gqa_attention`` on full sequences.

    q: [B, Sq, H, D]; k/v: [B, Sk, KV, D], H % KV == 0. Sequence lengths
    must divide the block sizes (callers pad or fall back to dense).
    Differentiable: the backward pass runs the dense reference VJP.
    """
    return _flash(q, k, v, causal, sm_scale, q_offset, block_q, block_k,
                  interpret)


def _flash_forward(q, k, v, causal, sm_scale, q_offset, block_q, block_k,
                   interpret):
    b, s_q, h, d = q.shape
    _, s_k, kv, _ = k.shape
    assert h % kv == 0, (h, kv)
    n_rep = h // kv
    block_q = min(block_q, s_q)
    block_k = min(block_k, s_k)
    assert s_q % block_q == 0 and s_k % block_k == 0, (s_q, s_k)
    scale = sm_scale if sm_scale is not None else d ** -0.5

    # [B, S, H, D] -> [B, H, S, D]: block maps want heads outermost
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    grid = (b, h, s_q // block_q, s_k // block_k)
    kernel = functools.partial(
        _flash_kernel, sm_scale=scale, causal=causal, q_offset=q_offset,
        block_q=block_q, block_k=block_k)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, qi, ki, n_rep=n_rep:
                         (bi, hi // n_rep, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, qi, ki, n_rep=n_rep:
                         (bi, hi // n_rep, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d),
                               lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, s_q, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),   # running max
            pltpu.VMEM((block_q, _LANES), jnp.float32),   # running denom
            pltpu.VMEM((block_q, d), jnp.float32),        # output acc
        ],
        compiler_params=pltpu.CompilerParams(
            # only the k axis carries state; batch/head/q-block tiles are
            # independent, letting Mosaic pipeline them
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"),
        ),
        interpret=interpret,
    )(qt, kt, vt)
    return out.transpose(0, 2, 1, 3)


def supports(q: jnp.ndarray, k: jnp.ndarray, *, kv_len=None,
             block_q: int = 128, block_k: int = 128) -> bool:
    """Whether the flash path can serve this call (else dense fallback)."""
    s_q, s_k = q.shape[1], k.shape[1]
    if kv_len is not None:
        return False  # padded decode caches use the dense path
    if q.shape[-1] > 256:
        return False  # head_dim beyond a VMEM-friendly tile
    return (s_q % min(block_q, s_q) == 0 and s_k % min(block_k, s_k) == 0
            and s_q >= 8 and s_k >= 128)
