"""Normalization ops (fp32 statistics, input-dtype output)."""

from __future__ import annotations

import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray,
             eps: float = 1e-5) -> jnp.ndarray:
    """RMSNorm over the last dim. Stats in fp32, result in x.dtype."""
    x32 = x.astype(jnp.float32)
    rms = jnp.sqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return ((x32 / rms) * weight.astype(jnp.float32)).astype(x.dtype)


def layer_norm(x: jnp.ndarray, weight: jnp.ndarray, bias: jnp.ndarray,
               eps: float = 1e-6) -> jnp.ndarray:
    """LayerNorm over the last dim. Stats in fp32, result in x.dtype."""
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean((x32 - mu) ** 2, axis=-1, keepdims=True)
    y = (x32 - mu) / jnp.sqrt(var + eps)
    return (y * weight.astype(jnp.float32)
            + bias.astype(jnp.float32)).astype(x.dtype)
