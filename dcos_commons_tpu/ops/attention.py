"""Dense grouped-query attention (the jit/GSPMD path).

Layout is [B, S, H, D] throughout (matching ``parallel.ring_attention`` and
``parallel.ulysses`` so the three attention impls are drop-in swappable).
Softmax is fp32; inputs/outputs ride in the caller's dtype (bf16 on TPU).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

_NEG = -1e30


def repeat_kv(kv: jnp.ndarray, n_rep: int) -> jnp.ndarray:
    """[B, S, KV, D] -> [B, S, KV*n_rep, D] by head-group broadcast."""
    if n_rep == 1:
        return kv
    b, s, h, d = kv.shape
    return jnp.broadcast_to(kv[:, :, :, None, :],
                            (b, s, h, n_rep, d)).reshape(b, s, h * n_rep, d)


def gqa_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                  causal: bool = True,
                  sm_scale: Optional[float] = None,
                  q_offset: int | jnp.ndarray = 0,
                  kv_len: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Attention with K/V head broadcast for GQA.

    q: [B, Sq, H, D]; k/v: [B, Sk, KV, D] with H % KV == 0.
    ``q_offset`` shifts query positions (decode: Sq=1, offset=cache
    length); a scalar applies to every row, a [B] vector per row (the
    paged speculative verify — every stream's K-token window starts at
    its own length). ``kv_len`` optionally masks out cache slots >=
    kv_len (padded KV cache); a scalar applies to every row, a [B]
    vector per slot (the continuous-batching decode step).
    """
    n_rep = q.shape[2] // k.shape[2]
    k, v = repeat_kv(k, n_rep), repeat_kv(v, n_rep)
    d = q.shape[-1]
    scale = sm_scale if sm_scale is not None else d ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32) * scale,
                        k.astype(jnp.float32))
    s_q, s_k = scores.shape[-2], scores.shape[-1]
    # mask broadcasts against scores [B, H, Sq, Sk]
    mask = None
    if causal:
        off = jnp.asarray(q_offset, jnp.int32)
        # [B|1, Sq, 1]: a scalar offset reshapes to [1, 1, 1] and this
        # reduces to the classic shared causal mask
        q_pos = (off.reshape(-1, 1, 1)
                 + lax.iota(jnp.int32, s_q)[None, :, None])
        mask = (q_pos >= lax.iota(jnp.int32, s_k)[None, None, :])[:, None]
    if kv_len is not None:
        kvl = jnp.asarray(kv_len).reshape(-1, 1, 1, 1)  # [B or 1,1,1,1]
        valid = lax.iota(jnp.int32, s_k)[None, None, None, :] < kvl
        mask = valid if mask is None else (mask & valid)
    if mask is not None:
        scores = jnp.where(mask, scores, _NEG)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)
