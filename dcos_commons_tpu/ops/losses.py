"""Loss functions (fp32 reductions, optional z-loss stabilizer).

Two cross-entropy entry points:

* :func:`softmax_cross_entropy` — the reference: consumes materialized
  logits ``[..., V]``. Fine for classifier heads (V ~ 1e3); at LM vocab
  sizes the fp32 logits tensor dominates the train step's HBM traffic.
* :func:`fused_linear_cross_entropy` — fuses the lm_head projection INTO
  the loss: chunks the sequence, computes ``x_blk @ lm_head`` ->
  blockwise logsumexp -> NLL inside a ``lax.scan``, with a
  ``jax.custom_vjp`` backward that *recomputes* each block's logits from
  the saved per-token logsumexp (the flash-attention recomputation idea
  applied to the loss head — cf. ``ops/flash_attention.py``). The full
  ``[B, S, V]`` fp32 tensor never exists in either direction; peak
  scratch is one ``[B, block, V]`` tile.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dcos_commons_tpu.ops.quant import QTensor, qmm


def softmax_cross_entropy(logits: jnp.ndarray, labels: jnp.ndarray, *,
                          mask: Optional[jnp.ndarray] = None,
                          z_loss: float = 0.0,
                          compute_accuracy: bool = True
                          ) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """Mean token cross-entropy. logits [..., V], labels [...] int32.

    Returns (loss, accuracy). ``z_loss`` adds the usual log-Z^2 penalty that
    keeps bf16 logits from drifting (weight is typically 1e-4).
    ``compute_accuracy=False`` returns (loss, None) and skips the full-vocab
    argmax — a second full read of the logits tensor that loss-only callers
    (evaluation loops that only track loss, the z-loss probe) never use.
    """
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    true_logit = jnp.take_along_axis(logits, labels[..., None],
                                     axis=-1)[..., 0]
    nll = logz - true_logit
    if z_loss:
        nll = nll + z_loss * logz ** 2
    correct = ((jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32)
               if compute_accuracy else None)
    if mask is not None:
        m = mask.astype(jnp.float32)
        denom = jnp.maximum(m.sum(), 1.0)
        return ((nll * m).sum() / denom,
                (correct * m).sum() / denom if compute_accuracy else None)
    return nll.mean(), correct.mean() if compute_accuracy else None


# ---------------------------------------------------------------------------
# fused linear + cross-entropy


def _seq_blocks(a: jnp.ndarray, block: int) -> jnp.ndarray:
    """[B, S, ...] -> [S/block, B, block, ...] (scan-major block stack)."""
    b, s = a.shape[:2]
    return a.reshape((b, s // block, block) + a.shape[2:]).swapaxes(0, 1)


def _block_logits(xb: jnp.ndarray, w) -> jnp.ndarray:
    """One block's logits in fp32: [B, blk, D] @ [D, V] -> [B, blk, V].
    ``w`` may be a plain array or an int8 :class:`QTensor` (qmm fuses the
    dequant into the weight load either way)."""
    return qmm(xb, w).astype(jnp.float32)


def _dx_block(dlog: jnp.ndarray, w, dtype) -> jnp.ndarray:
    """dlogits [B, blk, V] -> dx [B, blk, D] against plain or quantized
    ``w``, fp32 accumulation. Quantized: ``W.T == q.T * s_row``, so scale
    the cotangent per vocab column and matmul the int8 payload — no
    dequantized [D, V] copy."""
    if isinstance(w, QTensor):
        srow = jnp.squeeze(w.s, axis=-2).astype(jnp.float32)     # [V]
        dx = (dlog * srow) @ w.q.astype(jnp.float32).T
    else:
        dx = dlog @ w.astype(jnp.float32).T
    return dx.astype(dtype)


def _fused_lce_impl(x, w, labels, maskf, z_loss, block, compute_acc):
    """Forward: scan sequence blocks, accumulate masked NLL / correct
    counts; returns (loss, acc, per-token logz [n, B, blk]) — logz is the
    only O(S) residual the backward needs."""
    xs = _seq_blocks(x, block)
    ls = _seq_blocks(labels, block)
    ms = _seq_blocks(maskf, block)

    def body(carry, inp):
        nll_sum, cor_sum = carry
        xb, lb, mb = inp
        logits = _block_logits(xb, w)                      # [B, blk, V]
        logz = jax.nn.logsumexp(logits, axis=-1)           # [B, blk]
        true_logit = jnp.take_along_axis(logits, lb[..., None],
                                         axis=-1)[..., 0]
        nll = logz - true_logit
        if z_loss:
            nll = nll + z_loss * logz ** 2
        nll_sum = nll_sum + (nll * mb).sum()
        if compute_acc:
            correct = (jnp.argmax(logits, axis=-1) == lb)
            cor_sum = cor_sum + (correct.astype(jnp.float32) * mb).sum()
        return (nll_sum, cor_sum), logz

    zero = jnp.zeros((), jnp.float32)
    (nll_sum, cor_sum), logz = lax.scan(body, (zero, zero), (xs, ls, ms))
    denom = jnp.maximum(maskf.sum(), 1.0)
    return nll_sum / denom, cor_sum / denom, (logz, denom)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _fused_lce(x, w, labels, maskf, z_loss, block, compute_acc):
    loss, acc, _ = _fused_lce_impl(x, w, labels, maskf, z_loss, block,
                                   compute_acc)
    return loss, acc


def _fused_lce_fwd(x, w, labels, maskf, z_loss, block, compute_acc):
    loss, acc, (logz, denom) = _fused_lce_impl(x, w, labels, maskf,
                                               z_loss, block, compute_acc)
    return (loss, acc), (x, w, labels, maskf, logz, denom)


def _fused_lce_bwd(z_loss, block, compute_acc, res, g):
    """Recompute each block's logits from the saved logsumexp (never the
    full [B, S, V]); per-token cotangent is
    ``p_j * (1 + 2*z*logz) - onehot_j`` scaled by ``g * mask / denom``.
    The accuracy output's cotangent is dropped — argmax is
    piecewise-constant, exactly as the unfused path's autodiff sees it.
    """
    x, w, labels, maskf, logz, denom = res
    g_loss = g[0]
    xs = _seq_blocks(x, block)
    ls = _seq_blocks(labels, block)
    ms = _seq_blocks(maskf, block)
    plain_w = not isinstance(w, QTensor)
    scale = (g_loss / denom).astype(jnp.float32)

    def body(dw_acc, inp):
        xb, lb, mb, lzb = inp
        logits = _block_logits(xb, w)
        p = jnp.exp(logits - lzb[..., None])               # softmax, f32
        if z_loss:
            p = p * (1.0 + (2.0 * z_loss) * lzb)[..., None]
        dlog = p - jax.nn.one_hot(lb, logits.shape[-1], dtype=jnp.float32)
        dlog = dlog * (scale * mb)[..., None]              # [B, blk, V]
        dxb = _dx_block(dlog, w, x.dtype)
        if plain_w:
            dw_acc = dw_acc + jnp.einsum(
                "bsd,bsv->dv", xb.astype(jnp.float32), dlog)
        return dw_acc, dxb

    dw0 = (jnp.zeros(w.shape, jnp.float32) if plain_w
           else jnp.zeros((), jnp.float32))
    dw_acc, dxs = lax.scan(body, dw0, (xs, ls, ms, logz))
    dx = dxs.swapaxes(0, 1).reshape(x.shape)
    if plain_w:
        dw = dw_acc.astype(w.dtype)
    else:
        # int8 payloads carry no tangent space (float0); scales are
        # treated as frozen calibration constants
        dw = QTensor(np.zeros(w.q.shape, dtype=jax.dtypes.float0),
                     jnp.zeros_like(w.s))
    return (dx, dw,
            np.zeros(labels.shape, dtype=jax.dtypes.float0),
            jnp.zeros_like(maskf))


_fused_lce.defvjp(_fused_lce_fwd, _fused_lce_bwd)


def fused_linear_cross_entropy(x: jnp.ndarray, lm_head, labels: jnp.ndarray,
                               *, mask: Optional[jnp.ndarray] = None,
                               z_loss: float = 0.0, block_size: int = 512,
                               compute_accuracy: bool = True
                               ) -> Tuple[jnp.ndarray,
                                          Optional[jnp.ndarray]]:
    """Cross-entropy of ``x @ lm_head`` WITHOUT materializing the logits.

    ``x`` [..., S, D] (final-norm hidden states), ``lm_head`` [D, V]
    (plain array or int8 :class:`~dcos_commons_tpu.ops.quant.QTensor`),
    ``labels`` [..., S] int32. Semantics match
    ``softmax_cross_entropy(qmm(x, lm_head).astype(f32), labels, ...)``
    exactly: masked mean NLL (+ z-loss) and argmax accuracy, but the
    sequence is processed in ``block_size`` chunks so peak logits scratch
    is ``[B, block_size, V]`` fp32 instead of ``[B, S, V]`` — at Llama-3
    vocab (V=128256) that is the difference between ~4 GB and ~128 MB
    per direction (docs/performance.md "HBM traffic on the loss head").

    The backward recomputes per-block logits from the saved per-token
    logsumexp (O(S) residual). Differentiable w.r.t. ``x`` and a plain
    ``lm_head``; quantized heads get cotangent only through ``x``; the
    mask is non-differentiable. ``S % block_size != 0`` is handled by
    masked padding. Under a ``tp``-sharded lm_head the per-block
    reductions partition over the vocab axis like the unfused loss did.
    """
    lead = x.shape[:-2]
    s, d = x.shape[-2], x.shape[-1]
    b = int(np.prod(lead)) if lead else 1
    xf = x.reshape((b, s, d))
    lab = labels.reshape((b, s))
    maskf = (jnp.ones((b, s), jnp.float32) if mask is None
             else mask.reshape((b, s)).astype(jnp.float32))
    block = max(1, min(int(block_size), s))
    pad = -s % block
    if pad:
        xf = jnp.pad(xf, ((0, 0), (0, pad), (0, 0)))
        lab = jnp.pad(lab, ((0, 0), (0, pad)))
        maskf = jnp.pad(maskf, ((0, 0), (0, pad)))   # pads never count
    loss, acc = _fused_lce(xf, lm_head, lab, maskf, float(z_loss), block,
                           bool(compute_accuracy))
    return loss, (acc if compute_accuracy else None)
