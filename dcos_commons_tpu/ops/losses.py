"""Loss functions (fp32 reductions, optional z-loss stabilizer).

Three loss-head entry points:

* :func:`softmax_cross_entropy` — the reference: consumes materialized
  logits ``[..., V]``. Fine for classifier heads (V ~ 1e3); at LM vocab
  sizes the fp32 logits tensor dominates the train step's HBM traffic.
* :func:`fused_linear_cross_entropy` — fuses the lm_head projection INTO
  the loss: chunks the sequence, computes ``x_blk @ lm_head`` ->
  blockwise logsumexp -> NLL inside a ``lax.scan``, with a
  ``jax.custom_vjp`` backward that *recomputes* each block's logits from
  the saved per-token logsumexp (the flash-attention recomputation idea
  applied to the loss head — cf. ``ops/flash_attention.py``). The full
  ``[B, S, V]`` fp32 tensor never exists in either direction; peak
  scratch is one ``[B, block, V]`` tile.
* :func:`fused_linear_distillation` — the same blockwise machinery for
  the distillation head: KL(teacher ‖ student) of ``x_s @ head_s``
  against a FROZEN ``x_t @ head_t``, both projections running inside
  the sequence-chunked loop so neither model's ``[B, S, V]`` fp32
  logits ever materializes (at Llama-3 vocab the teacher tensor alone
  would double the train step's loss-head HBM traffic). Gradients flow
  to the student only; the teacher side is structurally stop-gradient.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dcos_commons_tpu.ops.quant import QTensor, qmm


def softmax_cross_entropy(logits: jnp.ndarray, labels: jnp.ndarray, *,
                          mask: Optional[jnp.ndarray] = None,
                          z_loss: float = 0.0,
                          compute_accuracy: bool = True
                          ) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """Mean token cross-entropy. logits [..., V], labels [...] int32.

    Returns (loss, accuracy). ``z_loss`` adds the usual log-Z^2 penalty that
    keeps bf16 logits from drifting (weight is typically 1e-4).
    ``compute_accuracy=False`` returns (loss, None) and skips the full-vocab
    argmax — a second full read of the logits tensor that loss-only callers
    (evaluation loops that only track loss, the z-loss probe) never use.
    """
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    true_logit = jnp.take_along_axis(logits, labels[..., None],
                                     axis=-1)[..., 0]
    nll = logz - true_logit
    if z_loss:
        nll = nll + z_loss * logz ** 2
    correct = ((jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32)
               if compute_accuracy else None)
    if mask is not None:
        m = mask.astype(jnp.float32)
        denom = jnp.maximum(m.sum(), 1.0)
        return ((nll * m).sum() / denom,
                (correct * m).sum() / denom if compute_accuracy else None)
    return nll.mean(), correct.mean() if compute_accuracy else None


# ---------------------------------------------------------------------------
# fused linear + cross-entropy


def _seq_blocks(a: jnp.ndarray, block: int) -> jnp.ndarray:
    """[B, S, ...] -> [S/block, B, block, ...] (scan-major block stack)."""
    b, s = a.shape[:2]
    return a.reshape((b, s // block, block) + a.shape[2:]).swapaxes(0, 1)


def _block_logits(xb: jnp.ndarray, w) -> jnp.ndarray:
    """One block's logits in fp32: [B, blk, D] @ [D, V] -> [B, blk, V].
    ``w`` may be a plain array or an int8 :class:`QTensor` (qmm fuses the
    dequant into the weight load either way)."""
    return qmm(xb, w).astype(jnp.float32)


def _dx_block(dlog: jnp.ndarray, w, dtype) -> jnp.ndarray:
    """dlogits [B, blk, V] -> dx [B, blk, D] against plain or quantized
    ``w``, fp32 accumulation. Quantized: ``W.T == q.T * s_row``, so scale
    the cotangent per vocab column and matmul the int8 payload — no
    dequantized [D, V] copy."""
    if isinstance(w, QTensor):
        srow = jnp.squeeze(w.s, axis=-2).astype(jnp.float32)     # [V]
        dx = (dlog * srow) @ w.q.astype(jnp.float32).T
    else:
        dx = dlog @ w.astype(jnp.float32).T
    return dx.astype(dtype)


def _fused_lce_impl(x, w, labels, maskf, z_loss, block, compute_acc):
    """Forward: scan sequence blocks, accumulate masked NLL / correct
    counts; returns (loss, acc, per-token logz [n, B, blk]) — logz is the
    only O(S) residual the backward needs."""
    xs = _seq_blocks(x, block)
    ls = _seq_blocks(labels, block)
    ms = _seq_blocks(maskf, block)

    def body(carry, inp):
        nll_sum, cor_sum = carry
        xb, lb, mb = inp
        logits = _block_logits(xb, w)                      # [B, blk, V]
        logz = jax.nn.logsumexp(logits, axis=-1)           # [B, blk]
        true_logit = jnp.take_along_axis(logits, lb[..., None],
                                         axis=-1)[..., 0]
        nll = logz - true_logit
        if z_loss:
            nll = nll + z_loss * logz ** 2
        nll_sum = nll_sum + (nll * mb).sum()
        if compute_acc:
            correct = (jnp.argmax(logits, axis=-1) == lb)
            cor_sum = cor_sum + (correct.astype(jnp.float32) * mb).sum()
        return (nll_sum, cor_sum), logz

    zero = jnp.zeros((), jnp.float32)
    (nll_sum, cor_sum), logz = lax.scan(body, (zero, zero), (xs, ls, ms))
    denom = jnp.maximum(maskf.sum(), 1.0)
    return nll_sum / denom, cor_sum / denom, (logz, denom)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _fused_lce(x, w, labels, maskf, z_loss, block, compute_acc):
    loss, acc, _ = _fused_lce_impl(x, w, labels, maskf, z_loss, block,
                                   compute_acc)
    return loss, acc


def _fused_lce_fwd(x, w, labels, maskf, z_loss, block, compute_acc):
    loss, acc, (logz, denom) = _fused_lce_impl(x, w, labels, maskf,
                                               z_loss, block, compute_acc)
    return (loss, acc), (x, w, labels, maskf, logz, denom)


def _fused_lce_bwd(z_loss, block, compute_acc, res, g):
    """Recompute each block's logits from the saved logsumexp (never the
    full [B, S, V]); per-token cotangent is
    ``p_j * (1 + 2*z*logz) - onehot_j`` scaled by ``g * mask / denom``.
    The accuracy output's cotangent is dropped — argmax is
    piecewise-constant, exactly as the unfused path's autodiff sees it.
    """
    x, w, labels, maskf, logz, denom = res
    g_loss = g[0]
    xs = _seq_blocks(x, block)
    ls = _seq_blocks(labels, block)
    ms = _seq_blocks(maskf, block)
    plain_w = not isinstance(w, QTensor)
    scale = (g_loss / denom).astype(jnp.float32)

    def body(dw_acc, inp):
        xb, lb, mb, lzb = inp
        logits = _block_logits(xb, w)
        p = jnp.exp(logits - lzb[..., None])               # softmax, f32
        if z_loss:
            p = p * (1.0 + (2.0 * z_loss) * lzb)[..., None]
        dlog = p - jax.nn.one_hot(lb, logits.shape[-1], dtype=jnp.float32)
        dlog = dlog * (scale * mb)[..., None]              # [B, blk, V]
        dxb = _dx_block(dlog, w, x.dtype)
        if plain_w:
            dw_acc = dw_acc + jnp.einsum(
                "bsd,bsv->dv", xb.astype(jnp.float32), dlog)
        return dw_acc, dxb

    dw0 = (jnp.zeros(w.shape, jnp.float32) if plain_w
           else jnp.zeros((), jnp.float32))
    dw_acc, dxs = lax.scan(body, dw0, (xs, ls, ms, logz))
    dx = dxs.swapaxes(0, 1).reshape(x.shape)
    if plain_w:
        dw = dw_acc.astype(w.dtype)
    else:
        # int8 payloads carry no tangent space (float0); scales are
        # treated as frozen calibration constants
        dw = QTensor(np.zeros(w.q.shape, dtype=jax.dtypes.float0),
                     jnp.zeros_like(w.s))
    return (dx, dw,
            np.zeros(labels.shape, dtype=jax.dtypes.float0),
            jnp.zeros_like(maskf))


_fused_lce.defvjp(_fused_lce_fwd, _fused_lce_bwd)


def fused_linear_cross_entropy(x: jnp.ndarray, lm_head, labels: jnp.ndarray,
                               *, mask: Optional[jnp.ndarray] = None,
                               z_loss: float = 0.0, block_size: int = 512,
                               compute_accuracy: bool = True
                               ) -> Tuple[jnp.ndarray,
                                          Optional[jnp.ndarray]]:
    """Cross-entropy of ``x @ lm_head`` WITHOUT materializing the logits.

    ``x`` [..., S, D] (final-norm hidden states), ``lm_head`` [D, V]
    (plain array or int8 :class:`~dcos_commons_tpu.ops.quant.QTensor`),
    ``labels`` [..., S] int32. Semantics match
    ``softmax_cross_entropy(qmm(x, lm_head).astype(f32), labels, ...)``
    exactly: masked mean NLL (+ z-loss) and argmax accuracy, but the
    sequence is processed in ``block_size`` chunks so peak logits scratch
    is ``[B, block_size, V]`` fp32 instead of ``[B, S, V]`` — at Llama-3
    vocab (V=128256) that is the difference between ~4 GB and ~128 MB
    per direction (docs/performance.md "HBM traffic on the loss head").

    The backward recomputes per-block logits from the saved per-token
    logsumexp (O(S) residual). Differentiable w.r.t. ``x`` and a plain
    ``lm_head``; quantized heads get cotangent only through ``x``; the
    mask is non-differentiable. ``S % block_size != 0`` is handled by
    masked padding. Under a ``tp``-sharded lm_head the per-block
    reductions partition over the vocab axis like the unfused loss did.
    """
    lead = x.shape[:-2]
    s, d = x.shape[-2], x.shape[-1]
    b = int(np.prod(lead)) if lead else 1
    xf = x.reshape((b, s, d))
    lab = labels.reshape((b, s))
    maskf = (jnp.ones((b, s), jnp.float32) if mask is None
             else mask.reshape((b, s)).astype(jnp.float32))
    block = max(1, min(int(block_size), s))
    pad = -s % block
    if pad:
        xf = jnp.pad(xf, ((0, 0), (0, pad), (0, 0)))
        lab = jnp.pad(lab, ((0, 0), (0, pad)))
        maskf = jnp.pad(maskf, ((0, 0), (0, pad)))   # pads never count
    loss, acc = _fused_lce(xf, lm_head, lab, maskf, float(z_loss), block,
                           bool(compute_accuracy))
    return loss, (acc if compute_accuracy else None)


# ---------------------------------------------------------------------------
# fused linear + KL distillation (teacher logits never materialized)


def softmax_kl_divergence(logits_s: jnp.ndarray, logits_t: jnp.ndarray, *,
                          mask: Optional[jnp.ndarray] = None,
                          temperature: float = 1.0) -> jnp.ndarray:
    """Reference distillation loss on MATERIALIZED logits: masked mean
    per-token ``KL(softmax(logits_t/T) || softmax(logits_s/T))``. The
    fused head is parity-tested against this at small vocab; real train
    steps must use :func:`fused_linear_distillation` (J1 budget)."""
    inv = 1.0 / temperature
    zs = logits_s.astype(jnp.float32) * inv
    zt = logits_t.astype(jnp.float32) * inv
    lzs = jax.nn.logsumexp(zs, axis=-1)
    lzt = jax.nn.logsumexp(zt, axis=-1)
    pt = jnp.exp(zt - lzt[..., None])
    kl = (lzs - lzt) + ((zt - zs) * pt).sum(axis=-1)
    if mask is not None:
        m = mask.astype(jnp.float32)
        return (kl * m).sum() / jnp.maximum(m.sum(), 1.0)
    return kl.mean()


def _zero_head_cotangent(w):
    """A frozen projection head's cotangent: float0 for int8 payloads
    (no tangent space), zeros elsewhere — the QTensor convention
    :func:`_fused_lce_bwd` established."""
    if isinstance(w, QTensor):
        return QTensor(np.zeros(w.q.shape, dtype=jax.dtypes.float0),
                       jnp.zeros_like(w.s))
    return jnp.zeros_like(w)


def _fused_kl_impl(xs_s, w_s, xs_t, w_t, maskf, temp, block):
    """Forward: scan sequence blocks; each block projects BOTH hidden
    states to logits tiles, reduces the per-token KL, and keeps only the
    two logsumexp rows — the O(S) residual the backward rebuilds the
    softmaxes from."""
    ss = _seq_blocks(xs_s, block)
    ts = _seq_blocks(xs_t, block)
    ms = _seq_blocks(maskf, block)
    inv = 1.0 / temp

    def body(kl_sum, inp):
        xb_s, xb_t, mb = inp
        zs = _block_logits(xb_s, w_s) * inv            # [B, blk, V]
        zt = _block_logits(xb_t, w_t) * inv
        lzs = jax.nn.logsumexp(zs, axis=-1)            # [B, blk]
        lzt = jax.nn.logsumexp(zt, axis=-1)
        pt = jnp.exp(zt - lzt[..., None])
        kl = (lzs - lzt) + ((zt - zs) * pt).sum(axis=-1)
        return kl_sum + (kl * mb).sum(), (lzs, lzt)

    kl_sum, (lzs, lzt) = lax.scan(body, jnp.zeros((), jnp.float32),
                                  (ss, ts, ms))
    denom = jnp.maximum(maskf.sum(), 1.0)
    return kl_sum / denom, (lzs, lzt, denom)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def _fused_kl(xs_s, w_s, xs_t, w_t, maskf, temp, block):
    loss, _ = _fused_kl_impl(xs_s, w_s, xs_t, w_t, maskf, temp, block)
    return loss


def _fused_kl_fwd(xs_s, w_s, xs_t, w_t, maskf, temp, block):
    loss, (lzs, lzt, denom) = _fused_kl_impl(xs_s, w_s, xs_t, w_t, maskf,
                                             temp, block)
    return loss, (xs_s, w_s, xs_t, w_t, maskf, lzs, lzt, denom)


def _fused_kl_bwd(temp, block, res, g):
    """Recompute both blocks' logits from the saved logsumexps; the
    per-token student-logit cotangent is the classic distillation
    gradient ``(softmax_s - softmax_t) / T`` scaled by ``g * mask /
    denom``. The teacher inputs get structural zeros — KL is minimized
    OVER the student, the teacher is a frozen reference (the workload
    additionally wraps it in stop_gradient; this makes the contract
    hold even without the wrap)."""
    xs_s, w_s, xs_t, w_t, maskf, lzs, lzt, denom = res
    ss = _seq_blocks(xs_s, block)
    ts = _seq_blocks(xs_t, block)
    ms = _seq_blocks(maskf, block)
    inv = 1.0 / temp
    plain_ws = not isinstance(w_s, QTensor)
    scale = (g / denom).astype(jnp.float32) * inv

    def body(dw_acc, inp):
        xb_s, xb_t, mb, lzs_b, lzt_b = inp
        zs = _block_logits(xb_s, w_s) * inv
        zt = _block_logits(xb_t, w_t) * inv
        p_s = jnp.exp(zs - lzs_b[..., None])
        p_t = jnp.exp(zt - lzt_b[..., None])
        dlog = (p_s - p_t) * (scale * mb)[..., None]   # [B, blk, V]
        dxb = _dx_block(dlog, w_s, xs_s.dtype)
        if plain_ws:
            dw_acc = dw_acc + jnp.einsum(
                "bsd,bsv->dv", xb_s.astype(jnp.float32), dlog)
        return dw_acc, dxb

    dw0 = (jnp.zeros(w_s.shape, jnp.float32) if plain_ws
           else jnp.zeros((), jnp.float32))
    dw_acc, dxs = lax.scan(body, dw0, (ss, ts, ms, lzs, lzt))
    dx_s = dxs.swapaxes(0, 1).reshape(xs_s.shape)
    dw_s = (dw_acc.astype(w_s.dtype) if plain_ws
            else _zero_head_cotangent(w_s))
    return (dx_s, dw_s, jnp.zeros_like(xs_t), _zero_head_cotangent(w_t),
            jnp.zeros_like(maskf))


_fused_kl.defvjp(_fused_kl_fwd, _fused_kl_bwd)


def fused_linear_distillation(x_s: jnp.ndarray, head_s, x_t: jnp.ndarray,
                              head_t, *,
                              mask: Optional[jnp.ndarray] = None,
                              temperature: float = 1.0,
                              block_size: int = 512) -> jnp.ndarray:
    """KL(teacher ‖ student) of ``x_s @ head_s`` vs ``x_t @ head_t``
    WITHOUT materializing either logits tensor.

    ``x_s``/``x_t`` [..., S, D_s]/[..., S, D_t] (final-norm hidden
    states — the dims may differ, only the vocab must match),
    ``head_s``/``head_t`` [D, V] (plain arrays or int8
    :class:`~dcos_commons_tpu.ops.quant.QTensor`). Semantics match
    ``softmax_kl_divergence(x_s @ head_s, x_t @ head_t, ...)`` exactly,
    but the sequence is processed in ``block_size`` chunks so peak
    logits scratch is two ``[B, block, V]`` fp32 tiles instead of two
    full ``[B, S, V]`` tensors — the distill train step's J1 budget
    (analysis/entrypoints.py) is set just below the materialized-teacher
    size, so a regression that materializes either tensor fails the
    lint, not just the profile.

    Differentiable w.r.t. ``x_s`` and a plain ``head_s`` ONLY: the
    teacher side (``x_t``, ``head_t``) gets structural zero cotangents,
    making the head safe even without an explicit ``stop_gradient`` on
    the teacher forward. ``temperature`` tempers BOTH distributions
    (standard Hinton distillation; gradients carry the 1/T factor).
    """
    if x_s.shape[:-1] != x_t.shape[:-1]:
        raise ValueError(f"student/teacher token shapes differ: "
                         f"{x_s.shape[:-1]} vs {x_t.shape[:-1]}")
    if temperature <= 0.0:
        raise ValueError(f"temperature must be > 0, got {temperature}")
    lead = x_s.shape[:-2]
    s = x_s.shape[-2]
    b = int(np.prod(lead)) if lead else 1
    xs_s = x_s.reshape((b, s, x_s.shape[-1]))
    xs_t = x_t.reshape((b, s, x_t.shape[-1]))
    maskf = (jnp.ones((b, s), jnp.float32) if mask is None
             else mask.reshape((b, s)).astype(jnp.float32))
    block = max(1, min(int(block_size), s))
    pad = -s % block
    if pad:
        xs_s = jnp.pad(xs_s, ((0, 0), (0, pad), (0, 0)))
        xs_t = jnp.pad(xs_t, ((0, 0), (0, pad), (0, 0)))
        maskf = jnp.pad(maskf, ((0, 0), (0, pad)))   # pads never count
    return _fused_kl(xs_s, head_s, xs_t, head_t, maskf,
                     float(temperature), block)
