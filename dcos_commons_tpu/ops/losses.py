"""Loss functions (fp32 reductions, optional z-loss stabilizer)."""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def softmax_cross_entropy(logits: jnp.ndarray, labels: jnp.ndarray, *,
                          mask: Optional[jnp.ndarray] = None,
                          z_loss: float = 0.0
                          ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Mean token cross-entropy. logits [..., V], labels [...] int32.

    Returns (loss, accuracy). ``z_loss`` adds the usual log-Z^2 penalty that
    keeps bf16 logits from drifting (weight is typically 1e-4).
    """
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    true_logit = jnp.take_along_axis(logits, labels[..., None],
                                     axis=-1)[..., 0]
    nll = logz - true_logit
    if z_loss:
        nll = nll + z_loss * logz ** 2
    correct = (jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32)
    if mask is not None:
        m = mask.astype(jnp.float32)
        denom = jnp.maximum(m.sum(), 1.0)
        return (nll * m).sum() / denom, (correct * m).sum() / denom
    return nll.mean(), correct.mean()
