"""Pallas TPU decode attention: one query position against a padded KV
cache, bf16 or int8.

The dense decode path (``ops.attention.gqa_attention`` called from
``models/llama.py:decode_step``) pays three HBM taxes the pallas kernel
removes, each a full-cache-sized read or write per decode step:

* ``repeat_kv`` materializes the GQA head broadcast;
* the fp32 cast of the whole cache for the score einsum;
* for int8 caches, the dequantized bf16 copy.

Kernel design (vs. ``ops.flash_attention``, which it follows closely):

* **GQA group as MXU rows.** A decode step has Sq == 1, useless as a
  matmul row count. But all H/KV query heads of one KV group attend to
  the SAME K/V rows, so the kernel tiles q as [group, D] and runs
  [group, D] @ [D, block_k] per KV head — the head broadcast becomes the
  matmul's row axis and never touches HBM (rows pad to the 8-sublane
  minimum).
* **Per-row scales fold into the lanes axis.** With symmetric per-row
  int8 scales, q.(s*kq_row) == (q.kq_row)*s and p@(s*vq) == (p*s)@vq:
  both corrections are lane-wise multiplies on the [group, block_k]
  score/probability tile, so scale vectors are consumed in their stored
  orientation — no transposes, and the int8 payload feeds the MXU
  straight from VMEM.
* **Live-length block skipping.** ``kv_len`` arrives by scalar prefetch;
  k-blocks at or beyond it are skipped with ``pl.when`` AND their index
  maps clamp to the last live block — Mosaic elides the DMA when a
  block's index repeats, so a 32-slot conversation in a 2048-slot cache
  streams ~1/64th of it. Per-step cost tracks kv_len, not max_seq.

Layout: q [B, 1, H, D]; k/v [B, S, KV, D] (QTensor for int8: payload +
[B, S, KV, 1] scales). Output [B, 1, H, D]. Requires D % 128 == 0 and
S % 128 == 0 (``supports_decode``); callers fall back to dense.
"""

from __future__ import annotations

import functools
from typing import Optional, Union

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dcos_commons_tpu import _jax_compat  # noqa: F401  (installs renames)
from dcos_commons_tpu.ops.quant import QTensor

_NEG = -1e30
_LANES = 128
_SUBLANES = 8


def _decode_kernel(kv_len_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref,
                   m_scr, l_scr, acc_scr, *, sm_scale: float,
                   block_k: int, quantized: bool):
    ik = pl.program_id(2)
    n_k = pl.num_programs(2)
    kv_len = kv_len_ref[pl.program_id(0)]       # per-slot live length

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(ik * block_k < kv_len)
    def _body():
        q = q_ref[0, 0]                                  # [gp, d] bf16
        k = k_ref[0, 0]                                  # [bk, d] i8/bf16
        s = jax.lax.dot_general(                         # [gp, bk] f32
            q, k.astype(q.dtype), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if quantized:
            # (q . kq_row) * s_row: per-row scale lands on the lanes axis
            s = s * ks_ref[0, 0][:1].astype(jnp.float32)
        # mask cache slots at/after the live length
        pos = ik * block_k + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        mask = pos < kv_len
        s = jnp.where(mask, s, _NEG)

        m_prev = m_scr[:, :1]
        l_prev = l_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        p = jnp.where(mask, p, 0.0)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        if quantized:
            # p @ (s_row * vq) == (p * s_row) @ vq
            p = p * vs_ref[0, 0][:1].astype(jnp.float32)
        v = v_ref[0, 0]                                  # [bk, d]
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(jnp.bfloat16), v.astype(jnp.bfloat16),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ik == n_k - 1)
    def _finish():
        l = l_scr[:, :1]
        safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scr[:] / safe).astype(o_ref.dtype)


def _clamped(block_k: int):
    """Index map component: clamp dead k-blocks to the last live one so
    Mosaic sees a repeated index and skips their DMAs entirely."""
    def clamp(bi, ki, kv_len_ref):
        last_live = jax.lax.div(
            jnp.maximum(kv_len_ref[bi] - 1, 0), block_k)
        return jnp.minimum(ki, last_live)
    return clamp


@functools.partial(
    jax.jit,
    static_argnames=("sm_scale", "block_k", "interpret"))
def flash_decode(q: jnp.ndarray, k: Union[jnp.ndarray, QTensor],
                 v: Union[jnp.ndarray, QTensor], kv_len: jnp.ndarray, *,
                 sm_scale: Optional[float] = None, block_k: int = 512,
                 interpret: bool = False) -> jnp.ndarray:
    """Decode-step attention against a padded cache; see module doc.

    Drop-in for ``gqa_attention(q, k, v, causal=False, q_offset=pos,
    kv_len=pos+1)`` with Sq == 1 (the single new position attends to
    every live cache slot, so no causal structure remains to exploit).
    """
    b, s_q, h, d = q.shape
    assert s_q == 1, "flash_decode serves single-position decode steps"
    quantized = isinstance(k, QTensor)
    kq, ks = (k.q, k.s) if quantized else (k, None)
    vq, vs = (v.q, v.s) if quantized else (v, None)
    _, s_k, kv, _ = kq.shape
    assert h % kv == 0, (h, kv)
    group = h // kv
    gp = -(-group // _SUBLANES) * _SUBLANES          # pad to sublanes
    # largest power-of-two block <= requested that divides s_k, floored
    # at one lane width — any s_k % 128 == 0 cache gets a legal block
    block_k = 1 << (min(block_k, s_k).bit_length() - 1)
    while block_k > _LANES and s_k % block_k:
        block_k //= 2
    assert s_k % block_k == 0 and d % _LANES == 0, (s_k, d)
    scale = sm_scale if sm_scale is not None else d ** -0.5
    # scalar kv_len broadcasts to every slot; a [B] vector is per-slot
    # (continuous batching: each slot at its own conversation length)
    kv_len = jnp.asarray(kv_len, jnp.int32)
    kv_len = jnp.broadcast_to(kv_len.reshape(-1), (b,))

    # q: [B, 1, H, D] -> [B, KV, gp, D] (group heads as matmul rows)
    qg = q[:, 0].reshape(b, kv, group, d)
    if gp != group:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, gp - group), (0, 0)))
    # caches: [B, S, KV, D] -> [B, KV, S, D]
    kt = kq.transpose(0, 2, 1, 3)
    vt = vq.transpose(0, 2, 1, 3)
    if quantized:
        # scales ride as [B, KV, 8, S] tiles (8 identical sublanes, the
        # lse-tile trick: TPU blocks want sublanes % 8)
        kst = jnp.broadcast_to(ks[..., 0].transpose(0, 2, 1)[:, :, None, :],
                               (b, kv, _SUBLANES, s_k))
        vst = jnp.broadcast_to(vs[..., 0].transpose(0, 2, 1)[:, :, None, :],
                               (b, kv, _SUBLANES, s_k))
    else:
        kst = vst = jnp.zeros((b, kv, _SUBLANES, _LANES), jnp.bfloat16)

    clamp = _clamped(block_k)
    n_blocks = s_k // block_k
    scale_block = block_k if quantized else _LANES

    def k_map(bi, hi, ki, kv_len_ref):
        return (bi, hi, clamp(bi, ki, kv_len_ref), 0)

    def s_map(bi, hi, ki, kv_len_ref):
        return (bi, hi, 0,
                clamp(bi, ki, kv_len_ref) if scale_block == block_k
                else 0)

    kernel = functools.partial(
        _decode_kernel, sm_scale=scale, block_k=block_k,
        quantized=quantized)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, kv, n_blocks),
            in_specs=[
                pl.BlockSpec((1, 1, gp, d),
                             lambda bi, hi, ki, kv_len_ref: (bi, hi, 0, 0)),
                pl.BlockSpec((1, 1, block_k, d), k_map),
                pl.BlockSpec((1, 1, block_k, d), k_map),
                pl.BlockSpec((1, 1, _SUBLANES, scale_block), s_map),
                pl.BlockSpec((1, 1, _SUBLANES, scale_block), s_map),
            ],
            out_specs=pl.BlockSpec(
                (1, 1, gp, d), lambda bi, hi, ki, kv_len_ref: (bi, hi, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((gp, _LANES), jnp.float32),    # running max
                pltpu.VMEM((gp, _LANES), jnp.float32),    # running denom
                pltpu.VMEM((gp, d), jnp.float32),         # output acc
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, kv, gp, d), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(kv_len, qg, kt, vt, kst, vst)
    return out[:, :, :group, :].reshape(b, 1, h, d)


def flash_decode_tp(q: jnp.ndarray, k: Union[jnp.ndarray, QTensor],
                    v: Union[jnp.ndarray, QTensor], kv_len: jnp.ndarray,
                    mesh, *, axis: str = "tp",
                    sm_scale: Optional[float] = None, block_k: int = 512,
                    interpret: bool = False) -> jnp.ndarray:
    """:func:`flash_decode` under tensor parallelism.

    Attention is head-local, so megatron-sharded serving (heads split
    over the ``tp`` mesh axis) runs the kernel independently per shard
    on its local head group — ``shard_map`` with head-axis specs and NO
    collectives. Requires the KV head count to divide evenly across the
    axis (the GQA group size is then preserved per shard).
    """
    from jax.sharding import PartitionSpec as P

    tp = mesh.shape[axis]
    kq = k.q if isinstance(k, QTensor) else k
    kv_heads = kq.shape[2]
    if kv_heads % tp:
        raise ValueError(
            f"flash_decode_tp: {kv_heads} KV heads do not divide over "
            f"{axis}={tp}")
    hspec = P(None, None, axis, None)
    cspec = (QTensor(hspec, hspec) if isinstance(k, QTensor) else hspec)

    def shard(q_l, k_l, v_l, kv_len_l):
        return flash_decode(q_l, k_l, v_l, kv_len_l, sm_scale=sm_scale,
                            block_k=block_k, interpret=interpret)

    # check_vma=False: pallas_call's out_shape carries no varying-mesh-
    # axes annotation, and the body is collective-free by construction
    return jax.shard_map(
        shard, mesh=mesh,
        in_specs=(hspec, cspec, cspec, P()),
        out_specs=hspec, check_vma=False)(
            q, k, v, jnp.asarray(kv_len, jnp.int32))


def supports_decode(q: jnp.ndarray, k) -> bool:
    """Whether the pallas decode path can serve this call."""
    kq = k.q if isinstance(k, QTensor) else k
    return (q.shape[1] == 1 and q.shape[-1] % _LANES == 0
            and kq.shape[1] % _LANES == 0)


# ---------------------------------------------------------------------------
# paged variant: the cache is a pool of pages + a per-stream page table

def _paged_kernel(kv_len_ref, pt_ref, *rest, **kw):
    # the page table is consumed entirely by the index maps (it decides
    # WHICH page each k-block DMA reads); the arithmetic body is the
    # slot kernel's verbatim — logical positions ik*block_k+iota vs
    # kv_len don't care where the bytes physically live
    del pt_ref
    _decode_kernel(kv_len_ref, *rest, **kw)


@functools.partial(
    jax.jit,
    static_argnames=("sm_scale", "block_k", "interpret"))
def flash_decode_paged(q: jnp.ndarray, k: Union[jnp.ndarray, QTensor],
                       v: Union[jnp.ndarray, QTensor],
                       page_table: jnp.ndarray, kv_len: jnp.ndarray, *,
                       sm_scale: Optional[float] = None,
                       block_k: int = 512,
                       interpret: bool = False) -> jnp.ndarray:
    """:func:`flash_decode` against a PAGED pool.

    ``k``/``v`` are per-layer pools [P, ps, KV, D] (QTensor for int8);
    ``page_table`` [B, MP] int32 maps stream b's logical page j to a
    physical pool page. Same online-softmax body as the slot kernel —
    the only new machinery is a second scalar-prefetch argument (the
    flattened table) consulted by the k-block index maps, so each
    k-block DMA lands on ``pt[b, logical_block // blocks_per_page]``.
    Block skipping via clamp-to-last-live-block survives unchanged:
    dead logical blocks clamp to a repeated (page, offset) pair and
    Mosaic elides their DMAs, so cost still tracks kv_len, not the
    table width.
    """
    b, s_q, h, d = q.shape
    assert s_q == 1, "flash_decode_paged serves single-position steps"
    quantized = isinstance(k, QTensor)
    kq, ks = (k.q, k.s) if quantized else (k, None)
    vq, vs = (v.q, v.s) if quantized else (v, None)
    pages, ps, kv, _ = kq.shape
    _, mp = page_table.shape
    assert h % kv == 0, (h, kv)
    group = h // kv
    gp = -(-group // _SUBLANES) * _SUBLANES
    # the block must tile a PAGE (DMAs cannot straddle two physically
    # unrelated pages), so divide ps rather than max_seq
    block_k = 1 << (min(block_k, ps).bit_length() - 1)
    while block_k > _LANES and ps % block_k:
        block_k //= 2
    assert ps % block_k == 0 and d % _LANES == 0, (ps, d)
    bpp = ps // block_k                          # blocks per page
    scale = sm_scale if sm_scale is not None else d ** -0.5
    kv_len = jnp.asarray(kv_len, jnp.int32)
    kv_len = jnp.broadcast_to(kv_len.reshape(-1), (b,))
    pt_flat = page_table.astype(jnp.int32).reshape(-1)     # [B*MP]

    qg = q[:, 0].reshape(b, kv, group, d)
    if gp != group:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, gp - group), (0, 0)))
    # pools: [P, ps, KV, D] -> [P, KV, ps, D]
    kt = kq.transpose(0, 2, 1, 3)
    vt = vq.transpose(0, 2, 1, 3)
    if quantized:
        kst = jnp.broadcast_to(
            ks[..., 0].transpose(0, 2, 1)[:, :, None, :],
            (pages, kv, _SUBLANES, ps))
        vst = jnp.broadcast_to(
            vs[..., 0].transpose(0, 2, 1)[:, :, None, :],
            (pages, kv, _SUBLANES, ps))
    else:
        kst = vst = jnp.zeros((1, kv, _SUBLANES, _LANES), jnp.bfloat16)

    clamp = _clamped(block_k)
    n_blocks = mp * bpp
    scale_block = block_k if quantized else _LANES

    def k_map(bi, hi, ki, kv_len_ref, pt_ref):
        kc = clamp(bi, ki, kv_len_ref)           # live logical block
        page = pt_ref[bi * mp + kc // bpp]
        return (page, hi, kc % bpp, 0)

    def s_map(bi, hi, ki, kv_len_ref, pt_ref):
        if scale_block != block_k:
            return (0, hi, 0, 0)
        kc = clamp(bi, ki, kv_len_ref)
        return (pt_ref[bi * mp + kc // bpp], hi, 0, kc % bpp)

    def q_map(bi, hi, ki, kv_len_ref, pt_ref):
        return (bi, hi, 0, 0)

    kernel = functools.partial(
        _paged_kernel, sm_scale=scale, block_k=block_k,
        quantized=quantized)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, kv, n_blocks),
            in_specs=[
                pl.BlockSpec((1, 1, gp, d), q_map),
                pl.BlockSpec((1, 1, block_k, d), k_map),
                pl.BlockSpec((1, 1, block_k, d), k_map),
                pl.BlockSpec((1, 1, _SUBLANES, scale_block), s_map),
                pl.BlockSpec((1, 1, _SUBLANES, scale_block), s_map),
            ],
            out_specs=pl.BlockSpec((1, 1, gp, d), q_map),
            scratch_shapes=[
                pltpu.VMEM((gp, _LANES), jnp.float32),
                pltpu.VMEM((gp, _LANES), jnp.float32),
                pltpu.VMEM((gp, d), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, kv, gp, d), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(kv_len, pt_flat, qg, kt, vt, kst, vst)
    return out[:, :, :group, :].reshape(b, 1, h, d)


def flash_decode_paged_tp(q: jnp.ndarray, k: Union[jnp.ndarray, QTensor],
                          v: Union[jnp.ndarray, QTensor],
                          page_table: jnp.ndarray, kv_len: jnp.ndarray,
                          mesh, *, axis: str = "tp",
                          sm_scale: Optional[float] = None,
                          block_k: int = 512,
                          interpret: bool = False) -> jnp.ndarray:
    """:func:`flash_decode_paged` under tensor parallelism — the page
    axis is replicated (every shard holds every page of its OWN heads),
    the KV-head axis shards, the table/lengths broadcast. Head-local as
    ever: no collectives."""
    from jax.sharding import PartitionSpec as P

    tp = mesh.shape[axis]
    kq = k.q if isinstance(k, QTensor) else k
    kv_heads = kq.shape[2]
    if kv_heads % tp:
        raise ValueError(
            f"flash_decode_paged_tp: {kv_heads} KV heads do not divide "
            f"over {axis}={tp}")
    qspec = P(None, None, axis, None)
    pspec = P(None, None, axis, None)            # [P, ps, KV, D]
    cspec = (QTensor(pspec, pspec) if isinstance(k, QTensor) else pspec)

    def shard(q_l, k_l, v_l, pt_l, kv_len_l):
        return flash_decode_paged(q_l, k_l, v_l, pt_l, kv_len_l,
                                  sm_scale=sm_scale, block_k=block_k,
                                  interpret=interpret)

    return jax.shard_map(
        shard, mesh=mesh,
        in_specs=(qspec, cspec, cspec, P(), P()),
        out_specs=qspec, check_vma=False)(
            q, k, v, page_table.astype(jnp.int32),
            jnp.asarray(kv_len, jnp.int32))


def supports_decode_paged(q: jnp.ndarray, k, page_size: int) -> bool:
    """Whether the paged pallas decode path can serve this call."""
    return (q.shape[1] == 1 and q.shape[-1] % _LANES == 0
            and page_size % _LANES == 0)
