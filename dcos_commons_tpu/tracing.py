"""Fleet-wide request tracing: trace/span IDs over the serving HTTP hops.

A request entering the fleet front door (``models/router.py``) is stamped
with a 64-bit trace id; every hop it crosses — router relay, prefill
worker, KV ship/adopt, decode frontend — records *spans* (name, service,
wall-clock start, duration, attributes) into a process-wide bounded ring
buffer. Hops propagate identity over the existing HTTP requests via one
header::

    X-Tpu-Trace: <trace_id>-<span_id>

where ``span_id`` is the caller's span, becoming the callee's parent.
Stdlib-only, allocation-light, and deliberately RNG-neutral: ids come
from :func:`os.urandom`, never from ``random`` — arming tracing inside a
seeded chaos soak must not perturb the draw order of a pinned seed.

Spans carry epoch timestamps derived from ``time.perf_counter()`` through
one per-process offset, so spans recorded retrospectively from stored
perf-counter stamps (the ingress path) interleave monotonically with
spans recorded live. A span marked ``terminal=True`` ends its trace —
the chaos soaks' trace-completeness invariant asserts every admitted
request's trace reaches one.

Export: per-trace JSON (``TraceStore.export``) and the Chrome
``trace_event`` format (:func:`chrome_trace` — load the file in
``chrome://tracing`` or Perfetto), both served over ``/v1/trace/<id>``
on the router and frontend tiers and fetched by ``tpuctl trace``.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from typing import List, Optional

TRACE_HEADER = "X-Tpu-Trace"

# one per-process perf_counter -> epoch offset: every span start computed
# as _EPOCH0 + perf_counter() is monotone w.r.t. every other span in the
# process, live or retrospective
_EPOCH0 = time.time() - time.perf_counter()


def perf_to_epoch(t_perf: float) -> float:
    """Map a ``time.perf_counter()`` stamp onto the process epoch line."""
    return _EPOCH0 + t_perf


def new_id() -> str:
    """64-bit hex id from the OS entropy pool (RNG-neutral by design)."""
    return os.urandom(8).hex()


class TraceContext:
    """Immutable (trace_id, span_id) pair — what crosses a hop."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id

    def header(self) -> str:
        return f"{self.trace_id}-{self.span_id}"

    def __repr__(self) -> str:
        return f"TraceContext({self.trace_id}, {self.span_id})"


def parse_header(value: Optional[str]) -> Optional[TraceContext]:
    """``<trace_id>-<span_id>`` -> context; None/garbage -> None."""
    if not value:
        return None
    trace_id, sep, span_id = value.strip().partition("-")
    if not sep or not trace_id or not span_id:
        return None
    if not all(c in "0123456789abcdef" for c in trace_id + span_id):
        return None
    return TraceContext(trace_id, span_id)


class Span:
    """One recorded operation. ``t_start`` is epoch seconds; ``dur_s`` the
    duration. ``terminal`` marks the end of the whole trace."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "service",
                 "t_start", "dur_s", "attrs", "terminal", "status")

    def __init__(self, trace_id: str, span_id: str, parent_id: Optional[str],
                 name: str, service: str, t_start: float, dur_s: float,
                 attrs: Optional[dict] = None, terminal: bool = False,
                 status: str = "ok"):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.service = service
        self.t_start = t_start
        self.dur_s = dur_s
        self.attrs = attrs or {}
        self.terminal = terminal
        self.status = status

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id, "span_id": self.span_id,
            "parent_id": self.parent_id, "name": self.name,
            "service": self.service,
            "t_start": round(self.t_start, 6),
            "dur_s": round(self.dur_s, 6),
            "attrs": self.attrs, "terminal": self.terminal,
            "status": self.status,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Span":
        return cls(d["trace_id"], d["span_id"], d.get("parent_id"),
                   d["name"], d.get("service", "?"),
                   float(d["t_start"]), float(d["dur_s"]),
                   dict(d.get("attrs") or {}), bool(d.get("terminal")),
                   d.get("status", "ok"))


class TraceStore:
    """Bounded per-process span store: a ring over whole traces. When the
    span budget is exceeded the *oldest trace* is evicted wholesale (an
    LRU over trace ids), so a retained trace is never half a trace."""

    def __init__(self, capacity: int = 8192):
        self._lock = threading.Lock()
        self._capacity = max(1, capacity)
        self._spans = 0
        # trace_id -> list of spans, insertion-ordered for eviction
        self._traces: "OrderedDict[str, List[Span]]" = OrderedDict()
        # maintained incrementally: the chaos invariant polls this every
        # tick, and a scan of all retained spans per tick is O(capacity)
        self._incomplete: set = set()

    def add(self, span: Span) -> None:
        with self._lock:
            bucket = self._traces.get(span.trace_id)
            if bucket is None:
                bucket = self._traces[span.trace_id] = []
                self._incomplete.add(span.trace_id)
            bucket.append(span)
            if span.terminal:
                self._incomplete.discard(span.trace_id)
            self._spans += 1
            while self._spans > self._capacity and len(self._traces) > 1:
                tid, evicted = self._traces.popitem(last=False)
                self._incomplete.discard(tid)
                self._spans -= len(evicted)

    def spans(self, trace_id: str) -> List[Span]:
        with self._lock:
            bucket = list(self._traces.get(trace_id, ()))
        return sorted(bucket, key=lambda s: (s.t_start, s.span_id))

    def trace_ids(self) -> List[str]:
        with self._lock:
            return list(self._traces)

    def complete(self, trace_id: str) -> bool:
        """A trace is complete once any of its spans is terminal."""
        with self._lock:
            return (trace_id in self._traces
                    and trace_id not in self._incomplete)

    def incomplete_trace_ids(self) -> List[str]:
        """Retained traces that never reached a terminal span — the chaos
        trace-completeness invariant reads this after settle."""
        with self._lock:
            return list(self._incomplete)

    def export(self, trace_id: str) -> dict:
        return {"trace_id": trace_id,
                "complete": self.complete(trace_id),
                "spans": [s.to_dict() for s in self.spans(trace_id)]}

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()
            self._incomplete.clear()
            self._spans = 0

    def __len__(self) -> int:
        with self._lock:
            return self._spans


# the default process-wide store: every tier in one process (the CI
# smokes, the benches, colocated deployments) shares it, so the router's
# /v1/trace endpoint can return the whole cross-tier trace
GLOBAL_STORE = TraceStore()


class _ActiveSpan:
    """Context manager for a live span. ``.ctx`` is what children parent
    to (and what ``header()`` serializes for the next hop)."""

    __slots__ = ("_tracer", "name", "ctx", "parent_id", "terminal",
                 "attrs", "_t0", "status")

    def __init__(self, tracer: "Tracer", name: str, ctx: TraceContext,
                 parent_id: Optional[str], terminal: bool, attrs: dict):
        self._tracer = tracer
        self.name = name
        self.ctx = ctx
        self.parent_id = parent_id
        self.terminal = terminal
        self.attrs = attrs
        self.status = "ok"
        self._t0 = time.perf_counter()

    def header(self) -> str:
        return self.ctx.header()

    def set(self, key: str, value) -> None:
        self.attrs[key] = value

    def __enter__(self) -> "_ActiveSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is not None and self.status == "ok":
            self.status = "error"
        self.end()

    def end(self) -> None:
        t1 = time.perf_counter()
        self._tracer.store.add(Span(
            self.ctx.trace_id, self.ctx.span_id, self.parent_id,
            self.name, self._tracer.service,
            perf_to_epoch(self._t0), t1 - self._t0,
            self.attrs, self.terminal, self.status))


class Tracer:
    """Per-component span factory bound to one service label and one
    store (the process-global one unless a private store is injected —
    tests use private stores for isolation)."""

    def __init__(self, service: str, store: Optional[TraceStore] = None):
        self.service = service
        self.store = store if store is not None else GLOBAL_STORE

    def start(self, name: str, parent: Optional[TraceContext] = None,
              terminal: bool = False, **attrs) -> _ActiveSpan:
        """Open a live span; a fresh trace id is minted when there is no
        parent (this hop is the trace root)."""
        trace_id = parent.trace_id if parent else new_id()
        ctx = TraceContext(trace_id, new_id())
        return _ActiveSpan(self, name, ctx,
                           parent.span_id if parent else None,
                           terminal, dict(attrs))

    def record(self, name: str, t0_perf: float, t1_perf: float,
               parent: Optional[TraceContext] = None,
               terminal: bool = False, status: str = "ok",
               **attrs) -> TraceContext:
        """Record a span retrospectively from two ``perf_counter`` stamps
        (the ingress path stores stamps and emits spans at completion).
        Returns the new span's context for chaining children."""
        trace_id = parent.trace_id if parent else new_id()
        ctx = TraceContext(trace_id, new_id())
        self.store.add(Span(
            trace_id, ctx.span_id, parent.span_id if parent else None,
            name, self.service, perf_to_epoch(t0_perf),
            max(0.0, t1_perf - t0_perf), dict(attrs), terminal, status))
        return ctx


def chrome_trace(spans: List[Span]) -> dict:
    """Spans -> Chrome ``trace_event`` JSON (complete events, ph="X",
    microsecond units, one pid row per service)."""
    pids = {}
    events = []
    for s in spans:
        pid = pids.setdefault(s.service, len(pids) + 1)
        events.append({
            "name": s.name, "cat": s.service, "ph": "X",
            "ts": round(s.t_start * 1e6, 1),
            "dur": round(s.dur_s * 1e6, 1),
            "pid": pid, "tid": 1,
            "args": {**s.attrs, "span_id": s.span_id,
                     "parent_id": s.parent_id, "status": s.status,
                     "terminal": s.terminal},
        })
    events.sort(key=lambda e: e["ts"])
    meta = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 1,
             "args": {"name": service}} for service, pid in pids.items()]
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}
