"""helloworld scenario registry.

Reference: ``frameworks/helloworld/src/main/java/.../Scenario.java`` +
``Main.java:54-82`` (yaml file selected by env/args; customizers applied per
scenario) and ``CosmosRenderer`` (universe config.json defaults rendered into
the scheduler env so templated svc.ymls resolve without a live cluster).
"""

from __future__ import annotations

import os
from typing import Mapping, Optional

from dcos_commons_tpu.specification import ServiceSpec, load_service_yaml

DIST = os.path.join(os.path.dirname(__file__), "dist")

# Defaults mirroring universe/config.json option defaults (the reference
# renders these via CosmosRenderer in tests; in production Marathon injects
# them from the user's package options).
DEFAULT_ENV: Mapping[str, str] = {
    "FRAMEWORK_NAME": "hello-world",
    "SERVICE_NAME": "hello-world",
    "HELLO_COUNT": "1",
    "WORLD_COUNT": "2",
    "HELLO_CPUS": "0.1",
    "HELLO_MEM": "256",
    "HELLO_DISK": "25",
    "WORLD_CPUS": "0.2",
    "WORLD_MEM": "512",
    "WORLD_DISK": "25",
    "HELLO_PLACEMENT": "",
    "WORLD_PLACEMENT": "",
    "SLEEP_DURATION": "1000",
    "HELLO_VOLUME_PROFILE": "fast-ssd",
    "TEST_BOOLEAN": "true",
    "DEPLOY_STRATEGY": "serial",
    "HELLO_URI": "https://example.com/artifact.tar.gz",
    "TPU_CHIPS": "4",
    "TPU_TOPOLOGY": "v4-8",
    # locally-built bootstrap fetched into sandboxes that need template
    # rendering (production overrides with the package artifact URL)
    "BOOTSTRAP_URI": "file://" + os.path.abspath(os.path.join(
        os.path.dirname(__file__), "..", "..", "native", "bin",
        "tpu-bootstrap")),
}


def scenario_env(overrides: Optional[Mapping[str, str]] = None) -> dict:
    env = dict(DEFAULT_ENV)
    env.update(os.environ)
    if overrides:
        env.update(overrides)
    return env


def load_scenario(name: str = "svc",
                  env: Optional[Mapping[str, str]] = None) -> ServiceSpec:
    """Load ``dist/<name>.yml`` with universe-default env rendering."""
    path = os.path.join(DIST, f"{name}.yml")
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"unknown scenario {name!r}; available: {sorted(list_scenarios())}")
    return load_service_yaml(path, scenario_env(env))


def list_scenarios() -> list[str]:
    return sorted(f[:-4] for f in os.listdir(DIST) if f.endswith(".yml"))
