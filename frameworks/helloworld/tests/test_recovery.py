"""helloworld recovery suite (reference
``frameworks/helloworld/tests/test_zzzrecovery.py``): task failure ->
transient relaunch; agent loss -> tasks recovered; replace moves off the
host."""

import pytest

from dcos_commons_tpu.state import TaskState
from dcos_commons_tpu.testing import integration

from frameworks.helloworld.tests.test_sanity import SERVICE_NAME, svc_yaml


@pytest.fixture()
def stack():
    from frameworks.conftest import make_stack
    with make_stack(n_agents=4, multi=True) as s:
        yield s


def test_task_failure_relaunches_in_place(stack):
    client = integration.install(stack.url, SERVICE_NAME,
                                 svc_yaml(env={"HELLO_COUNT": "1",
                                               "WORLD_COUNT": "1"}),
                                 timeout_s=30)
    old = integration.get_task_ids(client, "hello-0")
    code, body = client.get("pod/status")
    before = {t["name"]: t["hostname"] for pod in body["pods"]
              for t in pod["tasks"]}
    # synthetic TASK_FAILED straight into the fake agent (the integration
    # suite's `dcos task exec kill` analogue)
    task = stack.cluster.task("hello-0-server")
    stack.cluster.send_status(task.task_id, TaskState.FAILED, "killed")
    integration.check_tasks_updated(client, "hello-0", old, timeout_s=30)
    integration.wait_for_recovery(client, timeout_s=30)
    code, body = client.get("pod/status")
    after = {t["name"]: t["hostname"] for pod in body["pods"]
             for t in pod["tasks"]}
    # transient recovery relaunches on the SAME host (volumes pin)
    assert after["hello-0-server"] == before["hello-0-server"]
    integration.uninstall(stack.url, SERVICE_NAME, timeout_s=30)


def test_replace_moves_off_host(stack):
    client = integration.install(stack.url, SERVICE_NAME,
                                 svc_yaml(env={"HELLO_COUNT": "1",
                                               "WORLD_COUNT": "1"}),
                                 timeout_s=30)
    code, body = client.get("pod/status")
    before = {t["name"]: t["hostname"] for pod in body["pods"]
              for t in pod["tasks"]}
    integration.pod_replace(client, "hello-0", timeout_s=30)
    code, body = client.get("pod/status")
    after = {t["name"]: t["hostname"] for pod in body["pods"]
             for t in pod["tasks"]}
    # permanent replace prefers a different host when one is available
    assert after["hello-0-server"] != before["hello-0-server"]
    integration.uninstall(stack.url, SERVICE_NAME, timeout_s=30)
