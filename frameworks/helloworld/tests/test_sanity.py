"""helloworld sanity suite (reference
``frameworks/helloworld/tests/test_sanity.py``): install, deploy, endpoint
checks, plan verbs, config update, pod verbs, teardown — all through the
HTTP API via the integration lib against an in-process live stack."""

import pytest

from dcos_commons_tpu.testing import integration

from frameworks.helloworld import scenarios

SERVICE_NAME = "hello-world"


@pytest.fixture()
def stack():
    from frameworks.conftest import make_stack
    with make_stack(n_agents=5, multi=True) as s:
        yield s


def svc_yaml(scenario="svc", env=None) -> str:
    import os
    path = os.path.join(scenarios.DIST, f"{scenario}.yml")
    from dcos_commons_tpu.utils.template import render_template
    with open(path) as f:
        return render_template(f.read(), scenarios.scenario_env(env))


def test_install_sanity_uninstall(stack):
    client = integration.install(stack.url, SERVICE_NAME,
                                 svc_yaml(env={"HELLO_COUNT": "1",
                                               "WORLD_COUNT": "2"}),
                                 timeout_s=30)
    # deploy plan shape: one phase per pod type, serial (reference
    # test_sanity verifies plan layout)
    plan = integration.get_plan(client, "deploy")
    assert plan["status"] == "COMPLETE"
    phase_names = [ph["name"] for ph in plan["phases"]]
    assert phase_names == ["hello", "world"]

    ids = integration.get_task_ids(client)
    assert set(ids) == {"hello-0-server", "world-0-server", "world-1-server"}

    # scheduler state endpoints respond
    code, fw = client.get("state/frameworkId")
    assert code == 200

    integration.uninstall(stack.url, SERVICE_NAME, timeout_s=30)


def test_pod_verbs_and_recovery(stack):
    client = integration.install(stack.url, SERVICE_NAME,
                                 svc_yaml(env={"HELLO_COUNT": "2",
                                               "WORLD_COUNT": "1"}),
                                 timeout_s=30)
    old = integration.get_task_ids(client, "hello-0")
    sibling = integration.get_task_ids(client, "hello-1")
    integration.pod_restart(client, "hello-0", timeout_s=30)
    integration.check_tasks_updated(client, "hello-0", old, timeout_s=30)
    # restart-in-place must not disturb the sibling
    integration.check_tasks_not_updated(client, "hello-1", sibling)
    integration.pod_replace(client, "hello-1", timeout_s=30)
    integration.check_tasks_updated(client, "hello-1", sibling, timeout_s=30)
    integration.uninstall(stack.url, SERVICE_NAME, timeout_s=30)


def test_config_update_rolls_only_changed_pods(stack):
    client = integration.install(stack.url, SERVICE_NAME,
                                 svc_yaml(env={"HELLO_COUNT": "1",
                                               "WORLD_COUNT": "1"}),
                                 timeout_s=30)
    old_target = integration.get_target_id(client)
    hello_ids = integration.get_task_ids(client, "hello")
    world_ids = integration.get_task_ids(client, "world")
    new_yaml = svc_yaml(env={"HELLO_COUNT": "1", "WORLD_COUNT": "1",
                             "SLEEP_DURATION": "2000"})
    integration.update_service_options(client, {}, yaml_text=new_yaml,
                                       timeout_s=30)
    integration.check_config_updated(client, old_target)
    # env change touches every pod (TASKCFG-free svc.yml: SLEEP_DURATION
    # lands in both pod types), so both roll
    integration.check_tasks_updated(client, "hello", hello_ids,
                                    timeout_s=30)
    integration.check_tasks_updated(client, "world", world_ids,
                                    timeout_s=30)
    integration.uninstall(stack.url, SERVICE_NAME, timeout_s=30)
