"""helloworld placement suite (reference
``frameworks/helloworld/tests/test_placement.py``): marathon-style
constraints evaluated against live agent inventories."""

import pytest

from dcos_commons_tpu.testing import integration

from frameworks.helloworld.tests.test_sanity import SERVICE_NAME, svc_yaml


@pytest.fixture()
def stack():
    from frameworks.conftest import make_stack
    with make_stack(n_agents=4, zones=True, multi=True) as s:
        yield s


def test_unique_hostname_spread(stack):
    client = integration.install(
        stack.url, SERVICE_NAME,
        svc_yaml(env={"HELLO_COUNT": "3", "WORLD_COUNT": "1",
                      "HELLO_PLACEMENT": '[["hostname", "UNIQUE"]]'}),
        timeout_s=30)
    code, body = client.get("pod/status")
    hosts = [t["hostname"] for pod in body["pods"]
             for t in pod["tasks"] if t["name"].startswith("hello")]
    assert len(hosts) == 3 and len(set(hosts)) == 3, hosts
    integration.uninstall(stack.url, SERVICE_NAME, timeout_s=30)


def test_zone_group_by(stack):
    client = integration.install(
        stack.url, SERVICE_NAME,
        svc_yaml(env={"HELLO_COUNT": "2", "WORLD_COUNT": "1",
                      "HELLO_PLACEMENT": '[["zone", "GROUP_BY", "2"]]'}),
        timeout_s=30)
    integration.check_spread(client, "hello", axis="zone", min_distinct=2)
    integration.uninstall(stack.url, SERVICE_NAME, timeout_s=30)


def test_infeasible_constraint_blocks_deploy(stack):
    yaml_text = svc_yaml(env={"HELLO_COUNT": "5", "WORLD_COUNT": "1",
                              "HELLO_PLACEMENT": '[["hostname", "UNIQUE"]]'})
    client = integration.install(stack.url, SERVICE_NAME, yaml_text,
                                 wait=False)
    # 5 unique hosts on a 4-agent cluster: deploy must stall, not complete
    with pytest.raises(integration.IntegrationError):
        integration.wait_for_deployment(client, timeout_s=3)
    integration.uninstall(stack.url, SERVICE_NAME, timeout_s=30)
