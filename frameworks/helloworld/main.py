"""helloworld scheduler entry point.

Reference ``frameworks/helloworld/src/main/java/.../Main.java:54-82``: one
binary that runs mono-service (a single scenario YAML), static multi-service
(several YAMLs hosted by one scheduler process), or dynamic multi-service
(start empty; services added/removed at runtime over HTTP, the
``ExampleMultiServiceResource`` pattern) depending on arguments.

Usage::

    python -m frameworks.helloworld.main [scenario ...] [--port N] [--state DIR]

* no scenario args -> dynamic multi-service mode
* one scenario     -> mono mode (e.g. ``svc``, ``simple``, ``canary``)
* many scenarios   -> static multi mode, one service per YAML
"""

from __future__ import annotations

import argparse
import logging
import os
import sys
import time

from dcos_commons_tpu.agent.remote import RemoteCluster
from dcos_commons_tpu.agent.retry import RetryingAgentClient
from dcos_commons_tpu.http import ApiServer
from dcos_commons_tpu.security import Authenticator
from dcos_commons_tpu.metrics import MetricsRegistry, PlanReporter
from dcos_commons_tpu.scheduler import (MultiServiceScheduler,
                                        ServiceScheduler)
from dcos_commons_tpu.scheduler.runner import CycleDriver
from dcos_commons_tpu.state.replicated import open_state

from . import scenarios


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("scenario", nargs="*",
                   help="scenario YAML name(s) under dist/ (omit for dynamic "
                        "multi-service mode)")
    p.add_argument("--port", type=int,
                   default=int(os.environ.get("API_PORT", "8080")))
    p.add_argument("--state", default=os.environ.get("STATE_DIR", "./state"))
    p.add_argument("--interval", type=float, default=1.0,
                   help="scheduler cycle period seconds")
    p.add_argument("--list", action="store_true", help="list scenarios")
    return p


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s %(message)s")
    args = build_parser().parse_args(argv)
    if args.list:
        print("\n".join(scenarios.list_scenarios()))
        return 0

    metrics = MetricsRegistry()
    statsd_host = os.environ.get("STATSD_UDP_HOST")
    if statsd_host:  # reference Metrics.configureStatsd:74-79
        metrics.configure_statsd(statsd_host,
                                 int(os.environ.get("STATSD_UDP_PORT", "8125")))
    # single-instance gate + state backend: the replicated
    # ensemble when TPU_STATE_ENDPOINTS is set, else local files
    persister, lock = open_state(args.state)
    cluster = RemoteCluster()
    # the scheduler's launch/kill RPCs ride the retrying wrapper
    # (bounded attempts, jittered backoff, per-call deadline); the
    # API server keeps the raw client for read-only passthrough
    sched_cluster = RetryingAgentClient(cluster)
    # control-plane auth: TPU_AUTH_FILE names the accounts file
    _auth = Authenticator.from_env()
    # transport security: TPU_TLS=1 mints from the persisted CA (or
    # TPU_TLS_CERT/TPU_TLS_KEY name provisioned PEMs)
    from dcos_commons_tpu.security import server_tls_from_env
    _tls = server_tls_from_env(persister, "helloworld", args.state)

    if len(args.scenario) == 1:
        # mono-service (reference Main.java runDefaultService path)
        spec = scenarios.load_scenario(args.scenario[0])
        scheduler = ServiceScheduler(spec, persister, sched_cluster,
                                     metrics=metrics, auth=_auth)
        # live updates: re-render this scenario with new option env
        scheduler.respec = (
            lambda env, _name=args.scenario[0]:
            scenarios.load_scenario(_name, env))
        server = ApiServer(scheduler, port=args.port, metrics=metrics,
                           cluster=cluster, auth=_auth, tls=_tls)
        PlanReporter(metrics, scheduler)
        driver = CycleDriver(scheduler, interval_s=args.interval)
    else:
        # multi-service, static or dynamic (reference
        # Main.java:54-82 multi paths + ExampleMultiServiceResource)
        multi = MultiServiceScheduler(persister, sched_cluster,
                                      metrics=metrics, auth=_auth)
        server = ApiServer(None, port=args.port, metrics=metrics,
                           cluster=cluster, multi=multi, auth=_auth,
                           tls=_tls)
        multi.set_api_server(server)
        for name in args.scenario:
            spec = scenarios.load_scenario(name)
            multi.add_service(spec)
        driver = CycleDriver(multi, interval_s=args.interval)

    server.start()
    print(f"helloworld scheduler API on {server.url}/v1/",
          flush=True)
    try:
        with driver:
            while True:
                time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
