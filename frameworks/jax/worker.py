"""Task-side worker: what each scheduled pod instance actually runs.

The scheduler's matcher injects the JAX distributed contract into the task
sandbox env (``JAX_COORDINATOR_ADDRESS`` / ``JAX_PROCESS_ID`` /
``JAX_NUM_PROCESSES``, see ``dcos_commons_tpu/matching/evaluator.py``);
``tpu-bootstrap`` re-exports it after peer-resolution (the reference's
``sdk/bootstrap/main.go:466-513`` analogue). This module is the consumer:
every workload starts with :func:`dcos_commons_tpu.parallel.distributed.
initialize` — a no-op single-process, a ``jax.distributed`` bring-up on a
gang — so one entry point serves 1 chip or a full pod slice.

Usage (as a task ``cmd``)::

    python3 -m frameworks.jax.worker mnist  --steps 200 --out ckpt
    python3 -m frameworks.jax.worker resnet --steps 200 --batch 256 --out ckpt
    python3 -m frameworks.jax.worker llama  --preset tiny --out ckpt
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import time
from typing import Optional

log = logging.getLogger("jax.worker")


# ---------------------------------------------------------------- checkpoints

def save_checkpoint(out_dir: str, step: int, params,
                    keep: int = 3) -> Optional[str]:
    """Step checkpoints on the sharded engine (``parallel/checkpoint.py``:
    per-shard files + manifest, write-tmp+rename atomicity, pruning).
    Control-plane state lives in the scheduler's state store; model state
    lives here, on the task's persistent volume (SURVEY.md §5
    checkpoint/resume split). EVERY process writes its own shards to its
    own volume — dp gangs stay lock-step on resume, tp/pp shards never
    congregate on one host."""
    from dcos_commons_tpu.parallel import checkpoint as ckpt
    os.makedirs(out_dir, exist_ok=True)
    return ckpt.save_sharded(out_dir, step, {"params": params}, keep=keep)


def latest_checkpoint(out_dir: str, template) -> Optional[dict]:
    """Resume support: a replaced/restarted pod picks up where it left off.

    ``template`` is the freshly-initialized (already sharded) params tree —
    it supplies structure/shapes/shardings; values come bitwise from disk.
    Returns ``{"step", "params"}`` or None when no complete checkpoint
    exists.
    """
    from dcos_commons_tpu.parallel import checkpoint as ckpt
    step = ckpt.latest_step(out_dir)
    if step is None:
        return None
    tree = ckpt.restore_sharded(out_dir, {"params": template}, step=step)
    return {"step": step, "params": tree["params"]}


def _emit(record: dict) -> None:
    """One JSON line per progress event; the integration-test lib greps
    these the way the reference's sdk_metrics.py asserts on StatsD."""
    print(json.dumps(record), flush=True)


# ------------------------------------------------------------------ workloads

def run_mnist(args) -> dict:
    """Single-host MLP on synthetic MNIST-shaped data (zero egress: no
    dataset downloads). BASELINE.json configs[2]."""
    import jax
    import jax.numpy as jnp

    from dcos_commons_tpu.models import mlp, train
    from dcos_commons_tpu.parallel import distributed

    contract = distributed.initialize()
    cfg = mlp.MLPConfig(in_dim=784, hidden=(512, 256), n_classes=10)
    params = mlp.init_params(cfg, jax.random.key(0))
    opt = train.make_optimizer(lr=1e-3)
    step_fn = train.make_train_step(
        lambda p, b: mlp.loss_fn(cfg, p, b), opt)
    opt_state = opt.init(params)

    resumed = latest_checkpoint(args.out, params) if args.out else None
    start = 0
    if resumed:
        params, start = resumed["params"], resumed["step"]
        _emit({"event": "resumed", "step": start})

    key = jax.random.key(1)
    batch = 256
    t0 = time.perf_counter()
    loss = None
    for step in range(start, args.steps):
        key, k1, k2 = jax.random.split(key, 3)
        x = jax.random.normal(k1, (batch, 784), jnp.float32)
        y = jax.random.randint(k2, (batch,), 0, 10)
        params, opt_state, out = step_fn(params, opt_state, (x, y))
        loss = out["loss"]
        if args.out and (step + 1) % max(1, args.steps // 4) == 0:
            save_checkpoint(args.out, step + 1, params)
    loss = float(jax.block_until_ready(loss)) if loss is not None else 0.0
    dt = time.perf_counter() - t0
    steps_run = max(args.steps - start, 1)
    result = {"workload": "mnist", "steps": steps_run, "final_loss": loss,
              "examples_per_sec": round(batch * steps_run / dt, 1),
              "process_id": contract["process_id"]}
    if args.out:
        save_checkpoint(args.out, args.steps, params)
    return result


def run_resnet(args) -> dict:
    """Data-parallel ResNet-50: batch sharded over the dp mesh axis, XLA
    inserts the ICI gradient all-reduce (BASELINE.json configs[3], the
    north-star metric images/sec/chip)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dcos_commons_tpu.models import resnet, train
    from dcos_commons_tpu.parallel import distributed
    from dcos_commons_tpu.parallel.mesh import MeshSpec

    contract = distributed.initialize()
    n = jax.device_count()
    # multislice: the matcher exports MEGASCALE_NUM_SLICES; dp spans slices
    # over DCN (slice-major), per-slice replicas stay on ICI
    num_slices = int(os.environ.get("MEGASCALE_NUM_SLICES", "1"))
    mesh = MeshSpec(dp=n // num_slices, dcn=num_slices).build()

    depth = args.depth
    cfg = resnet.ResNetConfig(depth=depth, n_classes=1000)
    with mesh:
        params, state = resnet.init_params(cfg, jax.random.key(0))
        # dp: params replicate over the mesh. Commit that sharding up front
        # so a restored checkpoint (which adopts the template's sharding)
        # is mesh-replicated too, not pinned to one device.
        params = jax.device_put(params, NamedSharding(mesh, P()))
        opt = train.make_optimizer(lr=getattr(args, "lr", 0.1) or 0.1)
        step_fn = train.make_train_step(
            lambda p, b: resnet.loss_fn(cfg, p, b[0], b[1]), opt,
            has_aux_state=True)
        opt_state = opt.init(params)

        per_host = args.batch
        n_proc = contract["num_processes"]
        global_batch = per_host * n_proc
        # synthetic imagenet-shaped data: each process contributes its local
        # shard of the dp-sharded global batch
        x_local = jax.random.normal(
            jax.random.key(1 + contract["process_id"]),
            (per_host, 224, 224, 3), jnp.bfloat16)
        y_local = jax.random.randint(
            jax.random.key(100 + contract["process_id"]),
            (per_host,), 0, 1000)
        sharding = NamedSharding(mesh, P("dp"))
        if n_proc > 1:
            x = jax.make_array_from_process_local_data(
                sharding, x_local, (global_batch, 224, 224, 3))
            y = jax.make_array_from_process_local_data(
                sharding, y_local, (global_batch,))
        else:
            x = jax.device_put(x_local, sharding)
            y = jax.device_put(y_local, sharding)

        # warmup/compile on the fresh init. Gang re-form resumes, not
        # restarts: EVERY process checkpoints the FULL step state
        # (params + opt momentum + batch-norm stats) to its own volume,
        # and a resumed run restores OVER the warmup outputs — they carry
        # the post-step shardings the loop will use, the warmup never
        # advances restored state, and the continued loss stream is
        # bitwise the one the dead gang would have produced (the gang
        # e2e tier asserts exactly this).
        from dcos_commons_tpu.parallel import checkpoint as ckpt
        params, opt_state, state, out = step_fn(params, opt_state,
                                                (state, (x, y)))
        jax.block_until_ready(out["loss"])
        start_step = 0
        rstep = ckpt.latest_step(args.out) if args.out else None
        if n_proc > 1 and args.out:
            # agree on the resume step across the gang: a kill can land
            # BETWEEN two ranks' saves at the same boundary, leaving one
            # rank a checkpoint ahead — resuming local-latest would run
            # different loop counts and deadlock the lock-step
            # collectives. Every member resumes from the MIN step the
            # whole gang holds (save pruning keeps several, so the
            # agreed step is still on disk for the rank that ran ahead).
            from jax.experimental import multihost_utils
            steps_all = multihost_utils.process_allgather(
                jnp.int32(rstep if rstep is not None else -1))
            agreed = int(jnp.min(steps_all))
            rstep = agreed if agreed >= 0 else None
        if rstep is not None:
            tree = ckpt.restore_sharded(
                args.out, {"params": params, "opt_state": opt_state,
                           "state": state}, rstep)
            params, opt_state, state = (tree["params"], tree["opt_state"],
                                        tree["state"])
            start_step = rstep
            _emit({"event": "resumed", "step": start_step})

        def save_full(step):
            ckpt.save_sharded(args.out, step,
                              {"params": params, "opt_state": opt_state,
                               "state": state})

        steps_run = max(args.steps - start_step, 0)
        ckpt_every = max(1, args.steps // 4)
        emit_every = getattr(args, "emit_every", 0)
        t0 = time.perf_counter()
        for step in range(start_step, args.steps):
            params, opt_state, state, out = step_fn(params, opt_state,
                                                    (state, (x, y)))
            if emit_every and (step + 1) % emit_every == 0:
                # a per-step loss stream for the gang e2e tier: the
                # host sync it forces is why this is opt-in
                _emit({"event": "progress", "step": step + 1,
                       "loss": float(jax.block_until_ready(out["loss"]))})
            if args.out and (step + 1) % ckpt_every == 0:
                save_full(step + 1)
        dt = time.perf_counter() - t0
        if steps_run == 0:
            # resumed at/past the target step (a relaunch after the job
            # finished): nothing ran, and `out` is the discarded warmup
            # of a fresh random init — report honestly instead of
            # labeling that warmup loss as the converged model's
            loss = None
            ips = 0.0
        else:
            loss = float(jax.block_until_ready(out["loss"]))
            ips = x.shape[0] * steps_run / dt
            if args.out:
                save_full(args.steps)
    return {"workload": "resnet", "depth": depth, "steps": steps_run,
            "final_loss": loss, "global_batch": global_batch,
            "images_per_sec_per_chip": round(ips / max(n, 1), 2),
            "process_id": contract["process_id"]}


def run_llama(args) -> dict:
    """Model-parallel Llama inference shard: weights pjit-sharded over the tp
    axis (megatron column/row layout, ``models/llama.py:shard_params``),
    decode via lax.scan (BASELINE.json configs[4])."""
    if args.serve and args.serve_role == "router":
        # the router tier is pure control plane — no model, no devices,
        # no jax: the front door comes up before anything can fail
        return _serve_router(args)
    import jax
    import jax.numpy as jnp

    from dcos_commons_tpu.models import llama
    from dcos_commons_tpu.parallel import distributed
    from dcos_commons_tpu.parallel.mesh import MeshSpec

    contract = distributed.initialize()
    n = jax.device_count()
    kv_quant = getattr(args, "kv_quant", False)
    if args.preset == "8b":
        # serving KV budget: 2048 default (0.5 GB at 8B) unless overridden;
        # weights only fit one chip quantized (~8.5 GB int8 vs 16 GB bf16)
        cfg = llama.LlamaConfig.llama3_8b(max_seq=args.max_seq or 2048,
                                          remat=False, kv_quant=kv_quant)
    elif args.preset == "400m":
        cfg = llama.LlamaConfig.llama_400m(max_seq=args.max_seq or 2048,
                                           kv_quant=kv_quant)
    elif args.max_seq:
        cfg = llama.LlamaConfig.tiny(max_seq=args.max_seq,
                                     kv_quant=kv_quant)
    else:
        cfg = llama.LlamaConfig.tiny(kv_quant=kv_quant)
    # round-18 serving arithmetic: MoE decode (ep mesh) or sequence-
    # parallel ring prefill (sp mesh) replace the tp weight shards —
    # one replica mesh carries one inner axis. Resolved ONCE here;
    # the engine constructors read the stash (_make_serving_engine)
    moe_cfg, longctx_ring, arith_spec = (
        _serving_arithmetic(args, cfg, n) if args.serve
        else (None, 0, None))
    args._moe_cfg, args._longctx_ring = moe_cfg, longctx_ring
    mesh = (arith_spec or MeshSpec(tp=n)).build()
    gen_len = args.gen_len
    # chunked for everything but tiny: the fused nested-scan generate
    # takes minutes to compile at 400m+ through tunneled backends;
    # decode_chunk compiles one K-step scan in seconds and amortizes
    # per-step dispatch K-fold (models/llama.py:decode_chunk)
    chunked = args.preset != "tiny" or args.quant != "none"

    # chunked rounds the continuation up to whole chunks before trimming;
    # divide by the EXECUTED token count or tps reads low off-alignment
    exec_len = (1 + -(-(gen_len - 1) // 16) * 16) if chunked else gen_len

    def timed_decode(prompt):
        # prompt must stay (1, 4) int32 so the compiled executable is reused
        t0 = time.perf_counter()
        with mesh:
            if moe_cfg is not None:
                # routed FFN: the dense generate paths read w_gate/
                # w_up/w_down which MoE trees don't carry
                toks = llama.generate_stepwise_moe(cfg, params, prompt,
                                                   gen_len, moe_cfg,
                                                   mesh=mesh)
            elif chunked:
                toks = llama.generate_chunked(cfg, params, prompt,
                                              gen_len, chunk=16,
                                              mesh=mesh)
            else:
                toks = llama.generate(cfg, params, prompt, gen_len,
                                      mesh=mesh)
        jax.block_until_ready(toks)
        return round(exec_len / max(time.perf_counter() - t0, 1e-9), 2)

    with mesh:
        if moe_cfg is not None:
            # raw bf16 expert banks, replicated shared weights: the tp
            # param_specs tree doesn't describe router/w_in/w_out, and
            # the ep shard_map reshards the expert axis at dispatch
            params = llama.init_moe_params(cfg, moe_cfg.num_experts,
                                           jax.random.key(0))
        elif args.quant == "int8":
            # init + quantize on host CPU, stream int8 shards to devices —
            # never materializes bf16 weights on-chip (models/llama.py:
            # init_quantized_params)
            params = llama.init_quantized_params(cfg, jax.random.key(0))
            params = llama.shard_params(params, mesh, cfg)
        else:
            params = llama.init_params(cfg, jax.random.key(0))
            params = llama.shard_params(params, mesh, cfg)
    registry = None
    boot_report = {"source": "init", "fetch_s": 0.0, "restore_s": 0.0}
    if args.serve:
        from dcos_commons_tpu.metrics import MetricsRegistry
        registry = MetricsRegistry()
        if args.quant == "none" and moe_cfg is None:
            # int8 replicas keep their freshly-quantized init: QTensor
            # trees are outside the sharded-checkpoint template
            # contract — and MoE trees are outside the dense template
            with mesh:
                params, boot_report = _boot_serving_weights(args, params,
                                                            registry)
        _emit({"event": "weights_loaded", **boot_report})
    prompt = jnp.array([[1, 2, 3, 4]], dtype=jnp.int32)
    timed_decode(prompt)  # warmup/compile
    tokens_per_sec = timed_decode(prompt)

    if args.out:  # readiness-check gate (llama.yml): shard is serving
        os.makedirs(args.out, exist_ok=True)
    with open("serving.ready", "w") as f:
        f.write("ok\n")
    weight_gb = sum(
        x.size * x.dtype.itemsize for x in jax.tree.leaves(params)
    ) / 1e9
    result = {"workload": "llama", "preset": args.preset,
              "quant": args.quant, "kv_quant": kv_quant,
              "weight_gb": round(weight_gb, 2),
              "tokens_per_sec": tokens_per_sec,
              "tp": n, "process_id": contract["process_id"]}
    if args.serve:
        # goal RUNNING: keep serving — exiting would read as a task failure
        # and trigger a gang re-form loop. Transient decode failures are
        # reported, not fatal: only the scheduler's own health/recovery
        # machinery should decide to restart the shard.
        # the slot engine composes with tensor parallelism: a sharded
        # mesh serves continuous batching through decode_step_slots
        # under shard_map (models/serving.py). Single-process (one
        # host's chips) the ingress drives the engine directly;
        # multi-PROCESS gangs serve through the rank-0 request
        # broadcast (models/serving_gang.py): rank 0 owns the HTTP
        # front door, every rank executes the identical submit/step
        # sequence in lock-step.
        slot_engine = args.slots > 0
        multiproc = contract["num_processes"] > 1
        role = args.serve_role
        if role != "colocated":
            # disaggregated tier (dist/disagg.yml): prefill serves
            # page spans flat-out, decode adopts them behind the
            # client front door. _serve_disagg never returns while
            # healthy; False means the config can't run that tier
            # (emitted as disagg_fallback) and the co-located paths
            # below keep the replica serving — degrade, not crash.
            if slot_engine and not multiproc:
                if _serve_disagg(args, cfg, params, mesh, result):
                    return result              # unreachable (serve loop)
            else:
                _emit({"event": "disagg_fallback", "role": role,
                       "reason": "disagg tiers need --slots on a "
                                 "single-process replica; serving "
                                 "co-located"})
        if slot_engine and multiproc:
            return _serve_gang(args, contract, cfg, params, mesh, result)
        if slot_engine:
            # continuous batching behind a REAL front door: the ingress
            # (models/ingress.py) accepts client requests on the
            # matcher-reserved PORT_SERVE (advertised via the scheduler's
            # endpoints surface), feeds a bounded queue into the slot
            # pool, and measures TTFT/TPOT per request. Heartbeats report
            # the ingress stats instead of draining synthetic bursts.
            from dcos_commons_tpu.models.ingress import ServingFrontend
            t_compile = time.perf_counter()
            server, page_stats = _make_serving_engine(args, cfg, params,
                                                      mesh,
                                                      registry=registry)
            warmup = getattr(server, "warmup", None)
            if warmup is not None:
                # trace + compile the serving executables NOW (AOT) so
                # the first admitted request never pays the trace; a
                # homogeneous scale-up with AOT_CACHE reuses a hot
                # sibling's wrappers and this is near-free
                warmup()
            compile_s = time.perf_counter() - t_compile
            registry.observe("autoscale.cold_start.compile_seconds",
                             compile_s)
            weight_srv = _start_weight_server(args, params, registry)
            port = args.serve_port
            if port < 0:          # default: the reserved port, else any
                port = int(os.environ.get("PORT_SERVE", "0"))
            t_admit = time.perf_counter()
            frontend = ServingFrontend(server, port=port,
                                       max_queue=args.queue_limit,
                                       decode_window=args.decode_window,
                                       metrics=registry)
            frontend.start()
            if getattr(server, "directory", None) is not None:
                # publish prefix-directory claims under the address
                # siblings can actually fetch from (POST /v1/prefix) —
                # the directory key IS the adoption endpoint
                import socket
                server.replica_id = ("http://" + socket.gethostname()
                                     + f":{frontend.port}")
            # re-stamp the readiness marker now that the ingress is
            # actually listening (the yml readiness probe hits healthz)
            with open("serving.ready", "w") as f:
                f.write(f"ok {frontend.port}\n")
            admit_s = time.perf_counter() - t_admit
            registry.observe("autoscale.cold_start.admit_seconds",
                             admit_s)
            cold_start_s = (boot_report["fetch_s"]
                            + boot_report["restore_s"]
                            + compile_s + admit_s)
            registry.observe("autoscale.cold_start_seconds",
                             cold_start_s)
            _emit({"event": "serving", "slots": args.slots,
                   "port": frontend.port,
                   "cold_start": {
                       "total_s": round(cold_start_s, 4),
                       "source": boot_report["source"],
                       "fetch_s": boot_report["fetch_s"],
                       "restore_s": boot_report["restore_s"],
                       "compile_s": round(compile_s, 4),
                       "admit_s": round(admit_s, 4)},
                   **({"weights_port": weight_srv.port}
                      if weight_srv else {}),
                   **({"paged": page_stats} if page_stats else {}),
                   **result})
            i = 0
            while True:
                time.sleep(args.serve_interval)
                i += 1
                try:
                    # the plain serving path reports the same rolling
                    # load gauges /v1/healthz serves — one autoscaler/
                    # router signal shape across every replica kind
                    hb = {"event": "heartbeat", "n": i,
                          **frontend.stats(),
                          "load": frontend.load_gauges()}
                    if page_stats is not None:
                        hb["paged"] = server.page_stats()
                    _emit(hb)
                except Exception as e:
                    _emit({"event": "heartbeat_error", "n": i,
                           "error": str(e)})
        else:
            # no slot engine (none requested, or --slots on a
            # multi-process gang — ignored, see above): fixed-prompt
            # heartbeat decode keeps the solo-serving liveness signal.
            # slots: 0 tells monitoring NOT to expect continuous
            # batching; slots_requested makes a silent degrade loud.
            _emit({"event": "serving", "slots": 0,
                   "slots_requested": args.slots, **result})
            i = 0
            while True:
                time.sleep(args.serve_interval)
                i += 1
                hb_prompt = jax.random.randint(
                    jax.random.key(1000 + i), (1, 4), 0, cfg.vocab_size
                ).astype(jnp.int32)
                try:
                    _emit({"event": "heartbeat", "n": i,
                           "tokens_per_sec": timed_decode(hb_prompt)})
                except Exception as e:
                    _emit({"event": "heartbeat_error", "n": i,
                           "error": str(e)})
    return result


def _boot_serving_weights(args, template, registry=None):
    """Round 14 boot path: resolve serving weights from, in order, a hot
    sibling's ``WeightServer`` (``WEIGHT_FETCH_PEERS``), the local
    sharded checkpoint (``--out``), or the freshly-initialized template.
    Degrade, never crash: any fetch or restore failure falls through to
    the next source with a loud event. Phase costs land in the shared
    registry as ``autoscale.cold_start.{fetch,restore}_seconds`` and in
    the returned report, so the frontend's ``/v1/metrics/prometheus``
    carries the replica's own boot breakdown.

    When ``--out`` is set, a peer boot mirrors the fetched step into the
    local checkpoint dir first (committed via the dot-tmp + rename
    protocol) — the freshly-booted replica immediately serves its OWN
    siblings and restarts from disk next time."""
    from dcos_commons_tpu.parallel import checkpoint as ckpt

    report = {"source": "init", "fetch_s": 0.0, "restore_s": 0.0}

    def obs(phase, dt):
        report[f"{phase}_s"] = round(dt, 4)
        if registry is not None:
            registry.observe(f"autoscale.cold_start.{phase}_seconds", dt)

    peers = [p.strip() for p in
             os.environ.get("WEIGHT_FETCH_PEERS", "").split(",")
             if p.strip()]
    timeout_s = float(os.environ.get("WEIGHT_FETCH_TIMEOUT_S") or 120.0)
    if peers:
        from dcos_commons_tpu.models import weights as weights_mod
        try:
            if args.out:
                t0 = time.perf_counter()
                step = weights_mod.mirror_from_peers(
                    peers, args.out, timeout_s=timeout_s)
                obs("fetch", time.perf_counter() - t0)
                t0 = time.perf_counter()
                params = ckpt.restore_sharded(args.out, template, step)
                obs("restore", time.perf_counter() - t0)
            else:
                t0 = time.perf_counter()
                params = weights_mod.restore_from_peers(
                    peers, template, timeout_s=timeout_s,
                    metrics=registry)
                obs("restore", time.perf_counter() - t0)
            report["source"] = "peer"
            return params, report
        except (weights_mod.WeightFetchError, ckpt.CheckpointCorrupt,
                OSError) as e:
            _emit({"event": "weight_fetch_fallback", "error": str(e),
                   "peers": peers})
    step = ckpt.latest_step(args.out) if args.out else None
    if step is not None:
        try:
            t0 = time.perf_counter()
            params = ckpt.restore_sharded(args.out, template, step)
            obs("restore", time.perf_counter() - t0)
            report["source"] = "disk"
            report["step"] = step
            return params, report
        except (FileNotFoundError, ckpt.CheckpointCorrupt) as e:
            _emit({"event": "weight_restore_fallback", "error": str(e),
                   "step": step})
    return template, report


def _start_weight_server(args, params, registry=None):
    """Expose this replica's checkpoint shards to booting siblings
    (``WEIGHT_SERVE_PORT``/``PORT_WEIGHTS`` + ``--out``). An
    init-booted replica seeds its dir first so the tier's FIRST replica
    is already a valid peer for the second. Failure is an event, not a
    crash — weight serving is an accelerant, never a liveness
    dependency."""
    port = (os.environ.get("WEIGHT_SERVE_PORT")
            or os.environ.get("PORT_WEIGHTS"))
    if not args.out or port is None:
        return None
    from dcos_commons_tpu.models import weights as weights_mod
    from dcos_commons_tpu.parallel import checkpoint as ckpt
    try:
        if ckpt.latest_step(args.out) is None:
            ckpt.save_sharded(args.out, 0, params)
        server = weights_mod.WeightServer(args.out, port=int(port),
                                          metrics=registry).start()
        _emit({"event": "weight_server", "port": server.port,
               "steps": server.steps()})
        return server
    except (OSError, ValueError) as e:
        _emit({"event": "weight_server_error", "error": str(e)})
        return None


def _serving_arithmetic(args, cfg, n):
    """Resolve the round-18 serving-arithmetic knobs (``--moe-experts``,
    ``--prefill-seq-parallel``/``--longctx-ring``) into
    ``(moe_cfg, ring, mesh_spec)`` — or ``(None, 0, None)`` for the
    plain dense/tp stack. Degrade-not-crash: every disqualifying combo
    emits a coded ``moe_fallback``/``longctx_fallback`` event and drops
    THAT feature, never the replica. The decision is pure config, so
    every gang rank resolves identically."""
    from dcos_commons_tpu.parallel.mesh import MeshSpec
    from dcos_commons_tpu.parallel.moe import MoEConfig, dropless
    from dcos_commons_tpu.specification import yaml_bool
    moe_cfg = None
    if args.moe_experts > 0:
        if not args.pages:
            _emit({"event": "moe_fallback", "code": "moe_needs_paged",
                   "error": "MoE serving routes through the paged "
                            "engine only: set --pages/SERVE_PAGES "
                            "(serving dense)"})
        elif args.quant != "none" or getattr(args, "kv_quant", False):
            _emit({"event": "moe_fallback", "code": "moe_quant",
                   "error": "MoE expert banks serve raw bf16 "
                            "(quantize_params rejects router trees); "
                            "drop --quant/--kv-quant (serving dense)"})
        else:
            moe_cfg = MoEConfig(args.moe_experts,
                                capacity_factor=args.moe_capacity_factor
                                or 1.0,
                                routing=args.moe_routing)
            if args.moe_capacity_factor <= 0:
                moe_cfg = dropless(moe_cfg)
    ring = 0
    if yaml_bool(getattr(args, "prefill_seq_parallel", "false")):
        want = args.longctx_ring or n
        if moe_cfg is not None:
            _emit({"event": "longctx_fallback",
                   "code": "longctx_with_moe",
                   "error": "one replica mesh carries ep OR sp; MoE "
                            "decode wins, prefill stays chunked"})
        elif not args.pages:
            _emit({"event": "longctx_fallback",
                   "code": "longctx_needs_paged",
                   "error": "ring prefill is a paged-engine path: set "
                            "--pages/SERVE_PAGES"})
        elif getattr(args, "kv_quant", False):
            _emit({"event": "longctx_fallback", "code": "longctx_quant",
                   "error": "ring prefill installs bf16 K/V spans; "
                            "drop --kv-quant"})
        elif n < 2 or want != n:
            _emit({"event": "longctx_fallback",
                   "code": "longctx_ring_devices",
                   "error": f"ring size {want} != device count {n}; "
                            "this build runs the sp axis over the "
                            "replica's whole device set"})
        elif cfg.max_seq % n:
            _emit({"event": "longctx_fallback",
                   "code": "longctx_ring_max_seq",
                   "error": f"ring {n} must divide max_seq "
                            f"{cfg.max_seq} so padded prompts stay "
                            "in-table"})
        else:
            ring = n
    if moe_cfg is not None:
        ep = n if moe_cfg.num_experts % n == 0 else 1
        if ep == 1 and n > 1:
            _emit({"event": "moe_note", "code": "moe_local_dispatch",
                   "experts": moe_cfg.num_experts, "devices": n,
                   "note": "device count does not divide the expert "
                           "count; experts stay replicated and "
                           "dispatch runs the bitwise-equal local "
                           "path (no all-to-all)"})
        return moe_cfg, 0, MeshSpec(ep=ep)
    if ring:
        return None, ring, MeshSpec(sp=ring)
    return None, 0, None


def _make_serving_engine(args, cfg, params, mesh, key=None,
                         registry=None):
    """SlotServer or PagedServer per ``--pages``, degrade-not-crash.

    A paged config the model can't satisfy (page size not dividing
    max_seq, chunk < 1, pool too small for a single stream) falls back
    to the monolithic slot engine with a loud ``paged_fallback`` event —
    a serving replica must come up serving, not crash-loop on a knob.
    The decision is pure config, so every gang rank makes the same one.

    ``--spec-decode`` with a ``--draft-checkpoint`` arms the paged
    engine's speculative path the same way: any draft problem (missing
    artifact, stale manifest, vocab/rope mismatch, compile rejection)
    emits a coded ``spec_fallback`` event and the replica serves SOLO —
    a draft is a speed-up, never a dependency.

    ``AOT_CACHE`` (on by default) shares one process-wide compile cache
    across paged engines: a homogeneous scale-up (same config, same
    topology) reuses the hot engine's jit wrappers instead of
    re-tracing; ``AOT_CACHE_DIR`` additionally arms jax's persistent
    compilation cache across process boots.
    """
    from dcos_commons_tpu.models.serving import PagedServer, SlotServer
    from dcos_commons_tpu.parallel import aot
    kw = {"mesh": mesh if mesh.size > 1 else None}
    if key is not None:
        kw["key"] = key
    spec_wanted = _spec_decode_wanted(args)
    # round-18 arithmetic resolved once in run_llama (the coded
    # fallback events fire there); stashed on args so the disagg/gang
    # constructors reach the same engine without signature churn
    moe_cfg = getattr(args, "_moe_cfg", None)
    longctx_ring = getattr(args, "_longctx_ring", 0)
    if args.pages:
        try:
            engine = PagedServer(
                cfg, params, slots=args.slots,
                pages=None if args.pages < 0 else args.pages,
                page_size=args.page_size,
                prefill_chunk=args.prefill_chunk,
                compile_cache=aot.from_env(),
                moe=moe_cfg, longctx_ring=longctx_ring,
                **_make_kv_tiers(args), **kw)
            if spec_wanted:
                _arm_spec_decode(args, cfg, engine, registry)
            return engine, engine.page_stats()
        except ValueError as e:
            _emit({"event": "paged_fallback", "error": str(e),
                   "pages": args.pages, "page_size": args.page_size,
                   "prefill_chunk": args.prefill_chunk})
    if spec_wanted:
        _emit({"event": "spec_fallback", "code": "spec_needs_paged",
               "error": "speculative decode needs the paged engine "
                        "(--pages); serving solo"})
    return SlotServer(cfg, params, slots=args.slots, **kw), None


def _spec_decode_wanted(args) -> bool:
    from dcos_commons_tpu.specification import yaml_bool
    return yaml_bool(getattr(args, "spec_decode", "false"))


def _arm_spec_decode(args, cfg, engine, registry) -> None:
    """Load the draft artifact and arm the paged engine, coded-fallback
    on ANY draft problem. The load path re-verifies the save-time
    manifest digest (a retrained/overwritten artifact reads as
    ``draft_manifest_stale``) and arm-time compiles the fused window,
    so everything that can go wrong goes wrong HERE, before a request
    exists."""
    from dcos_commons_tpu.models.speculative import (DraftIncompatible,
                                                     load_draft)
    path = getattr(args, "draft_checkpoint", "") or ""
    if not path:
        _emit({"event": "spec_fallback", "code": "draft_config_missing",
               "error": "--spec-decode without --draft-checkpoint"})
        return
    try:
        cfg_d, params_d, meta = load_draft(path, cfg)
        engine.arm_draft(cfg_d, params_d,
                         k=max(2, getattr(args, "draft_k", 4)),
                         metrics=registry)
    except DraftIncompatible as e:
        _emit({"event": "spec_fallback", "code": e.code, "error": str(e),
               "draft_checkpoint": path})
        return
    except Exception as e:  # compiler rejection at arm-time warmup
        engine.disarm_draft()
        _emit({"event": "spec_fallback", "code": "draft_arm_failed",
               "error": str(e), "draft_checkpoint": path})
        return
    _emit({"event": "spec_armed", "draft_checkpoint": path,
           "k": engine.draft_k, "draft_layers": cfg_d.n_layers,
           "draft_step": meta.get("step")})


def _make_kv_tiers(args) -> dict:
    """PagedServer tier/directory kwargs per the KV_TIER_* /
    PREFIX_DIRECTORY knobs, degrade-not-crash: an unusable disk dir
    (permissions, read-only volume) drops the tier store with a loud
    ``kv_tier_fallback`` event and the replica serves single-tier —
    the tiers are an economy, never a dependency. The directory knob
    also wires ``disagg.fetch_prefix`` as the peer-fetch transport so
    directory hits adopt over sibling ``/v1/prefix`` endpoints."""
    from dcos_commons_tpu.models.disagg import fetch_prefix
    from dcos_commons_tpu.models.paging import (PageTierStore,
                                                PrefixDirectory)
    kw: dict = {}
    host = max(0, getattr(args, "kv_tier_host_pages", 0))
    disk_dir = getattr(args, "kv_tier_disk_dir", "") or None
    disk = max(0, getattr(args, "kv_tier_disk_pages", 0)) if disk_dir \
        else 0
    if host or disk:
        try:
            kw["tiers"] = PageTierStore(host_pages=host,
                                        disk_dir=disk_dir,
                                        disk_pages=disk)
        except (OSError, ValueError) as e:
            _emit({"event": "kv_tier_fallback", "error": str(e),
                   "host_pages": host, "disk_dir": disk_dir,
                   "disk_pages": disk})
    window = getattr(args, "prefix_directory", 0.0)
    if window and window > 0:
        kw["directory"] = PrefixDirectory(max_age_s=window)
        kw["peer_fetch"] = fetch_prefix
    return kw


def _serve_disagg(args, cfg, params, mesh, result) -> bool:
    """Disaggregated serving tiers (``SERVE_ROLE=prefill|decode``,
    dist/disagg.yml). The prefill tier answers ``/v1/prefill`` with
    packed page spans, chunked prefill flat-out with no decode
    interleave; the decode tier runs the client front door with a
    DisaggCoordinator shipping prompts to ``SERVE_PEER`` and adopting
    the returned pages on pages free. Never returns while healthy.

    Degrade-not-crash: a config the tier can't run — no page pool,
    paged engine infeasible, decode tier without a peer — emits
    ``disagg_fallback`` and returns False so the caller's co-located
    paths keep the replica serving. A peer that dies LATER degrades
    per-request inside the coordinator (``peer_fallbacks``)."""
    from dcos_commons_tpu.models.disagg import (DisaggCoordinator,
                                                PrefillWorker)
    from dcos_commons_tpu.models.ingress import ServingFrontend
    role = args.serve_role
    if not args.pages:
        _emit({"event": "disagg_fallback", "role": role,
               "reason": "disagg tiers are paged-only: set "
                         "--pages/SERVE_PAGES"})
        return False
    engine, page_stats = _make_serving_engine(args, cfg, params, mesh)
    if page_stats is None:
        _emit({"event": "disagg_fallback", "role": role,
               "reason": "paged engine infeasible (see paged_fallback)"})
        return False
    port = args.serve_port
    if port < 0:
        port = int(os.environ.get("PORT_SERVE", "0"))
    if role == "prefill":
        worker = PrefillWorker(engine, port=port).start()
        with open("serving.ready", "w") as f:
            f.write(f"ok {worker.port}\n")
        _emit({"event": "serving", "role": "prefill",
               "port": worker.port, "paged": page_stats, **result})
        i = 0
        while True:
            time.sleep(args.serve_interval)
            i += 1
            try:
                _emit({"event": "heartbeat", "n": i, "role": "prefill",
                       **engine.page_stats()})
            except Exception as e:
                _emit({"event": "heartbeat_error", "n": i,
                       "error": str(e)})
    peer = args.serve_peer.strip()
    if not peer:
        _emit({"event": "disagg_fallback", "role": role,
               "reason": "no --serve-peer/SERVE_PEER: serving "
                         "co-located"})
        return False
    frontend = ServingFrontend(engine, port=port,
                               max_queue=args.queue_limit,
                               decode_window=args.decode_window)
    frontend.start(drive=False)
    coord = DisaggCoordinator(engine, frontend, peer,
                              decode_window=args.decode_window).start()
    with open("serving.ready", "w") as f:
        f.write(f"ok {frontend.port}\n")
    _emit({"event": "serving", "role": "decode", "port": frontend.port,
           "peer": peer, "peers": coord.peers, "paged": page_stats,
           **result})
    i = 0
    while True:
        time.sleep(args.serve_interval)
        i += 1
        try:
            _emit({"event": "heartbeat", "n": i, "role": "decode",
                   **frontend.stats(), "paged": engine.page_stats(),
                   "disagg": coord.stats()})
        except Exception as e:
            _emit({"event": "heartbeat_error", "n": i, "error": str(e)})


def _serve_router(args) -> dict:
    """The fleet front door (``SERVE_ROLE=router``, dist/fleet.yml):
    prefix-affinity consistent-hash routing across the decode replicas
    in ``--route-replicas``, per-tenant token-bucket admission from
    ``--tenant-classes``, streaming relay with health/load-aware spill
    (``models/router.py``). Never returns while healthy.

    The router carries no model: ``--page-size`` only parameterizes the
    affinity hash and MUST match the decode tier's page size, or
    requests hash to keys the replicas' radixes never cache under.
    Decode-tier resizes land through ``POST /v1/replicas`` (the
    autoscaler's config update redeploys pods; the operator or
    controller pushes the fresh endpoint list — ``tpuctl endpoints
    serve`` is the source)."""
    from dcos_commons_tpu.models.router import Router, parse_qos_classes
    replicas = [p.strip() for p in args.route_replicas.split(",")
                if p.strip()]
    try:
        classes = parse_qos_classes(args.tenant_classes)
    except ValueError as e:
        # a bad knob must not crash-loop the front door: serve with
        # admission wide open and say so
        _emit({"event": "router_config_error", "error": str(e),
               "tenant_classes": args.tenant_classes})
        classes = {}
    port = args.serve_port
    if port < 0:
        port = int(os.environ.get("PORT_SERVE", "0"))
    router = Router(replicas, port=port, page_size=args.page_size,
                    affinity_pages=args.route_affinity_pages,
                    vnodes=args.route_vnodes, classes=classes,
                    policy=args.route_policy,
                    spill_pressure=args.route_spill_pressure,
                    spill_floor=args.route_spill_floor,
                    max_tenants=args.tenant_max_tracked).start()
    with open("serving.ready", "w") as f:
        f.write(f"ok {router.port}\n")
    _emit({"event": "serving", "role": "router", "port": router.port,
           "replicas": replicas, "policy": args.route_policy,
           "classes": sorted(classes)})
    i = 0
    while True:
        time.sleep(args.serve_interval)
        i += 1
        try:
            _emit({"event": "heartbeat", "n": i, "role": "router",
                   **router.stats()})
        except Exception as e:
            _emit({"event": "heartbeat_error", "n": i, "error": str(e)})


def _serve_gang(args, contract, cfg, params, mesh, result) -> dict:
    """Multi-process serving: rank 0 runs the HTTP front door, every
    rank runs the lock-step broadcast/submit/step loop
    (models/serving_gang.py). Never returns in normal operation."""
    import jax

    from dcos_commons_tpu.models.ingress import ServingFrontend
    from dcos_commons_tpu.models.serving_gang import GangServingDriver

    rank = contract["process_id"]
    server, page_stats = _make_serving_engine(
        args, cfg, params, mesh, key=jax.random.key(0))  # rank-identical
    frontend = None
    if rank == 0:
        port = args.serve_port
        if port < 0:
            port = int(os.environ.get("PORT_SERVE", "0"))
        frontend = ServingFrontend(server, port=port,
                                   max_queue=args.queue_limit)
        frontend.start(drive=False)
        frontend.mark_driven()
        with open("serving.ready", "w") as f:
            f.write(f"ok {frontend.port}\n")
        _emit({"event": "serving", "slots": args.slots,
               "port": frontend.port, "gang": True,
               **({"paged": page_stats} if page_stats else {}),
               **result})
    else:
        _emit({"event": "serving", "slots": args.slots, "gang": True,
               "rank": rank, **result})
    driver = GangServingDriver(
        server, frontend,
        num_processes=contract["num_processes"], process_id=rank,
        decode_window=args.decode_window)
    beat = {"n": 0}

    def on_heartbeat(stats):
        beat["n"] += 1
        _emit({"event": "heartbeat", "n": beat["n"], **stats})

    driver.run(heartbeat_s=args.serve_interval, on_heartbeat=on_heartbeat)
    return result


def run_llama_train(args) -> dict:
    """Long-context LM training: sequence parallelism over the ``sp`` mesh
    axis with ring attention (``ppermute`` KV rotation over the ICI ring),
    tensor parallelism over ``tp`` — the SURVEY §2.4 long-context module,
    deployed as a schedulable workload (``dist/longctx.yml``)."""
    import jax
    import jax.numpy as jnp

    from dcos_commons_tpu.models import llama, train
    from dcos_commons_tpu.parallel import distributed
    from dcos_commons_tpu.parallel.mesh import MeshSpec

    contract = distributed.initialize()
    n = jax.device_count()

    def divisor_at_most(limit: int, total: int) -> int:
        # largest divisor of total that is <= limit: requested axis sizes
        # that don't factorize n are clamped, not crashed (a bad config
        # must not crash-loop the gang)
        for cand in range(max(min(limit, total), 1), 0, -1):
            if total % cand == 0:
                return cand
        return 1

    if args.pp > 1:
        return _llama_train_pipelined(args, contract, n, divisor_at_most)
    if args.ep > 1:
        return _llama_train_moe(args, contract, n, divisor_at_most)
    sp = (divisor_at_most(args.sp, n) if args.sp > 0
          else (2 if n % 2 == 0 else 1))
    tp = divisor_at_most(args.tp, n // sp) if args.tp > 0 else 1
    dp = n // (sp * tp)
    mesh = MeshSpec(dp=dp, sp=sp, tp=tp).build()
    seq = args.seq
    attn = args.attn if args.attn != "auto" else (
        "ring" if sp > 1 else "auto")
    ring_layout = args.ring_layout
    if ring_layout == "zigzag" and (attn != "ring" or seq % (2 * sp)):
        # an incompatible layout must degrade, not crash-loop the gang
        ring_layout = "contiguous"
    cfg = llama.LlamaConfig.tiny(attn_impl=attn, max_seq=seq + 1,
                                 ring_layout=ring_layout,
                                 fused_ce=_fused_ce(args))
    with mesh:
        params = llama.shard_params(
            llama.init_params(cfg, jax.random.key(0)), mesh, cfg)
    toks = jax.random.randint(jax.random.key(1), (max(2 * dp, 1), seq + 1),
                              0, cfg.vocab_size)
    mesh_report = {"dp": dp, "sp": sp, "tp": tp}
    if attn == "ring":
        mesh_report["ring_layout"] = ring_layout
    return _llama_train_loop(
        args, contract, cfg, mesh,
        lambda p, b: llama.loss_fn(cfg, p, b, mesh),
        llama.param_specs(cfg), params, toks,
        mesh_report, attn)


def _fused_ce(args) -> bool:
    """--fused-ce arrives as a mustache-rendered string ('true'/'false');
    parse it exactly like the scheduler parses spec booleans."""
    from dcos_commons_tpu.specification import yaml_bool
    return yaml_bool(getattr(args, "fused_ce", "true"))


def _llama_train_loop(args, contract, cfg, mesh, loss_fn, specs, params,
                      toks, mesh_report, attn_name):
    """Shared optimizer/compile/timed-loop/report tail of every llama-train
    variant (dp-sp-tp, pipeline, MoE). Checkpoints are SHARDED
    (parallel/checkpoint.py): each gang member persists only its own
    shards on its own volume; a re-formed gang resumes bitwise from the
    newest step every member holds."""
    import jax
    from dcos_commons_tpu.models import train
    from dcos_commons_tpu.parallel import checkpoint as ckpt

    grad_accum = max(1, getattr(args, "grad_accum", 1))
    if grad_accum > 1 and toks.shape[0] % grad_accum:
        # degrade, don't crash-loop the gang: a grad-accum the batch
        # doesn't divide into equal microbatches falls back to one pass
        _emit({"event": "grad_accum_fallback",
               "requested": grad_accum, "batch": int(toks.shape[0])})
        grad_accum = 1
    with mesh:
        opt = train.make_optimizer(lr=1e-3, warmup=5,
                                   decay_steps=max(args.steps, 10))
        step = train.make_train_step(loss_fn, opt, mesh=mesh,
                                     param_spec_tree=specs, batch_spec=None,
                                     grad_accum=grad_accum)
        opt_state = train.init_opt_state(opt, params, mesh, specs)
        # compile/warmup on the freshly-initialized values; a resumed
        # run overwrites params/opt_state AFTER, so the warmup step does
        # not advance the restored state
        w_params, w_opt, out = step(params, opt_state, toks)
        float(out["loss"])
        start = 0
        resumed = False
        # restart-free reshard (parallel/reshard.py, RESHARD_* knobs):
        # when enabled, a resized/relaunched worker first tries to ADOPT
        # the live state a frozen peer published over the weight channel —
        # no checkpoint round-trip; any failure degrades to the disk
        # restore below, which stays exactly as it was
        from dcos_commons_tpu.parallel import reshard as reshard_mod
        rs_mgr = reshard_mod.manager_from_env(emit=_emit)
        rs_srv = None
        if rs_mgr is not None:
            from dcos_commons_tpu.models import weights as weights_mod
            rs_peers = os.environ.get("RESHARD_PEERS", "").strip()
            if rs_peers:
                try:
                    t_r = time.perf_counter()
                    fetcher = weights_mod.PeerFetcher(
                        rs_peers, timeout_s=rs_mgr.timeout_s)
                    tree, hdr, _ = rs_mgr.adopt(
                        {"params": w_params, "opt_state": w_opt},
                        fetcher=fetcher)
                    params, opt_state = tree["params"], tree["opt_state"]
                    start = hdr["step"]
                    resumed = True
                    _emit({"event": "resharded", "step": start,
                           "cursor": hdr.get("cursor", 0),
                           "restore_s": round(
                               time.perf_counter() - t_r, 6)})
                except Exception as e:  # degrade-not-crash
                    _emit({"event": "reshard_fallback", "error": str(e)})
            if args.out:
                try:
                    rs_srv = weights_mod.WeightServer(
                        args.out,
                        port=int(os.environ.get("RESHARD_PORT", "0") or 0),
                        host="127.0.0.1").start()
                    _emit({"event": "reshard_serving", "port": rs_srv.port})
                except Exception as e:  # serving is optional, not load-bearing
                    _emit({"event": "reshard_serve_failed", "error": str(e)})
        if not resumed and args.out \
                and (resume_step := ckpt.latest_step(args.out)) is not None:
            # template = the warmup OUTPUTS: the step donates its inputs
            # (the originals are deleted buffers by now), and the outputs
            # carry exactly the shardings later steps will use
            t_r = time.perf_counter()
            tree = ckpt.restore_sharded(
                args.out, {"params": w_params, "opt_state": w_opt},
                resume_step)
            params, opt_state = tree["params"], tree["opt_state"]
            start = resume_step
            resumed = True
            _emit({"event": "resumed", "step": start, "sharded": True,
                   "restore_s": round(time.perf_counter() - t_r, 6)})
        if not resumed:
            params, opt_state = w_params, w_opt

        # fault sentinel: preemption flush, NaN rollback, stall watchdog
        # (frameworks/jax/sentinel.py; knobs SENTINEL_* in the task env)
        from . import sentinel as sentinel_mod
        sent = sentinel_mod.FaultSentinel.from_env(emit=_emit)
        sent.install()
        t0 = time.perf_counter()
        steps_run = 0

        def run_step(i):
            nonlocal params, opt_state, out, steps_run
            params, opt_state, out = step(params, opt_state, toks)
            steps_run += 1
            if args.out and args.ckpt_every \
                    and steps_run % args.ckpt_every == 0:
                ckpt.save_sharded(args.out, i + 1,
                                  {"params": params,
                                   "opt_state": opt_state})
                _emit({"event": "checkpoint", "step": i + 1})
            return out

        def save(i):
            if rs_mgr is not None and rs_srv is not None:
                # freeze + publish LIVE state first: surviving peers can
                # adopt over the weight channel with zero checkpoint I/O;
                # the flush below stays the fallback either way
                try:
                    rs_mgr.freeze(i, {"params": params,
                                      "opt_state": opt_state},
                                  server=rs_srv)
                except Exception as e:  # degrade-not-crash
                    _emit({"event": "reshard_freeze_failed", "step": i,
                           "error": str(e)})
            if args.out:
                ckpt.save_sharded(args.out, i,
                                  {"params": params, "opt_state": opt_state})
                _emit({"event": "checkpoint", "step": i})

        def restore():
            nonlocal params, opt_state
            if not args.out:
                return None
            restore_step = ckpt.latest_step(args.out)
            if restore_step is None:
                return None
            tree = ckpt.restore_sharded(
                args.out, {"params": params, "opt_state": opt_state},
                restore_step)
            # optimizer state travels with the params: the LR schedule
            # resumes at the restored step, not at a reset one
            params, opt_state = tree["params"], tree["opt_state"]
            return restore_step

        stopped, end_step = sentinel_mod.guarded_loop(
            sent, start, args.steps, run_step,
            lambda result: float(result["loss"]), save, restore, emit=_emit)
        sent.uninstall()
        if rs_srv is not None:
            # on preemption the frozen live state was already published;
            # give a resharding peer its grace window to pull it before
            # the server dies with this process (the checkpoint flush
            # above remains the durable fallback)
            if stopped == "preempted" and rs_mgr.frozen is not None:
                time.sleep(min(rs_mgr.timeout_s,
                               float(os.environ.get(
                                   "RESHARD_LINGER_S", "0") or 0)))
            rs_srv.stop()
        dt = time.perf_counter() - t0
        if stopped == "preempted":
            # checkpoint already flushed by guarded_loop; report honestly
            # and let main() exit with the conventional SIGTERM code
            seq = toks.shape[1] - 1
            return {"workload": "llama-train", "attn": attn_name,
                    "seq": seq, "mesh": mesh_report, "stopped": "preempted",
                    "resume_step": end_step, "steps_run": steps_run,
                    "process_id": contract["process_id"]}
        if resumed and steps_run == 0:
            # already at/past the target step: nothing ran, and `out` is
            # the discarded warmup of a random init — report honestly and
            # do NOT re-label the restored state under a smaller step
            loss = None
        else:
            loss = float(out["loss"])
            if args.out:
                ckpt.save_sharded(args.out, args.steps,
                                  {"params": params,
                                   "opt_state": opt_state})

    seq = toks.shape[1] - 1
    return {"workload": "llama-train", "attn": attn_name, "seq": seq,
            "fused_ce": bool(cfg.fused_ce), "grad_accum": grad_accum,
            "mesh": mesh_report, "final_loss": loss,
            "steps_run": steps_run,
            "tokens_per_sec": (round(
                toks.shape[0] * seq * steps_run / dt, 1) if steps_run
                else 0.0),
            "process_id": contract["process_id"]}


def _llama_train_pipelined(args, contract, n, divisor_at_most) -> dict:
    """Pipeline-parallel LM training: decoder trunk stage-sharded over the
    pp mesh axis, microbatched GPipe schedule (SURVEY.md §2.4 PP)."""
    import jax

    from dcos_commons_tpu.models import llama
    from dcos_commons_tpu.parallel.mesh import MeshSpec

    pp = divisor_at_most(args.pp, n)
    # mesh spans ALL devices (remainder folds into dp as replicas): a
    # partial-device mesh would crash multi-process gangs whose local
    # shards fall outside it and idle the rest of the reservation
    mesh = MeshSpec(dp=n // pp, pp=pp).build()
    seq = args.seq
    cfg = llama.LlamaConfig.tiny(attn_impl="dense", max_seq=seq + 1,
                                 n_layers=max(4, pp * 2),
                                 fused_ce=_fused_ce(args))
    n_micro = max(2, pp)
    params = llama.stack_pipeline_params(
        llama.init_params(cfg, jax.random.key(0)), pp)
    toks = jax.random.randint(jax.random.key(1), (n_micro * 2, seq + 1),
                              0, cfg.vocab_size)
    return _llama_train_loop(
        args, contract, cfg, mesh,
        lambda p, b: llama.loss_fn_pipelined(cfg, p, b, mesh, n_micro),
        llama.pipeline_param_specs(cfg), params, toks,
        {"pp": pp, "microbatches": n_micro}, "dense")


def _llama_train_moe(args, contract, n, divisor_at_most) -> dict:
    """Expert-parallel LM training: FFNs replaced by a routed expert
    bank sharded over the ep mesh axis with all-to-all dispatch
    (SURVEY.md §2.4 EP). Routing per ``--moe-routing``: GShard top-2
    (causal-LM default) or expert-choice (balanced/dropless, with the
    non-causality caveat documented in parallel/moe.py); the mesh
    report carries the routing used."""
    import jax

    from dcos_commons_tpu.models import llama
    from dcos_commons_tpu.parallel.mesh import MeshSpec
    from dcos_commons_tpu.parallel.moe import MoEConfig

    ep = divisor_at_most(args.ep, n)
    mesh = MeshSpec(dp=n // ep, ep=ep).build()
    seq = args.seq
    # expert count must be a multiple of ep or shard_map rejects the bank
    num_experts = ep * max(1, -(-4 // ep))
    cfg = llama.LlamaConfig.tiny(attn_impl="dense", max_seq=seq + 1,
                                 fused_ce=_fused_ce(args))
    moe_cfg = MoEConfig(num_experts=num_experts,
                        routing=args.moe_routing)
    params = llama.init_moe_params(cfg, num_experts, jax.random.key(0))
    toks = jax.random.randint(jax.random.key(1), (4, seq + 1),
                              0, cfg.vocab_size)
    return _llama_train_loop(
        args, contract, cfg, mesh,
        lambda p, b: llama.loss_fn_moe(cfg, p, b, mesh, moe_cfg),
        llama.moe_param_specs(cfg), params, toks,
        {"dp": n // ep, "ep": ep, "experts": num_experts,
         "routing": args.moe_routing}, "dense")


def run_distill(args) -> dict:
    """Draft distillation (``dist/distill.yml``): train a small draft
    model against the FROZEN serving target's own logits so the paged
    engine's speculative decode has something worth proposing.

    The teacher is constructed exactly like the serving replica's model
    (same preset branch, same ``init_params(cfg, key(0))`` seed), so the
    artifact this run saves is compatible with the engine that will arm
    it. The student starts as the teacher's first ``--draft-layers``
    decoder layers (``llama.truncate_layers``) and trains EVERY one of
    its own weights — embed/head included — against the teacher's
    tempered distribution through the fused linear-KL head
    (``ops/losses.py:fused_linear_distillation``): the teacher's
    [B, S, V] fp32 logits never materialize, same memory contract as
    the fused-CE training loss. Gradients flow to the draft alone (the
    head gives the teacher structural zero cotangents; its trunk sits
    behind ``stop_gradient``).

    Saves a resumable train checkpoint under ``--out`` and, at the end,
    a sealed draft artifact under ``--out/draft``
    (``speculative.save_draft``) that ``--spec-decode`` serving loads
    and compat-checks."""
    import jax
    import jax.numpy as jnp

    from dcos_commons_tpu.models import llama, train
    from dcos_commons_tpu.models.speculative import save_draft
    from dcos_commons_tpu.ops.losses import fused_linear_distillation
    from dcos_commons_tpu.parallel import checkpoint as ckpt
    from dcos_commons_tpu.parallel import distributed
    from dcos_commons_tpu.parallel.mesh import MeshSpec

    contract = distributed.initialize()
    n = jax.device_count()
    if args.preset == "8b":
        cfg_t = llama.LlamaConfig.llama3_8b(max_seq=args.max_seq or 2048,
                                            remat=False)
    elif args.preset == "400m":
        cfg_t = llama.LlamaConfig.llama_400m(max_seq=args.max_seq or 2048)
    elif args.max_seq:
        cfg_t = llama.LlamaConfig.tiny(max_seq=args.max_seq)
    else:
        cfg_t = llama.LlamaConfig.tiny()
    seq = min(args.seq, cfg_t.max_seq)
    temp = max(float(getattr(args, "distill_temp", 1.0)), 1e-3)
    layers = max(1, min(getattr(args, "draft_layers", 1),
                        cfg_t.n_layers - 1))
    mesh = MeshSpec(dp=n).build()
    with mesh:
        params_t = llama.init_params(cfg_t, jax.random.key(0))
        cfg_d, params_d = llama.truncate_layers(cfg_t, params_t, layers)
        # the student trains its OWN copies; the view-sharing with the
        # teacher ends at the first optimizer step either way
        params_d = jax.tree.map(jnp.array, params_d)
    toks = jax.random.randint(jax.random.key(1), (max(args.batch, 1), seq),
                              0, cfg_t.vocab_size)

    def loss_fn(p_d, batch):
        x_t = jax.lax.stop_gradient(
            llama.forward(cfg_t, params_t, batch,
                          mesh if n > 1 else None, return_hidden=True))
        x_s = llama.forward(cfg_d, p_d, batch,
                            mesh if n > 1 else None, return_hidden=True)
        loss = fused_linear_distillation(
            x_s, p_d["lm_head"], x_t, params_t["lm_head"],
            temperature=temp)
        return loss, loss

    with mesh:
        opt = train.make_optimizer(lr=1e-3, warmup=5,
                                   decay_steps=max(args.steps, 10))
        step = train.make_train_step(loss_fn, opt, mesh=mesh,
                                     param_spec_tree=llama.param_specs(
                                         cfg_d),
                                     batch_spec=None)
        opt_state = train.init_opt_state(opt, params_d, mesh,
                                         llama.param_specs(cfg_d))
        w_params, w_opt, out = step(params_d, opt_state, toks)
        float(out["loss"])                       # compile barrier
        start = 0
        if args.out and (resume := ckpt.latest_step(args.out)) is not None:
            tree = ckpt.restore_sharded(
                args.out, {"params": w_params, "opt_state": w_opt},
                resume)
            params_d, opt_state = tree["params"], tree["opt_state"]
            start = resume
            _emit({"event": "resumed", "step": start, "sharded": True})
        else:
            params_d, opt_state = w_params, w_opt
        t0 = time.perf_counter()
        trajectory = []
        for i in range(start, args.steps):
            params_d, opt_state, out = step(params_d, opt_state, toks)
            loss = float(out["loss"])
            trajectory.append(round(loss, 6))
            if args.emit_every and (i + 1) % args.emit_every == 0:
                _emit({"event": "progress", "step": i + 1, "loss": loss})
            if args.out and args.ckpt_every \
                    and (i + 1 - start) % args.ckpt_every == 0:
                ckpt.save_sharded(args.out, i + 1,
                                  {"params": params_d,
                                   "opt_state": opt_state})
                _emit({"event": "checkpoint", "step": i + 1})
        dt = time.perf_counter() - t0
        draft_dir = ""
        if args.out:
            ckpt.save_sharded(args.out, args.steps,
                              {"params": params_d,
                               "opt_state": opt_state})
            draft_dir = os.path.join(args.out, "draft")
            save_draft(draft_dir, args.steps, cfg_d, params_d, cfg_t)
            _emit({"event": "draft_saved", "path": draft_dir,
                   "step": args.steps, "draft_layers": cfg_d.n_layers})
    steps_run = len(trajectory)
    return {"workload": "distill", "preset": args.preset,
            "draft_layers": cfg_d.n_layers, "teacher_layers": cfg_t.n_layers,
            "seq": seq, "temperature": temp,
            "loss_first": trajectory[0] if trajectory else None,
            "loss_final": trajectory[-1] if trajectory else None,
            "loss_trajectory": trajectory[-16:],
            "steps_run": steps_run, "draft_dir": draft_dir,
            "tokens_per_sec": (round(
                toks.shape[0] * seq * steps_run / dt, 1) if steps_run
                else 0.0),
            "process_id": contract["process_id"]}


WORKLOADS = {"mnist": run_mnist, "resnet": run_resnet, "llama": run_llama,
             "llama-train": run_llama_train, "distill": run_distill}


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("workload", choices=sorted(WORKLOADS))
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--depth", type=int, default=50,
                   help="resnet depth (18 for CPU smoke tests)")
    p.add_argument("--preset", default="tiny",
                   choices=["tiny", "400m", "8b"])
    p.add_argument("--kv-quant", action="store_true",
                   help="int8 KV cache (models/llama.py init_kv_cache): "
                        "halves cache traffic / doubles KV that fits")
    p.add_argument("--quant", default="none", choices=["none", "int8"],
                   help="llama: weight-only int8 serving (ops/quant.py); "
                        "required to fit the 8b preset on one 16 GB chip")
    p.add_argument("--max-seq", type=int, default=0,
                   help="llama: KV-cache length override (0 = preset "
                        "default; 8b serving defaults to 2048)")
    p.add_argument("--gen-len", type=int, default=16)
    p.add_argument("--slots", type=int, default=0,
                   help="llama --serve: continuous-batching slot count "
                        "(models/serving.py SlotServer); 0 = plain "
                        "heartbeat decode")
    p.add_argument("--serve", action="store_true",
                   help="llama: keep serving after warmup (RUNNING goal)")
    p.add_argument("--serve-port", type=int, default=-1,
                   help="llama --serve --slots: HTTP ingress port "
                        "(default: the PORT_SERVE env the matcher "
                        "reserved, else an ephemeral port; the bound "
                        "port is in the serving event)")
    p.add_argument("--pages", type=int,
                   default=int(os.environ.get("SERVE_PAGES", "0")),
                   help="llama --serve --slots: KV pages in the "
                        "block-paged engine's pool (models/serving.py "
                        "PagedServer); -1 = auto (slots x max_seq / "
                        "page_size), 0 = monolithic slot engine. An "
                        "infeasible paged config degrades to the slot "
                        "engine with a paged_fallback event")
    p.add_argument("--page-size", type=int,
                   default=int(os.environ.get("SERVE_PAGE_SIZE", "64")),
                   help="llama --serve --slots --pages: tokens per KV "
                        "page (must divide max_seq; multiples of 128 "
                        "keep the pallas decode kernel eligible)")
    p.add_argument("--prefill-chunk", type=int,
                   default=int(os.environ.get("SERVE_PREFILL_CHUNK",
                                              "64")),
                   help="llama --serve --slots --pages: prompt tokens "
                        "prefilled per engine step, interleaved with "
                        "decode (bounds head-of-line TTFT impact of "
                        "long prompts)")
    p.add_argument("--kv-tier-host-pages", type=int,
                   default=int(os.environ.get("KV_TIER_HOST_PAGES",
                                              "0")),
                   help="llama --serve --pages: pinned-host KV tier "
                        "capacity in pages; cold radix pages demote "
                        "here as digest-checked frames and promote "
                        "back asynchronously on prefix hit (0 = evict "
                        "frees outright, no tier)")
    p.add_argument("--kv-tier-disk-dir",
                   default=os.environ.get("KV_TIER_DISK_DIR", ""),
                   help="llama --serve --pages: directory for the "
                        "disk KV tier the host tier's LRU spills to "
                        "(empty = no disk tier)")
    p.add_argument("--kv-tier-disk-pages", type=int,
                   default=int(os.environ.get("KV_TIER_DISK_PAGES",
                                              "0")),
                   help="llama --serve --pages: disk KV tier capacity "
                        "in pages (overflow drops the coldest frame)")
    p.add_argument("--prefix-directory", type=float,
                   default=float(os.environ.get("PREFIX_DIRECTORY",
                                                "0")),
                   help="llama --serve --pages: fleet prefix-directory "
                        "staleness window in seconds; > 0 publishes "
                        "this replica's cached chains and adopts "
                        "fleet-hot prefixes from sibling /v1/prefix "
                        "endpoints instead of recomputing (0 = off)")
    p.add_argument("--spec-decode",
                   default=os.environ.get("SPEC_DECODE", "false"),
                   help="llama --serve --pages: arm speculative decode "
                        "on the paged engine — draft-propose + fused "
                        "paged verify, 1 + accepted tokens per target "
                        "pass, token-exact greedy output. true/false "
                        "(spec boolean); any draft problem degrades to "
                        "solo with a coded spec_fallback event")
    p.add_argument("--draft-checkpoint",
                   default=os.environ.get("DRAFT_CHECKPOINT", ""),
                   help="llama --serve --spec-decode: save_draft "
                        "artifact directory (the distill workload's "
                        "--out/draft) — sharded draft weights + "
                        "draft_config.json compat/staleness seal")
    p.add_argument("--draft-k", type=int,
                   default=int(os.environ.get("DRAFT_K", "4") or 4),
                   help="speculative window: draft proposals verified "
                        "per target pass (>= 2; each window emits "
                        "1..k target-verified tokens)")
    p.add_argument("--draft-layers", type=int,
                   default=int(os.environ.get("DRAFT_LAYERS", "1") or 1),
                   help="distill: student decoder layers (initialized "
                        "as the teacher's first N via truncate_layers; "
                        "clamped to teacher layers - 1)")
    p.add_argument("--distill-temp", type=float,
                   default=float(os.environ.get("DISTILL_TEMP", "1.0")
                                 or 1.0),
                   help="distill: softmax temperature both "
                        "distributions are smoothed by in the KL loss")
    p.add_argument("--moe-experts", type=int,
                   default=int(os.environ.get("MOE_EXPERTS", "0") or 0),
                   help="llama --serve --pages: experts in the routed "
                        "MLP (0 = dense). Serving weights are built raw "
                        "bf16 (init_moe_params) and every decode/prefill "
                        "executable routes its FFN through parallel/"
                        "moe.py; when the replica's device count divides "
                        "the expert count the experts shard over an ep "
                        "mesh axis and dispatch runs the capacity-"
                        "bounded all-to-all (dist/moe.yml)")
    p.add_argument("--moe-capacity-factor", type=float,
                   default=float(os.environ.get("MOE_CAPACITY_FACTOR",
                                                "0") or 0),
                   help="llama --serve --moe-experts: expert buffer "
                        "slots = tokens/experts * factor. 0 (default) = "
                        "dropless (factor = experts): capacity never "
                        "binds, so routing is independent of token "
                        "grouping and serving stays token-exact vs the "
                        "stepwise reference — the parity contract. "
                        "Smaller factors trade that exactness for "
                        "bounded buffers (dropped tokens pass through "
                        "on the residual)")
    p.add_argument("--longctx-ring", type=int,
                   default=int(os.environ.get("LONGCTX_RING", "0") or 0),
                   help="llama --serve --prefill-seq-parallel: sp-axis "
                        "size for ring prefill; 0 = the replica's whole "
                        "device count (the only size this build "
                        "accepts, so the knob is an explicit assertion "
                        "of gang geometry — a mismatch degrades with a "
                        "coded longctx_fallback)")
    p.add_argument("--prefill-seq-parallel",
                   default=os.environ.get("PREFILL_SEQ_PARALLEL",
                                          "false"),
                   help="llama --serve --pages: true/false (spec "
                        "boolean) — prompts >= 2*prefill_chunk prefill "
                        "in ONE sequence-parallel tick via "
                        "llama.prefill_ring over the sp mesh axis "
                        "(~seq/N per-host time, dist/longctx.yml) "
                        "instead of serial chunks; disqualified "
                        "configs degrade to chunked prefill with a "
                        "coded longctx_fallback event")
    p.add_argument("--queue-limit", type=int, default=64,
                   help="llama --serve --slots: bounded ingress queue "
                        "(overflow answers 503 + Retry-After)")
    p.add_argument("--decode-window", type=int, default=8,
                   help="llama --serve --slots: tokens decoded per "
                        "device dispatch (SlotServer.step_many); "
                        "dispatch latency bounds TPOT on tunneled "
                        "backends — raise to amortize, lower for "
                        "tighter intake latency")
    p.add_argument("--serve-interval", type=float, default=30.0,
                   help="llama --serve: seconds between decode heartbeats")
    p.add_argument("--serve-role",
                   default=os.environ.get("SERVE_ROLE", "colocated"),
                   choices=["colocated", "prefill", "decode", "router"],
                   help="llama --serve: tier role. 'prefill' answers "
                        "/v1/prefill with packed KV page spans, "
                        "chunked prefill flat-out; 'decode' runs the "
                        "client front door and adopts pages shipped "
                        "from --serve-peer (dist/disagg.yml); 'router' "
                        "runs the model-free fleet front door — "
                        "prefix-affinity routing across "
                        "--route-replicas (dist/fleet.yml, "
                        "models/router.py); the default serves both "
                        "phases co-located on one engine")
    p.add_argument("--route-replicas",
                   default=os.environ.get("ROUTE_REPLICAS", ""),
                   help="llama --serve --serve-role router: decode "
                        "replica base URLs, comma-separated (from "
                        "`tpuctl endpoints serve`). Resizes land at "
                        "runtime via POST /v1/replicas")
    p.add_argument("--route-policy",
                   default=os.environ.get("ROUTE_POLICY", "affinity"),
                   choices=["affinity", "random"],
                   help="router: prefix-affinity consistent hashing, "
                        "or uniform random (the A/B control arm)")
    p.add_argument("--route-affinity-pages", type=int,
                   default=int(os.environ.get("ROUTE_AFFINITY_PAGES",
                                              "1")),
                   help="router: full prompt pages hashed into the "
                        "affinity key (1 = the shared system-prompt "
                        "page; more pins deeper prefixes)")
    p.add_argument("--route-vnodes", type=int,
                   default=int(os.environ.get("ROUTE_VNODES", "64")),
                   help="router: virtual nodes per replica on the "
                        "hash ring (more = smoother balance, bigger "
                        "ring)")
    p.add_argument("--route-spill-pressure", type=float,
                   default=float(os.environ.get("ROUTE_SPILL_PRESSURE",
                                                "0.85")),
                   help="router: back-pressure (scheduler/elastic.py "
                        "backpressure() over the replica's /v1/healthz "
                        "load gauges) above which the affinity target "
                        "counts as hot and requests spill to the "
                        "least-loaded healthy replica")
    p.add_argument("--route-spill-floor", type=int,
                   default=int(os.environ.get("ROUTE_SPILL_FLOOR", "0")),
                   help="router: minimum QoS-class priority allowed to "
                        "spill on HOT (spill on DOWN applies to all "
                        "classes — availability is not a paid feature)")
    p.add_argument("--tenant-classes",
                   default=os.environ.get("TENANT_CLASSES", ""),
                   help="router: per-tenant QoS classes, "
                        "name:priority:rate:burst[:ttft_slo_ms] "
                        "comma-separated, e.g. "
                        "'gold:10:50:100:250,free:1:2:4'. priority "
                        "shares the scheduler's priority: integer "
                        "scale; rate/burst parameterize each tenant's "
                        "token bucket; empty = admission wide open")
    p.add_argument("--tenant-max-tracked", type=int,
                   default=int(os.environ.get("TENANT_MAX_TRACKED",
                                              "4096")),
                   help="router: LRU cap on tracked per-tenant state "
                        "(buckets + counters), bounding memory against "
                        "unique-X-Tenant floods; an idle tenant "
                        "evicted past the cap restarts from a fresh "
                        "burst on return")
    p.add_argument("--serve-peer",
                   default=os.environ.get("SERVE_PEER", ""),
                   help="llama --serve --serve-role decode: prefill "
                        "tier base URL(s) (http[s]://host:port, from "
                        "the scheduler's endpoints surface; "
                        "comma-separated for multiple peers — "
                        "round-robin with /v1/healthz-gated "
                        "per-peer fallback). Empty "
                        "degrades loudly to co-located serving "
                        "(disagg_fallback)")
    p.add_argument("--attn", default="auto",
                   choices=["auto", "dense", "flash", "ring", "ulysses"])
    p.add_argument("--ring-layout", default="contiguous",
                   choices=["contiguous", "zigzag"],
                   help="llama-train --attn ring: zigzag balances causal "
                        "work across the ring (each shard holds one "
                        "early + one late chunk); needs seq %% (2*sp) "
                        "== 0, else falls back to contiguous")
    p.add_argument("--seq", type=int, default=256,
                   help="llama-train: sequence length")
    p.add_argument("--fused-ce", default=os.environ.get("FUSED_CE", "true"),
                   help="llama-train: fused linear-cross-entropy loss head "
                        "(ops/losses.py) — never materializes the "
                        "[B, S, V] fp32 logits. true/false; mustache "
                        "renders the spec's {{FUSED_CE}} env knob here, "
                        "parsed like any spec boolean (yaml_bool)")
    p.add_argument("--grad-accum", type=int,
                   default=int(os.environ.get("GRAD_ACCUM", "1") or 1),
                   help="llama-train: gradient-accumulation microbatches "
                        "per optimizer step (models/train.py); 1 = off. "
                        "Spec env knob {{GRAD_ACCUM}}. A value the batch "
                        "isn't divisible by degrades to 1 (a bad config "
                        "must not crash-loop the gang)")
    p.add_argument("--sp", type=int, default=0,
                   help="llama-train: sequence-parallel mesh size (0=auto)")
    p.add_argument("--tp", type=int, default=0,
                   help="llama-train: tensor-parallel mesh size (0=auto)")
    p.add_argument("--pp", type=int, default=0,
                   help="llama-train: pipeline-parallel stages (GPipe)")
    p.add_argument("--ep", type=int, default=0,
                   help="llama-train: expert-parallel mesh size (MoE)")
    p.add_argument("--moe-routing", default="top2",
                   choices=["top2", "expert_choice"],
                   help="llama-train --ep: token-choice top-2 (GShard, "
                        "capacity drops + aux loss; the causal-LM "
                        "default) or expert-choice (dropless, balanced "
                        "by construction — but ranks tokens against "
                        "FUTURE positions, so it is non-causal for "
                        "strict next-token training; see "
                        "parallel/moe.py)")
    p.add_argument("--lr", type=float, default=0.0,
                   help="resnet: learning-rate override (0 = default "
                        "0.1; the gang e2e tier uses a small lr so the "
                        "loss stream stays informative across the "
                        "kill/resume boundary)")
    p.add_argument("--emit-every", type=int, default=0,
                   help="resnet: emit a {event: progress, step, loss} "
                        "line every N steps (0 = off; forces a per-emit "
                        "host sync, so leave off when benchmarking)")
    p.add_argument("--out", default="")
    p.add_argument("--ckpt-every", type=int, default=0,
                   help="llama-train: save a sharded checkpoint every N "
                        "steps (0 = only at the end); resume is automatic "
                        "when --out holds one")
    p.add_argument("--profile-dir", default="",
                   help="write a jax.profiler trace of the whole workload "
                        "here (env TPU_PROFILE_DIR also works, so specs "
                        "can toggle profiling via TASKCFG_* env without "
                        "editing cmds); view with tensorboard/xprof")
    return p


def main(argv=None) -> int:
    logging.basicConfig(level=logging.INFO)
    args = build_parser().parse_args(argv)
    # Environments whose sitecustomize pre-registers a backend ignore the
    # JAX_PLATFORMS env var; backend SELECTION is still lazy, so an
    # explicit config.update honors the operator's choice (the
    # tests/_jax_cpu.py mechanism — without this, CPU-mesh subprocess
    # runs silently land on the default backend)
    want_platform = os.environ.get("JAX_PLATFORMS")
    if want_platform:
        import jax
        # keep the host cpu platform available ALONGSIDE the requested
        # one: quantized init (llama.init_quantized_params) streams
        # weights through the cpu backend, and jax_platforms is a
        # priority list — the first entry stays the default backend, so
        # appending cpu changes nothing else
        if "cpu" not in [p.strip() for p in want_platform.split(",")]:
            want_platform += ",cpu"
        jax.config.update("jax_platforms", want_platform)
    num_slices = int(os.environ.get("MEGASCALE_NUM_SLICES", "1"))
    if num_slices > 1 and args.workload != "resnet":
        # only the dp trainer builds a dcn-aware mesh today; any other mode
        # would lay a pure-ICI mesh across slices and route per-layer
        # collectives over DCN — fail fast instead
        print(f"error: workload {args.workload!r} does not support "
              f"multislice (MEGASCALE_NUM_SLICES={num_slices}); "
              "use the resnet dp trainer or drop tpu.slices",
              file=sys.stderr)
        return 2
    # XLA dump plumbing (SURVEY §5 tracing/profiling): the flag must be in
    # the env BEFORE jax initializes, so it only takes effect when the
    # worker runs as its own process (the production path — tasks are
    # `python -m frameworks.jax.worker ...`); in-process callers that
    # already imported jax keep their existing backend flags
    dump_dir = os.environ.get("TPU_XLA_DUMP_DIR", "")
    if dump_dir and "xla_dump_to" not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + f" --xla_dump_to={dump_dir}").strip()
    _emit({"event": "start", "workload": args.workload,
           "task": os.environ.get("TASK_NAME", "?"),
           "pod_index": os.environ.get("POD_INSTANCE_INDEX", "0"),
           # the interpreter's own pid (the sh wrapper's is in task.pid):
           # fault-injection tiers kill exactly the training process
           "pid": os.getpid()})
    profile_dir = args.profile_dir or os.environ.get("TPU_PROFILE_DIR", "")
    if profile_dir:
        import jax
        os.makedirs(profile_dir, exist_ok=True)
        _emit({"event": "profiling", "dir": profile_dir})
        with jax.profiler.trace(profile_dir):
            result = WORKLOADS[args.workload](args)
    else:
        result = WORKLOADS[args.workload](args)
    _emit({"event": "done", **result})
    if result.get("stopped") == "preempted":
        # conventional SIGTERM exit: the checkpoint is flushed, and the
        # scheduler's relaunch resumes from it
        return 143
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
