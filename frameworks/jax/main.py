"""frameworks/jax scheduler entry point.

Deploys one of the JAX workload scenarios (mnist / resnet / llama / svc)
as a long-running scheduled service: gang-placed TPU pods, deploy plan,
recovery plan with coordinated gang re-form on worker failure (the core
recovery manager restarts siblings of a gang pod so ``jax.distributed``
can re-initialize with stable ranks — SURVEY.md §7 hard part (3)).

Usage::

    python -m frameworks.jax.main [scenario] [--port N] [--state DIR]
"""

from __future__ import annotations

import argparse
import logging
import os
import sys
import threading
import time

from dcos_commons_tpu.agent.remote import RemoteCluster
from dcos_commons_tpu.agent.retry import RetryingAgentClient
from dcos_commons_tpu.http import ApiServer
from dcos_commons_tpu.security import Authenticator
from dcos_commons_tpu.metrics import MetricsRegistry, PlanReporter
from dcos_commons_tpu.scheduler import ServiceScheduler
from dcos_commons_tpu.scheduler.runner import CycleDriver
from dcos_commons_tpu.state.replicated import open_state

from . import scenarios


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("scenario", nargs="?", default="svc",
                   help="workload YAML under dist/ (svc, mnist, resnet, llama)")
    p.add_argument("--port", type=int,
                   default=int(os.environ.get("API_PORT", "8080")))
    p.add_argument("--state", default=os.environ.get("STATE_DIR", "./state"))
    p.add_argument("--interval", type=float, default=1.0)
    p.add_argument("--list", action="store_true", help="list scenarios")
    return p


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s %(message)s")
    args = build_parser().parse_args(argv)
    if args.list:
        print("\n".join(scenarios.list_scenarios()))
        return 0

    metrics = MetricsRegistry()
    statsd_host = os.environ.get("STATSD_UDP_HOST")
    if statsd_host:
        metrics.configure_statsd(statsd_host,
                                 int(os.environ.get("STATSD_UDP_PORT", "8125")))
    # single-instance gate + state backend: the replicated
    # ensemble when TPU_STATE_ENDPOINTS is set, else local files
    persister, lock = open_state(args.state)
    cluster = RemoteCluster()
    # the scheduler's launch/kill RPCs ride the retrying wrapper
    # (bounded attempts, jittered backoff, per-call deadline); the
    # API server keeps the raw client for read-only passthrough
    sched_cluster = RetryingAgentClient(cluster)
    # control-plane auth: TPU_AUTH_FILE names the accounts file
    _auth = Authenticator.from_env()
    # transport security: TPU_TLS=1 mints from the persisted CA (or
    # TPU_TLS_CERT/TPU_TLS_KEY name provisioned PEMs)
    from dcos_commons_tpu.security import server_tls_from_env
    _tls = server_tls_from_env(persister, "jax", args.state)
    spec = scenarios.load_scenario(args.scenario)
    scheduler = ServiceScheduler(spec, persister, sched_cluster,
                                 metrics=metrics, auth=_auth)
    scheduler.respec = (lambda env, _name=args.scenario:
                        scenarios.load_scenario(_name, env))
    server = ApiServer(scheduler, port=args.port, metrics=metrics,
                       cluster=cluster, auth=_auth, tls=_tls)
    PlanReporter(metrics, scheduler)
    driver = CycleDriver(scheduler, interval_s=args.interval)

    # live elastic loop: AUTOSCALE_POD_TYPE + AUTOSCALE_GAUGE_URLS arm a
    # back-pressure autoscaler fed by the decode frontends' /v1/healthz
    # "load" gauges (ServingFrontend.load_gauges() over HTTP). The shared
    # registry also carries the WARM_POOL_SIZE tier's headroom gauges
    # (autoscale.warm_pool.*) so `tpuctl warm-pool` reads them off
    # /v1/metrics
    from dcos_commons_tpu.scheduler.elastic import autoscaler_from_env
    autoscaler = autoscaler_from_env(scheduler, metrics=metrics,
                                     registry=metrics)
    auto_stop = threading.Event()
    if autoscaler is not None:
        interval_s = float(os.environ.get("AUTOSCALE_INTERVAL_S", "5"))

        def _auto_loop():
            while not auto_stop.wait(interval_s):
                try:
                    autoscaler.tick()
                except Exception:
                    logging.getLogger("autoscale").exception(
                        "autoscaler tick failed")

        threading.Thread(target=_auto_loop, daemon=True,
                         name="autoscaler").start()
        print(f"autoscaler armed: pod type "
              f"{autoscaler.config.pod_type}, "
              f"count {autoscaler.config.min_count}.."
              f"{autoscaler.config.max_count}, "
              f"tick every {interval_s}s", flush=True)

    server.start()
    print(f"jax scheduler API on {server.url}/v1/",
          flush=True)
    try:
        with driver:
            while True:
                time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        auto_stop.set()
        server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
