"""Worker-side fault sentinel: preemption, NaN, and stall handling.

The scheduler recovers *processes*; this module recovers *training runs*.
Three failure modes every long TPU job eventually meets, each with a
worker-local first response:

* **Preemption** (SIGTERM): TPU reservations are revoked with a grace
  window. The sentinel flips a flag; the training loop checks it between
  steps, flushes a sharded checkpoint, and exits cleanly — the relaunched
  incarnation resumes from that step instead of the last periodic save.
* **Non-finite loss**: one bad batch or a flaky interconnect reduction
  can poison the params. The loop rolls back to the newest
  ``save_sharded`` checkpoint (optimizer state included, so the LR
  schedule resumes exactly) and re-runs from there, up to a bounded
  number of rollbacks before giving up — crash-looping on a
  deterministically-bad step must still surface to the scheduler.
* **Stall**: a wedged collective (lost gang peer, hung host transfer)
  blocks inside one step forever, which no between-step check can see.
  A watchdog timer aborts the process so the scheduler's recovery plan
  takes over; a dead worker is recoverable, a silent one is not.

Env knobs (read by :meth:`FaultSentinel.from_env`):

* ``SENTINEL_STALL_S`` — seconds a single step may take before the
  watchdog aborts the process. ``0`` (default) disables the watchdog.
* ``SENTINEL_NAN_EVERY`` — check the loss for finiteness every N steps
  (each check syncs the device). ``1`` (default) checks every step;
  ``0`` disables.
* ``SENTINEL_MAX_ROLLBACKS`` — NaN rollbacks allowed per run before the
  loop raises (default ``3``).

Pure Python on purpose: no jax imports, so the loop logic is unit-testable
with stub step functions on any host (tests/test_sentinel.py).
"""

from __future__ import annotations

import contextlib
import math
import os
import signal
import threading
import time
from typing import Callable, Optional

STALL_EXIT_CODE = 74  # EX_IOERR: distinguishable from crash (1) and OOM kills


def _default_abort(step: int, stall_s: float) -> None:
    # os._exit, not sys.exit: the wedged step holds the main thread, and
    # an exception raised from this timer thread would go nowhere
    os._exit(STALL_EXIT_CODE)


class FaultSentinel:
    def __init__(self, stall_s: float = 0.0, nan_every: int = 1,
                 max_rollbacks: int = 3,
                 emit: Optional[Callable[[dict], None]] = None,
                 abort: Optional[Callable[[int, float], None]] = None):
        self.stall_s = stall_s
        self.nan_every = nan_every
        self.max_rollbacks = max_rollbacks
        self.preempted = False
        self._emit = emit or (lambda record: None)
        self._abort = abort or _default_abort
        self._prev_handler = None

    @classmethod
    def from_env(cls, emit: Optional[Callable[[dict], None]] = None,
                 env=os.environ) -> "FaultSentinel":
        return cls(stall_s=float(env.get("SENTINEL_STALL_S", "0") or 0),
                   nan_every=int(env.get("SENTINEL_NAN_EVERY", "1") or 0),
                   max_rollbacks=int(env.get("SENTINEL_MAX_ROLLBACKS", "3")),
                   emit=emit)

    # -- preemption --------------------------------------------------------

    def install(self) -> None:
        """Register the SIGTERM flag-flip. Safe to skip silently when not
        on the main thread (in-process test harnesses)."""
        def handler(signum, frame):
            self.preempted = True
            self._emit({"event": "sigterm", "action": "flush-and-exit"})
        try:
            self._prev_handler = signal.signal(signal.SIGTERM, handler)
        except ValueError:
            pass  # not the main thread; preemption handling stays manual

    def uninstall(self) -> None:
        if self._prev_handler is not None:
            try:
                signal.signal(signal.SIGTERM, self._prev_handler)
            except ValueError:
                pass
            self._prev_handler = None

    # -- stall watchdog ----------------------------------------------------

    @contextlib.contextmanager
    def watch(self, step: int):
        """Arm the watchdog around one training step."""
        if not self.stall_s:
            yield
            return
        def fire():
            self._emit({"event": "stall", "step": step,
                        "stall_s": self.stall_s})
            self._abort(step, self.stall_s)
        timer = threading.Timer(self.stall_s, fire)
        timer.daemon = True
        timer.start()
        try:
            yield
        finally:
            timer.cancel()

    # -- NaN policy --------------------------------------------------------

    def should_check_loss(self, step: int) -> bool:
        return self.nan_every > 0 and step % self.nan_every == 0


def guarded_loop(sentinel: FaultSentinel, start: int, steps: int,
                 run_step: Callable[[int], object],
                 loss_of: Callable[[object], float],
                 save: Callable[[int], None],
                 restore: Callable[[], Optional[int]],
                 emit: Optional[Callable[[dict], None]] = None
                 ) -> tuple[str, int]:
    """Drive ``run_step`` from ``start`` to ``steps`` under the sentinel.

    ``run_step(i)`` executes step ``i`` (mutating the caller's state via
    closure) and returns an opaque result; ``loss_of(result)`` materializes
    its loss (called only on checked steps — each call syncs the device).
    ``save(i)`` checkpoints the state as of ``i`` completed steps;
    ``restore()`` rolls state back to the newest checkpoint and returns
    its step, or None when there is nothing to roll back to.

    Returns ``(reason, next_step)`` where reason is ``"completed"`` or
    ``"preempted"`` and next_step is where a resumed run would continue.
    """
    emit = emit or (lambda record: None)
    rollbacks = 0
    i = start
    while i < steps:
        if sentinel.preempted:
            # stamp the SIGTERM flush receipt with the flushed step and
            # the wall-clock flush cost: the restart-free reshard A/B
            # (bench_r19/reshard.jsonl) needs a per-phase
            # checkpoint-restart baseline, not just aggregate tick counts
            t0 = time.monotonic()
            save(i)
            emit({"event": "preempted", "step": i, "flushed_step": i,
                  "flush_s": round(time.monotonic() - t0, 6)})
            return "preempted", i
        with sentinel.watch(i):
            result = run_step(i)
        if sentinel.should_check_loss(i):
            loss = loss_of(result)
            if loss is not None and not math.isfinite(loss):
                rollbacks += 1
                emit({"event": "nonfinite_loss", "step": i, "loss": repr(loss),
                      "rollback": rollbacks})
                if rollbacks > sentinel.max_rollbacks:
                    raise RuntimeError(
                        f"loss non-finite at step {i} after "
                        f"{sentinel.max_rollbacks} rollbacks — giving up so "
                        "the scheduler sees the crash-loop")
                t0 = time.monotonic()
                restored = restore()
                if restored is None:
                    raise RuntimeError(
                        f"loss non-finite at step {i} and no checkpoint to "
                        "roll back to")
                emit({"event": "rolled_back", "to_step": restored,
                      "restore_s": round(time.monotonic() - t0, 6)})
                i = restored
                continue
        i += 1
    return "completed", i
