"""frameworks/jax scenario registry.

Mirrors the helloworld registry: ``dist/<name>.yml`` rendered with
universe-default env (the reference renders package defaults via
``CosmosRenderer``, ``sdk/testing/.../CosmosRenderer.java``).
"""

from __future__ import annotations

import os
from typing import Mapping, Optional

from dcos_commons_tpu.specification import ServiceSpec, load_service_yaml

DIST = os.path.join(os.path.dirname(__file__), "dist")

# universe/config.json option defaults (Marathon injects these in production)
DEFAULT_ENV: Mapping[str, str] = {
    "FRAMEWORK_NAME": "jax",
    "SERVICE_NAME": "jax",
    # worker gang shape: v4-32 = 4 hosts x 4 chips (north-star config)
    "WORKER_COUNT": "4",
    "CHIPS_PER_WORKER": "4",
    "TPU_TOPOLOGY": "v4-32",
    "WORKER_CPUS": "8",
    "WORKER_MEM": "65536",
    "CKPT_DISK": "65536",
    # trainer knobs routed into the worker cmd
    "TRAIN_STEPS": "200",
    "BATCH_PER_HOST": "256",
    "RESNET_DEPTH": "50",
    "LLAMA_PRESET": "tiny",
    "SHARD_COUNT": "4",
    # multislice scenario knobs (multislice.yml)
    "NUM_SLICES": "2",
    # sharded-checkpoint cadence for llama-train scenarios (0 = final only)
    "CKPT_EVERY": "0",
    # continuous-batching scenario knobs (serving.yml): single-chip
    # slot-engine replicas; SERVE_FLAGS carries e.g.
    # "--quant int8 --kv-quant" for the 8b preset
    "SERVER_COUNT": "4",
    "SERVE_SLOTS": "8",
    "SERVE_CHIPS": "1",
    "SERVE_FLAGS": "",
    # paged-KV engine knobs (models/serving.py PagedServer): SERVE_PAGES
    # > 0 switches the replica to the block-paged engine with that many
    # KV pages (-1 = auto: slots x max_seq/page_size, i.e. slot-equivalent
    # provisioning); 0 keeps the monolithic slot engine. The worker
    # degrades to the slot engine (loudly, never crashing) when the
    # paged config is infeasible for the model, e.g. max_seq not a
    # multiple of SERVE_PAGE_SIZE.
    "SERVE_PAGES": "0",
    "SERVE_PAGE_SIZE": "64",
    "SERVE_PREFILL_CHUNK": "64",
    # hierarchical KV economy (models/paging.py PageTierStore +
    # PrefixDirectory): KV_TIER_HOST_PAGES > 0 arms a pinned-host tier
    # that cold radix pages demote into as digest-checked frames
    # instead of being freed; KV_TIER_DISK_DIR + KV_TIER_DISK_PAGES
    # add a disk tier the host LRU spills to (capacities in PAGES, so
    # host+disk >= SERVE_PAGES doubles effective cache at equal HBM).
    # PREFIX_DIRECTORY > 0 arms the fleet prefix directory with that
    # staleness window in seconds: the replica publishes its cached
    # chains and adopts fleet-hot prefixes from sibling /v1/prefix
    # endpoints instead of recomputing (stale hints cost one failed
    # fetch and fall back to recompute — never a wrong answer).
    "KV_TIER_HOST_PAGES": "0",
    "KV_TIER_DISK_DIR": "",
    "KV_TIER_DISK_PAGES": "0",
    "PREFIX_DIRECTORY": "0",
    # speculative decoding on the paged engine (models/serving.py
    # arm_draft + models/speculative.py draft artifacts): SPEC_DECODE=
    # true arms draft-propose + fused paged-verify windows — each
    # target pass emits 1 + accepted tokens, output token-exact with
    # solo greedy decode. DRAFT_CHECKPOINT points at a save_draft
    # artifact (the distill workload's --out/draft); any draft problem
    # (missing, stale manifest seal, vocab/rope mismatch, compile
    # rejection) degrades to solo with a coded spec_fallback event.
    # DRAFT_K sizes the window (proposals verified per pass).
    # DRAFT_LAYERS / DISTILL_TEMP parameterize the distill trainer
    # (distill.yml) that produces the artifact.
    "SPEC_DECODE": "false",
    "DRAFT_CHECKPOINT": "",
    "DRAFT_K": "4",
    "DRAFT_LAYERS": "1",
    "DISTILL_TEMP": "1.0",
    # disaggregated prefill/decode tiers (disagg.yml + models/disagg.py):
    # SERVE_ROLE picks the tier a replica runs (colocated|prefill|decode)
    # and SERVE_PEER points a decode replica at its prefill tier's
    # /v1/prefill endpoint (from `tpuctl endpoints serve`; a comma-
    # separated list round-robins across prefill peers with per-peer
    # /v1/healthz fallback; empty degrades
    # loudly to co-located serving). DISAGG_PAGES sizes the tiers' page
    # pools (-1 = auto slot-equivalent) — disagg is paged-only, so the
    # yml does not inherit the co-located SERVE_PAGES=0 default.
    "SERVE_ROLE": "colocated",
    "SERVE_PEER": "",
    "DISAGG_PAGES": "-1",
    "PREFILL_COUNT": "1",
    "DECODE_COUNT": "2",
    # fleet front-door knobs (fleet.yml + models/router.py): a router
    # pod consistent-hashes prompts onto the decode replicas listed in
    # ROUTE_REPLICAS (filled from `tpuctl endpoints serve`; resizes
    # land at runtime via POST /v1/replicas).
    # ROUTE_POLICY=random is the A/B control arm the bench uses.
    # TENANT_CLASSES maps tenants onto the scheduler's priority:
    # integers with token-bucket admission —
    # name:priority:rate:burst[:ttft_slo_ms], comma-separated.
    # cold-start collapse knobs (scheduler/elastic.py WarmPool +
    # models/weights.py + parallel/aot.py): WARM_POOL_SIZE > 0 keeps that
    # many weights-resident standby pods the autoscaler promotes in one
    # tick (WARM_POOL_MIN_SERVING floors demotion-into-the-pool);
    # AUTOSCALE_RESERVE_AUTO=1 sizes the BackfillGate reserve from the
    # rolling max of pending expansion demand. WEIGHT_FETCH_PEERS points
    # a booting replica at hot peers' /v1/weights endpoints (falls back
    # to disk, loudly, on any fetch error); WEIGHT_SERVE_PORT makes the
    # replica serve its own shards once up (0 = ephemeral port).
    # AOT_CACHE=0 disables the in-process compile cache shared across
    # homogeneous engine builds; AOT_CACHE_DIR additionally arms the
    # persistent jax compilation cache at that path.
    "WARM_POOL_SIZE": "0",
    "WARM_POOL_MIN_SERVING": "1",
    "AUTOSCALE_RESERVE_AUTO": "0",
    "WEIGHT_FETCH_PEERS": "",
    "WEIGHT_FETCH_TIMEOUT_S": "120",
    "WEIGHT_SERVE_PORT": "",
    "AOT_CACHE": "1",
    "AOT_CACHE_DIR": "",
    "ROUTER_COUNT": "1",
    "ROUTE_REPLICAS": "",
    "ROUTE_POLICY": "affinity",
    "ROUTE_AFFINITY_PAGES": "1",
    "ROUTE_VNODES": "64",
    "ROUTE_SPILL_PRESSURE": "0.85",
    "ROUTE_SPILL_FLOOR": "0",
    "TENANT_CLASSES": "gold:10:50:100:500,bronze:1:5:10",
    # LRU cap on tracked per-tenant router state (buckets/counters):
    # bounds memory against unique-X-Tenant floods
    "TENANT_MAX_TRACKED": "4096",
    # round-18 serving arithmetic (moe.yml + longctx.yml serving pods,
    # frameworks/jax/worker.py _serving_arithmetic). MOE_EXPERTS > 0
    # serves the routed-MLP Llama variant through the paged engine:
    # raw-bf16 expert banks, decode dispatch through parallel/moe.py
    # (experts sharded over an ep mesh axis when the replica's chip
    # count divides the expert count — the capacity-bounded all-to-all
    # in the analysis manifest). MOE_CAPACITY_FACTOR=0 means dropless
    # (factor = experts): routing independent of token grouping, so
    # serving stays token-exact vs the stepwise reference (chaos
    # invariant 19's contract). PREFILL_SEQ_PARALLEL=true arms ring
    # prefill on the paged engine: prompts >= 2*prefill_chunk run
    # llama.prefill_ring over the sp mesh axis in ONE tick (~seq/N
    # per-host time) with the K/V span landing page-aligned in the
    # local pool; LONGCTX_RING asserts the sp size (0 = the replica's
    # whole chip count). Every disqualifying combo degrades with a
    # coded moe_fallback/longctx_fallback event, never a crash.
    "MOE_EXPERTS": "0",
    "MOE_CAPACITY_FACTOR": "0",
    "LONGCTX_RING": "0",
    "PREFILL_SEQ_PARALLEL": "false",
    # long-context scenario knobs (longctx.yml)
    "SEQ_LEN": "8192",
    "ATTN_IMPL": "ring",
    # zigzag balances causal ring work (parallel/ring_attention.py);
    # the default long-context seq (8192) divides any 2*sp it meets
    "RING_LAYOUT": "zigzag",
    "SP": "0",
    "TP": "0",
    # loss-head knobs (ops/losses.py fused linear-CE + models/train.py
    # microbatching); overridable per-pod via TASKCFG_* like any env knob
    "FUSED_CE": "true",
    "GRAD_ACCUM": "1",
    # restart-free gang resharding (parallel/reshard.py + the
    # scheduler/elastic.py ReshardConfig contract): RESHARD_ENABLE=1
    # arms the train tier's live-migration path — on resize/preemption
    # the gang freezes at a step boundary, publishes its state over the
    # P2P weight channel (GANGSTATE frame + WTSHARD1 shards), and the
    # surviving mesh adopts it transactionally; any leg that fails
    # degrades to the sentinel checkpoint-flush -> relaunch path.
    # RESHARD_PEERS points an adopting worker at frozen peers'
    # /v1/weights endpoints; RESHARD_PORT serves this worker's own live
    # state (0 = ephemeral); RESHARD_TIMEOUT_S bounds one
    # freeze->install leg; RESHARD_WORKERS is the concurrent shard
    # transfer width; RESHARD_LINGER_S keeps a preempted worker's
    # live-state server up inside the grace window so peers finish
    # pulling before exit.
    "RESHARD_ENABLE": "0",
    "RESHARD_PEERS": "",
    "RESHARD_PORT": "0",
    "RESHARD_TIMEOUT_S": "60",
    "RESHARD_WORKERS": "4",
    "RESHARD_LINGER_S": "0",
    # fetched into every task sandbox pre-launch (reference: resource.json
    # assets fetched by Mesos; in production the universe template overrides
    # this with the artifact URL). Default: the locally-built binary.
    "BOOTSTRAP_URI": "file://" + os.path.abspath(os.path.join(
        os.path.dirname(__file__), "..", "..", "native", "bin",
        "tpu-bootstrap")),
}


def scenario_env(overrides: Optional[Mapping[str, str]] = None) -> dict:
    env = dict(DEFAULT_ENV)
    env.update(os.environ)
    if overrides:
        env.update(overrides)
    return env


# the default service is the north-star data-parallel trainer
ALIASES = {"svc": "resnet"}


def load_scenario(name: str = "svc",
                  env: Optional[Mapping[str, str]] = None) -> ServiceSpec:
    name = ALIASES.get(name, name)
    path = os.path.join(DIST, f"{name}.yml")
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"unknown scenario {name!r}; available: {sorted(list_scenarios())}")
    return load_service_yaml(path, scenario_env(env))


def list_scenarios() -> list[str]:
    return sorted({f[:-4] for f in os.listdir(DIST) if f.endswith(".yml")}
                  | set(ALIASES))
