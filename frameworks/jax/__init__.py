"""frameworks/jax — the TPU training/inference service this SDK exists for.

The reference ships database example frameworks (cassandra/hdfs); the
BASELINE.json north star replaces them with a JAX service whose pods run
``jax.distributed.initialize()`` and all-reduce over ICI, scheduled and
healed by the SDK core. Workloads (BASELINE.json ``configs[2..4]``):

* ``mnist``  — single-host MLP, 1 chip, no collectives (minimum e2e slice)
* ``resnet`` — data-parallel ResNet-50 over a gang-placed TPU slice
* ``llama``  — model-parallel Llama inference shards (pjit + NamedSharding)
"""
