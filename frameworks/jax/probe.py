"""Readiness probe for serving pods: exit 0 iff the ingress accepts work.

Run by the agent as the ``serving.yml`` readiness-check with the task's
env (so ``PORT_SERVE`` is the matcher-reserved, endpoint-advertised
port). Gates the deploy plan on the pod actually ACCEPTING REQUESTS —
not on a heartbeat having happened (reference readiness semantics:
``ReadinessCheckSpec`` passes only when the service serves).
"""

import json
import os
import sys
import urllib.request


def main() -> int:
    # multi-process gangs: only rank 0 exposes HTTP (the rank-0 request
    # broadcast, models/serving_gang.py); non-zero members are ready
    # once their worker wrote the post-warmup marker
    if (os.environ.get("JAX_NUM_PROCESSES", "1") != "1"
            and os.environ.get("POD_INSTANCE_INDEX", "0") != "0"):
        if os.path.exists("serving.ready"):
            return 0
        print("probe: member not warmed (no serving.ready)",
              file=sys.stderr)
        return 1
    port = os.environ.get("PORT_SERVE", "")
    if not port:
        print("probe: PORT_SERVE not set", file=sys.stderr)
        return 1
    url = f"http://127.0.0.1:{port}/v1/healthz"
    try:
        with urllib.request.urlopen(url, timeout=3) as r:
            health = json.loads(r.read())
    except Exception as e:                       # any probe failure = not ready
        print(f"probe: {url}: {e}", file=sys.stderr)
        return 1
    if health.get("ok") is not True:
        print(f"probe: not ready: {health}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
