"""Framework integration suites: same virtual-CPU-mesh config as tests/,
plus the shared live-stack builder every suite's fixture wraps."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import tests._jax_cpu  # noqa: E402,F401


def make_stack(n_agents=3, full_ports=False, zones=False,
               scheduler_factory=None, multi=False, env=None):
    """One place to build the per-suite LiveStack: synthetic agents (with
    optional well-known-port ranges / zone labels), a FakeCluster, and
    either a single-service scheduler (via ``scheduler_factory(persister,
    cluster, env=...)``, the frameworks' ``build_scheduler`` signature) or a
    multi-service scheduler. Caller enters/exits the returned context."""
    import dataclasses

    from dcos_commons_tpu.agent.fake import FakeCluster
    from dcos_commons_tpu.agent.inventory import PortRange
    from dcos_commons_tpu.state import MemPersister
    from dcos_commons_tpu.testing.live import LiveStack
    from dcos_commons_tpu.testing.simulation import default_agents

    agents = default_agents(n_agents)
    if full_ports:
        # services pinning well-known ports (9042, 8020, ...) need the full
        # unprivileged range a real host would advertise
        agents = [dataclasses.replace(a, ports=(PortRange(1025, 32000),))
                  for a in agents]
    if zones:
        agents = [dataclasses.replace(a, zone=f"zone-{i % 2}")
                  for i, a in enumerate(agents)]
    cluster = FakeCluster(agents)
    persister = MemPersister()
    if multi:
        from dcos_commons_tpu.scheduler import MultiServiceScheduler
        return LiveStack(multi=MultiServiceScheduler(persister, cluster),
                         cluster=cluster)
    sched = scheduler_factory(persister, cluster, env=env)
    return LiveStack(scheduler=sched, cluster=cluster)
