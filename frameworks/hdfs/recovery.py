"""HDFS recovery overrider (reference
``HdfsRecoveryPlanOverrider.java:25-81``): permanently replacing a *name*
node is not a plain relaunch — the fresh node must first re-sync metadata
(``bootstrapStandby``) before serving, so the phase is a serial two-step
bootstrap -> node. Journal nodes likewise re-sync from the quorum. Data
nodes use default recovery.
"""

from __future__ import annotations

from typing import Optional

from dcos_commons_tpu.plan import Phase, SerialStrategy
from dcos_commons_tpu.plan.requirement import RecoveryType
from dcos_commons_tpu.specification import PodInstance, ServiceSpec


def hdfs_recovery_overrider(manager, spec: ServiceSpec,
                            pod_instance: PodInstance,
                            recovery_type: RecoveryType) -> Optional[Phase]:
    if recovery_type is not RecoveryType.PERMANENT:
        return None
    if pod_instance.pod.type not in ("name", "journal"):
        return None
    # two-step: re-sync first (PERMANENT => fresh placement + reservation),
    # then the server in place on that new reservation
    bootstrap = manager.recovery_step(pod_instance, RecoveryType.PERMANENT,
                                      name_suffix=":bootstrap",
                                      task_names=("bootstrap",))
    node = manager.recovery_step(pod_instance, RecoveryType.TRANSIENT,
                                 name_suffix=":node", task_names=("node",))
    return Phase(f"recover-{pod_instance.name}", [bootstrap, node],
                 SerialStrategy())
