"""Name-node two-step replace under REAL agent binaries (reference
``frameworks/hdfs/tests``: a replaced name node must bootstrapStandby
before serving — ``HdfsRecoveryPlanOverrider.java:25-81``), plus proof
that every node's HA config is genuinely rendered by tpu-bootstrap."""

import subprocess
import time
from pathlib import Path

import pytest

from dcos_commons_tpu.agent.remote import RemoteCluster
from dcos_commons_tpu.plan import Status
from dcos_commons_tpu.state import MemPersister

from frameworks.hdfs.main import build_scheduler

NATIVE = Path(__file__).resolve().parents[3] / "native"
BIN = NATIVE / "bin"

SMALL = {"JOURNAL_COUNT": "3", "DATA_COUNT": "1",
         "JOURNAL_CPUS": "0.2", "JOURNAL_MEM": "64",
         "NAME_CPUS": "0.2", "NAME_MEM": "64",
         "DATA_CPUS": "0.2", "DATA_MEM": "64",
         "JOURNAL_DISK": "64", "NAME_DISK": "64", "DATA_DISK": "64"}


def wait_for(predicate, timeout=90, interval=0.1, message="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        value = predicate()
        if value:
            return value
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {message}")


@pytest.fixture(scope="module")
def native_bins():
    subprocess.run(["make", "-C", str(NATIVE)], check=True,
                   capture_output=True)
    return BIN


@pytest.fixture()
def real_stack(native_bins, tmp_path):
    cluster = RemoteCluster(expiry_s=10.0, poll_interval_s=0.05)
    sched = build_scheduler(MemPersister(), cluster, env=SMALL)
    from dcos_commons_tpu.http import ApiServer
    server = ApiServer(sched, port=0, cluster=cluster)
    server.start()
    url = f"http://127.0.0.1:{server.port}"
    agents = []
    for i in range(6):
        agents.append(subprocess.Popen(
            [str(native_bins / "tpu-agent"), "--scheduler", url,
             "--agent-id", f"h{i}", "--hostname", f"hhost{i}",
             "--cpus", "4", "--memory-mb", "4096", "--disk-mb", "20000",
             "--base-dir", str(tmp_path / f"agent-{i}"),
             "--ports", "1025-32000",
             "--poll-interval", "0.05", "--tpu-chips", "0"],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL))
    try:
        yield sched, tmp_path
    finally:
        for p in agents:
            p.terminate()
        for p in agents:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
        server.stop()


def drive_to(sched, plan, status, timeout=120):
    def check():
        sched.run_cycle()
        return sched.plan(plan).status is status
    wait_for(check, timeout=timeout, message=f"plan {plan} -> {status}")


def test_two_step_name_replace_and_rendered_topology(real_stack):
    sched, tmp_path = real_stack
    drive_to(sched, "deploy", Status.COMPLETE)

    # every name node's serving gate passed through a REAL rendered
    # hdfs-site.xml with the full HA topology
    def rendered():
        hits = list(tmp_path.glob("agent-*/name-*-node__*/etc/hdfs-site.xml"))
        return hits if len(hits) >= 2 else None

    configs = wait_for(rendered, message="2 rendered hdfs-site.xml")
    text = configs[0].read_text()
    assert "qjournal://journal-0-node.hdfs.tpu.local:8485" in text
    assert "name-0-node.hdfs.tpu.local:9001" in text
    assert "<value>HTTP_ONLY</value>" in text  # TLS off by default

    # permanent replace of name-0: the overrider inserts the serial
    # bootstrapStandby -> node phase; drive it and confirm the order by
    # the artifacts the steps leave behind
    old_task = sched.state.fetch_task("name-0-node")
    sched.replace_pod("name-0")
    deadline = time.time() + 120
    saw_recovery = False
    while time.time() < deadline:
        sched.run_cycle()
        plan = sched.plan("recovery")
        if plan is not None and any("name-0" in ph.name
                                    for ph in plan.phases):
            saw_recovery = True
        new_task = sched.state.fetch_task("name-0-node")
        if saw_recovery and new_task is not None \
                and new_task.task_id != old_task.task_id \
                and sched.state.fetch_status("name-0-node") is not None \
                and sched.state.fetch_status("name-0-node").state.name \
                == "RUNNING":
            break
        time.sleep(0.05)
    else:
        raise AssertionError("name-0 two-step replace did not finish")

    # the replacement went through bootstrapStandby on its NEW volume
    # before serving: VERSION says standby-synced, not formatted
    def version_file():
        for agent_dir in tmp_path.glob("agent-*"):
            v = agent_dir / "volumes" / "name-0" / "name-data" / "VERSION"
            if v.exists():
                return v.read_text().strip()
        return None

    assert wait_for(version_file,
                    message="name-0 VERSION") == "standby-synced"
