"""hdfs integration suite (reference ``frameworks/hdfs/tests/``): multi-pod
deploy ordering and the two-step (bootstrap -> node) replace recovery for
journal/name nodes."""

import pytest

from dcos_commons_tpu.testing import integration

from frameworks.hdfs.main import build_scheduler

SMALL = {"JOURNAL_CPUS": "0.2", "JOURNAL_MEM": "64",
         "NAME_CPUS": "0.2", "NAME_MEM": "64",
         "DATA_CPUS": "0.2", "DATA_MEM": "64", "DATA_COUNT": "3"}


@pytest.fixture()
def stack():
    from frameworks.conftest import make_stack
    with make_stack(n_agents=6, full_ports=True,
                    scheduler_factory=build_scheduler, env=SMALL) as s:
        yield s


def test_deploy_order_and_task_set(stack):
    client = stack.client()
    integration.wait_for_deployment(client, timeout_s=60)
    plan = integration.get_plan(client, "deploy")
    phase_names = [ph["name"] for ph in plan["phases"]]
    # journal quorum before name nodes before data nodes (reference
    # svc.yml plan ordering)
    assert (phase_names.index("journal") < phase_names.index("name")
            < phase_names.index("data")), phase_names


def test_name_node_replace_is_two_step(stack):
    from dcos_commons_tpu.agent.fake import TaskBehavior
    client = stack.client()
    integration.wait_for_deployment(client, timeout_s=60)
    # stall the relaunched node task so the in-flight recovery plan stays
    # observable (completed recovery phases are pruned every cycle)
    stack.cluster.script("name-0-node", TaskBehavior.MANUAL)
    old = integration.get_task_ids(client, "name-0-node")
    code, body = client.post("pod/name-0/replace")
    assert code == 200, body
    # the custom recovery phase relaunches bootstrap+node but NOT the
    # one-time format task, so track the node task only (the generic
    # pod_replace helper expects every task of the pod to churn)
    integration.check_tasks_updated(client, "name-0-node", old,
                                    timeout_s=60)
    code, plan = client.get("plans/recovery")
    steps = [s["name"] for ph in plan["phases"] for s in ph["steps"]]
    assert any("bootstrap" in s for s in steps), steps
    # release the stalled task; recovery must then drain to COMPLETE
    task = stack.cluster.task("name-0-node")
    from dcos_commons_tpu.state import TaskState
    stack.cluster.send_status(task.task_id, TaskState.RUNNING,
                              readiness_passed=True)
    integration.wait_for_recovery(client, timeout_s=60)
