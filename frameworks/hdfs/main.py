"""hdfs scheduler entry point (reference ``frameworks/hdfs/.../Main.java``)."""

from __future__ import annotations

import argparse
import logging
import os
import sys
import time
from typing import Mapping, Optional

from dcos_commons_tpu.agent.remote import RemoteCluster
from dcos_commons_tpu.agent.retry import RetryingAgentClient
from dcos_commons_tpu.http import ApiServer
from dcos_commons_tpu.security import Authenticator
from dcos_commons_tpu.metrics import MetricsRegistry, PlanReporter
from dcos_commons_tpu.scheduler import ServiceScheduler
from dcos_commons_tpu.scheduler.runner import CycleDriver
from dcos_commons_tpu.specification import ServiceSpec, load_service_yaml
from dcos_commons_tpu.state.replicated import open_state

from .recovery import hdfs_recovery_overrider

DIST = os.path.join(os.path.dirname(__file__), "dist")

DEFAULT_ENV: Mapping[str, str] = {
    "FRAMEWORK_NAME": "hdfs",
    "SERVICE_NAME": "hdfs",
    "JOURNAL_COUNT": "3",
    "DATA_COUNT": "3",
    "JOURNAL_CPUS": "1",
    "JOURNAL_MEM": "2048",
    "JOURNAL_DISK": "5120",
    "JOURNAL_DISK_TYPE": "ROOT",
    "JOURNAL_PLACEMENT": '[["hostname", "MAX_PER", "1"]]',
    "NAME_CPUS": "1",
    "NAME_MEM": "4096",
    "NAME_DISK": "5120",
    "NAME_DISK_TYPE": "ROOT",
    "NAME_PLACEMENT": '[["hostname", "MAX_PER", "1"]]',
    "DATA_CPUS": "1",
    "DATA_MEM": "4096",
    "DATA_DISK": "10240",
    "DATA_DISK_TYPE": "ROOT",
    "DATA_PLACEMENT": '[["hostname", "MAX_PER", "1"]]',
    "SLEEP_DURATION": "1000",
    # hdfs-site/core-site knobs (reference universe/config.json surface)
    "HDFS_SERVICE_NAME": "hdfs",
    "HDFS_NAME_RPC_PORT": "9001",
    "HDFS_NAME_HTTP_PORT": "9002",
    "HDFS_JOURNAL_PORT": "8485",
    "HDFS_JOURNAL_HTTP_PORT": "8480",
    "HDFS_REPLICATION": "3",
    "HDFS_AUTOMATIC_FAILOVER": "true",
    "HDFS_PERMISSIONS_ENABLED": "false",
    "HDFS_IMAGE_COMPRESS": "true",
    "HDFS_NAME_HANDLER_COUNT": "20",
    "HDFS_DATA_HANDLER_COUNT": "10",
    "HDFS_HEARTBEAT_RECHECK_INTERVAL_MS": "60000",
    "SECURITY_TRANSPORT_ENCRYPTION_ENABLED": "",
    # locally-built bootstrap fetched into sandboxes for config rendering
    "BOOTSTRAP_URI": "file://" + os.path.abspath(os.path.join(
        os.path.dirname(__file__), "..", "..", "native", "bin",
        "tpu-bootstrap")),
}


# knobs every task's rendered config needs: routed via TASKCFG_ALL_* (the
# reference TaskEnvRouter mechanism) instead of triplicated env blocks
_CONFIG_KEYS = (
    "HDFS_SERVICE_NAME", "HDFS_NAME_RPC_PORT", "HDFS_NAME_HTTP_PORT",
    "HDFS_JOURNAL_PORT", "HDFS_REPLICATION", "HDFS_AUTOMATIC_FAILOVER",
    "HDFS_PERMISSIONS_ENABLED", "HDFS_IMAGE_COMPRESS",
    "HDFS_NAME_HANDLER_COUNT", "HDFS_DATA_HANDLER_COUNT",
    "HDFS_HEARTBEAT_RECHECK_INTERVAL_MS",
    "SECURITY_TRANSPORT_ENCRYPTION_ENABLED", "HDFS_QJOURNAL",
)


def _inject_computed_env(merged: dict) -> dict:
    """Reference Main.java-style env injection: the qjournal URI follows
    JOURNAL_COUNT, and config knobs are routed into every task env."""
    if not merged.get("HDFS_QJOURNAL"):
        name = merged["FRAMEWORK_NAME"]
        tld = merged.get("SERVICE_TLD", "tpu.local")
        port = merged["HDFS_JOURNAL_PORT"]
        count = int(merged.get("JOURNAL_COUNT", "3"))
        hosts = ";".join(f"journal-{i}-node.{name}.{tld}:{port}"
                         for i in range(count))
        merged["HDFS_QJOURNAL"] = \
            f"qjournal://{hosts}/{merged['HDFS_SERVICE_NAME']}"
    for key in _CONFIG_KEYS:
        merged.setdefault(f"TASKCFG_ALL_{key}", merged[key])
    return merged


def load_spec(env: Optional[Mapping[str, str]] = None) -> ServiceSpec:
    merged = dict(DEFAULT_ENV)
    merged.update(os.environ)
    if env:
        merged.update(env)
    _inject_computed_env(merged)
    return load_service_yaml(os.path.join(DIST, "svc.yml"), merged)


def build_scheduler(persister, cluster, env=None, **kwargs):
    spec = load_spec(env)
    return ServiceScheduler(
        spec, persister, cluster,
        recovery_overriders=[hdfs_recovery_overrider], **kwargs)


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s %(message)s")
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--port", type=int,
                   default=int(os.environ.get("API_PORT", "8080")))
    p.add_argument("--state", default=os.environ.get("STATE_DIR", "./state"))
    p.add_argument("--interval", type=float, default=1.0)
    args = p.parse_args(argv)

    metrics = MetricsRegistry()
    # single-instance gate + state backend: the replicated
    # ensemble when TPU_STATE_ENDPOINTS is set, else local files
    persister, lock = open_state(args.state)
    cluster = RemoteCluster()
    # the scheduler's launch/kill RPCs ride the retrying wrapper
    # (bounded attempts, jittered backoff, per-call deadline); the
    # API server keeps the raw client for read-only passthrough
    sched_cluster = RetryingAgentClient(cluster)
    # control-plane auth: TPU_AUTH_FILE names the accounts file
    _auth = Authenticator.from_env()
    # transport security: TPU_TLS=1 mints from the persisted CA (or
    # TPU_TLS_CERT/TPU_TLS_KEY name provisioned PEMs)
    from dcos_commons_tpu.security import server_tls_from_env
    _tls = server_tls_from_env(persister, "hdfs", args.state)
    scheduler = build_scheduler(persister, sched_cluster, metrics=metrics,
                                auth=_auth)
    scheduler.respec = lambda env: load_spec(env)
    server = ApiServer(scheduler, port=args.port, metrics=metrics,
                       cluster=cluster, auth=_auth, tls=_tls)
    PlanReporter(metrics, scheduler)
    driver = CycleDriver(scheduler, interval_s=args.interval)
    server.start()
    print(f"hdfs scheduler API on {server.url}/v1/",
          flush=True)
    try:
        with driver:
            while True:
                time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
