"""hdfs scheduler entry point (reference ``frameworks/hdfs/.../Main.java``)."""

from __future__ import annotations

import argparse
import logging
import os
import sys
import time
from typing import Mapping, Optional

from dcos_commons_tpu.agent.remote import RemoteCluster
from dcos_commons_tpu.http import ApiServer
from dcos_commons_tpu.security import Authenticator
from dcos_commons_tpu.metrics import MetricsRegistry, PlanReporter
from dcos_commons_tpu.scheduler import ServiceScheduler
from dcos_commons_tpu.scheduler.runner import CycleDriver
from dcos_commons_tpu.specification import ServiceSpec, load_service_yaml
from dcos_commons_tpu.state.replicated import open_state

from .recovery import hdfs_recovery_overrider

DIST = os.path.join(os.path.dirname(__file__), "dist")

DEFAULT_ENV: Mapping[str, str] = {
    "FRAMEWORK_NAME": "hdfs",
    "SERVICE_NAME": "hdfs",
    "JOURNAL_COUNT": "3",
    "DATA_COUNT": "3",
    "JOURNAL_CPUS": "1",
    "JOURNAL_MEM": "2048",
    "JOURNAL_DISK": "5120",
    "NAME_CPUS": "1",
    "NAME_MEM": "4096",
    "NAME_DISK": "5120",
    "DATA_CPUS": "1",
    "DATA_MEM": "4096",
    "DATA_DISK": "10240",
    "SLEEP_DURATION": "1000",
}


def load_spec(env: Optional[Mapping[str, str]] = None) -> ServiceSpec:
    merged = dict(DEFAULT_ENV)
    merged.update(os.environ)
    if env:
        merged.update(env)
    return load_service_yaml(os.path.join(DIST, "svc.yml"), merged)


def build_scheduler(persister, cluster, env=None, **kwargs):
    spec = load_spec(env)
    return ServiceScheduler(
        spec, persister, cluster,
        recovery_overriders=[hdfs_recovery_overrider], **kwargs)


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s %(message)s")
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--port", type=int,
                   default=int(os.environ.get("API_PORT", "8080")))
    p.add_argument("--state", default=os.environ.get("STATE_DIR", "./state"))
    p.add_argument("--interval", type=float, default=1.0)
    args = p.parse_args(argv)

    metrics = MetricsRegistry()
    # single-instance gate + state backend: the replicated
    # ensemble when TPU_STATE_ENDPOINTS is set, else local files
    persister, lock = open_state(args.state)
    cluster = RemoteCluster()
    # control-plane auth: TPU_AUTH_FILE names the accounts file
    _auth = Authenticator.from_env()
    scheduler = build_scheduler(persister, cluster, metrics=metrics)
    scheduler.respec = lambda env: load_spec(env)
    server = ApiServer(scheduler, port=args.port, metrics=metrics,
                       cluster=cluster, auth=_auth)
    PlanReporter(metrics, scheduler)
    driver = CycleDriver(scheduler, interval_s=args.interval)
    server.start()
    print(f"hdfs scheduler API on http://127.0.0.1:{server.port}/v1/",
          flush=True)
    try:
        with driver:
            while True:
                time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
