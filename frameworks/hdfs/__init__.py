"""frameworks/hdfs — multi-pod-type example with a custom deploy plan DSL.

Parity with the reference's hdfs framework (``frameworks/hdfs``, svc.yml
600+ lines): three pod types (journal/name/data), a YAML ``plans:`` deploy
DSL with per-step task lists (format-then-start ordering, reference
``svc.yml:566-596``), and a recovery overrider where replacing a journal or
name node is a two-step bootstrap+start phase
(``HdfsRecoveryPlanOverrider.java:25-81``).
"""
