"""cassandra scheduler entry point (reference
``frameworks/cassandra/src/main/java/.../Main.java:33-76``: custom env
injection + custom validators + the seed-aware recovery overrider).
"""

from __future__ import annotations

import argparse
import logging
import os
import sys
import time
from typing import Mapping, Optional

from dcos_commons_tpu.agent.remote import RemoteCluster
from dcos_commons_tpu.agent.retry import RetryingAgentClient
from dcos_commons_tpu.http import ApiServer
from dcos_commons_tpu.security import Authenticator
from dcos_commons_tpu.metrics import MetricsRegistry, PlanReporter
from dcos_commons_tpu.scheduler import ServiceScheduler
from dcos_commons_tpu.scheduler.runner import CycleDriver
from dcos_commons_tpu.specification import ServiceSpec, load_service_yaml
from dcos_commons_tpu.state.replicated import open_state

from .recovery import seed_recovery_overrider

DIST = os.path.join(os.path.dirname(__file__), "dist")

DEFAULT_ENV: Mapping[str, str] = {
    "FRAMEWORK_NAME": "cassandra",
    "SERVICE_NAME": "cassandra",
    "NODE_COUNT": "3",
    "SEED_COUNT": "2",
    "NODE_CPUS": "2",
    "NODE_MEM": "8192",
    "NODE_DISK": "10240",
    "NODE_DISK_TYPE": "ROOT",
    "NODE_PLACEMENT": '[["hostname", "MAX_PER", "1"]]',
    "SIDECAR_CPUS": "0.5",
    "SIDECAR_MEM": "512",
    "SLEEP_DURATION": "1000",
    "PERMANENT_FAILURE_TIMEOUT_SECS": "120",
    "MIN_REPLACE_DELAY_SECS": "0",
    # backup/restore parameterization (reference S3_BUCKET_PATH et al.;
    # EXTERNAL_LOCATION is any mounted or remote path)
    "BACKUP_NAME": "default-backup",
    "EXTERNAL_LOCATION": "./backups",
    "BACKUP_DIR": "./backups",  # legacy alias kept for operators
    # cassandra.yaml knobs (reference universe/config.json option surface)
    "CASSANDRA_CLUSTER_NAME": "cassandra",
    "CASSANDRA_NATIVE_PORT": "9042",
    "CASSANDRA_STORAGE_PORT": "7000",
    "CASSANDRA_SSL_STORAGE_PORT": "7001",
    "CASSANDRA_JMX_PORT": "7199",
    "CASSANDRA_LISTEN_ADDRESS": "0.0.0.0",
    "CASSANDRA_RPC_ADDRESS": "0.0.0.0",
    "CASSANDRA_NUM_TOKENS": "256",
    "CASSANDRA_HINTED_HANDOFF_ENABLED": "true",
    "CASSANDRA_MAX_HINT_WINDOW_IN_MS": "10800000",
    "CASSANDRA_HINTED_HANDOFF_THROTTLE_IN_KB": "1024",
    "CASSANDRA_HINTS_FLUSH_PERIOD_IN_MS": "10000",
    "CASSANDRA_BATCHLOG_REPLAY_THROTTLE_IN_KB": "1024",
    "CASSANDRA_AUTHENTICATOR": "AllowAllAuthenticator",
    "CASSANDRA_AUTHORIZER": "AllowAllAuthorizer",
    "CASSANDRA_ROLES_VALIDITY_IN_MS": "2000",
    "CASSANDRA_PERMISSIONS_VALIDITY_IN_MS": "2000",
    "CASSANDRA_CONCURRENT_READS": "16",
    "CASSANDRA_CONCURRENT_WRITES": "32",
    "CASSANDRA_CONCURRENT_COUNTER_WRITES": "16",
    "CASSANDRA_MEMTABLE_ALLOCATION_TYPE": "heap_buffers",
    "CASSANDRA_MEMTABLE_FLUSH_WRITERS": "2",
    "CASSANDRA_KEY_CACHE_SIZE_MB": "100",
    "CASSANDRA_KEY_CACHE_SAVE_PERIOD": "14400",
    "CASSANDRA_ROW_CACHE_SIZE_MB": "0",
    "CASSANDRA_COUNTER_CACHE_SIZE_MB": "50",
    "CASSANDRA_COMMITLOG_SYNC_PERIOD_IN_MS": "10000",
    "CASSANDRA_COMMITLOG_SEGMENT_SIZE_IN_MB": "32",
    "CASSANDRA_COMMITLOG_TOTAL_SPACE_IN_MB": "8192",
    "CASSANDRA_COMPACTION_THROUGHPUT_MB_PER_SEC": "16",
    "CASSANDRA_CONCURRENT_COMPACTORS": "2",
    "CASSANDRA_READ_REQUEST_TIMEOUT_IN_MS": "5000",
    "CASSANDRA_WRITE_REQUEST_TIMEOUT_IN_MS": "2000",
    "CASSANDRA_RANGE_REQUEST_TIMEOUT_IN_MS": "10000",
    "CASSANDRA_REQUEST_TIMEOUT_IN_MS": "10000",
    "CASSANDRA_ENDPOINT_SNITCH": "GossipingPropertyFileSnitch",
    "CASSANDRA_HEAP_MB": "4096",
    "CASSANDRA_HEAP_NEW_MB": "400",
    "CASSANDRA_RLIMIT_NOFILE": "100000",
    "CASSANDRA_KEYSPACE": "system_auth",
    "SECURITY_TRANSPORT_ENCRYPTION_ENABLED": "",
    # locally-built bootstrap fetched into sandboxes for config rendering
    # (production overrides with the package artifact URL)
    "BOOTSTRAP_URI": "file://" + os.path.abspath(os.path.join(
        os.path.dirname(__file__), "..", "..", "native", "bin",
        "tpu-bootstrap")),
}


def _inject_computed_env(merged: dict) -> dict:
    """Reference ``Main.java:33-76`` custom env injection: the seed list is
    the stable discovery names of instances 0..SEED_COUNT-1."""
    # legacy knob: operators who set BACKUP_DIR (the old name) keep their
    # backup location when EXTERNAL_LOCATION was left at its default
    default_loc = DEFAULT_ENV["EXTERNAL_LOCATION"]
    if merged.get("EXTERNAL_LOCATION", default_loc) == default_loc \
            and merged.get("BACKUP_DIR", default_loc) != default_loc:
        merged["EXTERNAL_LOCATION"] = merged["BACKUP_DIR"]
    if not merged.get("CASSANDRA_SEEDS"):
        name = merged["FRAMEWORK_NAME"]
        tld = merged.get("SERVICE_TLD", "tpu.local")
        seeds = int(merged.get("SEED_COUNT", "2"))
        merged["CASSANDRA_SEEDS"] = ",".join(
            f"node-{i}-server.{name}.{tld}" for i in range(seeds))
    return merged


def load_spec(env: Optional[Mapping[str, str]] = None) -> ServiceSpec:
    merged = dict(DEFAULT_ENV)
    merged.update(os.environ)
    if env:
        merged.update(env)
    _inject_computed_env(merged)
    return load_service_yaml(os.path.join(DIST, "svc.yml"), merged)


def build_scheduler(persister, cluster, env=None, **kwargs):
    """Construct the service scheduler with the seed-aware overrider wired
    in — shared by main() and the simulation tests."""
    merged = dict(DEFAULT_ENV)
    merged.update(os.environ)
    if env:
        merged.update(env)
    _inject_computed_env(merged)
    spec = load_service_yaml(os.path.join(DIST, "svc.yml"), merged)
    seeds = int(merged["SEED_COUNT"])
    return ServiceScheduler(
        spec, persister, cluster,
        recovery_overriders=[seed_recovery_overrider(seeds)], **kwargs)


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s %(message)s")
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--port", type=int,
                   default=int(os.environ.get("API_PORT", "8080")))
    p.add_argument("--state", default=os.environ.get("STATE_DIR", "./state"))
    p.add_argument("--interval", type=float, default=1.0)
    args = p.parse_args(argv)

    metrics = MetricsRegistry()
    # single-instance gate + state backend: the replicated
    # ensemble when TPU_STATE_ENDPOINTS is set, else local files
    persister, lock = open_state(args.state)
    cluster = RemoteCluster()
    # the scheduler's launch/kill RPCs ride the retrying wrapper
    # (bounded attempts, jittered backoff, per-call deadline); the
    # API server keeps the raw client for read-only passthrough
    sched_cluster = RetryingAgentClient(cluster)
    # control-plane auth: TPU_AUTH_FILE names the accounts file
    _auth = Authenticator.from_env()
    # transport security: TPU_TLS=1 mints from the persisted CA (or
    # TPU_TLS_CERT/TPU_TLS_KEY name provisioned PEMs)
    from dcos_commons_tpu.security import server_tls_from_env
    _tls = server_tls_from_env(persister, "cassandra", args.state)
    scheduler = build_scheduler(persister, sched_cluster, metrics=metrics,
                                auth=_auth)
    scheduler.respec = lambda env: load_spec(env)
    server = ApiServer(scheduler, port=args.port, metrics=metrics,
                       cluster=cluster, auth=_auth, tls=_tls)
    PlanReporter(metrics, scheduler)
    driver = CycleDriver(scheduler, interval_s=args.interval)
    server.start()
    print(f"cassandra scheduler API on {server.url}/v1/",
          flush=True)
    try:
        with driver:
            while True:
                time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
