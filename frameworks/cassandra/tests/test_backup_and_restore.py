"""Backup -> wipe -> restore cycle and TLS toggle under REAL agent binaries
(reference ``frameworks/cassandra/tests/test_backup_and_restore.py``:
write data, back up to the external location, wipe, restore, verify).

Unlike the fake-cluster sanity suite, these tests run the compiled
``tpu-agent``/``tpu-bootstrap``: node config is genuinely rendered from
cassandra.yaml.mustache inside each sandbox, data lives on real
persistent volumes, and the backup tarballs land in a real external
location directory.
"""

import os
import subprocess
import time
from pathlib import Path

import pytest

from dcos_commons_tpu.agent.remote import RemoteCluster
from dcos_commons_tpu.plan import Status
from dcos_commons_tpu.state import MemPersister

from frameworks.cassandra.main import build_scheduler

NATIVE = Path(__file__).resolve().parents[3] / "native"
BIN = NATIVE / "bin"


def wait_for(predicate, timeout=60, interval=0.1, message="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        value = predicate()
        if value:
            return value
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {message}")


@pytest.fixture(scope="module")
def native_bins():
    subprocess.run(["make", "-C", str(NATIVE)], check=True,
                   capture_output=True)
    return BIN


@pytest.fixture()
def real_stack(native_bins, tmp_path):
    """3 real agents + the cassandra scheduler (tiny resources)."""
    external = tmp_path / "external-backups"
    env = {"NODE_COUNT": "3", "SEED_COUNT": "2", "NODE_CPUS": "0.5",
           "NODE_MEM": "256", "NODE_DISK": "64", "SIDECAR_CPUS": "0.2",
           "SIDECAR_MEM": "64", "CASSANDRA_HEAP_MB": "256",
           "CASSANDRA_HEAP_NEW_MB": "25",
           "BACKUP_NAME": "snap-1",
           "EXTERNAL_LOCATION": str(external)}
    cluster = RemoteCluster(expiry_s=10.0, poll_interval_s=0.05)
    sched = build_scheduler(MemPersister(), cluster, env=env)
    from dcos_commons_tpu.http import ApiServer
    server = ApiServer(sched, port=0, cluster=cluster)
    server.start()
    url = f"http://127.0.0.1:{server.port}"
    agents = []
    for i in range(3):
        agents.append(subprocess.Popen(
            [str(native_bins / "tpu-agent"), "--scheduler", url,
             "--agent-id", f"c{i}", "--hostname", f"chost{i}",
             "--cpus", "4", "--memory-mb", "4096", "--disk-mb", "20000",
             "--base-dir", str(tmp_path / f"agent-{i}"),
             "--ports", "1025-32000",  # classic fixed ports (9042/7000)
             "--poll-interval", "0.05", "--tpu-chips", "0"],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL))
    try:
        yield sched, tmp_path, external
    finally:
        for p in agents:
            p.terminate()
        for p in agents:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
        server.stop()


def drive_to(sched, plan, status, timeout=90):
    def check():
        sched.run_cycle()
        return sched.plan(plan).status is status
    wait_for(check, timeout=timeout, message=f"plan {plan} -> {status}")


def run_sidecar_plan(sched, plan, timeout=90):
    sched.plan(plan).proceed()  # sidecar plans start INTERRUPTED
    drive_to(sched, plan, Status.COMPLETE, timeout=timeout)


def volume_dir(tmp_path, instance):
    for agent_dir in tmp_path.glob("agent-*"):
        v = agent_dir / "volumes" / instance / "data"
        if v.exists():
            return v
    return None


def test_backup_wipe_restore_cycle(real_stack):
    sched, tmp_path, external = real_stack
    drive_to(sched, "deploy", Status.COMPLETE)

    # the server only reached RUNNING because tpu-bootstrap rendered its
    # config and the grep gates passed — confirm the render is real
    def rendered():
        found = {}
        for agent_dir in tmp_path.glob("agent-*"):
            for cfg in agent_dir.glob("node-*-server__*/conf/cassandra.yaml"):
                found[cfg.parent.parent.name.split("__")[0]] = cfg
        return found if len(found) == 3 else None

    configs = wait_for(rendered, message="3 rendered cassandra.yaml")
    text = configs["node-0-server"].read_text()
    assert "cluster_name: 'cassandra'" in text
    assert "native_transport_port: 9042" in text
    assert "seeds: 'node-0-server.cassandra.tpu.local" in text
    assert "internode_encryption: none" in text  # TLS off by default

    # write user data onto each node's persistent volume
    for i in range(3):
        vol = wait_for(lambda i=i: volume_dir(tmp_path, f"node-{i}"),
                       message=f"node-{i} volume")
        (vol / "data").mkdir(exist_ok=True)
        (vol / "data" / "keyspace1").write_text(f"rows-of-node-{i}")

    # backup plan: per-node tarballs appear in the external location
    run_sidecar_plan(sched, "backup")
    for i in range(3):
        assert (external / "snap-1" / f"{i}.tar.gz").exists()

    # wipe: simulate data loss on every node
    for i in range(3):
        vol = volume_dir(tmp_path, f"node-{i}")
        (vol / "data" / "keyspace1").unlink()
        assert not (vol / "data" / "keyspace1").exists()

    # restore plan brings the data back from the external location
    run_sidecar_plan(sched, "restore")
    for i in range(3):
        vol = volume_dir(tmp_path, f"node-{i}")
        content = wait_for(
            lambda v=vol: (v / "data" / "keyspace1").exists()
            and (v / "data" / "keyspace1").read_text(),
            message=f"restored data on node-{i}")
        assert content == f"rows-of-node-{i}"

    # cleanup plan removes the external snapshot
    run_sidecar_plan(sched, "cleanup")
    assert not (external / "snap-1").exists()


def test_tls_toggle_provisions_certs(native_bins, tmp_path):
    """SECURITY_TRANSPORT_ENCRYPTION_ENABLED=true: every node sandbox gets
    a CA-signed cert/key/ca bundle and the rendered config flips to
    internode_encryption: all (reference test_tls toggling)."""
    env = {"NODE_COUNT": "1", "SEED_COUNT": "1", "NODE_CPUS": "0.5",
           "NODE_MEM": "256", "NODE_DISK": "64", "SIDECAR_CPUS": "0.2",
           "SIDECAR_MEM": "64", "CASSANDRA_HEAP_MB": "256",
           "CASSANDRA_HEAP_NEW_MB": "25",
           "SECURITY_TRANSPORT_ENCRYPTION_ENABLED": "true"}
    cluster = RemoteCluster(expiry_s=10.0, poll_interval_s=0.05)
    # TLS specs deploy only on an authed control plane (tls_requires_auth)
    from dcos_commons_tpu.security import Authenticator, generate_auth_config
    auth_cfg = generate_auth_config()
    authenticator = Authenticator.from_config(auth_cfg)
    sched = build_scheduler(
        MemPersister(), cluster, env=env, auth=authenticator)
    from dcos_commons_tpu.http import ApiServer
    server = ApiServer(sched, port=0, cluster=cluster, auth=authenticator)
    server.start()
    url = f"http://127.0.0.1:{server.port}"
    secret_file = tmp_path / "fleet.secret"
    secret_file.write_text(auth_cfg["accounts"]["fleet"]["secret"] + "\n")
    agent = subprocess.Popen(
        [str(native_bins / "tpu-agent"), "--scheduler", url,
         "--agent-id", "t0", "--hostname", "thost0",
         "--cpus", "4", "--memory-mb", "4096", "--disk-mb", "20000",
         "--base-dir", str(tmp_path / "agent-0"),
         "--ports", "1025-32000",
         "--poll-interval", "0.05", "--tpu-chips", "0"],
        env=dict(os.environ, TPU_AUTH_UID="fleet",
                 TPU_AUTH_SECRET_FILE=str(secret_file)),
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        drive_to(sched, "deploy", Status.COMPLETE)

        def sandbox():
            hits = list((tmp_path / "agent-0").glob("node-0-server__*"))
            return hits[0] if hits else None

        sb = wait_for(sandbox, message="node-0 sandbox")
        for artifact in ("node-tls.crt", "node-tls.key", "node-tls.ca"):
            f = wait_for(lambda a=artifact: (sb / a).exists()
                         and (sb / a).stat().st_size > 0,
                         message=f"TLS artifact {artifact}")
        text = wait_for(
            lambda: (sb / "conf" / "cassandra.yaml").exists()
            and (sb / "conf" / "cassandra.yaml").read_text(),
            message="rendered config")
        assert "internode_encryption: all" in text
        assert "keystore: node-tls.crt" in text
    finally:
        agent.terminate()
        try:
            agent.wait(timeout=5)
        except subprocess.TimeoutExpired:
            agent.kill()
        server.stop()
