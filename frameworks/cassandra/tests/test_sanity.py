"""cassandra integration suite (reference
``frameworks/cassandra/tests/``): deploy, run the backup sidecar plan on
demand, seed replace triggers the rolling-restart recovery phase."""

import pytest

from dcos_commons_tpu.testing import integration

from frameworks.cassandra.main import build_scheduler

SMALL = {"NODE_COUNT": "3", "SEED_COUNT": "2", "NODE_CPUS": "0.5",
         "NODE_MEM": "256", "NODE_DISK": "64"}


@pytest.fixture()
def stack():
    from frameworks.conftest import make_stack
    with make_stack(n_agents=4, full_ports=True,
                    scheduler_factory=build_scheduler, env=SMALL) as s:
        yield s


def test_deploy_and_backup_plan(stack):
    client = stack.client()
    integration.wait_for_deployment(client, timeout_s=30)
    ids = integration.get_task_ids(client, "node")
    assert set(ids) == {"node-0-server", "node-1-server", "node-2-server"}

    # sidecar plans start INTERRUPTED; an operator start runs them
    code, plan = client.get("plans/backup")
    assert plan["status"] != "COMPLETE"
    code, _ = client.post("plans/backup/continue")
    assert code == 200
    integration.wait_for_plan_status(client, "backup", "COMPLETE",
                                     timeout_s=30)
    # backup tasks ran once per node and did not disturb the servers
    integration.check_tasks_not_updated(client, "node", ids)


def test_seed_replace_rolls_other_nodes(stack):
    client = stack.client()
    integration.wait_for_deployment(client, timeout_s=30)
    all_ids = integration.get_task_ids(client, "node")
    # replacing seed node-0 must also restart node-1/node-2 (rolling), the
    # CassandraRecoveryPlanOverrider behavior
    integration.pod_replace(client, "node-0", timeout_s=30)
    integration.check_tasks_updated(client, "node", all_ids, timeout_s=30)


def test_non_seed_replace_rolls_nothing_else(stack):
    client = stack.client()
    integration.wait_for_deployment(client, timeout_s=30)
    others = {k: v for k, v in
              integration.get_task_ids(client, "node").items()
              if not k.startswith("node-2")}
    integration.pod_replace(client, "node-2", timeout_s=30)
    integration.check_tasks_not_updated(client, "node", others)
