"""frameworks/cassandra — production-grade stateful-service example.

Parity with the reference's cassandra framework (``frameworks/cassandra``,
``svc.yml`` 621 lines): shared resource-sets (sidecar tasks reuse the node's
reservation), on-demand sidecar plans (backup/restore), persistent data
volumes, replacement-failure-policy, and a seed-aware recovery overrider
(``CassandraRecoveryPlanOverrider.java:38-162``): replacing a seed node
triggers a rolling restart of the other nodes so they learn the new seed
address.
"""
