"""Seed-aware recovery, the reference's
``CassandraRecoveryPlanOverrider.java:38-162``: when a *seed* node (instance
index < SEED_COUNT) is permanently replaced, every other node must be
restarted (rolling, serial) so its seed list picks up the replacement's new
address. Non-seed replacement and transient failures use the default
single-pod recovery.
"""

from __future__ import annotations

from typing import Optional

from dcos_commons_tpu.plan import Phase, SerialStrategy
from dcos_commons_tpu.plan.requirement import RecoveryType
from dcos_commons_tpu.specification import PodInstance, ServiceSpec


def seed_recovery_overrider(seed_count: int):
    """Build a RecoveryOverrider closing over the seed count."""

    def overrider(manager, spec: ServiceSpec, pod_instance: PodInstance,
                  recovery_type: RecoveryType) -> Optional[Phase]:
        if pod_instance.pod.type != "node":
            return None
        if recovery_type is not RecoveryType.PERMANENT:
            return None
        if pod_instance.index >= seed_count:
            return None  # non-seed: default recovery
        steps = [manager.recovery_step(pod_instance, RecoveryType.PERMANENT)]
        for index in range(pod_instance.pod.count):
            if index == pod_instance.index:
                continue
            steps.append(manager.recovery_step(
                PodInstance(pod_instance.pod, index), RecoveryType.TRANSIENT,
                name_suffix=":seed-change-restart"))
        return Phase(f"recover-seed-{pod_instance.name}", steps,
                     SerialStrategy())

    return overrider
