// tpu-bootstrap — in-sandbox task initializer (C++17, no dependencies).
//
// Native equivalent of the reference's Go bootstrap (sdk/bootstrap/main.go):
//   1. render CONFIG_TEMPLATE_<n>=<src>,<dst> templates against the task env
//      (mustache-style {{VAR}} substitution, missing vars are fatal —
//      reference TemplateUtils.renderMustache missing-value errors,
//      main.go:351-376)
//   2. wait until the JAX distributed coordinator (pod instance 0) is
//      reachable, so jax.distributed.initialize() doesn't race the gang
//      (replaces the reference's DNS self-resolution wait, main.go:218-287)
//   3. echo the resolved TPU/JAX contract for the task log
//
// The scheduler's matcher injects JAX_COORDINATOR_ADDRESS / JAX_PROCESS_ID /
// JAX_NUM_PROCESSES / TPU_* (dcos_commons_tpu/matching/evaluator.py), the
// agent exports them into the sandbox, and the task cmd runs
// `tpu-bootstrap && <real command>`.

#include <arpa/inet.h>
#include <netdb.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cstdlib>
#include <cstring>
#include <fstream>
#include <iostream>
#include <sstream>
#include <string>
#include <vector>

namespace {

std::string getenv_str(const char* name, const std::string& dflt = "") {
  const char* v = getenv(name);
  return v ? std::string(v) : dflt;
}

// Mustache-style rendering from env, matching the scheduler-side
// utils/template.py subset (the reference's Go bootstrap renders full
// mustache): {{VAR}} substitution (missing var fatal in strict mode),
// {{!comment}} dropped, {{#KEY}}...{{/KEY}} sections rendered iff KEY is
// set, non-empty and != "false", {{^KEY}}...{{/KEY}} inverted.
bool env_truthy(const std::string& key) {
  const char* v = getenv(key.c_str());
  if (v == nullptr) return false;
  std::string s(v);
  if (s.empty()) return false;
  for (auto& c : s) c = static_cast<char>(tolower(c));
  return s != "false";
}

// Renders from `pos` until {{/until}} (or end when until empty).
// Appends to `out` when emit; returns the position after the close tag.
size_t render_block(const std::string& t, size_t pos,
                    const std::string& until, bool strict, bool emit,
                    const std::string& src, std::string& out) {
  while (true) {
    size_t open = t.find("{{", pos);
    if (open == std::string::npos) {
      if (!until.empty()) {
        std::cerr << "[tpu-bootstrap] unclosed section {{#" << until
                  << "}} in " << src << "\n";
        exit(1);
      }
      if (emit) out += t.substr(pos);
      return t.size();
    }
    if (emit) out += t.substr(pos, open - pos);
    size_t close = t.find("}}", open);
    if (close == std::string::npos) {
      std::cerr << "[tpu-bootstrap] unterminated {{ in " << src << "\n";
      exit(1);
    }
    std::string key = t.substr(open + 2, close - open - 2);
    pos = close + 2;
    if (key.empty()) continue;
    if (key[0] == '!') continue;  // comment
    if (key[0] == '/') {
      std::string name = key.substr(1);
      if (name != until) {
        std::cerr << "[tpu-bootstrap] mismatched {{/" << name
                  << "}} in " << src << " (open section: "
                  << (until.empty() ? "<none>" : until) << ")\n";
        exit(1);
      }
      return pos;
    }
    if (key[0] == '#' || key[0] == '^') {
      std::string name = key.substr(1);
      bool truthy = env_truthy(name);
      bool inner_emit = emit && (key[0] == '#' ? truthy : !truthy);
      pos = render_block(t, pos, name, strict, inner_emit, src, out);
      continue;
    }
    const char* val = getenv(key.c_str());
    if (val == nullptr) {
      if (strict && emit) {
        std::cerr << "[tpu-bootstrap] template " << src
                  << " references undefined env var {{" << key << "}}\n";
        exit(1);
      }
      continue;
    }
    if (emit) out += val;
  }
}

std::string render(const std::string& tmpl, const std::string& src,
                   bool strict) {
  std::string out;
  render_block(tmpl, 0, "", strict, true, src, out);
  return out;
}

void render_templates(bool strict) {
  for (int i = 0; i < 1024; ++i) {
    std::string spec =
        getenv_str(("CONFIG_TEMPLATE_" + std::to_string(i)).c_str());
    if (spec.empty()) {
      if (i == 0) continue;  // allow sparse numbering to start at 1
      break;
    }
    size_t comma = spec.find(',');
    if (comma == std::string::npos) {
      std::cerr << "[tpu-bootstrap] bad CONFIG_TEMPLATE_" << i
                << " (want <src>,<dst>): " << spec << "\n";
      exit(1);
    }
    std::string src = spec.substr(0, comma);
    std::string dst = spec.substr(comma + 1);
    // destinations may be nested (e.g. secrets/two): create parent dirs
    for (size_t pos = dst.find('/'); pos != std::string::npos;
         pos = dst.find('/', pos + 1)) {
      if (pos > 0) ::mkdir(dst.substr(0, pos).c_str(), 0755);
    }
    std::ifstream in(src);
    if (!in) {
      std::cerr << "[tpu-bootstrap] missing template " << src << "\n";
      exit(1);
    }
    std::stringstream buf;
    buf << in.rdbuf();
    std::ofstream out(dst);
    if (!out) {
      std::cerr << "[tpu-bootstrap] cannot write " << dst << "\n";
      exit(1);
    }
    out << render(buf.str(), src, strict);
    std::cerr << "[tpu-bootstrap] rendered " << src << " -> " << dst << "\n";
  }
}

bool tcp_reachable(const std::string& host, const std::string& port,
                   int timeout_s) {
  struct addrinfo hints;
  memset(&hints, 0, sizeof hints);
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  if (getaddrinfo(host.c_str(), port.c_str(), &hints, &res) != 0) {
    return false;
  }
  bool ok = false;
  for (struct addrinfo* ai = res; ai != nullptr && !ok; ai = ai->ai_next) {
    int fd = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    struct timeval tv{timeout_s, 0};
    setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof tv);
    ok = connect(fd, ai->ai_addr, ai->ai_addrlen) == 0;
    close(fd);
  }
  freeaddrinfo(res);
  return ok;
}

int wait_for_coordinator(int timeout_s) {
  std::string addr = getenv_str("JAX_COORDINATOR_ADDRESS");
  std::string num = getenv_str("JAX_NUM_PROCESSES", "1");
  std::string pid = getenv_str("JAX_PROCESS_ID", "0");
  if (addr.empty() || num == "1" || num.empty()) {
    return 0;  // single-process: nothing to wait for
  }
  if (pid == "0") {
    // we ARE the coordinator; peers wait for us
    return 0;
  }
  size_t colon = addr.rfind(':');
  if (colon == std::string::npos) {
    std::cerr << "[tpu-bootstrap] bad JAX_COORDINATOR_ADDRESS " << addr
              << "\n";
    return 1;
  }
  std::string host = addr.substr(0, colon);
  std::string port = addr.substr(colon + 1);
  std::cerr << "[tpu-bootstrap] waiting for coordinator " << addr << "\n";
  for (int waited = 0; waited < timeout_s; ++waited) {
    if (tcp_reachable(host, port, 2)) {
      std::cerr << "[tpu-bootstrap] coordinator reachable\n";
      return 0;
    }
    sleep(1);
  }
  std::cerr << "[tpu-bootstrap] coordinator " << addr << " unreachable after "
            << timeout_s << "s\n";
  return 1;
}

}  // namespace

int main(int argc, char** argv) {
  bool strict = true;
  bool do_wait = true;
  int timeout_s = 600;
  for (int i = 1; i < argc; ++i) {
    std::string a = argv[i];
    if (a == "--no-strict-templates") strict = false;
    else if (a == "--no-wait") do_wait = false;
    else if (a == "--wait-timeout" && i + 1 < argc) {
      timeout_s = std::stoi(argv[++i]);
    } else if (a == "--help" || a == "-h") {
      std::cerr << "usage: tpu-bootstrap [--no-strict-templates] [--no-wait]"
                << " [--wait-timeout S]\n";
      return 0;
    }
  }

  render_templates(strict);
  if (do_wait) {
    int rc = wait_for_coordinator(timeout_s);
    if (rc != 0) return rc;
  }

  // echo the contract (reference bootstrap prints env at main.go:466-513)
  std::cerr << "[tpu-bootstrap] TASK_NAME=" << getenv_str("TASK_NAME")
            << " JAX_PROCESS_ID=" << getenv_str("JAX_PROCESS_ID", "-")
            << " JAX_NUM_PROCESSES=" << getenv_str("JAX_NUM_PROCESSES", "-")
            << " JAX_COORDINATOR_ADDRESS="
            << getenv_str("JAX_COORDINATOR_ADDRESS", "-")
            << " TPU_SLICE_ID=" << getenv_str("TPU_SLICE_ID", "-")
            << " TPU_TOPOLOGY=" << getenv_str("TPU_TOPOLOGY", "-")
            << " TPU_WORKER_COORDS=" << getenv_str("TPU_WORKER_COORDS", "-")
            << "\n";
  return 0;
}
