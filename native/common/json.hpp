// Minimal JSON value type + parser/serializer (C++17, no dependencies).
//
// Shared by the native components (tpu-agent, tpu-bootstrap, tpuctl) that
// speak the scheduler's HTTP/JSON protocol — the role protobuf played on the
// reference's libmesos boundary. Deliberately small: objects, arrays,
// strings, doubles, bools, null; UTF-8 passthrough; \uXXXX parsed to UTF-8.

#pragma once

#include <cmath>
#include <cstdint>
#include <map>
#include <memory>
#include <sstream>
#include <stdexcept>
#include <string>
#include <vector>

namespace tpu {

class Json;
using JsonObject = std::map<std::string, Json>;
using JsonArray = std::vector<Json>;

class Json {
 public:
  enum class Type { Null, Bool, Number, String, Array, Object };

  Json() : type_(Type::Null) {}
  Json(std::nullptr_t) : type_(Type::Null) {}
  Json(bool b) : type_(Type::Bool), bool_(b) {}
  Json(int v) : type_(Type::Number), num_(v) {}
  Json(int64_t v) : type_(Type::Number), num_(static_cast<double>(v)) {}
  Json(double v) : type_(Type::Number), num_(v) {}
  Json(const char* s) : type_(Type::String), str_(s) {}
  Json(std::string s) : type_(Type::String), str_(std::move(s)) {}
  Json(JsonArray a) : type_(Type::Array),
                      arr_(std::make_shared<JsonArray>(std::move(a))) {}
  Json(JsonObject o) : type_(Type::Object),
                       obj_(std::make_shared<JsonObject>(std::move(o))) {}

  static Json object() { return Json(JsonObject{}); }
  static Json array() { return Json(JsonArray{}); }

  Type type() const { return type_; }
  bool is_null() const { return type_ == Type::Null; }
  bool is_object() const { return type_ == Type::Object; }
  bool is_array() const { return type_ == Type::Array; }
  bool is_string() const { return type_ == Type::String; }
  bool is_number() const { return type_ == Type::Number; }
  bool is_bool() const { return type_ == Type::Bool; }

  bool as_bool(bool dflt = false) const {
    return type_ == Type::Bool ? bool_ : dflt;
  }
  double as_number(double dflt = 0) const {
    return type_ == Type::Number ? num_ : dflt;
  }
  int64_t as_int(int64_t dflt = 0) const {
    return type_ == Type::Number ? static_cast<int64_t>(num_) : dflt;
  }
  const std::string& as_string() const {
    static const std::string empty;
    return type_ == Type::String ? str_ : empty;
  }

  const JsonArray& items() const {
    static const JsonArray empty;
    return type_ == Type::Array ? *arr_ : empty;
  }
  JsonArray& items() {
    if (type_ != Type::Array) throw std::runtime_error("not an array");
    return *arr_;
  }
  const JsonObject& fields() const {
    static const JsonObject empty;
    return type_ == Type::Object ? *obj_ : empty;
  }

  // object access: get() is safe on any type (returns null Json on miss)
  const Json& get(const std::string& key) const {
    static const Json null_json;
    if (type_ != Type::Object) return null_json;
    auto it = obj_->find(key);
    return it == obj_->end() ? null_json : it->second;
  }
  Json& set(const std::string& key, Json value) {
    if (type_ != Type::Object) throw std::runtime_error("not an object");
    (*obj_)[key] = std::move(value);
    return *this;
  }
  void push_back(Json value) { items().push_back(std::move(value)); }

  std::string dump() const {
    std::ostringstream out;
    write(out);
    return out.str();
  }

  static Json parse(const std::string& text) {
    size_t pos = 0;
    Json v = parse_value(text, pos);
    skip_ws(text, pos);
    if (pos != text.size()) throw std::runtime_error("trailing JSON data");
    return v;
  }

 private:
  Type type_;
  bool bool_ = false;
  double num_ = 0;
  std::string str_;
  std::shared_ptr<JsonArray> arr_;
  std::shared_ptr<JsonObject> obj_;

  void write(std::ostringstream& out) const {
    switch (type_) {
      case Type::Null: out << "null"; break;
      case Type::Bool: out << (bool_ ? "true" : "false"); break;
      case Type::Number: {
        if (std::isfinite(num_) && num_ == std::floor(num_) &&
            std::fabs(num_) < 1e15) {
          out << static_cast<int64_t>(num_);
        } else {
          out << num_;
        }
        break;
      }
      case Type::String: write_string(out, str_); break;
      case Type::Array: {
        out << '[';
        bool first = true;
        for (const auto& v : *arr_) {
          if (!first) out << ',';
          first = false;
          v.write(out);
        }
        out << ']';
        break;
      }
      case Type::Object: {
        out << '{';
        bool first = true;
        for (const auto& [k, v] : *obj_) {
          if (!first) out << ',';
          first = false;
          write_string(out, k);
          out << ':';
          v.write(out);
        }
        out << '}';
        break;
      }
    }
  }

  static void write_string(std::ostringstream& out, const std::string& s) {
    out << '"';
    for (unsigned char c : s) {
      switch (c) {
        case '"': out << "\\\""; break;
        case '\\': out << "\\\\"; break;
        case '\n': out << "\\n"; break;
        case '\r': out << "\\r"; break;
        case '\t': out << "\\t"; break;
        default:
          if (c < 0x20) {
            char buf[8];
            snprintf(buf, sizeof buf, "\\u%04x", c);
            out << buf;
          } else {
            out << c;
          }
      }
    }
    out << '"';
  }

  static void skip_ws(const std::string& t, size_t& pos) {
    while (pos < t.size() &&
           (t[pos] == ' ' || t[pos] == '\t' || t[pos] == '\n' ||
            t[pos] == '\r')) {
      ++pos;
    }
  }

  static Json parse_value(const std::string& t, size_t& pos) {
    skip_ws(t, pos);
    if (pos >= t.size()) throw std::runtime_error("unexpected end of JSON");
    char c = t[pos];
    if (c == '{') return parse_object(t, pos);
    if (c == '[') return parse_array(t, pos);
    if (c == '"') return Json(parse_string(t, pos));
    if (c == 't' || c == 'f') return parse_bool(t, pos);
    if (c == 'n') {
      expect(t, pos, "null");
      return Json();
    }
    return parse_number(t, pos);
  }

  static void expect(const std::string& t, size_t& pos,
                     const std::string& word) {
    if (t.compare(pos, word.size(), word) != 0) {
      throw std::runtime_error("bad JSON literal at " + std::to_string(pos));
    }
    pos += word.size();
  }

  static Json parse_bool(const std::string& t, size_t& pos) {
    if (t[pos] == 't') {
      expect(t, pos, "true");
      return Json(true);
    }
    expect(t, pos, "false");
    return Json(false);
  }

  static Json parse_number(const std::string& t, size_t& pos) {
    size_t start = pos;
    while (pos < t.size() &&
           (isdigit(static_cast<unsigned char>(t[pos])) || t[pos] == '-' ||
            t[pos] == '+' || t[pos] == '.' || t[pos] == 'e' ||
            t[pos] == 'E')) {
      ++pos;
    }
    if (pos == start) throw std::runtime_error("bad JSON number");
    return Json(std::stod(t.substr(start, pos - start)));
  }

  static std::string parse_string(const std::string& t, size_t& pos) {
    if (t[pos] != '"') throw std::runtime_error("expected string");
    ++pos;
    std::string out;
    while (pos < t.size() && t[pos] != '"') {
      char c = t[pos++];
      if (c != '\\') {
        out += c;
        continue;
      }
      if (pos >= t.size()) throw std::runtime_error("bad escape");
      char e = t[pos++];
      switch (e) {
        case '"': out += '"'; break;
        case '\\': out += '\\'; break;
        case '/': out += '/'; break;
        case 'b': out += '\b'; break;
        case 'f': out += '\f'; break;
        case 'n': out += '\n'; break;
        case 'r': out += '\r'; break;
        case 't': out += '\t'; break;
        case 'u': {
          if (pos + 4 > t.size()) throw std::runtime_error("bad \\u");
          unsigned code = std::stoul(t.substr(pos, 4), nullptr, 16);
          pos += 4;
          // encode UTF-8 (surrogate pairs folded to replacement scope)
          if (code < 0x80) {
            out += static_cast<char>(code);
          } else if (code < 0x800) {
            out += static_cast<char>(0xC0 | (code >> 6));
            out += static_cast<char>(0x80 | (code & 0x3F));
          } else {
            out += static_cast<char>(0xE0 | (code >> 12));
            out += static_cast<char>(0x80 | ((code >> 6) & 0x3F));
            out += static_cast<char>(0x80 | (code & 0x3F));
          }
          break;
        }
        default: throw std::runtime_error("bad escape char");
      }
    }
    if (pos >= t.size()) throw std::runtime_error("unterminated string");
    ++pos;  // closing quote
    return out;
  }

  static Json parse_array(const std::string& t, size_t& pos) {
    ++pos;  // [
    Json arr = Json::array();
    skip_ws(t, pos);
    if (pos < t.size() && t[pos] == ']') {
      ++pos;
      return arr;
    }
    while (true) {
      arr.push_back(parse_value(t, pos));
      skip_ws(t, pos);
      if (pos >= t.size()) throw std::runtime_error("unterminated array");
      if (t[pos] == ',') {
        ++pos;
        continue;
      }
      if (t[pos] == ']') {
        ++pos;
        return arr;
      }
      throw std::runtime_error("bad array separator");
    }
  }

  static Json parse_object(const std::string& t, size_t& pos) {
    ++pos;  // {
    Json obj = Json::object();
    skip_ws(t, pos);
    if (pos < t.size() && t[pos] == '}') {
      ++pos;
      return obj;
    }
    while (true) {
      skip_ws(t, pos);
      std::string key = parse_string(t, pos);
      skip_ws(t, pos);
      if (pos >= t.size() || t[pos] != ':') {
        throw std::runtime_error("expected ':' in object");
      }
      ++pos;
      obj.set(key, parse_value(t, pos));
      skip_ws(t, pos);
      if (pos >= t.size()) throw std::runtime_error("unterminated object");
      if (t[pos] == ',') {
        ++pos;
        continue;
      }
      if (t[pos] == '}') {
        ++pos;
        return obj;
      }
      throw std::runtime_error("bad object separator");
    }
  }
};

}  // namespace tpu
