// Control-plane authentication for the native clients (agent, CLI).
//
// The scheduler mints short-lived HMAC bearer tokens at
// POST /v1/auth/login (see dcos_commons_tpu/security/auth.py); every
// other route wants "Authorization: token=<...>". This is the C++ twin of
// the reference's service-account token plumbing
// (dcos/auth/CachedTokenProvider.java, cli/client/http.go): log in
// lazily, cache the token, re-login once on a 401.
//
// Credentials come from the environment:
//   TPU_AUTH_TOKEN        pre-minted token (wins; no login round-trip)
//   TPU_AUTH_UID          service-account id            } login flow
//   TPU_AUTH_SECRET       account secret                }
//   TPU_AUTH_SECRET_FILE  file holding the secret (preferred over env:
//                         not visible in /proc/<pid>/environ of others)
// None set => auth disabled (open scheduler), token() returns "".

#pragma once

#include <cstdlib>
#include <fstream>
#include <sstream>
#include <string>

#include "http.hpp"
#include "json.hpp"

namespace tpu {

inline std::string getenv_str(const char* name) {
  const char* v = std::getenv(name);
  return v == nullptr ? "" : std::string(v);
}

class AuthSession {
 public:
  explicit AuthSession(const std::string& scheduler_url)
      : base_(scheduler_url) {
    fixed_token_ = getenv_str("TPU_AUTH_TOKEN");
    uid_ = getenv_str("TPU_AUTH_UID");
    secret_ = getenv_str("TPU_AUTH_SECRET");
    const std::string secret_file = getenv_str("TPU_AUTH_SECRET_FILE");
    if (secret_.empty() && !secret_file.empty()) {
      std::ifstream f(secret_file);
      std::stringstream ss;
      ss << f.rdbuf();
      secret_ = ss.str();
      // strip trailing newline(s) from `echo secret > file` style writes
      while (!secret_.empty() &&
             (secret_.back() == '\n' || secret_.back() == '\r')) {
        secret_.pop_back();
      }
    }
  }

  bool enabled() const {
    return !fixed_token_.empty() || (!uid_.empty() && !secret_.empty());
  }

  // Whether a 401 can be repaired by logging in again (a fixed
  // TPU_AUTH_TOKEN cannot — retrying it just re-sends the same token).
  bool can_relogin() const {
    return fixed_token_.empty() && !uid_.empty() && !secret_.empty();
  }

  // Current token ("" when auth is disabled). Logs in on first use.
  std::string token() {
    if (!fixed_token_.empty()) return fixed_token_;
    if (!enabled()) return "";
    if (cached_.empty()) login();
    return cached_;
  }

  // Drop the cached token (call after an HTTP 401, then retry once).
  void invalidate() { cached_.clear(); }

 private:
  void login() {
    std::string body = std::string("{\"uid\": \"") + json_escape(uid_) +
                       "\", \"secret\": \"" + json_escape(secret_) + "\"}";
    HttpResponse resp = http_post(base_ + "/v1/auth/login", body);
    if (resp.status != 200) {
      throw std::runtime_error("auth login failed: HTTP " +
                               std::to_string(resp.status));
    }
    Json reply = Json::parse(resp.body);
    cached_ = reply.get("token").as_string();
    if (cached_.empty()) {
      throw std::runtime_error("auth login returned no token");
    }
  }

  static std::string json_escape(const std::string& s) {
    std::string out;
    for (char c : s) {
      if (c == '"' || c == '\\') out.push_back('\\');
      out.push_back(c);
    }
    return out;
  }

  std::string base_;
  std::string fixed_token_;
  std::string uid_;
  std::string secret_;
  std::string cached_;
};

}  // namespace tpu
