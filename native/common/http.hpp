// Minimal blocking HTTP/1.1 client over POSIX sockets (C++17).
//
// The native components' transport to the scheduler ApiServer — the role the
// reference delegated to libmesos/JNI (scheduler side) and Go's net/http
// (bootstrap/CLI side). Supports http://host:port/path and — via tls.hpp,
// verifying against the TPU_TLS_CA bundle like the reference's
// cli/client/http.go verifies the cluster CA — https://. Each request uses
// a fresh connection (Connection: close) — the protocol is low-rate
// (1 Hz polls), so simplicity beats keep-alive.

#pragma once

#include <arpa/inet.h>
#include <netdb.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdlib>
#include <cstring>
#include <memory>
#include <stdexcept>
#include <string>

#include "tls.hpp"

namespace tpu {

struct HttpResponse {
  int status = 0;
  std::string body;
};

struct Url {
  std::string host;
  std::string port;
  std::string path;
  bool tls = false;
};

inline Url parse_url(const std::string& url) {
  const std::string http = "http://";
  const std::string https = "https://";
  Url out;
  std::string rest;
  if (url.compare(0, https.size(), https) == 0) {
    out.tls = true;
    rest = url.substr(https.size());
  } else if (url.compare(0, http.size(), http) == 0) {
    rest = url.substr(http.size());
  } else {
    throw std::runtime_error("only http:// and https:// URLs supported: " +
                             url);
  }
  size_t slash = rest.find('/');
  std::string hostport = slash == std::string::npos ? rest
                                                    : rest.substr(0, slash);
  out.path = slash == std::string::npos ? "/" : rest.substr(slash);
  size_t colon = hostport.rfind(':');
  if (colon == std::string::npos) {
    out.host = hostport;
    out.port = out.tls ? "443" : "80";
  } else {
    out.host = hostport.substr(0, colon);
    out.port = hostport.substr(colon + 1);
  }
  return out;
}

inline HttpResponse http_request(const std::string& method,
                                 const std::string& url,
                                 const std::string& body = "",
                                 int timeout_s = 30,
                                 const std::string& auth = "") {
  // auth: bearer-token value sent as "Authorization: token=<auth>" —
  // the scheduler's control-plane credential (see security/auth.py)
  Url u = parse_url(url);

  struct addrinfo hints;
  memset(&hints, 0, sizeof hints);
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  int rc = getaddrinfo(u.host.c_str(), u.port.c_str(), &hints, &res);
  if (rc != 0) {
    throw std::runtime_error("resolve " + u.host + ": " + gai_strerror(rc));
  }

  int fd = -1;
  for (struct addrinfo* ai = res; ai != nullptr; ai = ai->ai_next) {
    fd = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    struct timeval tv{timeout_s, 0};
    setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof tv);
    setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof tv);
    if (connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) break;
    close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  if (fd < 0) {
    throw std::runtime_error("connect to " + u.host + ":" + u.port +
                             " failed");
  }

  // transport security (env contract shared with the Python clients,
  // dcos_commons_tpu/security/transport.py)
  std::unique_ptr<tls::Conn> tls_conn;
  if (u.tls) {
    const char* ca = std::getenv("TPU_TLS_CA");
    const char* insecure_env = std::getenv("TPU_TLS_INSECURE");
    // accepted values mirror the Python twin (transport.py): 1/true/yes
    bool insecure = insecure_env != nullptr &&
                    (std::string(insecure_env) == "1" ||
                     std::string(insecure_env) == "true" ||
                     std::string(insecure_env) == "yes");
    if (!insecure && (ca == nullptr || *ca == '\0')) {
      close(fd);
      throw std::runtime_error(
          "https:// control-plane URL but no trust configured: set "
          "TPU_TLS_CA to the scheduler's CA bundle (or TPU_TLS_INSECURE=1)");
    }
    try {
      tls_conn = std::make_unique<tls::Conn>(
          fd, u.host, ca != nullptr ? std::string(ca) : std::string(),
          insecure);
    } catch (...) {
      close(fd);
      throw;
    }
  }
  auto send_all = [&](const char* data, size_t len) -> bool {
    size_t sent = 0;
    while (sent < len) {
      long n = tls_conn != nullptr
                   ? tls_conn->write(data + sent, len - sent)
                   : static_cast<long>(send(fd, data + sent, len - sent, 0));
      if (n <= 0) return false;
      sent += static_cast<size_t>(n);
    }
    return true;
  };

  std::string req = method + " " + u.path + " HTTP/1.1\r\n" +
                    "Host: " + u.host + ":" + u.port + "\r\n" +
                    "Content-Type: application/json\r\n" +
                    "Content-Length: " + std::to_string(body.size()) +
                    "\r\n";
  if (!auth.empty()) {
    req += "Authorization: token=" + auth + "\r\n";
  }
  req += "Connection: close\r\n\r\n" + body;
  if (!send_all(req.data(), req.size())) {
    tls_conn.reset();
    close(fd);
    throw std::runtime_error("send failed");
  }

  // Read headers + exactly Content-Length body bytes. The length lives
  // INSIDE the TLS stream, so a torn connection (no close_notify, e.g. a
  // truncation attack or mid-body crash) is detected as an incomplete
  // body and must fail — it must never parse as a short-but-valid
  // response. (Stacks like Python's ssl close without close_notify, so
  // EOF after a complete body is accepted.)
  std::string raw;
  char buf[8192];
  size_t header_end = std::string::npos;
  size_t body_need = std::string::npos;  // npos = read until close
  bool torn = false;
  while (true) {
    long n = tls_conn != nullptr
                 ? tls_conn->read(buf, sizeof buf)
                 : static_cast<long>(recv(fd, buf, sizeof buf, 0));
    if (n <= 0) {
      torn = n < 0 && tls_conn != nullptr;
      break;
    }
    raw.append(buf, static_cast<size_t>(n));
    if (header_end == std::string::npos) {
      header_end = raw.find("\r\n\r\n");
      if (header_end != std::string::npos) {
        // case-insensitive Content-Length scan within the header block
        std::string headers = raw.substr(0, header_end);
        for (auto& c : headers) c = static_cast<char>(tolower(c));
        size_t cl = headers.find("content-length:");
        if (cl != std::string::npos) {
          body_need = std::strtoul(headers.c_str() + cl + 15, nullptr, 10);
        }
      }
    }
    if (header_end != std::string::npos && body_need != std::string::npos &&
        raw.size() - (header_end + 4) >= body_need) {
      break;  // complete response; don't wait for close
    }
  }
  tls_conn.reset();  // close_notify before the socket goes away
  close(fd);

  if (header_end == std::string::npos) {
    throw std::runtime_error(torn ? "TLS read error (connection truncated)"
                                  : "malformed HTTP response");
  }
  size_t body_have = raw.size() - (header_end + 4);
  if (body_need != std::string::npos && body_have < body_need) {
    throw std::runtime_error("truncated HTTP response body (" +
                             std::to_string(body_have) + "/" +
                             std::to_string(body_need) + " bytes)");
  }
  if (torn && body_need == std::string::npos) {
    throw std::runtime_error("TLS read error (connection truncated)");
  }
  HttpResponse out;
  size_t sp = raw.find(' ');
  if (sp != std::string::npos) {
    out.status = std::stoi(raw.substr(sp + 1, 3));
  }
  out.body = raw.substr(header_end + 4);
  if (body_need != std::string::npos) {
    out.body.resize(body_need);
  }
  return out;
}

inline HttpResponse http_get(const std::string& url, int timeout_s = 30,
                             const std::string& auth = "") {
  return http_request("GET", url, "", timeout_s, auth);
}

inline HttpResponse http_post(const std::string& url, const std::string& body,
                              int timeout_s = 30,
                              const std::string& auth = "") {
  return http_request("POST", url, body, timeout_s, auth);
}

}  // namespace tpu
