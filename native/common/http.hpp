// Minimal blocking HTTP/1.1 client over POSIX sockets (C++17, no deps).
//
// The native components' transport to the scheduler ApiServer — the role the
// reference delegated to libmesos/JNI (scheduler side) and Go's net/http
// (bootstrap/CLI side). Supports http://host:port/path only; each request
// uses a fresh connection (Connection: close) — the protocol is low-rate
// (1 Hz polls), so simplicity beats keep-alive.

#pragma once

#include <arpa/inet.h>
#include <netdb.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstring>
#include <stdexcept>
#include <string>

namespace tpu {

struct HttpResponse {
  int status = 0;
  std::string body;
};

struct Url {
  std::string host;
  std::string port;
  std::string path;
};

inline Url parse_url(const std::string& url) {
  const std::string scheme = "http://";
  if (url.compare(0, scheme.size(), scheme) != 0) {
    throw std::runtime_error("only http:// URLs supported: " + url);
  }
  std::string rest = url.substr(scheme.size());
  size_t slash = rest.find('/');
  std::string hostport = slash == std::string::npos ? rest
                                                    : rest.substr(0, slash);
  Url out;
  out.path = slash == std::string::npos ? "/" : rest.substr(slash);
  size_t colon = hostport.rfind(':');
  if (colon == std::string::npos) {
    out.host = hostport;
    out.port = "80";
  } else {
    out.host = hostport.substr(0, colon);
    out.port = hostport.substr(colon + 1);
  }
  return out;
}

inline HttpResponse http_request(const std::string& method,
                                 const std::string& url,
                                 const std::string& body = "",
                                 int timeout_s = 30,
                                 const std::string& auth = "") {
  // auth: bearer-token value sent as "Authorization: token=<auth>" —
  // the scheduler's control-plane credential (see security/auth.py)
  Url u = parse_url(url);

  struct addrinfo hints;
  memset(&hints, 0, sizeof hints);
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  int rc = getaddrinfo(u.host.c_str(), u.port.c_str(), &hints, &res);
  if (rc != 0) {
    throw std::runtime_error("resolve " + u.host + ": " + gai_strerror(rc));
  }

  int fd = -1;
  for (struct addrinfo* ai = res; ai != nullptr; ai = ai->ai_next) {
    fd = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    struct timeval tv{timeout_s, 0};
    setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof tv);
    setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof tv);
    if (connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) break;
    close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  if (fd < 0) {
    throw std::runtime_error("connect to " + u.host + ":" + u.port +
                             " failed");
  }

  std::string req = method + " " + u.path + " HTTP/1.1\r\n" +
                    "Host: " + u.host + ":" + u.port + "\r\n" +
                    "Content-Type: application/json\r\n" +
                    "Content-Length: " + std::to_string(body.size()) +
                    "\r\n";
  if (!auth.empty()) {
    req += "Authorization: token=" + auth + "\r\n";
  }
  req += "Connection: close\r\n\r\n" + body;
  size_t sent = 0;
  while (sent < req.size()) {
    ssize_t n = send(fd, req.data() + sent, req.size() - sent, 0);
    if (n <= 0) {
      close(fd);
      throw std::runtime_error("send failed");
    }
    sent += static_cast<size_t>(n);
  }

  std::string raw;
  char buf[8192];
  while (true) {
    ssize_t n = recv(fd, buf, sizeof buf, 0);
    if (n <= 0) break;
    raw.append(buf, static_cast<size_t>(n));
  }
  close(fd);

  size_t header_end = raw.find("\r\n\r\n");
  if (header_end == std::string::npos) {
    throw std::runtime_error("malformed HTTP response");
  }
  HttpResponse out;
  size_t sp = raw.find(' ');
  if (sp != std::string::npos) {
    out.status = std::stoi(raw.substr(sp + 1, 3));
  }
  out.body = raw.substr(header_end + 4);
  return out;
}

inline HttpResponse http_get(const std::string& url, int timeout_s = 30,
                             const std::string& auth = "") {
  return http_request("GET", url, "", timeout_s, auth);
}

inline HttpResponse http_post(const std::string& url, const std::string& body,
                              int timeout_s = 30,
                              const std::string& auth = "") {
  return http_request("POST", url, body, timeout_s, auth);
}

}  // namespace tpu
