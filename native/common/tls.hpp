// TLS client for the native control-plane components (agent, tpuctl).
//
// Role: the client half of the control-plane transport security that the
// reference got from DC/OS adminrouter + a TLS-configured client stack
// (sdk/.../dcos/DcosHttpClientBuilder.java, cli/client/http.go). The Python
// twin is dcos_commons_tpu/security/transport.py — same env contract:
//   TPU_TLS_CA       path to the scheduler CA bundle (verify peer + host)
//   TPU_TLS_INSECURE "1" to skip verification (development only)
//
// The image ships libssl.so.3/libcrypto.so.3 but no OpenSSL headers, so the
// handful of client-side entry points (a stable C ABI) are declared here and
// resolved with dlopen at first use. No link-time OpenSSL dependency: a box
// without libssl can still run cleartext http://.

#pragma once

#include <arpa/inet.h>
#include <dlfcn.h>

#include <cstdlib>
#include <cstring>
#include <stdexcept>
#include <string>

namespace tpu {
namespace tls {

// opaque OpenSSL handles (we only pass pointers through the C ABI)
struct SslCtx;
struct Ssl;
struct SslMethod;
struct VerifyParam;

// OpenSSL ABI constants (stable across 1.1/3.x)
constexpr int kSslVerifyPeer = 0x01;
constexpr long kSslCtrlSetMinProtoVersion = 123;
constexpr long kTls12Version = 0x0303;
constexpr long kSslCtrlSetTlsextHostname = 55;
constexpr int kTlsextNametypeHostName = 0;
constexpr int kSslErrorZeroReturn = 6;  // clean close_notify

struct Api {
  SslMethod* (*TLS_client_method)();
  SslCtx* (*SSL_CTX_new)(SslMethod*);
  void (*SSL_CTX_free)(SslCtx*);
  void (*SSL_CTX_set_verify)(SslCtx*, int, void*);
  int (*SSL_CTX_load_verify_locations)(SslCtx*, const char*, const char*);
  int (*SSL_CTX_set_default_verify_paths)(SslCtx*);
  long (*SSL_CTX_ctrl)(SslCtx*, int, long, void*);
  Ssl* (*SSL_new)(SslCtx*);
  void (*SSL_free)(Ssl*);
  int (*SSL_set_fd)(Ssl*, int);
  int (*SSL_connect)(Ssl*);
  int (*SSL_read)(Ssl*, void*, int);
  int (*SSL_write)(Ssl*, const void*, int);
  int (*SSL_shutdown)(Ssl*);
  int (*SSL_get_error)(const Ssl*, int);
  long (*SSL_get_verify_result)(const Ssl*);
  long (*SSL_ctrl)(Ssl*, int, long, void*);
  VerifyParam* (*SSL_get0_param)(Ssl*);
  int (*X509_VERIFY_PARAM_set1_host)(VerifyParam*, const char*, size_t);
  int (*X509_VERIFY_PARAM_set1_ip_asc)(VerifyParam*, const char*);
  unsigned long (*ERR_get_error)();
  void (*ERR_error_string_n)(unsigned long, char*, size_t);

  static const Api& instance() {
    static Api api = load();
    return api;
  }

 private:
  static Api load() {
    // libssl pulls in libcrypto as a dependency; RTLD_GLOBAL lets the
    // libcrypto symbols resolve from the same namespace
    void* ssl = dlopen("libssl.so.3", RTLD_NOW | RTLD_GLOBAL);
    if (ssl == nullptr) ssl = dlopen("libssl.so", RTLD_NOW | RTLD_GLOBAL);
    if (ssl == nullptr) {
      throw std::runtime_error(
          "https:// requested but libssl is not available: " +
          std::string(dlerror()));
    }
    void* crypto = dlopen("libcrypto.so.3", RTLD_NOW | RTLD_GLOBAL);
    if (crypto == nullptr) crypto = dlopen("libcrypto.so", RTLD_NOW | RTLD_GLOBAL);
    Api api;
    auto need = [](void* lib, const char* name) -> void* {
      void* sym = dlsym(lib, name);
      if (sym == nullptr) {
        throw std::runtime_error(std::string("missing OpenSSL symbol ") +
                                 name);
      }
      return sym;
    };
#define TPU_TLS_SYM(lib, name) \
  api.name = reinterpret_cast<decltype(api.name)>(need(lib, #name))
    TPU_TLS_SYM(ssl, TLS_client_method);
    TPU_TLS_SYM(ssl, SSL_CTX_new);
    TPU_TLS_SYM(ssl, SSL_CTX_free);
    TPU_TLS_SYM(ssl, SSL_CTX_set_verify);
    TPU_TLS_SYM(ssl, SSL_CTX_load_verify_locations);
    TPU_TLS_SYM(ssl, SSL_CTX_set_default_verify_paths);
    TPU_TLS_SYM(ssl, SSL_CTX_ctrl);
    TPU_TLS_SYM(ssl, SSL_new);
    TPU_TLS_SYM(ssl, SSL_free);
    TPU_TLS_SYM(ssl, SSL_set_fd);
    TPU_TLS_SYM(ssl, SSL_connect);
    TPU_TLS_SYM(ssl, SSL_read);
    TPU_TLS_SYM(ssl, SSL_write);
    TPU_TLS_SYM(ssl, SSL_shutdown);
    TPU_TLS_SYM(ssl, SSL_get_error);
    TPU_TLS_SYM(ssl, SSL_get_verify_result);
    TPU_TLS_SYM(ssl, SSL_ctrl);
    TPU_TLS_SYM(ssl, SSL_get0_param);
    void* cl = crypto != nullptr ? crypto : ssl;
    TPU_TLS_SYM(cl, X509_VERIFY_PARAM_set1_host);
    TPU_TLS_SYM(cl, X509_VERIFY_PARAM_set1_ip_asc);
    TPU_TLS_SYM(cl, ERR_get_error);
    TPU_TLS_SYM(cl, ERR_error_string_n);
#undef TPU_TLS_SYM
    return api;
  }
};

inline bool is_ip_literal(const std::string& host) {
  unsigned char buf[sizeof(struct in6_addr)];
  return inet_pton(AF_INET, host.c_str(), buf) == 1 ||
         inet_pton(AF_INET6, host.c_str(), buf) == 1;
}

inline std::string last_error(const Api& api) {
  unsigned long code = api.ERR_get_error();
  if (code == 0) return "unknown TLS error";
  char buf[256];
  api.ERR_error_string_n(code, buf, sizeof buf);
  return std::string(buf);
}

// One verified TLS session over an already-connected fd. The fd stays owned
// by the caller (http.hpp closes it after shutdown).
class Conn {
 public:
  Conn(int fd, const std::string& host, const std::string& ca_file,
       bool insecure)
      : api_(Api::instance()) {
    ctx_ = api_.SSL_CTX_new(api_.TLS_client_method());
    if (ctx_ == nullptr) throw std::runtime_error("SSL_CTX_new failed");
    api_.SSL_CTX_ctrl(ctx_, kSslCtrlSetMinProtoVersion, kTls12Version,
                      nullptr);
    if (!insecure) {
      api_.SSL_CTX_set_verify(ctx_, kSslVerifyPeer, nullptr);
      int ok = ca_file.empty()
                   ? api_.SSL_CTX_set_default_verify_paths(ctx_)
                   : api_.SSL_CTX_load_verify_locations(ctx_, ca_file.c_str(),
                                                        nullptr);
      if (ok != 1) {
        cleanup();
        throw std::runtime_error("cannot load CA bundle " + ca_file + ": " +
                                 last_error(api_));
      }
    }
    ssl_ = api_.SSL_new(ctx_);
    if (ssl_ == nullptr) {
      cleanup();
      throw std::runtime_error("SSL_new failed");
    }
    if (!insecure) {
      // hostname (or IP SAN) verification, enforced during the handshake
      VerifyParam* param = api_.SSL_get0_param(ssl_);
      int ok = is_ip_literal(host)
                   ? api_.X509_VERIFY_PARAM_set1_ip_asc(param, host.c_str())
                   : api_.X509_VERIFY_PARAM_set1_host(param, host.c_str(), 0);
      if (ok != 1) {
        cleanup();
        throw std::runtime_error("cannot pin expected peer name " + host);
      }
    }
    if (!is_ip_literal(host)) {  // SNI (servers may key certs on it)
      api_.SSL_ctrl(ssl_, kSslCtrlSetTlsextHostname, kTlsextNametypeHostName,
                    const_cast<char*>(host.c_str()));
    }
    api_.SSL_set_fd(ssl_, fd);
    if (api_.SSL_connect(ssl_) != 1) {
      long verify = api_.SSL_get_verify_result(ssl_);
      std::string detail = last_error(api_);
      cleanup();
      throw std::runtime_error(
          "TLS handshake with " + host + " failed" +
          (verify != 0 ? " (certificate verification error " +
                             std::to_string(verify) + ")"
                       : "") +
          ": " + detail);
    }
  }

  Conn(const Conn&) = delete;
  Conn& operator=(const Conn&) = delete;

  ~Conn() {
    if (ssl_ != nullptr) api_.SSL_shutdown(ssl_);
    cleanup();
  }

  // >0 bytes; 0 ONLY on a clean close_notify; <0 on any error — including
  // a transport EOF without close_notify, which is how a truncation attack
  // (or a mid-body crash) looks and must NOT parse as a complete response
  long read(char* buf, size_t len) {
    int n = api_.SSL_read(ssl_, buf, static_cast<int>(len));
    if (n > 0) return n;
    int err = api_.SSL_get_error(ssl_, n);
    return err == kSslErrorZeroReturn ? 0 : -1;
  }

  long write(const char* buf, size_t len) {
    int n = api_.SSL_write(ssl_, buf, static_cast<int>(len));
    return n > 0 ? n : -1;
  }

 private:
  void cleanup() {
    if (ssl_ != nullptr) {
      api_.SSL_free(ssl_);
      ssl_ = nullptr;
    }
    if (ctx_ != nullptr) {
      api_.SSL_CTX_free(ctx_);
      ctx_ = nullptr;
    }
  }

  const Api& api_;
  SslCtx* ctx_ = nullptr;
  Ssl* ssl_ = nullptr;
};

}  // namespace tls
}  // namespace tpu
