// tpuctl — native operator CLI for the scheduler HTTP API (C++17).
//
// Native build of the same command surface as the Python CLI
// (dcos_commons_tpu/cli/main.py), mirroring the reference's Go CLI
// (cli/commands.go:38-52): plan / pod / endpoints / debug / describe /
// config / state / health against /v1/* (or /v1/service/<name>/* with
// --service).

#include <limits.h>
#include <sys/stat.h>

#include <cstdlib>
#include <fstream>
#include <iostream>
#include <sstream>
#include <string>
#include <vector>

#include "../common/auth.hpp"
#include "../common/http.hpp"
#include "../common/json.hpp"

namespace {

struct Ctx {
  std::string base = "http://127.0.0.1:8080";
  std::string prefix = "/v1";
  // control-plane credential (TPU_AUTH_TOKEN or TPU_AUTH_UID/SECRET env;
  // reference cli/client/http.go auth-header plumbing)
  mutable tpu::AuthSession* auth = nullptr;

  std::string token() const { return auth ? auth->token() : ""; }
};

int emit(const tpu::HttpResponse& resp) {
  // re-indent through the Json layer when possible for stable output
  try {
    std::cout << tpu::Json::parse(resp.body).dump() << "\n";
  } catch (...) {
    std::cout << resp.body << "\n";
  }
  return resp.status < 400 ? 0 : 1;
}

int get(const Ctx& ctx, const std::string& path) {
  return emit(tpu::http_get(ctx.base + ctx.prefix + "/" + path, 30,
                            ctx.token()));
}

int post(const Ctx& ctx, const std::string& path,
         const std::string& body = "") {
  return emit(tpu::http_post(ctx.base + ctx.prefix + "/" + path, body, 30,
                             ctx.token()));
}

int request(const Ctx& ctx, const std::string& method,
            const std::string& path, const std::string& body = "") {
  return emit(tpu::http_request(method, ctx.base + ctx.prefix + "/" + path,
                                body, 30, ctx.token()));
}

std::string url_escape_role(const std::string& role) {
  // full percent-encoding of non-unreserved chars ('%' included, or the
  // server's unquote would rewrite "a%2Fb" into the DIFFERENT role "a/b")
  static const char* hex = "0123456789ABCDEF";
  std::string out;
  for (unsigned char c : role) {
    if (isalnum(c) || c == '-' || c == '_' || c == '.' || c == '~') {
      out += static_cast<char>(c);
    } else {
      out += '%';
      out += hex[c >> 4];
      out += hex[c & 0xF];
    }
  }
  return out;
}

void usage() {
  std::cerr
      << "usage: tpuctl [--url URL] [--service NAME] <command> ...\n"
      << "  plan list|show|start|stop|continue|interrupt|force-complete|"
      << "restart [PLAN] [--phase P] [--step S]\n"
      << "  pod list|status|info|restart|replace|pause|resume [POD]\n"
      << "  endpoints [NAME]\n"
      << "  debug offers|plans|statuses|reservations\n"
      << "  describe | config list|show|target-id [ID]\n"
      << "  config set-cluster URL [--ca FILE] [--token-file FILE] | "
      << "show-cluster\n"
      << "  update [--set KEY=VALUE ...] [--yaml FILE]\n"
      << "  state framework-id|properties|property [KEY]\n"
      << "  agents [list|info]\n"
      << "  quota list | set ROLE [--set DIM=N ...] | delete ROLE\n"
      << "  health\n";
}

// -- cluster config (reference cli/config/config.go attached-cluster
// ergonomics): ~/.tpuctl/config.json (TPUCTL_HOME overrides the dir),
// shared byte-for-byte with the Python CLI. Precedence: flag > env >
// config — applied by folding config values into UNSET env vars, so the
// shared auth/TLS plumbing needs no second code path.

std::string cluster_config_dir() {
  const char* o = getenv("TPUCTL_HOME");
  if (o != nullptr) return o;
  const char* home = getenv("HOME");
  return std::string(home ? home : ".") + "/.tpuctl";
}

void apply_cluster_config() {
  std::ifstream in(cluster_config_dir() + "/config.json");
  if (!in) return;
  std::ostringstream ss;
  ss << in.rdbuf();
  tpu::Json cfg = tpu::Json::object();
  try {
    cfg = tpu::Json::parse(ss.str());
  } catch (...) {
    return;  // corrupt config: behave as unconfigured
  }
  std::string url = cfg.get("url").as_string();
  if (!url.empty()) setenv("TPU_SCHEDULER_URL", url.c_str(), 0);
  std::string ca = cfg.get("ca").as_string();
  if (!ca.empty()) setenv("TPU_TLS_CA", ca.c_str(), 0);
  std::string token_file = cfg.get("token_file").as_string();
  if (!token_file.empty() && getenv("TPU_AUTH_TOKEN") == nullptr) {
    std::ifstream tf(token_file);
    if (tf) {
      std::string token;
      std::getline(tf, token);
      while (!token.empty() &&
             (token.back() == '\n' || token.back() == '\r' ||
              token.back() == ' '))
        token.pop_back();
      if (!token.empty()) setenv("TPU_AUTH_TOKEN", token.c_str(), 1);
    }
  }
}

int set_cluster(const std::string& url, const std::string& ca,
                const std::string& token_file) {
  if (url.rfind("http://", 0) != 0 && url.rfind("https://", 0) != 0) {
    std::cerr << "config set-cluster needs an http(s):// URL\n";
    return 2;
  }
  if (url.rfind("https://", 0) == 0 && ca.empty()) {
    std::cerr << "https cluster needs --ca FILE (scheduler CA cert)\n";
    return 2;
  }
  // store ABSOLUTE paths (the Python twin does the same with abspath):
  // the config is read from arbitrary cwds later, where a relative path
  // written from this one would silently stop resolving
  char resolved[PATH_MAX];
  std::string ca_abs = ca, token_abs = token_file;
  if (!ca.empty()) {
    if (realpath(ca.c_str(), resolved) == nullptr) {
      std::cerr << "--ca file not found: " << ca << "\n";
      return 2;
    }
    ca_abs = resolved;
  }
  if (!token_file.empty()) {
    if (realpath(token_file.c_str(), resolved) == nullptr) {
      std::cerr << "--token-file not found: " << token_file << "\n";
      return 2;
    }
    token_abs = resolved;
  }
  std::string trimmed = url;
  while (!trimmed.empty() && trimmed.back() == '/') trimmed.pop_back();
  tpu::Json cfg = tpu::Json::object();
  cfg.set("url", trimmed);
  if (!ca_abs.empty()) cfg.set("ca", ca_abs);
  if (!token_abs.empty()) cfg.set("token_file", token_abs);
  std::string dir = cluster_config_dir();
  mkdir(dir.c_str(), 0700);  // EEXIST is fine
  std::string path = dir + "/config.json";
  std::string tmp = path + ".tmp";
  {
    std::ofstream out(tmp);
    if (!out) {
      std::cerr << "cannot write " << tmp << "\n";
      return 2;
    }
    out << cfg.dump() << "\n";
  }
  if (rename(tmp.c_str(), path.c_str()) != 0) {
    std::cerr << "cannot commit " << path << "\n";
    return 2;
  }
  cfg.set("ok", true);
  cfg.set("path", path);
  std::cout << cfg.dump() << "\n";
  return 0;
}

int show_cluster() {
  std::string path = cluster_config_dir() + "/config.json";
  tpu::Json cfg = tpu::Json::object();
  std::ifstream in(path);
  if (in) {
    std::ostringstream ss;
    ss << in.rdbuf();
    try {
      cfg = tpu::Json::parse(ss.str());
    } catch (...) {
    }
  }
  cfg.set("path", path);
  std::cout << cfg.dump() << "\n";
  return 0;
}

}  // namespace

int main(int argc, char** argv) {
  Ctx ctx;
  apply_cluster_config();  // env still wins (setenv without overwrite)
  const char* env_url = getenv("TPU_SCHEDULER_URL");
  if (env_url != nullptr) ctx.base = env_url;

  std::vector<std::string> args;
  for (int i = 1; i < argc; ++i) {
    std::string a = argv[i];
    if (a == "--url" && i + 1 < argc) {
      ctx.base = argv[++i];
    } else if (a == "--service" && i + 1 < argc) {
      ctx.prefix = std::string("/v1/service/") + argv[++i];
    } else {
      args.push_back(a);
    }
  }
  if (args.empty()) {
    usage();
    return 2;
  }
  tpu::AuthSession auth(ctx.base);  // after --url so login hits the right host
  ctx.auth = &auth;

  // extract --phase/--step/--set/--yaml/--ca/--token-file wherever they
  // appear
  std::string phase, step, yaml_file, ca_file, token_file;
  std::vector<std::string> sets;
  std::vector<std::string> pos;
  for (size_t i = 0; i < args.size(); ++i) {
    if (args[i] == "--phase" && i + 1 < args.size()) phase = args[++i];
    else if (args[i] == "--step" && i + 1 < args.size()) step = args[++i];
    else if (args[i] == "--set" && i + 1 < args.size()) sets.push_back(args[++i]);
    else if (args[i] == "--yaml" && i + 1 < args.size()) yaml_file = args[++i];
    else if (args[i] == "--ca" && i + 1 < args.size()) ca_file = args[++i];
    else if (args[i] == "--token-file" && i + 1 < args.size()) token_file = args[++i];
    else pos.push_back(args[i]);
  }

  try {
    const std::string& cmd = pos[0];
    std::string action = pos.size() > 1 ? pos[1] : "";
    std::string arg = pos.size() > 2 ? pos[2] : "";

    if (cmd == "health") return get(ctx, "health");
    if (cmd == "describe") return get(ctx, "configurations/target");

    if (cmd == "agents") {
      if (!action.empty() && action != "list" && action != "info") {
        std::cerr << "agents: unknown action '" << action
                  << "' (expected list|info)\n";
        return 2;
      }
      // process-level route: never under a /v1/service/<name> prefix
      Ctx root = ctx;
      root.prefix = "/v1";
      return get(root, action == "info" ? "agents/info" : "agents");
    }

    if (cmd == "update") {
      // live config update (`dcos <svc> update start --options` analogue)
      if (sets.empty() && yaml_file.empty()) {
        std::cerr << "update: provide --set KEY=VALUE and/or --yaml FILE\n";
        return 2;
      }
      tpu::Json env = tpu::Json::object();
      for (const auto& pair : sets) {
        size_t eq = pair.find('=');
        if (eq == std::string::npos) {
          std::cerr << "--set needs KEY=VALUE, got '" << pair << "'\n";
          return 2;
        }
        env.set(pair.substr(0, eq), tpu::Json(pair.substr(eq + 1)));
      }
      tpu::Json body = tpu::Json::object();
      body.set("env", env);
      if (!yaml_file.empty()) {
        std::ifstream in(yaml_file);
        if (!in) {
          std::cerr << "cannot read " << yaml_file << "\n";
          return 2;
        }
        std::ostringstream ss;
        ss << in.rdbuf();
        body.set("yaml", tpu::Json(ss.str()));
      }
      return post(ctx, "update", body.dump());
    }

    if (cmd == "plan") {
      if (action == "list" || action.empty()) return get(ctx, "plans");
      std::string plan = arg.empty() ? "deploy" : arg;
      if (action == "show") return get(ctx, "plans/" + plan);
      std::string verb = action == "force-complete" ? "forceComplete"
                                                    : action;
      std::string qs;
      if (!phase.empty()) qs += (qs.empty() ? "?" : "&") + ("phase=" + phase);
      if (!step.empty()) qs += (qs.empty() ? "?" : "&") + ("step=" + step);
      return post(ctx, "plans/" + plan + "/" + verb + qs);
    }

    if (cmd == "pod") {
      if (action == "list" || action.empty()) return get(ctx, "pod");
      if (action == "status") {
        return get(ctx, arg.empty() ? "pod/status" : "pod/" + arg +
                                                         "/status");
      }
      if (action == "info") return get(ctx, "pod/" + arg + "/info");
      return post(ctx, "pod/" + arg + "/" + action);
    }

    if (cmd == "endpoints") {
      return get(ctx, action.empty() ? "endpoints" : "endpoints/" + action);
    }

    if (cmd == "debug") {
      if (action == "offers") return get(ctx, "debug/offers");
      if (action == "plans") return get(ctx, "debug/plans");
      if (action == "statuses") return get(ctx, "debug/taskStatuses");
      if (action == "reservations") return get(ctx, "debug/reservations");
    }

    if (cmd == "config") {
      if (action == "set-cluster") return set_cluster(arg, ca_file,
                                                      token_file);
      if (action == "show-cluster") return show_cluster();
      if (action == "list") return get(ctx, "configurations");
      if (action == "target-id") return get(ctx, "configurations/targetId");
      if (action == "show") {
        return get(ctx, arg.empty() ? "configurations/target"
                                    : "configurations/" + arg);
      }
    }

    if (cmd == "state") {
      if (action == "framework-id") return get(ctx, "state/frameworkId");
      if (action == "properties") return get(ctx, "state/properties");
      if (action == "property") return get(ctx, "state/properties/" + arg);
    }

    if (cmd == "quota") {
      // cluster-level route, never under a service prefix
      Ctx root = ctx;
      root.prefix = "/v1";
      if (action == "list" || action.empty()) return get(root, "quota");
      if (action == "set") {
        if (arg.empty() || sets.empty()) {
          std::cerr << "quota set ROLE --set cpus=N [--set memory_mb=N "
                       "--set disk_mb=N --set tpus=N]\n";
          return 2;
        }
        std::string body = "{";
        for (size_t i = 0; i < sets.size(); ++i) {
          size_t eq = sets[i].find('=');
          if (eq == std::string::npos) {
            std::cerr << "--set needs DIM=N, got '" << sets[i] << "'\n";
            return 2;
          }
          if (i > 0) body += ",";
          body += "\"" + sets[i].substr(0, eq) + "\": " +
                  sets[i].substr(eq + 1);
        }
        body += "}";
        return request(root, "PUT", "quota/" + url_escape_role(arg), body);
      }
      if (action == "delete") {
        if (arg.empty()) {
          std::cerr << "quota delete ROLE\n";
          return 2;
        }
        return request(root, "DELETE",
                       "quota/" + url_escape_role(arg));
      }
      std::cerr << "quota: unknown action '" << action
                << "' (expected list|set|delete)\n";
      return 2;
    }
  } catch (const std::exception& e) {
    std::cerr << "error: " << e.what() << "\n";
    return 2;
  }
  usage();
  return 2;
}
