// tpu-agent — per-host agent daemon (C++17, no dependencies).
//
// The native equivalent of the reference's Mesos agent + default executor +
// libmesos driver rolled into one (SURVEY.md §2.2 row 1): it inventories
// the host (cpus/mem/disk/ports + TPU chips and ICI topology coords),
// registers with the scheduler, polls for launch/kill commands, supervises
// task processes in per-task sandboxes, and reports status updates
// (TASK_RUNNING / TASK_FINISHED / TASK_FAILED / TASK_KILLED) on the next
// poll — the reference's status-update channel
// (FrameworkScheduler.statusUpdate, FrameworkScheduler.java:273).
//
// Protocol (scheduler side: dcos_commons_tpu/agent/remote.py):
//   POST /v1/agents/register   {agent_id, hostname, cpus, ...} -> {ok}
//   POST /v1/agents/<id>/poll  {running_task_ids, statuses} -> {commands}
//
// Tasks run as process groups under /bin/sh -c <cmd> in
// <base_dir>/<task_id>/ with the launch env exported; kill sends SIGTERM to
// the group, then SIGKILL after the grace period. Readiness checks
// (reference ReadinessCheckSpec) run after launch; success is reported as
// TASK_RUNNING with readiness_passed=true.

#include <dirent.h>
#include <fcntl.h>
#include <limits.h>
#include <linux/audit.h>
#include <linux/filter.h>
#include <linux/seccomp.h>
#include <sched.h>
#include <signal.h>
#include <stddef.h>
#include <sys/mount.h>
#include <sys/prctl.h>
#include <sys/resource.h>
#include <sys/stat.h>
#include <sys/syscall.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

#include <cstdlib>
#include <cstring>
#include <ctime>
#include <fstream>
#include <iostream>
#include <map>
#include <sstream>
#include <string>
#include <vector>

#include "../common/auth.hpp"
#include "../common/http.hpp"
#include "../common/json.hpp"

using tpu::Json;

namespace {

double now_s() {
  struct timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  return static_cast<double>(ts.tv_sec) + ts.tv_nsec * 1e-9;
}

struct RunningTask {
  std::string task_id;
  std::string task_name;
  pid_t pid = -1;
  std::string goal;               // RUNNING | ONCE | FINISH
  pid_t readiness_pid = -1;       // readiness-check process, if any
  bool readiness_reported = false;
  // readiness is retried every readiness_interval until it passes; a probe
  // running longer than readiness_timeout is killed and retried
  // (reference ReadinessCheckSpec interval/timeout semantics)
  std::string readiness_cmd;
  std::string sandbox;
  std::map<std::string, std::string> env;
  double readiness_next_try = 0;
  double readiness_interval = 5;
  double readiness_timeout = 10;
  double readiness_deadline = 0;  // when the in-flight probe times out
  // liveness probe (reference HealthCheckSpec): after the grace period the
  // check runs every interval; max_consecutive_failures kills the task
  std::string health_cmd;
  pid_t health_pid = -1;
  double health_interval = 30;
  double health_grace_until = 0;
  double health_next_try = 0;
  double health_timeout = 20;
  double health_deadline = 0;     // in-flight probe SIGKILLed past this
  int health_max_failures = 3;
  int health_failures = 0;
  bool health_killed = false;  // TASK_FAILED already emitted by the probe
  double kill_grace = 5;       // SIGTERM->SIGKILL window for agent kills
  bool kill_requested = false;
  double sigkill_deadline = 0;    // when to escalate SIGTERM -> SIGKILL
};

struct Config {
  std::string scheduler_url = "http://127.0.0.1:8080";
  std::string agent_id;
  std::string hostname;
  std::string base_dir = "./sandboxes";
  double cpus = 0;
  long memory_mb = 0;
  long disk_mb = 0;
  long port_lo = 10000, port_hi = 20000;
  int tpu_chips = -1;  // -1: probe <tpu_probe_dir>/accel*
  // chip-level health (SURVEY.md §5): when probing is active the agent
  // re-probes every poll and reports {tpu_health: {chips}} so the
  // scheduler notices a chip falling off the bus without waiting for the
  // task to crash. The dir override is the test hook for simulating
  // hot-unplug against the real binary (point it at a tmp dir, remove an
  // accelN file mid-run).
  std::string tpu_probe_dir = "/dev";
  bool tpu_probe = false;  // set when chips were probed or dir overridden
  std::string slice_id, topology, zone, region;
  std::vector<std::string> volume_profiles;  // mount-disk profiles served
  std::vector<std::string> roles = {"*"};    // reservation role pools
  // freeform host attributes (rack=r1 ...) consumed by the attribute
  // placement rules (attribute / max-per-attribute / round-robin-attribute)
  std::vector<std::pair<std::string, std::string>> attributes;
  int worker_index = -1;
  double poll_interval_s = 1.0;
  long max_polls = -1;  // test hook: exit after N polls (-1 = forever)
};

int probe_tpu_chips(const std::string& dir = "/dev") {
  // TPU VM chips appear as /dev/accel0..N (PJRT libtpu contract)
  int count = 0;
  for (int i = 0; i < 64; ++i) {
    std::string path = dir + "/accel" + std::to_string(i);
    if (access(path.c_str(), F_OK) == 0) {
      ++count;
    }
  }
  return count;
}

std::string detect_hostname() {
  char buf[256];
  if (gethostname(buf, sizeof buf) == 0) return buf;
  return "localhost";
}

double detect_cpus() {
  long n = sysconf(_SC_NPROCESSORS_ONLN);
  return n > 0 ? static_cast<double>(n) : 1.0;
}

long detect_memory_mb() {
  long pages = sysconf(_SC_PHYS_PAGES);
  long page_size = sysconf(_SC_PAGE_SIZE);
  if (pages <= 0 || page_size <= 0) return 1024;
  return pages / 1024 * page_size / 1024;
}

// Resolve an rlimit name like "RLIMIT_NOFILE" / "NOFILE" to the resource
// constant (reference specification/RLimitSpec.java name validation).
int rlimit_by_name(std::string name) {
  if (name.rfind("RLIMIT_", 0) == 0) name = name.substr(7);
  if (name == "NOFILE") return RLIMIT_NOFILE;
  if (name == "NPROC") return RLIMIT_NPROC;
  if (name == "CORE") return RLIMIT_CORE;
  if (name == "CPU") return RLIMIT_CPU;
  if (name == "DATA") return RLIMIT_DATA;
  if (name == "FSIZE") return RLIMIT_FSIZE;
  if (name == "MEMLOCK") return RLIMIT_MEMLOCK;
  if (name == "STACK") return RLIMIT_STACK;
  if (name == "AS") return RLIMIT_AS;
  if (name == "RSS") return RLIMIT_RSS;
  return -1;
}

// -- pod security controls (reference seccomp.yml / shm.yml scenarios) ----

// The "default" seccomp profile: a denylist of host-takeover syscalls
// answered with EPERM (the task keeps running; the syscall just fails the
// way it would for an unprivileged user). Mirrors the intent of the
// reference's containerizer default profile without a container runtime.
bool install_seccomp_default() {
#if defined(__x86_64__)
  constexpr unsigned int kArch = AUDIT_ARCH_X86_64;
#elif defined(__aarch64__)
  constexpr unsigned int kArch = AUDIT_ARCH_AARCH64;
#else
  return false;  // unknown arch: refuse rather than install a wrong filter
#endif
  static const long denied[] = {
    SYS_mount, SYS_umount2, SYS_swapon, SYS_swapoff, SYS_reboot,
    SYS_init_module, SYS_finit_module, SYS_delete_module,
    SYS_pivot_root, SYS_acct, SYS_unshare, SYS_setns,
    SYS_open_by_handle_at, SYS_kexec_load,
#ifdef SYS_kexec_file_load
    SYS_kexec_file_load,
#endif
#ifdef SYS_iopl
    SYS_iopl,
#endif
#ifdef SYS_ioperm
    SYS_ioperm,
#endif
  };
  std::vector<struct sock_filter> prog;
  // non-native ABIs would bypass a nr-based denylist (i386 via int 0x80
  // reports a different arch; x32 reports the NATIVE arch with a biased
  // nr) — deny both outright instead of trying to mirror the list
  prog.push_back(BPF_STMT(BPF_LD | BPF_W | BPF_ABS,
                          offsetof(struct seccomp_data, arch)));
  prog.push_back(BPF_JUMP(BPF_JMP | BPF_JEQ | BPF_K, kArch, 1, 0));
  prog.push_back(BPF_STMT(BPF_RET | BPF_K,
                          SECCOMP_RET_ERRNO | (ENOSYS & SECCOMP_RET_DATA)));
  prog.push_back(BPF_STMT(BPF_LD | BPF_W | BPF_ABS,
                          offsetof(struct seccomp_data, nr)));
#if defined(__x86_64__)
  // x32 ABI: nr has bit 30 set while arch is AUDIT_ARCH_X86_64
  prog.push_back(BPF_JUMP(BPF_JMP | BPF_JGE | BPF_K, 0x40000000u, 0, 1));
  prog.push_back(BPF_STMT(BPF_RET | BPF_K,
                          SECCOMP_RET_ERRNO | (ENOSYS & SECCOMP_RET_DATA)));
#endif
  for (long nr : denied) {
    prog.push_back(BPF_JUMP(BPF_JMP | BPF_JEQ | BPF_K,
                            static_cast<unsigned int>(nr), 0, 1));
    prog.push_back(BPF_STMT(BPF_RET | BPF_K,
                            SECCOMP_RET_ERRNO | (EPERM & SECCOMP_RET_DATA)));
  }
  prog.push_back(BPF_STMT(BPF_RET | BPF_K, SECCOMP_RET_ALLOW));
  struct sock_fprog fprog;
  fprog.len = static_cast<unsigned short>(prog.size());
  fprog.filter = prog.data();
  if (prctl(PR_SET_NO_NEW_PRIVS, 1, 0, 0, 0) != 0) return false;
  if (syscall(SYS_seccomp, SECCOMP_SET_MODE_FILTER, 0, &fprog) == 0) {
    return true;
  }
  // older kernels: the prctl spelling of the same operation
  return prctl(PR_SET_SECCOMP, SECCOMP_MODE_FILTER, &fprog) == 0;
}

// ipc-mode PRIVATE: own IPC namespace + private /dev/shm sized shm_mb
// (reference shm.yml `ipc-mode: PRIVATE` + `shm-size:`). Runs in the
// child BEFORE the seccomp filter (which denies unshare/mount).
bool enter_private_ipc(long shm_mb, std::string& err) {
  if (unshare(CLONE_NEWIPC | CLONE_NEWNS) != 0) {
    err = std::string("unshare(ipc|mnt): ") + strerror(errno);
    return false;
  }
  // keep our mounts from leaking back to the host namespace
  if (mount(nullptr, "/", nullptr, MS_REC | MS_PRIVATE, nullptr) != 0) {
    err = std::string("mount --make-rprivate /: ") + strerror(errno);
    return false;
  }
  // PRIVATE always gets a private /dev/shm — without the mount, POSIX
  // shm (shm_open) would still land in the host's shared tmpfs and only
  // SysV IPC would be isolated. 64 MB default when no size was declared.
  long size = shm_mb > 0 ? shm_mb : 64;
  std::string opts = "mode=1777,size=" + std::to_string(size) + "m";
  if (mount("tpu-shm", "/dev/shm", "tmpfs", MS_NOSUID | MS_NODEV,
            opts.c_str()) != 0) {
    err = std::string("mount tmpfs /dev/shm: ") + strerror(errno);
    return false;
  }
  return true;
}

bool mkdirs(const std::string& path) {
  std::string partial;
  for (size_t i = 0; i < path.size(); ++i) {
    partial += path[i];
    if (path[i] == '/' || i + 1 == path.size()) {
      if (partial == "/" || partial.empty()) continue;
      if (mkdir(partial.c_str(), 0755) != 0 && errno != EEXIST) return false;
    }
  }
  return true;
}

class Agent {
 public:
  explicit Agent(Config cfg) : cfg_(std::move(cfg)) {}

  int run() {
    if (!register_with_retry()) return 1;
    long polls = 0;
    while (cfg_.max_polls < 0 || polls < cfg_.max_polls) {
      ++polls;
      reap_children();
      escalate_kills();
      retry_readiness();
      run_health_checks();
      if (!poll_once()) {
        // scheduler asked us to re-register (restarted / expired us)
        if (!register_with_retry()) return 1;
      }
      usleep(static_cast<useconds_t>(cfg_.poll_interval_s * 1e6));
    }
    return 0;
  }

 private:
  Config cfg_;
  tpu::AuthSession auth_{cfg_.scheduler_url};
  // per-agent session identity from the register reply: polls MUST carry
  // it (the scheduler rejects fleet-credential polls, so one host's
  // leaked credential cannot drain another agent's command queue)
  std::string session_token_;
  std::map<std::string, RunningTask> tasks_;  // task_id -> state
  std::vector<Json> pending_statuses_;

  // POST with the control-plane credential; one re-login retry on 401
  // (token expiry mid-run), mirroring CachedTokenProvider semantics.
  tpu::HttpResponse authed_post(const std::string& url,
                                const std::string& body) {
    auto resp = tpu::http_post(url, body, 30, auth_.token());
    if (resp.status == 401 && auth_.can_relogin()) {
      auth_.invalidate();
      resp = tpu::http_post(url, body, 30, auth_.token());
    }
    return resp;
  }

  // -- registration -----------------------------------------------------

  Json inventory() const {
    Json tpu = Json::object();
    tpu.set("chips", cfg_.tpu_chips);
    if (!cfg_.slice_id.empty()) tpu.set("slice_id", cfg_.slice_id);
    if (!cfg_.topology.empty()) tpu.set("topology", cfg_.topology);
    if (cfg_.worker_index >= 0) tpu.set("worker_index", cfg_.worker_index);
    Json ports = Json::array();
    Json range = Json::array();
    range.push_back(static_cast<double>(cfg_.port_lo));
    range.push_back(static_cast<double>(cfg_.port_hi));
    ports.push_back(range);
    Json body = Json::object();
    body.set("agent_id", cfg_.agent_id)
        .set("hostname", cfg_.hostname)
        .set("cpus", cfg_.cpus)
        .set("memory_mb", static_cast<double>(cfg_.memory_mb))
        .set("disk_mb", static_cast<double>(cfg_.disk_mb))
        .set("ports", ports)
        .set("tpu", tpu);
    if (!cfg_.zone.empty()) body.set("zone", cfg_.zone);
    if (!cfg_.region.empty()) body.set("region", cfg_.region);
    if (!cfg_.attributes.empty()) {
      Json attrs = Json::object();
      for (const auto& kv : cfg_.attributes) attrs.set(kv.first, kv.second);
      body.set("attributes", attrs);
    }
    if (!cfg_.volume_profiles.empty()) {
      Json profiles = Json::array();
      for (const auto& p : cfg_.volume_profiles) profiles.push_back(p);
      body.set("volume_profiles", profiles);
    }
    Json roles = Json::array();
    for (const auto& r : cfg_.roles) roles.push_back(r);
    body.set("roles", roles);
    return body;
  }

  bool register_with_retry() {
    std::string url = cfg_.scheduler_url + "/v1/agents/register";
    for (int attempt = 0; attempt < 120; ++attempt) {
      try {
        auto resp = authed_post(url, inventory().dump());
        if (resp.status == 200) {
          Json reply = Json::parse(resp.body);
          if (reply.get("ok").as_bool()) {
            session_token_ = reply.get("session_token").as_string();
            std::cerr << "[tpu-agent] registered " << cfg_.agent_id
                      << " with " << cfg_.scheduler_url << "\n";
            return true;
          }
        }
        std::cerr << "[tpu-agent] register rejected: " << resp.status
                  << " " << resp.body << "\n";
      } catch (const std::exception& e) {
        std::cerr << "[tpu-agent] register retry: " << e.what() << "\n";
      }
      sleep(1);
    }
    return false;
  }

  // -- poll cycle --------------------------------------------------------

  bool poll_once() {
    Json running = Json::array();
    for (const auto& [task_id, t] : tasks_) {
      if (t.pid > 0) running.push_back(task_id);
    }
    Json statuses = Json::array();
    for (auto& s : pending_statuses_) statuses.push_back(s);
    Json body = Json::object();
    body.set("running_task_ids", running).set("statuses", statuses);
    if (cfg_.tpu_probe) {
      // re-probe every poll (a handful of access() calls at 1 Hz): the
      // scheduler compares against registered inventory and degrades the
      // host on chip loss (agent/remote.py poll handler)
      Json th = Json::object();
      if (access(cfg_.tpu_probe_dir.c_str(), F_OK) != 0) {
        th.set("chips", 0.0);
        th.set("error", "probe dir missing: " + cfg_.tpu_probe_dir);
      } else {
        th.set("chips",
               static_cast<double>(probe_tpu_chips(cfg_.tpu_probe_dir)));
      }
      body.set("tpu_health", th);
    }

    std::string url =
        cfg_.scheduler_url + "/v1/agents/" + cfg_.agent_id + "/poll";
    Json reply;
    try {
      // polls carry the per-agent session token when the scheduler
      // issued one; plain auth otherwise (open schedulers)
      auto resp = tpu::http_post(
          url, body.dump(), 30,
          session_token_.empty() ? auth_.token() : session_token_);
      if (resp.status == 401 || resp.status == 403) {
        // expired/rejected session: re-register for a fresh one
        std::cerr << "[tpu-agent] poll auth " << resp.status
                  << "; re-registering\n";
        return false;
      }
      if (resp.status != 200) {
        std::cerr << "[tpu-agent] poll HTTP " << resp.status << "\n";
        return true;  // transient; keep statuses queued
      }
      reply = Json::parse(resp.body);
    } catch (const std::exception& e) {
      std::cerr << "[tpu-agent] poll failed: " << e.what() << "\n";
      return true;  // keep statuses for next successful poll
    }
    if (!reply.get("ok").as_bool() &&
        reply.get("reregister").as_bool()) {
      // scheduler restarted/expired us: keep queued statuses so terminal
      // updates are re-delivered after re-registration
      return false;
    }
    pending_statuses_.clear();
    for (const auto& cmd : reply.get("commands").items()) {
      const std::string type = cmd.get("type").as_string();
      if (type == "launch") {
        for (const auto& task : cmd.get("tasks").items()) launch(task);
      } else if (type == "destroy_volumes") {
        destroy_volumes(cmd.get("pod_instance").as_string());
      } else if (type == "kill") {
        kill_task(cmd.get("task_id").as_string(),
                  cmd.get("grace_period_s").as_number(0));
      }
    }
    return true;
  }

  // -- task lifecycle ----------------------------------------------------

  void emit(const std::string& task_id, const std::string& task_name,
            const std::string& state, const std::string& message,
            bool readiness = false) {
    Json s = Json::object();
    s.set("task_id", task_id)
        .set("task_name", task_name)
        .set("state", state)
        .set("message", message)
        .set("timestamp", now_s());
    if (readiness) s.set("readiness_passed", true);
    pending_statuses_.push_back(std::move(s));
  }

  static std::string b64_decode(const std::string& in) {
    static const std::string chars =
        "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789+/";
    std::string out;
    int val = 0, bits = -8;
    for (unsigned char c : in) {
      if (c == '=' || c == '\n' || c == '\r') continue;
      size_t pos = chars.find(c);
      if (pos == std::string::npos) continue;
      val = (val << 6) + static_cast<int>(pos);
      bits += 6;
      if (bits >= 0) {
        out.push_back(static_cast<char>((val >> bits) & 0xFF));
        bits -= 8;
      }
    }
    return out;
  }

  // Write one raw sandbox file (TLS artifacts / secret files): verbatim
  // bytes, never mustache-rendered (unlike config templates), parent dirs
  // created, secrets kept 0600.
  static bool write_raw_file(const std::string& dest_rel,
                             const std::string& content,
                             const std::string& sandbox, std::string& err) {
    if (dest_rel.empty() || dest_rel[0] == '/' ||
        dest_rel.find("..") != std::string::npos) {
      err = "file dest must be sandbox-relative: " + dest_rel;
      return false;
    }
    std::string dest = sandbox + "/" + dest_rel;
    for (size_t pos = dest.find('/', sandbox.size() + 1);
         pos != std::string::npos; pos = dest.find('/', pos + 1)) {
      ::mkdir(dest.substr(0, pos).c_str(), 0755);
    }
    // create 0600 BEFORE any secret byte lands — an ofstream would open
    // umask-wide (0644) and chmod after the plaintext is already readable
    int fd = ::open(dest.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0600);
    if (fd < 0) { err = "cannot write " + dest; return false; }
    size_t off = 0;
    while (off < content.size()) {
      ssize_t n = ::write(fd, content.data() + off, content.size() - off);
      if (n <= 0) {
        ::close(fd);
        ::unlink(dest.c_str());
        err = "short write to " + dest;
        return false;
      }
      off += static_cast<size_t>(n);
    }
    if (::close(fd) != 0) { err = "close failed: " + dest; return false; }
    return true;
  }

  // Delete a pod instance's persistent volumes (reference: Mesos DESTROY
  // of persistent volumes on pod replace / uninstall).
  void destroy_volumes(const std::string& pod_instance) {
    if (pod_instance.empty() || pod_instance == "." ||
        pod_instance.find('/') != std::string::npos ||
        pod_instance.find("..") != std::string::npos) {
      return;  // refuse anything that could escape or widen the target
    }
    std::string root = cfg_.base_dir + "/volumes/" + pod_instance;
    rm_rf(root);
  }

  static void rm_rf(const std::string& path) {
    DIR* dir = ::opendir(path.c_str());
    if (dir != nullptr) {
      while (struct dirent* e = ::readdir(dir)) {
        std::string name = e->d_name;
        if (name == "." || name == "..") continue;
        std::string child = path + "/" + name;
        struct stat st;
        if (::lstat(child.c_str(), &st) == 0 && S_ISDIR(st.st_mode)) {
          rm_rf(child);
        } else {
          ::unlink(child.c_str());
        }
      }
      ::closedir(dir);
    }
    ::rmdir(path.c_str());
  }

  // Fetch one task URI into the sandbox (reference: the Mesos fetcher,
  // which is how sdk/bootstrap and config artifacts reach a task's
  // sandbox). file:// and bare paths are copied; http(s):// downloaded.
  // Fetched files are marked executable, matching how the reference's
  // resource.json assets (bootstrap, CLI) are fetched.
  static bool fetch_uri(const std::string& uri, const std::string& sandbox,
                        std::string& err) {
    std::string src, data;
    // basename excludes any query/fragment (?sig=... on signed URLs), like
    // the Mesos fetcher's path-component basename
    std::string path_part = uri.substr(0, uri.find_first_of("?#"));
    std::string base = path_part.substr(path_part.find_last_of('/') + 1);
    if (base.empty()) { err = "uri has no basename: " + uri; return false; }
    std::string dst = sandbox + "/" + base;
    if (uri.rfind("http://", 0) == 0 || uri.rfind("https://", 0) == 0) {
      if (uri.rfind("https://", 0) == 0) {
        err = "https fetch unsupported by tpu-agent (serve artifacts over "
              "the scheduler's plain-http ArtifactResource): " + uri;
        return false;
      }
      auto resp = tpu::http_get(uri, 60);
      if (resp.status != 200) {
        err = "GET " + uri + " -> " + std::to_string(resp.status);
        return false;
      }
      data = resp.body;
    } else {
      src = uri.rfind("file://", 0) == 0 ? uri.substr(7) : uri;
      std::ifstream in(src, std::ios::binary);
      if (!in) { err = "cannot read " + src; return false; }
      std::ostringstream ss;
      ss << in.rdbuf();
      data = ss.str();
    }
    std::ofstream out(dst, std::ios::binary | std::ios::trunc);
    if (!out) { err = "cannot write " + dst; return false; }
    out << data;
    out.close();
    if (!out) {  // short write (disk full/quota): don't launch against a
                 // truncated artifact
      ::unlink(dst.c_str());
      err = "short write to " + dst;
      return false;
    }
    ::chmod(dst.c_str(), 0755);
    return true;
  }

  void launch(const Json& task) {
    const std::string task_id = task.get("task_id").as_string();
    const std::string task_name = task.get("task_name").as_string();
    const std::string cmd = task.get("cmd").as_string();
    std::string sandbox = cfg_.base_dir + "/" + task_id;
    if (!mkdirs(sandbox)) {
      emit(task_id, task_name, "TASK_FAILED",
           "cannot create sandbox " + sandbox);
      return;
    }

    // persistent pod-instance volumes (reference: Mesos persistent volumes
    // + the shared executor sandbox): <base>/volumes/<pod-instance>/<path>
    // survives task relaunch and is symlinked into every sibling task's
    // sandbox, so cassandra-style sidecars see the server's data
    const std::string pod_instance = task.get("pod_instance").as_string();
    for (const auto& vol : task.get("volumes").items()) {
      const std::string rel = vol.as_string();
      if (rel.empty() || rel[0] == '/' ||
          rel.find("..") != std::string::npos || pod_instance.empty()) {
        emit(task_id, task_name, "TASK_FAILED",
             "volume path must be sandbox-relative: " + rel);
        return;
      }
      std::string store = cfg_.base_dir + "/volumes/" + pod_instance +
                          "/" + rel;
      mkdirs(store);
      // symlink target must be absolute: base_dir is often relative and
      // the link is resolved from inside the sandbox cwd
      char resolved[PATH_MAX];
      if (::realpath(store.c_str(), resolved) != nullptr) {
        store = resolved;
      }
      std::string link = sandbox + "/" + rel;
      size_t parent_end = link.rfind('/');
      if (parent_end != std::string::npos) {
        mkdirs(link.substr(0, parent_end));
      }
      if (::symlink(store.c_str(), link.c_str()) != 0 && errno != EEXIST) {
        emit(task_id, task_name, "TASK_FAILED",
             "cannot link volume " + rel + " -> " + store);
        return;
      }
    }

    // host volumes (reference host-volume.yml): an absolute host directory
    // appears at a sandbox-relative path via symlink
    for (const auto& hv : task.get("host_volumes").items()) {
      const auto& pair = hv.items();
      if (pair.size() != 2) continue;
      const std::string host_path = pair[0].as_string();
      const std::string rel = pair[1].as_string();
      if (host_path.empty() || host_path[0] != '/' || rel.empty() ||
          rel[0] == '/' || rel.find("..") != std::string::npos) {
        emit(task_id, task_name, "TASK_FAILED",
             "bad host volume " + host_path + " -> " + rel);
        return;
      }
      std::string link = sandbox + "/" + rel;
      size_t parent_end = link.rfind('/');
      if (parent_end != std::string::npos) {
        mkdirs(link.substr(0, parent_end));
      }
      if (::symlink(host_path.c_str(), link.c_str()) != 0 &&
          errno != EEXIST) {
        emit(task_id, task_name, "TASK_FAILED",
             "cannot link host volume " + rel + " -> " + host_path);
        return;
      }
    }

    for (const auto& uri : task.get("uris").items()) {
      std::string err;
      if (!fetch_uri(uri.as_string(), sandbox, err)) {
        emit(task_id, task_name, "TASK_FAILED", "uri fetch: " + err);
        return;
      }
    }

    for (const auto& file : task.get("files").items()) {
      std::string err;
      if (!write_raw_file(file.get("dest").as_string(),
                          b64_decode(file.get("content_b64").as_string()),
                          sandbox, err)) {
        emit(task_id, task_name, "TASK_FAILED", "file write: " + err);
        return;
      }
    }

    // write config templates for tpu-bootstrap to render (reference:
    // CONFIG_TEMPLATE_* env + ArtifactResource downloads)
    std::vector<std::pair<std::string, std::string>> template_env;
    int tmpl_idx = 0;
    for (const auto& tmpl : task.get("config_templates").items()) {
      std::string name = tmpl.get("name").as_string();
      std::string src = sandbox + "/.tpu-templates/" + name;
      mkdirs(sandbox + "/.tpu-templates");
      std::ofstream f(src);
      f << tmpl.get("template").as_string();
      f.close();
      template_env.emplace_back(
          "CONFIG_TEMPLATE_" + std::to_string(tmpl_idx++),
          src + "," + tmpl.get("dest").as_string());
    }

    // POSIX limits for the task process (reference RLimitSpec): parsed
    // before fork so a bad name fails the launch, applied in the child
    struct RLimitReq { int resource; rlim_t soft; rlim_t hard; };
    std::vector<RLimitReq> rlimits;
    for (const auto& rl : task.get("rlimits").items()) {
      int resource = rlimit_by_name(rl.get("name").as_string());
      if (resource < 0) {
        emit(task_id, task_name, "TASK_FAILED",
             "unknown rlimit " + rl.get("name").as_string());
        return;
      }
      RLimitReq req;
      req.resource = resource;
      req.soft = rl.get("soft").is_null()
                     ? RLIM_INFINITY
                     : static_cast<rlim_t>(rl.get("soft").as_number());
      req.hard = rl.get("hard").is_null()
                     ? RLIM_INFINITY
                     : static_cast<rlim_t>(rl.get("hard").as_number());
      rlimits.push_back(req);
    }

    // pod security controls, validated before fork so a bad value fails
    // the launch with a readable status instead of an exit code
    const std::string ipc_mode = task.get("ipc_mode").as_string();
    const long shm_mb =
        static_cast<long>(task.get("shm_size_mb").as_number(0));
    if (!ipc_mode.empty() && ipc_mode != "PRIVATE"
        && ipc_mode != "SHARE_PARENT") {
      emit(task_id, task_name, "TASK_FAILED",
           "unknown ipc_mode " + ipc_mode);
      return;
    }
    const bool seccomp_unconfined =
        task.get("seccomp_unconfined").as_bool();
    const std::string seccomp_profile =
        task.get("seccomp_profile").as_string();
    if (!seccomp_unconfined && !seccomp_profile.empty()
        && seccomp_profile != "default") {
      emit(task_id, task_name, "TASK_FAILED",
           "unknown seccomp profile " + seccomp_profile);
      return;
    }

    pid_t pid = fork();
    if (pid < 0) {
      emit(task_id, task_name, "TASK_FAILED", "fork failed");
      return;
    }
    if (pid == 0) {
      // child: own process group so kill() reaps the whole task tree
      setpgid(0, 0);
      if (chdir(sandbox.c_str()) != 0) _exit(126);
      // task env (launch env wins over inherited env)
      for (const auto& [k, v] : task.get("env").fields()) {
        setenv(k.c_str(), v.as_string().c_str(), 1);
      }
      for (const auto& [k, v] : template_env) {
        setenv(k.c_str(), v.c_str(), 1);
      }
      setenv("TPU_SANDBOX", sandbox.c_str(), 1);
      int out = open("stdout.log", O_WRONLY | O_CREAT | O_APPEND, 0644);
      int err = open("stderr.log", O_WRONLY | O_CREAT | O_APPEND, 0644);
      if (out >= 0) dup2(out, 1);
      if (err >= 0) dup2(err, 2);
      // rlimits after dup2 so failures land in stderr.log. Raising a hard
      // limit past the inherited one needs CAP_SYS_RESOURCE; "unlimited"
      // (RLIM_INFINITY) therefore falls back to the agent's current hard
      // limit instead of killing the task with an opaque EPERM.
      for (const auto& rl : rlimits) {
        struct rlimit lim;
        lim.rlim_cur = rl.soft;
        lim.rlim_max = rl.hard;
        if (setrlimit(rl.resource, &lim) != 0) {
          struct rlimit cur;
          if (getrlimit(rl.resource, &cur) == 0) {
            if (lim.rlim_max == RLIM_INFINITY || lim.rlim_max > cur.rlim_max)
              lim.rlim_max = cur.rlim_max;
            if (lim.rlim_cur == RLIM_INFINITY || lim.rlim_cur > lim.rlim_max)
              lim.rlim_cur = lim.rlim_max;
            fprintf(stderr,
                    "[tpu-agent] clamping rlimit %d to hard=%llu\n",
                    rl.resource,
                    static_cast<unsigned long long>(lim.rlim_max));
          }
          if (setrlimit(rl.resource, &lim) != 0) {
            fprintf(stderr, "[tpu-agent] setrlimit(%d) failed: %s\n",
                    rl.resource, strerror(errno));
            _exit(125);
          }
        }
      }
      // ipc/shm isolation first (needs unshare+mount), seccomp LAST so
      // the filter cannot block our own setup
      if (ipc_mode == "PRIVATE") {
        std::string ipc_err;
        if (!enter_private_ipc(shm_mb, ipc_err)) {
          fprintf(stderr, "[tpu-agent] private ipc/shm: %s\n",
                  ipc_err.c_str());
          _exit(124);
        }
      }
      if (!seccomp_unconfined && !seccomp_profile.empty()) {
        if (!install_seccomp_default()) {
          fprintf(stderr, "[tpu-agent] seccomp install failed: %s\n",
                  strerror(errno));
          _exit(123);
        }
      }
      execl("/bin/sh", "sh", "-c", cmd.c_str(), (char*)nullptr);
      _exit(127);
    }
    setpgid(pid, pid);  // also from parent (avoid the exec race)
    {
      std::ofstream pf(sandbox + "/task.pid");
      pf << pid << "\n";
    }

    RunningTask rt;
    rt.task_id = task_id;
    rt.task_name = task_name;
    rt.pid = pid;
    rt.goal = task.get("goal").as_string();
    rt.sandbox = sandbox;
    rt.readiness_cmd = task.get("readiness_check_cmd").as_string();
    rt.readiness_interval = task.get("readiness_interval_s").as_number(5);
    rt.readiness_timeout = task.get("readiness_timeout_s").as_number(10);
    rt.health_cmd = task.get("health_check_cmd").as_string();
    rt.health_interval = task.get("health_interval_s").as_number(30);
    rt.health_timeout = task.get("health_timeout_s").as_number(20);
    rt.health_grace_until =
        now_s() + task.get("health_grace_s").as_number(60);
    rt.health_next_try = rt.health_grace_until +
                         task.get("health_delay_s").as_number(0);
    rt.health_max_failures =
        static_cast<int>(task.get("health_max_failures").as_number(3));
    rt.kill_grace = task.get("kill_grace_s").as_number(5);
    for (const auto& [k, v] : task.get("env").fields()) {
      rt.env[k] = v.as_string();
    }
    rt.readiness_reported = rt.readiness_cmd.empty();
    tasks_[task_id] = rt;
    emit(task_id, task_name, "TASK_RUNNING", "started pid " +
                                                 std::to_string(pid));
    spawn_readiness(tasks_[task_id]);
  }

  void spawn_readiness(RunningTask& t) {
    if (t.readiness_reported || t.readiness_cmd.empty() ||
        t.readiness_pid > 0 || t.kill_requested) {
      return;
    }
    pid_t rp = fork();
    if (rp == 0) {
      setpgid(0, 0);
      if (chdir(t.sandbox.c_str()) != 0) _exit(126);
      for (const auto& [k, v] : t.env) {
        setenv(k.c_str(), v.c_str(), 1);
      }
      execl("/bin/sh", "sh", "-c", t.readiness_cmd.c_str(), (char*)nullptr);
      _exit(127);
    }
    t.readiness_pid = rp;
    t.readiness_deadline = now_s() + t.readiness_timeout;
  }

  // retry readiness probes that failed, and kill probes that hang past
  // their timeout (reference ReadinessCheckSpec interval/timeout: the
  // check repeats until it first passes)
  void retry_readiness() {
    double now = now_s();
    for (auto& [task_id, t] : tasks_) {
      if (t.readiness_reported) continue;
      if (t.readiness_pid > 0 && now >= t.readiness_deadline) {
        ::kill(-t.readiness_pid, SIGKILL);  // reap marks the retry time
      } else if (t.readiness_pid < 0 && now >= t.readiness_next_try) {
        spawn_readiness(t);
      }
    }
  }

  static pid_t spawn_probe(const RunningTask& t, const std::string& cmd) {
    pid_t p = fork();
    if (p == 0) {
      setpgid(0, 0);
      if (chdir(t.sandbox.c_str()) != 0) _exit(126);
      for (const auto& [k, v] : t.env) setenv(k.c_str(), v.c_str(), 1);
      execl("/bin/sh", "sh", "-c", cmd.c_str(), (char*)nullptr);
      _exit(127);
    }
    return p;
  }

  // liveness probes (reference HealthCheckSpec): run every interval after
  // the grace period; max consecutive failures -> kill + TASK_FAILED so
  // the scheduler's recovery plan relaunches the pod
  void run_health_checks() {
    double now = now_s();
    for (auto& [task_id, t] : tasks_) {
      if (t.health_cmd.empty() || t.pid <= 0 || t.kill_requested) continue;
      if (t.health_pid < 0 && now >= t.health_next_try) {
        t.health_pid = spawn_probe(t, t.health_cmd);
        t.health_deadline = now + t.health_timeout;
        t.health_next_try = now + t.health_interval;
      } else if (t.health_pid > 0 && now >= t.health_deadline) {
        // a probe hung past its timeout counts as a failure now, not at
        // the next interval boundary (reference HealthCheckSpec timeout)
        ::kill(-t.health_pid, SIGKILL);
      }
    }
  }

  void on_health_result(RunningTask& t, bool passed) {
    if (passed) {
      t.health_failures = 0;
      return;
    }
    ++t.health_failures;
    if (t.health_failures >= t.health_max_failures && !t.kill_requested) {
      std::cerr << "[tpu-agent] health check failed x"
                << t.health_failures << " for " << t.task_name
                << "; killing\n";
      emit(t.task_id, t.task_name, "TASK_FAILED",
           "health check failed " + std::to_string(t.health_failures) +
               " times");
      t.kill_requested = true;
      t.health_killed = true;
      ::kill(-t.pid, SIGTERM);
      // honor the task's configured shutdown window (kill-grace-period),
      // same as scheduler-initiated kills
      t.sigkill_deadline = now_s() + t.kill_grace;
    }
  }

  void kill_task(const std::string& task_id, double grace_s) {
    auto it = tasks_.find(task_id);
    if (it == tasks_.end() || it->second.pid <= 0) {
      return;  // already gone; reconciliation handles the rest
    }
    RunningTask& t = it->second;
    t.kill_requested = true;
    ::kill(-t.pid, SIGTERM);
    if (t.readiness_pid > 0) {
      ::kill(-t.readiness_pid, SIGKILL);  // its target task is going away
    }
    t.sigkill_deadline = now_s() + grace_s;
  }

  void escalate_kills() {
    double now = now_s();
    for (auto& [task_id, t] : tasks_) {
      if (t.kill_requested && t.pid > 0 && now >= t.sigkill_deadline) {
        ::kill(-t.pid, SIGKILL);
      }
    }
  }

  void reap_children() {
    while (true) {
      int status = 0;
      pid_t pid = waitpid(-1, &status, WNOHANG);
      if (pid <= 0) break;
      for (auto it = tasks_.begin(); it != tasks_.end(); ++it) {
        RunningTask& t = it->second;
        if (t.readiness_pid == pid) {
          t.readiness_pid = -1;
          if (WIFEXITED(status) && WEXITSTATUS(status) == 0 &&
              !t.readiness_reported) {
            t.readiness_reported = true;
            emit(t.task_id, t.task_name, "TASK_RUNNING", "readiness passed",
                 /*readiness=*/true);
          } else if (!t.readiness_reported) {
            t.readiness_next_try = now_s() + t.readiness_interval;
          }
          break;
        }
        if (t.health_pid == pid) {
          t.health_pid = -1;
          on_health_result(t, WIFEXITED(status) && WEXITSTATUS(status) == 0);
          break;
        }
        if (t.pid == pid) {
          int code = WIFEXITED(status) ? WEXITSTATUS(status) : -1;
          if (t.health_killed) {
            // TASK_FAILED already emitted when the probe gave up; just
            // clean up the checker processes and the record
            if (t.readiness_pid > 0) ::kill(-t.readiness_pid, SIGKILL);
            if (t.health_pid > 0) ::kill(-t.health_pid, SIGKILL);
            tasks_.erase(it);
            break;
          }
          std::string state;
          std::string msg;
          if (t.kill_requested) {
            state = "TASK_KILLED";
            msg = "killed by scheduler";
          } else if (code == 0) {
            state = "TASK_FINISHED";
            msg = "exit 0";
          } else {
            state = "TASK_FAILED";
            msg = WIFSIGNALED(status)
                      ? ("signal " + std::to_string(WTERMSIG(status)))
                      : ("exit " + std::to_string(code));
          }
          emit(t.task_id, t.task_name, state, msg);
          if (t.readiness_pid > 0) {
            ::kill(-t.readiness_pid, SIGKILL);  // don't leak the checkers
          }
          if (t.health_pid > 0) {
            ::kill(-t.health_pid, SIGKILL);
          }
          t.pid = -1;
          tasks_.erase(it);
          break;
        }
      }
    }
  }
};

void usage(const char* argv0) {
  std::cerr
      << "usage: " << argv0 << " --scheduler URL [options]\n"
      << "  --scheduler URL     scheduler base url (http://host:port)\n"
      << "  --agent-id ID       unique agent id (default: hostname)\n"
      << "  --base-dir DIR      sandbox root (default ./sandboxes)\n"
      << "  --cpus N --memory-mb N --disk-mb N   advertised resources\n"
      << "  --ports LO-HI       advertised port range\n"
      << "  --tpu-chips N       TPU chips (default: probe /dev/accel*)\n"
      << "  --tpu-probe-dir D   probe D/accel* instead of /dev/accel* and\n"
         "                      re-probe every poll (chip-health test hook)\n"
      << "  --slice-id S --topology T --worker-index N   ICI identity\n"
      << "  --zone Z --region R\n"
      << "  --attribute K=V     freeform host attribute (repeatable; "
         "placement rules)\n"
      << "  --volume-profiles P1,P2   mount-disk profiles served\n"
      << "  --roles R1,R2       reservation role pools (default '*')\n"
      << "  --poll-interval S   seconds between polls (default 1)\n"
      << "  --max-polls N       exit after N polls (testing)\n";
}

}  // namespace

int main(int argc, char** argv) {
  Config cfg;
  cfg.hostname = detect_hostname();
  cfg.cpus = detect_cpus();
  cfg.memory_mb = detect_memory_mb();
  cfg.disk_mb = 10240;

  for (int i = 1; i < argc; ++i) {
    std::string a = argv[i];
    auto next = [&]() -> std::string {
      if (i + 1 >= argc) {
        usage(argv[0]);
        exit(2);
      }
      return argv[++i];
    };
    if (a == "--scheduler") cfg.scheduler_url = next();
    else if (a == "--agent-id") cfg.agent_id = next();
    else if (a == "--hostname") cfg.hostname = next();
    else if (a == "--base-dir") cfg.base_dir = next();
    else if (a == "--cpus") cfg.cpus = std::stod(next());
    else if (a == "--memory-mb") cfg.memory_mb = std::stol(next());
    else if (a == "--disk-mb") cfg.disk_mb = std::stol(next());
    else if (a == "--ports") {
      std::string v = next();
      size_t dash = v.find('-');
      if (dash == std::string::npos) {
        usage(argv[0]);
        return 2;
      }
      cfg.port_lo = std::stol(v.substr(0, dash));
      cfg.port_hi = std::stol(v.substr(dash + 1));
    } else if (a == "--tpu-chips") cfg.tpu_chips = std::stoi(next());
    else if (a == "--tpu-probe-dir") {
      cfg.tpu_probe_dir = next();
      cfg.tpu_probe = true;
    }
    else if (a == "--slice-id") cfg.slice_id = next();
    else if (a == "--topology") cfg.topology = next();
    else if (a == "--worker-index") cfg.worker_index = std::stoi(next());
    else if (a == "--zone") cfg.zone = next();
    else if (a == "--region") cfg.region = next();
    else if (a == "--attribute") {
      std::string kv = next();
      size_t eq = kv.find('=');
      if (eq == std::string::npos || eq == 0) {
        std::cerr << "--attribute needs KEY=VALUE, got: " << kv << "\n";
        return 2;
      }
      cfg.attributes.emplace_back(kv.substr(0, eq), kv.substr(eq + 1));
    }
    else if (a == "--volume-profiles") {
      cfg.volume_profiles.clear();
      std::istringstream ss(next());
      std::string item;
      while (std::getline(ss, item, ',')) {
        if (!item.empty()) cfg.volume_profiles.push_back(item);
      }
    } else if (a == "--roles") {
      cfg.roles.clear();
      std::istringstream ss(next());
      std::string item;
      while (std::getline(ss, item, ',')) {
        if (!item.empty()) cfg.roles.push_back(item);
      }
      if (cfg.roles.empty()) cfg.roles.push_back("*");
    }
    else if (a == "--poll-interval") cfg.poll_interval_s = std::stod(next());
    else if (a == "--max-polls") cfg.max_polls = std::stol(next());
    else {
      usage(argv[0]);
      return 2;
    }
  }
  if (cfg.agent_id.empty()) cfg.agent_id = cfg.hostname;
  if (cfg.tpu_chips < 0) {
    cfg.tpu_chips = probe_tpu_chips(cfg.tpu_probe_dir);
    // probed inventory stays live: re-probe + report health every poll.
    // An explicit --tpu-chips N without a probe dir stays static (dev
    // boxes advertise synthetic chips with no /dev/accel* to probe).
    cfg.tpu_probe = true;
  }
  mkdirs(cfg.base_dir);

  signal(SIGPIPE, SIG_IGN);
  return Agent(std::move(cfg)).run();
}
