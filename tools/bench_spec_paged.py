"""Round-17 receipts: speculative decoding on the PAGED engine with a
TRAINED draft — the measurement ``tools/bench_speculative.py`` (Round 5,
monolithic cache, checkpoint-free drafts) could not make.

Three arms over the same request set, one JSON line each:

* ``solo`` — the paged engine undrafted: the tok/s baseline and the
  token streams every other arm must reproduce exactly.
* ``spec_untrained`` — the target's first ``--draft-layers`` layers,
  untrained: the acceptance floor layer-skip gives you for free.
* ``spec_trained`` — the same architecture after ``--steps`` of
  distillation against the frozen target through the fused linear-KL
  head (the real ``distill`` workload via ``worker.run_distill``, so
  the artifact seam — save_draft/load_draft — is on the measured path).
  A ``distill`` line carries the loss trajectory.

Every spec line carries ``parity_ok``: the drained streams compared
token-for-token against solo greedy — the gate that makes the tok/s
numbers mean anything. On this CPU image the absolute tok/s are not
TPU-representative (``backend`` says so); the accept-rate lift
(trained vs untrained) and the parity gate are the portable results.

Usage::

    python -m tools.bench_spec_paged [--steps 48] [--k 4]
        [--draft-layers 1] [--max-new 12] [--requests 6]
"""

from __future__ import annotations

import argparse
import contextlib
import json
import sys
import tempfile
import time


def _emit(rec):
    print(json.dumps(rec), flush=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--steps", type=int, default=48,
                    help="distillation steps for the trained arm")
    ap.add_argument("--k", type=int, default=4)
    ap.add_argument("--draft-layers", type=int, default=1)
    ap.add_argument("--max-new", type=int, default=12)
    ap.add_argument("--requests", type=int, default=6)
    ap.add_argument("--seed", type=int, default=110,
                    help="prompt seed base (110: verified tie-free for "
                         "the tiny preset — an exact bf16 argmax tie is "
                         "legally broken differently by the K-wide "
                         "verify reduction and would fail parity for a "
                         "reason that is not a bug)")
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp

    from dcos_commons_tpu.models import llama, serving, speculative
    from frameworks.jax import worker

    backend = jax.devices()[0].platform
    # the full tiny preset (4 layers) with the same max_seq the distill
    # workload will be given, so the target served here IS the teacher
    # run_distill freezes — the trained draft's acceptance is measured
    # against the model it was distilled from
    cfg = llama.LlamaConfig.tiny(max_seq=64, attn_impl="dense")
    params = llama.init_params(cfg, jax.random.key(0))

    def rand_prompt(seed, n):
        return [int(t) for t in jax.random.randint(
            jax.random.key(seed), (n,), 0, cfg.vocab_size)]

    shapes = [(8, args.max_new), (5, args.max_new), (14, args.max_new),
              (20, args.max_new), (6, args.max_new), (11, args.max_new)]
    reqs = [{"prompt": rand_prompt(args.seed + i, n), "max_new": m,
             "request_id": i}
            for i, (n, m) in enumerate(shapes[:args.requests])]
    want = {}
    for r in reqs:
        toks = llama.generate_stepwise(
            cfg, params, jnp.asarray([r["prompt"]], jnp.int32),
            r["max_new"])
        want[r["request_id"]] = [int(t) for t in toks[0]]

    def drain_arm(arm, cfg_d=None, params_d=None):
        eng = serving.PagedServer(cfg, params, slots=2, page_size=16,
                                  prefill_chunk=8)
        if cfg_d is not None:
            eng.arm_draft(cfg_d, params_d, k=args.k)
        # two throwaway drains compile every executable the timed run
        # needs: the first covers the cold-start window widths, the
        # second (post-reset, prefix cache warm) covers the widths the
        # prefix-adopted replay actually hits — so tok/s measures
        # steady-state serving, not jit
        for _ in range(2):
            eng.drain([dict(r) for r in reqs], decode_window=args.k)
            eng.reset()
        t0 = time.perf_counter()
        got = eng.drain([dict(r) for r in reqs], decode_window=args.k)
        dt = time.perf_counter() - t0
        toks = sum(len(v) for v in got.values())
        stats = eng.page_stats()["spec"]
        rec = {
            "metric": "spec_decode_paged", "arm": arm, "preset": "tiny",
            "backend": backend, "k": args.k,
            "draft_layers": args.draft_layers,
            "requests": len(reqs), "max_new": args.max_new,
            "seed": args.seed, "tokens": toks,
            "duration_s": round(dt, 3),
            "tokens_per_sec": round(toks / dt, 2),
            "parity_ok": got == want,
            "windows": stats["windows"],
            "accept_rate": round(stats["accept_rate"], 4),
            "fallbacks": stats["fallbacks"],
            "ledger_clean": eng.ledger_violations() == [],
        }
        _emit(rec)
        return rec

    solo = drain_arm("solo")

    cfg_u, params_u = llama.truncate_layers(cfg, params,
                                            args.draft_layers)
    params_u = jax.tree.map(jnp.array, params_u)
    untrained = drain_arm("spec_untrained", cfg_u, params_u)

    with tempfile.TemporaryDirectory() as tmp:
        t0 = time.perf_counter()
        wargs = worker.build_parser().parse_args(
            ["distill", "--preset", "tiny", "--steps", str(args.steps),
             "--batch", "8", "--seq", "64", "--max-seq", "64",
             "--draft-layers", str(args.draft_layers),
             "--out", tmp + "/ckpt"])
        # the workload narrates progress events on stdout; route them to
        # stderr so this tool's stdout stays pure JSONL receipts
        with contextlib.redirect_stdout(sys.stderr):
            result = worker.run_distill(wargs)
        _emit({
            "metric": "distill", "preset": "tiny", "backend": backend,
            "steps": args.steps, "draft_layers": args.draft_layers,
            "duration_s": round(time.perf_counter() - t0, 2),
            "loss_first": result["loss_first"],
            "loss_final": result["loss_final"],
            "loss_trajectory": result["loss_trajectory"],
            "tokens_per_sec": result.get("tokens_per_sec"),
        })
        cfg_t, params_t, _ = speculative.load_draft(result["draft_dir"],
                                                    cfg)
        trained = drain_arm("spec_trained", cfg_t, params_t)

    _emit({
        "metric": "spec_summary", "backend": backend,
        "accept_rate_untrained": untrained["accept_rate"],
        "accept_rate_trained": trained["accept_rate"],
        "accept_lift": round(
            trained["accept_rate"] - untrained["accept_rate"], 4),
        "solo_tokens_per_sec": solo["tokens_per_sec"],
        "spec_trained_tokens_per_sec": trained["tokens_per_sec"],
        "speedup_vs_solo": round(
            trained["tokens_per_sec"] / solo["tokens_per_sec"], 3),
        "all_parity_ok": all(r["parity_ok"] for r in
                             (untrained, trained)),
        "all_ledger_clean": all(r["ledger_clean"] for r in
                                (solo, untrained, trained)),
    })
    ok = (untrained["parity_ok"] and trained["parity_ok"]
          and solo["parity_ok"])
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
