"""Release promotion: stub package bundle -> published release.

Reference ``tools/release_builder.py`` + ``tools/universe/package_publisher
.py``: a CI-built "stub" package references artifacts wherever the build
uploaded them; releasing means copying the artifacts to their permanent
location, rewriting every artifact URL in resource.json, re-verifying
SHA256s, stamping the release version, and re-indexing the repo. The
reference publishes to S3/Azure/http; here the publisher target is a
directory (serve it with any static file server — the C++ agent fetches
plain http).

Usage::

    python -m tools.release_builder build/packages/jax-0.1.0-dev \
        --release-version 0.1.0 \
        --release-dir /srv/releases --url-base http://repo.example.com
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
from typing import Dict, Optional

from .package_builder import _sha256
from .package_repo import write_index


class ReleaseError(Exception):
    pass


class ReleaseBuilder:
    def __init__(self, bundle_dir: str, release_version: str,
                 release_dir: str, url_base: str,
                 artifact_sources: Optional[Dict[str, str]] = None):
        if not os.path.isfile(os.path.join(bundle_dir, "manifest.json")):
            raise ReleaseError(f"not a package bundle: {bundle_dir}")
        self.bundle_dir = bundle_dir
        self.release_version = release_version
        self.release_dir = release_dir
        self.url_base = url_base.rstrip("/")
        # local artifact files keyed by basename; default: <bundle>/artifacts
        self.artifact_sources = dict(artifact_sources or {})
        with open(os.path.join(bundle_dir, "manifest.json")) as f:
            self.manifest = json.load(f)

    def _resolve_artifact(self, name: str) -> str:
        local = self.artifact_sources.get(name)
        if local is None:
            local = os.path.join(self.bundle_dir, "artifacts", name)
        if not os.path.isfile(local):
            raise ReleaseError(
                f"artifact {name!r} not found (pass --artifact {name}=path)")
        return local

    def release(self) -> str:
        """Publish; returns the released bundle directory."""
        name = self.manifest["name"]
        dest_root = os.path.join(self.release_dir, name,
                                 self.release_version)
        if os.path.isdir(dest_root):
            raise ReleaseError(
                f"release {name} {self.release_version} already exists at "
                f"{dest_root} (releases are immutable)")
        # stage in a temp sibling and rename into place at the end: a failed
        # release must not leave a half-built dest_root behind (it would
        # permanently trip the immutability check above)
        staging = dest_root + ".releasing"
        if os.path.isdir(staging):
            shutil.rmtree(staging)
        try:
            self._build_into(staging, name)
            os.rename(staging, dest_root)
        except Exception:
            shutil.rmtree(staging, ignore_errors=True)
            raise
        write_index(self.release_dir)
        return dest_root

    def _build_into(self, staging: str, name: str) -> None:
        artifact_dest = os.path.join(staging, "artifacts")
        os.makedirs(artifact_dest)
        url_prefix = (f"{self.url_base}/{name}/{self.release_version}"
                      "/artifacts")

        # 1. copy artifacts + recompute SHAs
        published: Dict[str, dict] = {}
        for art_name, meta in sorted(self.manifest.get("artifacts",
                                                       {}).items()):
            local = self._resolve_artifact(art_name)
            sha = _sha256(local)
            if meta.get("sha256") and meta["sha256"] != sha:
                raise ReleaseError(
                    f"artifact {art_name}: sha256 mismatch vs stub manifest "
                    f"({sha} != {meta['sha256']}) — refusing to release "
                    "mutated artifacts")
            shutil.copy2(local, os.path.join(artifact_dest, art_name))
            published[art_name] = {"sha256": sha,
                                   "url": f"{url_prefix}/{art_name}"}

        # 2. rewrite package files: version stamp + artifact URL rebase
        old_urls = {a: m.get("url", "") for a, m in
                    self.manifest.get("artifacts", {}).items()}
        # every stub URL base must be fully rebased; any leftover points the
        # "immutable" release at ephemeral CI storage
        stub_bases = {u.rsplit("/", 1)[0] for u in old_urls.values() if u}
        stub_bases.add(self.manifest.get("artifact_dir", ""))
        stub_bases.discard("")
        for fname in self.manifest["files"]:
            src = os.path.join(self.bundle_dir, fname)
            with open(src) as f:
                content = f.read()
            # quoted form only: a bare replace of e.g. version "1" would
            # mangle every "1" in the document
            content = content.replace(f'"{self.manifest["version"]}"',
                                      f'"{self.release_version}"')
            for art_name, old_url in old_urls.items():
                if old_url:
                    content = content.replace(old_url,
                                              published[art_name]["url"])
            for base in stub_bases:
                if base in content:
                    raise ReleaseError(
                        f"{fname}: still references stub artifact location "
                        f"{base} after rebasing — an artifact referenced by "
                        "the package was not passed to the stub build via "
                        "--artifact; releasing would point at ephemeral CI "
                        "storage")
            with open(os.path.join(staging, fname), "w") as f:
                f.write(content)

        # 3. released manifest
        manifest = dict(self.manifest)
        manifest["version"] = self.release_version
        manifest["artifacts"] = published
        manifest["released_from"] = self.manifest["version"]
        with open(os.path.join(staging, "manifest.json"), "w") as f:
            json.dump(manifest, f, indent=2, sort_keys=True)
            f.write("\n")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("bundle_dir", help="stub bundle from tools.package_builder")
    p.add_argument("--release-version", required=True)
    p.add_argument("--release-dir", required=True)
    p.add_argument("--url-base", required=True,
                   help="base URL the release dir will be served from")
    p.add_argument("--artifact", action="append", default=[],
                   metavar="NAME=PATH",
                   help="local source for a manifest artifact (repeatable)")
    args = p.parse_args(argv)
    sources = {}
    for spec in args.artifact:
        name, _, path = spec.partition("=")
        if not path:
            print(f"error: --artifact expects NAME=PATH, got {spec!r}",
                  file=sys.stderr)
            return 2
        sources[name] = path
    try:
        builder = ReleaseBuilder(args.bundle_dir, args.release_version,
                                 args.release_dir, args.url_base, sources)
        dest = builder.release()
    except ReleaseError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    print(dest)
    return 0


if __name__ == "__main__":
    sys.exit(main())
