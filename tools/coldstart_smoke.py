"""Time-capped cold-start smoke for CI: boot a decode replica three ways
— sharded disk restore, digest-checked peer fetch from a live
``WeightServer``, and a warm-pool promotion sharing the AOT compile
cache — and fail the build on the first greedy-token divergence.

The full phase-timed ladder with receipts lives in
``tools/bench_autoscale.py --mode coldstart``; this is the always-on
slice test.sh runs next to the other smokes. It also exercises the
degrade-not-crash contract: a fetch aimed at a dead peer must raise
``WeightFetchError`` (so the worker's disk fallback path fires), never
hang or crash. Checks run in a fixed order and stop (skip, not fail)
when the time budget runs out.
"""

from __future__ import annotations

import argparse
import sys
import tempfile
import time
from pathlib import Path


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--budget-s", type=float, default=90.0,
                    help="wall-clock cap; tail checks are skipped, not "
                         "failed, when it runs out (default 90)")
    args = ap.parse_args(argv)
    deadline = time.monotonic() + args.budget_s

    import jax
    import jax.numpy as jnp

    from dcos_commons_tpu.models import llama, serving, weights
    from dcos_commons_tpu.parallel import aot
    from dcos_commons_tpu.parallel import checkpoint as ckpt

    cfg = llama.LlamaConfig.tiny(n_layers=2, max_seq=64,
                                 attn_impl="dense")
    params = llama.init_params(cfg, jax.random.key(0))
    engine_kw = dict(slots=2, page_size=16, prefill_chunk=8)
    rng = jax.random.key(11)
    reqs = []
    for i, (n, m) in enumerate([(8, 6), (5, 9), (12, 4)]):
        rng, sub = jax.random.split(rng)
        prompt = [int(t) for t in jax.random.randint(
            sub, (n,), 0, cfg.vocab_size)]
        reqs.append({"prompt": prompt, "max_new": m, "request_id": i})

    ran = 0

    def _spent(name: str) -> bool:
        if time.monotonic() >= deadline:
            print(f"coldstart-smoke: time budget exhausted after {ran} "
                  f"checks; {name!r} and later checks skipped")
            return True
        return False

    with tempfile.TemporaryDirectory(prefix="coldstart_smoke_") as tmp:
        ckpt_dir = str(Path(tmp) / "ckpt")
        ckpt.save_sharded(ckpt_dir, 1, params)
        template = jax.tree.map(jnp.zeros_like, params)

        # 1. the anchor: disk restore -> serve (every real replica's
        # fallback path, and the parity reference for the peer boot)
        if _spent("disk-restore"):
            return 0
        cache = aot.CompileCache()
        disk = serving.PagedServer(
            cfg, ckpt.restore_sharded(ckpt_dir, template),
            compile_cache=cache, **engine_kw)
        want = disk.drain([dict(r) for r in reqs])
        ran += 1

        # 2. peer boot: the disk-restored replica exposes its shards
        # over live HTTP; a second replica fetches digest-checked
        # frames and must emit bit-identical tokens
        if _spent("peer-boot"):
            return 0
        server = weights.WeightServer(ckpt_dir, port=0,
                                      host="127.0.0.1").start()
        try:
            peers = [f"http://127.0.0.1:{server.port}"]
            fetcher = weights.PeerFetcher(peers)
            booted = weights.restore_from_peers(peers, template,
                                                fetcher=fetcher)
            peer = serving.PagedServer(cfg, booted, compile_cache=cache,
                                       **engine_kw)
            got = peer.drain([dict(r) for r in reqs])
            if got != want:
                print(f"coldstart-smoke FAILED: peer-booted streams != "
                      f"disk streams\n  peer: {got}\n  disk: {want}",
                      file=sys.stderr)
                return 1
            stats = fetcher.stats()
            if not stats["shards_fetched"]:
                print("coldstart-smoke FAILED: peer boot fetched zero "
                      "shards (restore silently used another source?)",
                      file=sys.stderr)
                return 1
        finally:
            server.stop()
        ran += 1

        # 3. warm promotion: a pool replica built against the shared
        # compile cache serves the same tokens with zero boot work left
        if _spent("warm-promotion"):
            return 0
        t0 = time.perf_counter()
        warm = serving.PagedServer(
            cfg, ckpt.restore_sharded(ckpt_dir, template),
            compile_cache=cache, **engine_kw)
        got = warm.drain([dict(r) for r in reqs])
        promote_s = time.perf_counter() - t0
        if got != want:
            print(f"coldstart-smoke FAILED: warm-pool streams != disk "
                  f"streams\n  warm: {got}\n  disk: {want}",
                  file=sys.stderr)
            return 1
        if not cache.stats()["hits"]:
            print("coldstart-smoke FAILED: warm replica missed the AOT "
                  "compile cache (homogeneous scale-up re-traced)",
                  file=sys.stderr)
            return 1
        ran += 1

        # 4. degrade-not-crash: a dead peer must fail fast with
        # WeightFetchError so the worker falls back to disk
        if _spent("dead-peer-fallback"):
            return 0
        try:
            weights.restore_from_peers(
                ["http://127.0.0.1:9"], template,
                fetcher=weights.PeerFetcher(["http://127.0.0.1:9"],
                                            timeout_s=2.0))
        except weights.WeightFetchError:
            pass
        else:
            print("coldstart-smoke FAILED: dead peer did not raise "
                  "WeightFetchError", file=sys.stderr)
            return 1
        ran += 1

    print(f"coldstart-smoke: {ran} checks passed — peer-booted and "
          f"warm-promoted replicas token-exact vs disk restore "
          f"({stats['shards_fetched']} shards / "
          f"{stats['bytes_fetched']} bytes over HTTP, warm serve in "
          f"{promote_s:.2f}s, AOT cache "
          f"{cache.stats()['hits']} hits), dead peer degrades cleanly")
    return 0


if __name__ == "__main__":
    sys.exit(main())
