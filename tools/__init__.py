"""Release/packaging tooling (reference ``tools/`` + ``tools/universe/``)."""
