"""Elasticity A/B bench: autoscaled decode tier vs a static fleet.

Drives the elastic soak harness directly — chaos weather OFF, a scripted
Poisson-ish load swing ON — twice per seed:

* ``autoscaled``: the back-pressure autoscaler resizes the decode tier
  through deploy plans; scale-up starves the training gang, so the
  preemptor fires (SIGTERM -> checkpoint flush -> exit 143 -> reclaim)
  and the backfill gate re-admits training once the burst passes.
* ``static``: same seed, same arrivals, no autoscaler — the 1-replica
  decode tier sheds everything a burst throws past its queue.

Receipts land in ``bench_r10/autoscale.jsonl`` (one line per run plus an
A/B summary per seed): scale events with the pressure that triggered
them, preemption records with flush/resume steps, and the shed-rate
comparison. Exit 1 if any run fails its invariants or the autoscaled
variant fails to beat the static baseline's shed rate.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

# one burst per third of the storm window: quiet -> swing -> quiet, so a
# run exercises scale-up, preemption, scale-down and backfill re-admission
BURST_SCHEDULE = ((6, 10), (30, 8))
DEFAULT_TICKS = 48


def run_variant(seed: int, ticks: int, autoscale: bool) -> dict:
    from dcos_commons_tpu.chaos.elastic_soak import ElasticSoak
    from dcos_commons_tpu.chaos.engine import FaultConfig

    soak = ElasticSoak(seed, ticks, FaultConfig.none(),
                       autoscale=autoscale, burst_schedule=BURST_SCHEDULE)
    report = soak.run()
    shed, done = soak.load.total_shed, soak.load.total_done
    trace_store = soak.routersim.trace_store
    return {
        "metric": "elastic_ab",
        "variant": "autoscaled" if autoscale else "static",
        "seed": seed,
        "ticks": ticks,
        "burst_schedule": [list(b) for b in BURST_SCHEDULE],
        "converged": report.converged,
        "violations": [str(v) for v in report.violations],
        "requests_done": done,
        "requests_shed": shed,
        "shed_rate": round(shed / max(1, shed + done), 4),
        "relays_completed": soak.routersim.completed,
        "traces_retained": len(trace_store.trace_ids()),
        "traces_incomplete": len(trace_store.incomplete_trace_ids()),
        "scale_events": [[n, round(p, 3)]
                         for n, p in soak.autoscaler.events],
        "final_decode_target": soak.autoscaler.target,
        "preemptions": [{
            "service": r.service,
            "pod_instances": list(r.pod_instances),
            "term_tick": r.term_tick,
            "terminal_tick": r.terminal_tick,
            "escalated_tick": r.escalated_tick,
            "reclaim_tick": r.reclaim_tick,
            "reclaimed_tasks": list(r.reclaimed_tasks),
        } for r in soak.preemptor.records],
        "checkpoint_flushes": [
            {"tick": t, "instance": inst, "step": step}
            for t, inst, step in soak.flushsim.flushes],
        "checkpoint_resumes": [
            {"tick": t, "instance": inst, "step": step}
            for t, inst, step in soak.flushsim.resumes],
        "plan_statuses": report.plan_statuses,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seeds", type=int, default=3,
                    help="A/B pairs to run, seeds 0..N-1 (default 3)")
    ap.add_argument("--ticks", type=int, default=DEFAULT_TICKS,
                    help=f"storm ticks per run (default {DEFAULT_TICKS})")
    ap.add_argument("--out", default="bench_r10/autoscale.jsonl",
                    help="receipts file (default bench_r10/autoscale.jsonl)")
    args = ap.parse_args(argv)

    lines = []
    failed = False
    for seed in range(args.seeds):
        auto = run_variant(seed, args.ticks, autoscale=True)
        static = run_variant(seed, args.ticks, autoscale=False)
        improved = auto["shed_rate"] < static["shed_rate"]
        # trace completeness: after settle every admitted relay's trace
        # must have reached a terminal span (the invariant also audits
        # this per tick; the receipt makes it visible in the A/B row)
        traces_ok = (auto["traces_incomplete"] == 0
                     and static["traces_incomplete"] == 0)
        ok = (auto["converged"] and static["converged"]
              and not auto["violations"] and not static["violations"]
              and improved and traces_ok)
        summary = {
            "metric": "elastic_ab_summary",
            "seed": seed,
            "shed_rate_autoscaled": auto["shed_rate"],
            "shed_rate_static": static["shed_rate"],
            "shed_improvement": round(
                static["shed_rate"] - auto["shed_rate"], 4),
            "scale_events": len(auto["scale_events"]),
            "preemptions": len(auto["preemptions"]),
            "flushes": len(auto["checkpoint_flushes"]),
            "resumes": len(auto["checkpoint_resumes"]),
            "traces_incomplete": (auto["traces_incomplete"]
                                  + static["traces_incomplete"]),
            "ok": ok,
        }
        lines += [auto, static, summary]
        print(f"seed {seed}: shed autoscaled={auto['shed_rate']:.3f} "
              f"static={static['shed_rate']:.3f} "
              f"scale_events={len(auto['scale_events'])} "
              f"preemptions={len(auto['preemptions'])} "
              f"flushes={len(auto['checkpoint_flushes'])} "
              f"resumes={len(auto['checkpoint_resumes'])} "
              f"{'OK' if ok else 'FAIL'}")
        if not ok:
            failed = True

    out = Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    with out.open("w") as fh:
        for line in lines:
            fh.write(json.dumps(line) + "\n")
    print(f"wrote {len(lines)} receipt line(s) to {out}")
    if failed:
        print("bench_autoscale: FAILED — see receipts", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
