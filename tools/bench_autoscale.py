"""Elasticity A/B bench: autoscaled decode tier vs a static fleet, plus
the Round 14 cold-start collapse ladder.

**Elastic mode** drives the elastic soak harness directly — chaos
weather OFF, a scripted Poisson-ish load swing ON — twice per seed:

* ``autoscaled``: the back-pressure autoscaler resizes the decode tier
  through deploy plans; scale-up starves the training gang, so the
  preemptor fires (SIGTERM -> checkpoint flush -> exit 143 -> reclaim)
  and the backfill gate re-admits training once the burst passes.
* ``static``: same seed, same arrivals, no autoscaler — the 1-replica
  decode tier sheds everything a burst throws past its queue.

**Cold-start mode** times autoscale-decision -> first token for a real
(scaled-down) decode replica three ways, with the phase breakdown
(fetch / restore / compile / admit) recorded through the shared
``MetricsRegistry`` Timer histograms:

* ``disk``: the baseline — restore the sharded checkpoint from shared
  storage, trace + compile every executable, warm up, serve.
* ``peer``: fetch digest-checked weight frames over HTTP from an
  already-hot sibling (``models/weights.py``) and reuse its AOT compile
  cache — no re-trace on a homogeneous scale-up.
* ``warm``: the warm-pool tier — weights resident, executables
  compiled; the only cold work left is admission itself.

All three variants must emit bit-exact greedy tokens; ``warm`` and
``peer`` must each beat ``disk`` on decision -> first token.

**Migrate mode** (``--migrate``, Round 15) is the zero-drop A/B: a
scripted decommission of one of two REAL paged engines under Poisson
load, once with the ``models/migrate.py`` drain (every live stream
ships to the survivor over the DECSTATE frame and must finish
token-exact; dropped_streams must be 0) and once without (the reclaim
aborts them — today's count, the baseline). Receipts land in
``bench_r15/migration.jsonl`` with the migration pause p50/p95.

**Reshard mode** (``--mode reshard``, Round 19) is the downtime A/B: a
live 4-way training gang resizes to 2 workers mid-run, once through
the restart road (sentinel checkpoint flush -> relaunch -> disk
restore, today's behaviour) and once restart-free through
``parallel/reshard.py`` (freeze -> GANGSTATE over the loopback weight
channel -> transactional adopt). Both must rejoin the uninterrupted
loss curve bitwise and the reshard road must be strictly faster.
Receipts land in ``bench_r19/reshard.jsonl``.

Receipts land in ``bench_r14/autoscale.jsonl`` (one line per run plus a
summary per seed). Exit 1 if any run fails its invariants, the
autoscaled variant fails to beat the static shed rate, token parity
breaks, the cold-start ladder fails to collapse, a migration run
drops or diverges a stream, or a reshard run diverges or fails to
beat the restart baseline.
"""

from __future__ import annotations

import argparse
import json
import sys
import tempfile
import time
from pathlib import Path

# one burst per third of the storm window: quiet -> swing -> quiet, so a
# run exercises scale-up, preemption, scale-down and backfill re-admission
BURST_SCHEDULE = ((6, 10), (30, 8))
DEFAULT_TICKS = 48


def run_variant(seed: int, ticks: int, autoscale: bool) -> dict:
    from dcos_commons_tpu.chaos.elastic_soak import ElasticSoak
    from dcos_commons_tpu.chaos.engine import FaultConfig

    soak = ElasticSoak(seed, ticks, FaultConfig.none(),
                       autoscale=autoscale, burst_schedule=BURST_SCHEDULE)
    report = soak.run()
    shed, done = soak.load.total_shed, soak.load.total_done
    trace_store = soak.routersim.trace_store
    return {
        "metric": "elastic_ab",
        "variant": "autoscaled" if autoscale else "static",
        "seed": seed,
        "ticks": ticks,
        "burst_schedule": [list(b) for b in BURST_SCHEDULE],
        "converged": report.converged,
        "violations": [str(v) for v in report.violations],
        "requests_done": done,
        "requests_shed": shed,
        "shed_rate": round(shed / max(1, shed + done), 4),
        "relays_completed": soak.routersim.completed,
        "traces_retained": len(trace_store.trace_ids()),
        "traces_incomplete": len(trace_store.incomplete_trace_ids()),
        "scale_events": [[n, round(p, 3)]
                         for n, p in soak.autoscaler.events],
        "final_decode_target": soak.autoscaler.target,
        "preemptions": [{
            "service": r.service,
            "pod_instances": list(r.pod_instances),
            "term_tick": r.term_tick,
            "terminal_tick": r.terminal_tick,
            "escalated_tick": r.escalated_tick,
            "reclaim_tick": r.reclaim_tick,
            "reclaimed_tasks": list(r.reclaimed_tasks),
        } for r in soak.preemptor.records],
        "checkpoint_flushes": [
            {"tick": t, "instance": inst, "step": step}
            for t, inst, step in soak.flushsim.flushes],
        "checkpoint_resumes": [
            {"tick": t, "instance": inst, "step": step}
            for t, inst, step in soak.flushsim.resumes],
        "plan_statuses": report.plan_statuses,
    }


# -- live-migration A/B -----------------------------------------------------

# scripted decommission mid-storm: arrivals stop at ARRIVAL_TICKS, the
# victim replica is reclaimed at DECOM_TICK — late enough that it holds
# live mid-decode streams, early enough that they are nowhere near done
MIGRATE_TICKS = 14
MIGRATE_ARRIVAL_TICKS = 8
MIGRATE_DECOM_TICK = 6
MIGRATE_LAMBDA = 1.2


def run_migration(seed: int, migrate: bool) -> dict:
    """One scripted scale-down under Poisson load over two REAL paged
    engines behind a hash ring: replica B is decommissioned mid-stream
    at ``MIGRATE_DECOM_TICK``. With ``migrate=True`` the
    :class:`~dcos_commons_tpu.models.migrate.MigrationManager` drains
    B's live streams to A through the DECSTATE wire round-trip first
    (dropped_streams must be 0 and every migrated stream must finish
    token-exact against the uninterrupted greedy reference); with
    ``migrate=False`` the reclaim aborts them — today's behaviour, the
    baseline the receipt quantifies."""
    import math
    import random as _random

    import jax
    import jax.numpy as jnp

    from dcos_commons_tpu.models import llama, serving
    from dcos_commons_tpu.models.migrate import MigrationManager
    from dcos_commons_tpu.models.router import HashRing, route_key
    from dcos_commons_tpu.utils.stats import percentiles

    cfg = llama.LlamaConfig.tiny(n_layers=2, max_seq=64, attn_impl="dense")
    params = llama.init_params(cfg, jax.random.key(0))
    kw = dict(slots=4, page_size=8, prefill_chunk=8)
    engines = {"A": serving.PagedServer(cfg, params, **kw),
               "B": serving.PagedServer(cfg, params, **kw)}
    ring = HashRing(["A", "B"], vnodes=16)
    rng = _random.Random(seed)

    def poisson(lam: float) -> int:
        L, k, p = math.exp(-lam), 0, 1.0
        while True:
            p *= rng.random()
            if p <= L:
                return k
            k += 1

    mgr = MigrationManager(enable=migrate, ring=ring, page_size=8)
    queues = {"A": [], "B": []}
    tokens_ref, prompts, budgets = {}, {}, {}
    live_names = ["A", "B"]
    serial = 0
    dropped_rids: list = []
    receipt = {"migrated": 0, "resubmitted": 0, "failed": 0, "live": 0}

    def pump(name: str) -> None:
        q = queues[name]
        while q:
            item = q[0]
            slot = engines[name].submit(item["prompt"], item["max_new"],
                                        request_id=item["rid"])
            if slot is None:
                break
            q.pop(0)
            tokens_ref[item["rid"]] = engines[name].requests[slot].tokens

    for tick in range(MIGRATE_TICKS):
        if tick < MIGRATE_ARRIVAL_TICKS:
            for _ in range(poisson(MIGRATE_LAMBDA)):
                serial += 1
                rid = f"q{serial}"
                prompt = [rng.randrange(cfg.vocab_size)
                          for _ in range(rng.randint(6, 12))]
                max_new = rng.randint(10, 16)
                prompts[rid], budgets[rid] = prompt, max_new
                target = next(
                    (c for c in ring.preference(route_key(prompt, 8))
                     if c in live_names), live_names[0])
                queues[target].append({"rid": rid, "prompt": prompt,
                                       "max_new": max_new})
        if tick == MIGRATE_DECOM_TICK:
            victim = engines["B"]
            live_rids = [r.request_id for r in victim.requests
                         if r is not None]
            receipt["live"] = len(live_rids)
            if migrate:
                # the drain rides the grace window: a destination with
                # no free slot refuses (victim stream untouched), the
                # survivor steps — retirements free slots — and the
                # drain retries until the victim is empty
                remaining = list(live_rids)
                for _ in range(24):
                    r = mgr.drain(victim, "B", [("A", engines["A"])])
                    receipt["migrated"] += r["migrated"]
                    receipt["resubmitted"] += r["resubmitted"]
                    # drained streams live on A now — re-point the
                    # token refs before A steps (a short stream can
                    # finish and retire during the grace window)
                    for x in engines["A"].requests:
                        if x is not None and x.request_id in live_rids:
                            tokens_ref[x.request_id] = x.tokens
                    remaining = [x.request_id for x in victim.requests
                                 if x is not None]
                    if not remaining:
                        break
                    engines["A"].step()
                dropped_rids = remaining
            else:
                dropped_rids = live_rids
            victim.abort_active()          # the reclaim itself
            queues["A"].extend(queues["B"])
            queues["B"] = []
            live_names = ["A"]
            ring.remove("B")
        for name in live_names:
            pump(name)
            engines[name].step()
    for _ in range(400):
        pump("A")
        if not engines["A"].requests_active() and not queues["A"]:
            break
        engines["A"].step()

    moved = [rid for rid in live_rids if rid not in dropped_rids]
    done = [rid for rid in tokens_ref
            if rid not in dropped_rids
            and len(tokens_ref[rid]) >= budgets[rid]]
    # token-exactness of every MIGRATED stream against the solo greedy
    # reference — the zero-drop claim is worthless if resumed streams
    # diverge
    exact = True
    for rid in moved:
        want = [int(t) for t in llama.generate_stepwise(
            cfg, params, jnp.asarray(prompts[rid])[None, :],
            budgets[rid])[0]]
        if tokens_ref.get(rid) != want:
            exact = False
    return {
        "metric": "migration",
        "variant": "migrated" if migrate else "baseline",
        "seed": seed,
        "ticks": MIGRATE_TICKS,
        "decom_tick": MIGRATE_DECOM_TICK,
        "requests": serial,
        "completed": len(done),
        "live_at_decommission": len(live_rids),
        "migrated": receipt["migrated"],
        "resubmitted": receipt["resubmitted"],
        "dropped_streams": len(dropped_rids),
        "token_exact": exact,
        "pause_ms": percentiles(mgr.pause_ms),
        "engine_stats": {
            n: {k: engines[n].page_stats()[k]
                for k in ("migrated_in", "migrated_out", "pages_free")}
            for n in engines},
    }


# -- restart-free reshard downtime A/B --------------------------------------

# state sized so the A/B measures real byte movement, not fixed overheads:
# 8 MiB of float32 params across two leaves (a scaled stand-in for the
# train gang's sharded state; the ordering claim is size-independent)
RESHARD_SHAPE = (512, 2048)
RESHARD_STEPS_BEFORE = 4
RESHARD_STEPS_AFTER = 8


def _reshard_xs(seed: int):
    """Deterministic problem state shared by the bench parent and the
    relaunched baseline child — both must replay the identical bytes."""
    import numpy as np

    rng = np.random.default_rng(seed)
    return {
        "w": rng.standard_normal(RESHARD_SHAPE).astype(np.float32),
        "b": rng.standard_normal(RESHARD_SHAPE).astype(np.float32),
    }


def _reshard_restart_child(seed: int, ckpt_dir: str) -> int:
    """The restart road's relaunched worker (baseline leg of
    :func:`run_reshard`): a FRESH process pays interpreter start, jax
    import, backend init and the sharded disk restore before the gang
    can take another step — exactly the downtime the restart-free road
    deletes. Prints one JSON line the moment training could resume
    (the parent's downtime endpoint) and one with the replayed losses
    (the bitwise audit)."""
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()

    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from dcos_commons_tpu.parallel import checkpoint as ckpt

    jax.config.update("jax_platforms", "cpu")
    xs = _reshard_xs(seed)
    mesh2 = Mesh(np.array(jax.devices()[:2]), ("dp",))

    def sharded(value):
        return jax.device_put(value, NamedSharding(mesh2, P("dp")))

    template = {k: sharded(np.zeros_like(v)) for k, v in xs.items()}
    restored = ckpt.restore_sharded(ckpt_dir, template,
                                    RESHARD_STEPS_BEFORE)
    jax.block_until_ready(restored)
    print(json.dumps({"event": "restored"}), flush=True)

    @jax.jit
    def step_fn(tree, target):
        return jax.tree_util.tree_map(
            lambda p, x: p - jnp.float32(0.05) * (p - x), tree, target)

    target = {k: sharded(v) for k, v in xs.items()}
    losses = []
    tree = restored
    for _ in range(RESHARD_STEPS_AFTER):
        tree = step_fn(tree, target)
        losses.append(float(sum(
            float(np.sum(np.asarray(v), dtype=np.float64))
            for _, v in sorted(tree.items()))))
    print(json.dumps({"losses": losses}), flush=True)
    return 0


def run_reshard(seed: int) -> list:
    """Round 19 downtime A/B: resize a live 4-way training gang down to
    2 workers mid-run, once through the restart road (sentinel
    checkpoint flush to disk -> worker relaunch in a fresh process ->
    sharded restore, today's fallback) and once restart-free through
    ``parallel/reshard.py`` (freeze at the step boundary -> GANGSTATE
    over the loopback weight channel -> transactional adopt in the
    surviving process). Both roads must rejoin the uninterrupted
    reference loss curve BITWISE; the reshard road must be strictly
    faster, every seed."""
    import subprocess

    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from dcos_commons_tpu.models import weights
    from dcos_commons_tpu.parallel import checkpoint as ckpt
    from dcos_commons_tpu.parallel import reshard

    xs = _reshard_xs(seed)

    def mesh(n):
        return Mesh(np.array(jax.devices()[:n]), ("dp",))

    def sharded(m, value):
        return jax.device_put(value, NamedSharding(m, P("dp")))

    def tree_on(m, init=None):
        return {k: sharded(m, np.zeros_like(v) if init is None else
                           init[k]) for k, v in xs.items()}

    @jax.jit
    def step_fn(tree, target):
        # elementwise: the trajectory is a pure function of state bytes
        return jax.tree_util.tree_map(
            lambda p, x: p - jnp.float32(0.05) * (p - x), tree, target)

    def loss(tree):
        return float(sum(
            float(np.sum(np.asarray(v), dtype=np.float64))
            for _, v in sorted(tree.items())))

    def run(tree, target, steps, losses):
        for _ in range(steps):
            tree = step_fn(tree, target)
            losses.append(loss(tree))
        return tree

    mesh4, mesh2 = mesh(4), mesh(2)
    total = RESHARD_STEPS_BEFORE + RESHARD_STEPS_AFTER
    ref_losses: list = []
    run(tree_on(mesh4), tree_on(mesh4, xs), total, ref_losses)

    rows = []

    # -- baseline: flush to disk, relaunch a fresh worker, restore --
    with tempfile.TemporaryDirectory() as td:
        losses: list = []
        tree = run(tree_on(mesh4), tree_on(mesh4, xs),
                   RESHARD_STEPS_BEFORE, losses)
        t0 = time.monotonic()
        ckpt.save_sharded(td, RESHARD_STEPS_BEFORE, tree)   # the flush
        flush_s = time.monotonic() - t0
        child = subprocess.Popen(
            [sys.executable, "-m", "tools.bench_autoscale",
             "--reshard-restart-child", str(seed), td],
            stdout=subprocess.PIPE, text=True)
        try:
            ready = json.loads(child.stdout.readline())
            downtime_baseline = time.monotonic() - t0
            replay = json.loads(child.stdout.readline())
        finally:
            child.stdout.close()
            child.wait(timeout=120)
        losses += replay["losses"]
        ok_base = (ready.get("event") == "restored"
                   and losses == ref_losses)
        rows.append({
            "metric": "reshard", "variant": "baseline",
            "seed": seed, "downtime_s": round(downtime_baseline, 6),
            "flush_s": round(flush_s, 6),
            "restart_restore_s": round(downtime_baseline - flush_s, 6),
            "step": RESHARD_STEPS_BEFORE, "from_workers": 4,
            "to_workers": 2, "bitwise": losses == ref_losses,
            "ok": ok_base,
        })

    # -- reshard: freeze, publish live, adopt over the weight channel --
    with tempfile.TemporaryDirectory() as td:
        mgr = reshard.ReshardManager()
        srv = weights.WeightServer(td, host="127.0.0.1").start()
        try:
            losses = []
            tree = run(tree_on(mesh4), tree_on(mesh4, xs),
                       RESHARD_STEPS_BEFORE, losses)
            t0 = time.monotonic()
            mgr.freeze(RESHARD_STEPS_BEFORE, tree, server=srv)
            adopted, hdr, receipt = mgr.adopt(
                tree_on(mesh2),
                fetcher=weights.PeerFetcher(
                    [f"http://127.0.0.1:{srv.port}"], timeout_s=60.0))
            downtime_reshard = time.monotonic() - t0
        finally:
            srv.stop()
        run(adopted, tree_on(mesh2, xs), RESHARD_STEPS_AFTER, losses)
        rows.append({
            "metric": "reshard", "variant": "reshard",
            "seed": seed, "downtime_s": round(downtime_reshard, 6),
            "step": hdr["step"], "from_workers": 4, "to_workers": 2,
            "files_fetched": receipt["files_fetched"],
            "bytes_fetched": receipt["bytes_fetched"],
            "bitwise": losses == ref_losses,
            "ok": bool(receipt["ok"] and losses == ref_losses),
        })

    ok = (rows[0]["bitwise"] and rows[1]["ok"]
          and rows[1]["downtime_s"] < rows[0]["downtime_s"])
    rows.append({
        "metric": "reshard_summary", "seed": seed,
        "downtime_baseline_s": rows[0]["downtime_s"],
        "downtime_reshard_s": rows[1]["downtime_s"],
        "speedup": round(rows[0]["downtime_s"]
                         / max(rows[1]["downtime_s"], 1e-9), 2),
        "bitwise_both": rows[0]["bitwise"] and rows[1]["bitwise"],
        "ok": ok,
    })
    return rows


# -- cold-start ladder ------------------------------------------------------

# scaled-down stand-in for the 8B homogeneous scale-up config: the phase
# structure (fetch / restore / compile / admit) and the parity contract
# are config-independent; absolute seconds are not the claim here, the
# ladder ordering (warm < peer < disk) is
COLDSTART_CONFIG = "8b-sim"
_PHASES = ("fetch", "restore", "compile", "admit")


def _probe_requests(vocab: int) -> list:
    import random
    rng = random.Random(1234)
    return [{"prompt": [rng.randrange(vocab) for _ in range(12)],
             "max_new": 8, "request_id": "probe"}]


def run_coldstart(seed: int) -> list:
    """One cold-start A/B/C at ``COLDSTART_CONFIG``: boot a decode
    replica from disk, from a hot peer, and from the warm pool, timing
    decision -> first token with the phase breakdown observed into a
    shared registry (the same ``autoscale.cold_start.*`` timers the
    worker exports over ``/v1/metrics/prometheus``)."""
    import jax
    import jax.numpy as jnp

    from dcos_commons_tpu.metrics import MetricsRegistry
    from dcos_commons_tpu.models import llama, serving, weights
    from dcos_commons_tpu.parallel import aot
    from dcos_commons_tpu.parallel import checkpoint as ckpt

    cfg = llama.LlamaConfig.tiny()
    engine_kw = dict(slots=2, page_size=16, prefill_chunk=8)
    params = llama.init_params(cfg, jax.random.key(seed))
    probe = _probe_requests(cfg.vocab_size)

    lines = []
    with tempfile.TemporaryDirectory(prefix="bench_coldstart_") as tmp:
        ckpt_dir = str(Path(tmp) / "ckpt")
        ckpt.save_sharded(ckpt_dir, 1, params)
        template = jax.tree.map(jnp.zeros_like, params)

        # the already-hot fleet: a serving replica holding the shared AOT
        # cache and exposing its checkpoint shards over HTTP. Its own
        # boot cost is NOT part of any variant — it represents steady
        # state before the autoscale decision fires. It booted from the
        # checkpoint like every real replica does (restored arrays are
        # device-committed, which is part of jit's executable cache key —
        # an init-params hot engine would never share with restored ones)
        cache = aot.CompileCache()
        hot = serving.PagedServer(cfg,
                                  ckpt.restore_sharded(ckpt_dir, template),
                                  compile_cache=cache, **engine_kw)
        hot.warmup()
        want = hot.drain([dict(r) for r in probe])
        server = weights.WeightServer(ckpt_dir, port=0,
                                      host="127.0.0.1").start()
        peers = [f"http://127.0.0.1:{server.port}"]

        # the warm-pool replica: weights resident (restored at pool-fill
        # time), executables compiled, zero traffic — all of that
        # happened before the decision too
        pooled = serving.PagedServer(cfg,
                                     ckpt.restore_sharded(ckpt_dir,
                                                          template),
                                     compile_cache=cache, **engine_kw)
        pooled.warmup()

        def timed(registry, phase, fn):
            t0 = time.perf_counter()
            out = fn()
            dt = time.perf_counter() - t0
            registry.observe(f"autoscale.cold_start.{phase}_seconds", dt)
            return out, dt

        def variant(name, steps):
            """steps: ordered {phase: thunk}; unlisted phases cost 0."""
            registry = MetricsRegistry()
            phases = {p: 0.0 for p in _PHASES}
            t0 = time.perf_counter()
            out = None
            for phase, fn in steps.items():
                out, phases[phase] = timed(registry, phase, fn)
            total = time.perf_counter() - t0
            registry.observe("autoscale.cold_start_seconds", total)
            tokens = out
            row = {
                "metric": "cold_start",
                "variant": name,
                "config": COLDSTART_CONFIG,
                "seed": seed,
                "cold_start_s": round(total, 4),
                "phases_s": {p: round(v, 4) for p, v in phases.items()},
                "parity": tokens == want,
                "timers": {
                    n: registry.timer(n) for n in
                    ["autoscale.cold_start_seconds"]
                    + [f"autoscale.cold_start.{p}_seconds"
                       for p in _PHASES]
                    if registry.timer(n) is not None},
            }
            registry.close()
            return row

        try:
            # disk: fetch is a no-op (shared storage is "local"), every
            # executable is traced + compiled from scratch
            state = {}
            disk = variant("disk", {
                "restore": lambda: state.update(
                    t=ckpt.restore_sharded(ckpt_dir, template)),
                "compile": lambda: state.update(
                    e=serving.PagedServer(cfg, state["t"], **engine_kw))
                and None or state["e"].warmup(),
                "admit": lambda: state["e"].drain(
                    [dict(r) for r in probe]),
            })

            # peer: manifest pin + digest-checked shard streaming from
            # the hot sibling; compile reuses the sibling's AOT cache
            pstate = {"f": weights.PeerFetcher(peers)}
            peer = variant("peer", {
                "fetch": lambda: pstate["f"].manifest(),
                "restore": lambda: pstate.update(
                    t=weights.restore_from_peers(
                        peers, template, fetcher=pstate["f"])),
                "compile": lambda: pstate.update(
                    e=serving.PagedServer(cfg, pstate["t"],
                                          compile_cache=cache,
                                          **engine_kw))
                and None or pstate["e"].warmup(),
                "admit": lambda: pstate["e"].drain(
                    [dict(r) for r in probe]),
            })
            peer["peer_stats"] = pstate["f"].stats()

            # warm: promotion is bookkeeping; admission is the whole bill
            warm = variant("warm", {
                "admit": lambda: pooled.drain([dict(r) for r in probe]),
            })
        finally:
            server.stop()

    parity = disk["parity"] and peer["parity"] and warm["parity"]
    collapsed = (warm["cold_start_s"] < disk["cold_start_s"]
                 and peer["cold_start_s"] < disk["cold_start_s"])
    summary = {
        "metric": "cold_start_summary",
        "config": COLDSTART_CONFIG,
        "seed": seed,
        "cold_start_s": {v["variant"]: v["cold_start_s"]
                         for v in (disk, peer, warm)},
        "speedup_peer": round(disk["cold_start_s"]
                              / max(1e-9, peer["cold_start_s"]), 2),
        "speedup_warm": round(disk["cold_start_s"]
                              / max(1e-9, warm["cold_start_s"]), 2),
        "token_parity": parity,
        "ok": parity and collapsed,
    }
    print(f"coldstart seed {seed}: disk={disk['cold_start_s']:.3f}s "
          f"peer={peer['cold_start_s']:.3f}s "
          f"warm={warm['cold_start_s']:.3f}s "
          f"parity={parity} {'OK' if summary['ok'] else 'FAIL'}")
    return [disk, peer, warm, summary]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seeds", type=int, default=3,
                    help="A/B pairs to run, seeds 0..N-1 (default 3)")
    ap.add_argument("--ticks", type=int, default=DEFAULT_TICKS,
                    help=f"storm ticks per run (default {DEFAULT_TICKS})")
    ap.add_argument("--out", default="bench_r14/autoscale.jsonl",
                    help="receipts file (default bench_r14/autoscale.jsonl)")
    ap.add_argument("--mode", choices=("all", "elastic", "coldstart",
                                       "migrate", "reshard"),
                    default="all",
                    help="which benches to run (default all)")
    ap.add_argument("--migrate", action="store_true",
                    help="shorthand for --mode migrate (live-migration "
                         "A/B; receipts default to "
                         "bench_r15/migration.jsonl)")
    ap.add_argument("--coldstart-seeds", type=int, default=1,
                    help="cold-start ladders to run (default 1)")
    ap.add_argument("--reshard-restart-child", nargs=2,
                    metavar=("SEED", "CKPT_DIR"), help=argparse.SUPPRESS)
    args = ap.parse_args(argv)
    if args.reshard_restart_child:
        return _reshard_restart_child(int(args.reshard_restart_child[0]),
                                      args.reshard_restart_child[1])
    if args.migrate:
        args.mode = "migrate"
    if args.mode == "migrate" \
            and args.out == ap.get_default("out"):
        args.out = "bench_r15/migration.jsonl"
    if args.mode == "reshard":
        if args.out == ap.get_default("out"):
            args.out = "bench_r19/reshard.jsonl"
        # the 4->2 meshes need a virtual multi-device CPU host; backend
        # selection is lazy, so setting flags here (before the first
        # run_reshard jax call) still wins
        import os
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        os.environ.setdefault("JAX_PLATFORMS", "cpu")

    lines = []
    failed = False
    if args.mode == "reshard":
        for seed in range(args.seeds):
            rows = run_reshard(seed)
            lines += rows
            summary = rows[-1]
            print(f"reshard seed {seed}: "
                  f"baseline={summary['downtime_baseline_s']:.3f}s "
                  f"reshard={summary['downtime_reshard_s']:.3f}s "
                  f"speedup={summary['speedup']}x "
                  f"bitwise={summary['bitwise_both']} "
                  f"{'OK' if summary['ok'] else 'FAIL'}")
            if not all(r["ok"] for r in rows):
                failed = True
    if args.mode == "migrate":
        for seed in range(args.seeds):
            with_m = run_migration(seed, migrate=True)
            without = run_migration(seed, migrate=False)
            ok = (with_m["dropped_streams"] == 0
                  and with_m["token_exact"]
                  and without["dropped_streams"] > 0
                  and with_m["live_at_decommission"] > 0)
            summary = {
                "metric": "migration_summary",
                "seed": seed,
                "live_at_decommission": with_m["live_at_decommission"],
                "dropped_with_migration": with_m["dropped_streams"],
                "dropped_without_migration": without["dropped_streams"],
                "migrated": with_m["migrated"],
                "resubmitted": with_m["resubmitted"],
                "token_exact": with_m["token_exact"],
                "pause_ms_p50": with_m["pause_ms"].get("p50"),
                "pause_ms_p95": with_m["pause_ms"].get("p95"),
                "ok": ok,
            }
            lines += [with_m, without, summary]
            print(f"migrate seed {seed}: live={summary['live_at_decommission']} "
                  f"dropped with={summary['dropped_with_migration']} "
                  f"without={summary['dropped_without_migration']} "
                  f"pause_p95={summary['pause_ms_p95']}ms "
                  f"exact={summary['token_exact']} "
                  f"{'OK' if ok else 'FAIL'}")
            if not ok:
                failed = True
    for seed in range(args.seeds
                      if args.mode in ("all", "elastic") else 0):
        auto = run_variant(seed, args.ticks, autoscale=True)
        static = run_variant(seed, args.ticks, autoscale=False)
        improved = auto["shed_rate"] < static["shed_rate"]
        # trace completeness: after settle every admitted relay's trace
        # must have reached a terminal span (the invariant also audits
        # this per tick; the receipt makes it visible in the A/B row)
        traces_ok = (auto["traces_incomplete"] == 0
                     and static["traces_incomplete"] == 0)
        ok = (auto["converged"] and static["converged"]
              and not auto["violations"] and not static["violations"]
              and improved and traces_ok)
        summary = {
            "metric": "elastic_ab_summary",
            "seed": seed,
            "shed_rate_autoscaled": auto["shed_rate"],
            "shed_rate_static": static["shed_rate"],
            "shed_improvement": round(
                static["shed_rate"] - auto["shed_rate"], 4),
            "scale_events": len(auto["scale_events"]),
            "preemptions": len(auto["preemptions"]),
            "flushes": len(auto["checkpoint_flushes"]),
            "resumes": len(auto["checkpoint_resumes"]),
            "traces_incomplete": (auto["traces_incomplete"]
                                  + static["traces_incomplete"]),
            "ok": ok,
        }
        lines += [auto, static, summary]
        print(f"seed {seed}: shed autoscaled={auto['shed_rate']:.3f} "
              f"static={static['shed_rate']:.3f} "
              f"scale_events={len(auto['scale_events'])} "
              f"preemptions={len(auto['preemptions'])} "
              f"flushes={len(auto['checkpoint_flushes'])} "
              f"resumes={len(auto['checkpoint_resumes'])} "
              f"{'OK' if ok else 'FAIL'}")
        if not ok:
            failed = True

    if args.mode in ("all", "coldstart"):
        for seed in range(args.coldstart_seeds):
            rows = run_coldstart(seed)
            lines += rows
            if not rows[-1]["ok"]:
                failed = True

    out = Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    with out.open("w") as fh:
        for line in lines:
            fh.write(json.dumps(line) + "\n")
    print(f"wrote {len(lines)} receipt line(s) to {out}")
    if failed:
        print("bench_autoscale: FAILED — see receipts", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
