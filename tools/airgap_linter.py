"""Airgap linter: frameworks must work with no external network.

Reference ``tools/airgap_linter.py``: in an airgapped cluster every
artifact must come through the package's resource.json (whose URLs the
release tooling rebases onto the local repo, ``tools/release_builder.py``).
A literal ``http(s)://`` URL anywhere else — a svc.yml `uris:`, a task cmd
`curl`, a config template — would silently depend on the outside world.

Usage::

    python -m tools.airgap_linter frameworks/jax [frameworks/... ...]

Exit 0 = clean; 1 = violations (each printed as file:line).
"""

from __future__ import annotations

import argparse
import os
import re
import sys
from typing import Iterator, List, Tuple

# the universe/ package dir is WHERE external artifact URLs belong: the
# release tooling rebases every URL there onto the local repo
# (tools/release_builder.py); anything outside it must not reach out
ALLOWED_DIRS = {"universe"}
_URL = re.compile(r"https?://[^\s\"'<>)\]}]+", re.IGNORECASE)
# loopback/example/doc hosts never leave the machine or are placeholders
_EXEMPT_HOST = re.compile(
    r"^(localhost|127\.0\.0\.1|0\.0\.0\.0|\[::1\]|example\.com"
    r"|.*\.example\.com|.*\.invalid)([:/]|$)", re.IGNORECASE)
# runtime-relevant text only (prose docs may cite external links freely)
TEXT_SUFFIXES = (".yml", ".yaml", ".json", ".mustache", ".py", ".sh",
                 ".cfg", ".conf")


def _iter_files(root: str) -> Iterator[str]:
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", "tests")
                       and d not in ALLOWED_DIRS]
        for fname in filenames:
            if fname.endswith(TEXT_SUFFIXES):
                yield os.path.join(dirpath, fname)


def _is_templated(url: str) -> bool:
    # "{{artifact-dir}}/x" style URLs are resolved by packaging, not the
    # network at deploy time; the scheme is inside the template variable so
    # a literal scheme followed by {{ also counts
    return "{{" in url


def lint_framework(root: str) -> List[Tuple[str, int, str]]:
    violations: List[Tuple[str, int, str]] = []
    for path in sorted(_iter_files(root)):
        # '*' only marks a comment in C-style block continuations; in
        # YAML/JSON (including mustache templates thereof) it begins
        # alias/list lines that are live config, so a URL there must not
        # escape the lint
        effective = path[:-len(".mustache")] if path.endswith(".mustache") \
            else path
        star_is_comment = not effective.endswith((".yml", ".yaml", ".json"))
        comment_leads = ("#", "//", "*") if star_is_comment else ("#", "//")
        with open(path, encoding="utf-8", errors="ignore") as f:
            for lineno, line in enumerate(f, 1):
                stripped = line.strip()
                if stripped.startswith(comment_leads):
                    continue  # comments/docs may cite URLs
                for url in _URL.findall(line):
                    if _is_templated(url):
                        continue
                    host = url.split("://", 1)[1]
                    if _EXEMPT_HOST.match(host):
                        continue
                    violations.append((path, lineno, url))
    return violations


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("frameworks", nargs="+",
                   help="framework directories to lint")
    args = p.parse_args(argv)
    bad = 0
    for root in args.frameworks:
        root = root.rstrip("/")
        if os.path.basename(root) == "__pycache__":
            continue  # shell globs like frameworks/*/ may include it
        if not os.path.isdir(root):
            print(f"error: not a directory: {root}", file=sys.stderr)
            return 2
        for path, lineno, url in lint_framework(root):
            print(f"{path}:{lineno}: external URL outside universe/: "
                  f"{url}")
            bad += 1
    if bad:
        print(f"{bad} airgap violation(s)", file=sys.stderr)
        return 1
    print("airgap-clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
