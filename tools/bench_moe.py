"""MoE train-step bench on one chip: routing variants, same window.

Measures the expert layer's real cost on the MXU — router softmax,
one-hot dispatch/combine einsums, expert SwiGLU matmuls — through a full
MoE-llama train step (``llama.loss_fn_moe``), for both routers
(``top2`` token-choice vs ``expert_choice``) against the SAME resident
weights in one process (tunnel-window discipline). Single chip runs
ep=1 (the all_to_all is an identity there; cross-chip dispatch is
validated on the virtual mesh + dryrun).

One JSON line per variant. Usage::

    python -m tools.bench_moe [--experts 8] [--batch 8] [--seq 512]
        [--dim 1024] [--layers 4] [--steps 10] [--trials 3]
"""

from __future__ import annotations

import argparse
import json
import time


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--experts", type=int, default=8)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seq", type=int, default=512)
    p.add_argument("--dim", type=int, default=1024)
    p.add_argument("--ffn", type=int, default=2048)
    p.add_argument("--layers", type=int, default=4)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--trials", type=int, default=3)
    p.add_argument("--capacity-factor", type=float, default=2.0)
    args = p.parse_args(argv)

    import jax
    import jax.numpy as jnp

    from dcos_commons_tpu.models import llama, train
    from dcos_commons_tpu.parallel.mesh import MeshSpec
    from dcos_commons_tpu.parallel.moe import MoEConfig

    cfg = llama.LlamaConfig(
        vocab_size=32000, dim=args.dim, n_layers=args.layers,
        n_heads=args.heads, n_kv_heads=args.heads,
        ffn_dim=args.ffn, max_seq=args.seq + 1, remat=False,
        attn_impl="dense")
    mesh = MeshSpec().build(jax.devices()[:1])
    params0 = llama.init_moe_params(cfg, args.experts, jax.random.key(0))
    n_params = sum(x.size for x in jax.tree.leaves(params0))
    toks = jax.random.randint(jax.random.key(1),
                              (args.batch, args.seq + 1), 0,
                              cfg.vocab_size)
    tokens_per_step = args.batch * args.seq

    for routing in ("top2", "expert_choice"):
        mcfg = MoEConfig(num_experts=args.experts,
                         capacity_factor=args.capacity_factor,
                         routing=routing)
        opt = train.make_optimizer(lr=1e-3, warmup=5, decay_steps=100)
        step = train.make_train_step(
            lambda p, b, m=mcfg: llama.loss_fn_moe(cfg, p, b, mesh, m),
            opt)
        params = jax.tree.map(jnp.copy, params0)
        opt_state = opt.init(params)
        with mesh:
            params, opt_state, out = step(params, opt_state, toks)
            float(out["loss"])                       # compile + sync
            trials = []
            for _ in range(args.trials):
                t0 = time.perf_counter()
                for _ in range(args.steps):
                    params, opt_state, out = step(params, opt_state,
                                                  toks)
                float(out["loss"])
                trials.append(tokens_per_step * args.steps
                              / (time.perf_counter() - t0))
        from dcos_commons_tpu.utils.stats import median
        tps = median(trials)
        print(json.dumps({
            "metric": "moe_train_step",
            "routing": routing,
            "experts": args.experts,
            "capacity_factor": args.capacity_factor,
            "params": n_params,
            "batch": args.batch, "seq": args.seq,
            "tokens_per_sec": round(tps, 1),
            "spread": {"min": round(min(trials), 1),
                       "max": round(max(trials), 1),
                       "trials": len(trials)},
            "backend": jax.devices()[0].platform,
        }), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
